"""
Adjoint benchmark: grad-step vs forward-step cost, and peak memory vs
checkpoint_segments, on the diffusion64 problem (1-D forced heat with a
parameter field — the same problem as the ensemble/serving benchmarks).

Two measurements:

  * cost ratio — post-compile steps/sec of the pure forward program vs
    the value-and-grad program over the same n steps (theory: the
    backward pass is one adjoint solve + one transposed RHS per step, so
    the ratio should sit in the 2-4x band; the row records reality);
  * memory sweep — peak process RSS of one grad call per
    checkpoint_segments value, each measured in a FRESH subprocess so
    ru_maxrss is that configuration's own high-water mark (on CPU the
    backward's stored segment states live in process RSS; the
    MemoryWatermark device number rides along where available).

Appends one `diffusion64_adjoint` row to benchmarks/results.jsonl (with
a one-shot finite-difference trust check on the gradient) — bench.py
re-reports it stale-stamped like the ensemble/serving rows.

Run: python benchmarks/adjoint.py [--quick]
  --quick   shortens windows and trims the sweep (CI smoke; no row
            appended, so a smoke run never shadows the full sweep).
"""

import json
import os
import resource
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

T0 = time.time()


def mark(msg):
    print(f"[adjoint {time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def build_diffusion(size=64):
    """The shared adjoint/fusion benchmark diffusion problem — all three
    differentiable operand classes present (`u` IC, parameter `a`,
    forcing `f`), and the Burgers term matters twice over: it exercises
    the dealiased transform chain under the adjoint, and it is what
    makes the backward pass STORE per-step residuals — a linear RHS
    needs none, and the checkpoint_segments memory sweep would show
    nothing. ONE definition in extras so cross-benchmark rows stay
    comparable."""
    from dedalus_tpu.extras.bench_problems import build_diffusion_solver
    return build_diffusion_solver(size)


def build_div(segments):
    import jax.numpy as jnp
    solver = build_diffusion()
    return solver.differentiable(
        wrt=("initial_state", "a", "f"),
        loss=lambda X: jnp.sum(X ** 2),
        checkpoint_segments=segments)


def measure_ratio(n, dt, repeats):
    """Post-compile forward vs grad steps/sec (+ a one-shot FD trust
    check so the recorded ratio is a ratio of CORRECT programs)."""
    div = build_div(None)
    mark(f"compiling forward + grad programs (n={n})")
    div.forward(n, dt)
    div.value_and_grad(n, dt)
    for _ in range(repeats):
        div.forward(n, dt)
        div.value_and_grad(n, dt)
    s = div.summary()
    # gradient trust: one central-difference probe on the IC operand
    X0 = np.asarray(div.solver.gather_fields()).copy()
    _, grads = div.value_and_grad(n, dt, initial_state=X0)
    v = np.random.default_rng(0).standard_normal(X0.shape)
    eps = 1e-6
    fd = (div.value(n, dt, initial_state=X0 + eps * v)
          - div.value(n, dt, initial_state=X0 - eps * v)) / (2 * eps)
    an = float(np.sum(np.asarray(grads["initial_state"]) * v))
    fd_rel = abs(fd - an) / max(abs(fd), 1e-30)
    finite = bool(np.isfinite(np.asarray(grads["initial_state"])).all())
    mark(f"forward {s['forward_steps_per_sec']} steps/s, grad "
         f"{s['grad_steps_per_sec']} steps/s "
         f"(ratio {s['grad_forward_ratio']}x), fd_rel={fd_rel:.2e}")
    return {
        "forward_steps_per_sec": s["forward_steps_per_sec"],
        "grad_steps_per_sec": s["grad_steps_per_sec"],
        "grad_forward_ratio": s["grad_forward_ratio"],
        "auto_segments": s["checkpoint_segments"],
        "fd_rel_err": round(fd_rel, 10),
        "plan": div.solver.plan_provenance(),
        "finite": finite,
    }


def child_measure(n, dt, segments):
    """One grad call at a fixed segment count; prints its own peak RSS
    (this process's high-water mark — why each point runs in a fresh
    interpreter)."""
    div = build_div(segments)
    div.value_and_grad(n, dt)        # compile
    t0 = time.perf_counter()
    div.value_and_grad(n, dt)
    wall = time.perf_counter() - t0
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    print(json.dumps({
        "segments": div.summary()["checkpoint_segments"],
        "grad_steps_per_sec": round(n / wall, 2),
        "peak_rss_bytes": peak,
        "device_mem_peak_bytes":
            div.summary()["device_mem_peak_bytes"] or None,
    }), flush=True)


def sweep_segments(n, dt, sweep):
    points = []
    for K in sweep:
        mark(f"memory sweep: checkpoint_segments={K} (fresh subprocess)")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             str(n), str(dt), str(K)],
            capture_output=True, text=True, timeout=900)
        line = next((ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")), None)
        if proc.returncode != 0 or line is None:
            mark(f"sweep point K={K} FAILED (rc={proc.returncode}): "
                 f"{proc.stderr[-500:]}")
            points.append({"segments": K, "error": f"rc={proc.returncode}"})
            continue
        point = json.loads(line)
        points.append(point)
        mark(f"K={point['segments']}: {point['grad_steps_per_sec']} "
             f"grad-steps/s, peak RSS "
             f"{point['peak_rss_bytes'] / 1e6:.1f} MB")
    return points


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        n, dt, K = int(sys.argv[2]), float(sys.argv[3]), int(sys.argv[4])
        child_measure(n, dt, K)
        return
    quick = "--quick" in sys.argv
    from __graft_entry__ import _append_result
    if quick:
        _append_result = lambda record: None  # noqa: E731
    n = 128 if quick else 512
    dt = 1e-3
    # The memory sweep runs MANY more steps than the ratio window: the
    # diffusion64 per-step carry is ~2.5 KB, so the K=1 backward only
    # rises visibly above the interpreter's RSS baseline once tens of
    # thousands of step states are stored — exactly the regime
    # checkpointing exists for.
    n_mem = 1024 if quick else 65536
    sweep = [1, 16] if quick else [1, 16, 256]
    ratio = measure_ratio(n, dt, repeats=1 if quick else 3)
    points = sweep_segments(n_mem, dt, sweep)
    row = {
        "config": "diffusion64_adjoint",
        "backend": os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0],
        "n_steps": n,
        "mem_sweep_steps": n_mem,
        "dt": dt,
        "wrt": ["initial_state", "a", "f"],
        "segments_sweep": points,
    }
    row.update(ratio)
    print(json.dumps(row), flush=True)
    if not ratio["finite"] or ratio["fd_rel_err"] > 1e-4:
        # the trust gate runs BEFORE the append: a wrong-but-finite
        # gradient must never become the re-reported bench headline
        mark("FAIL: gradient non-finite or FD mismatch; row not recorded")
        sys.exit(1)
    _append_result(row)


if __name__ == "__main__":
    main()
