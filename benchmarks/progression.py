"""
BASELINE.md progression benchmarks (configs 1-4) on the current backend.

Each config builds the example-equivalent solver, runs warmup + measured
steps, and records steps/sec plus the reference's mode-stages/sec metric
(reference: dedalus/core/solvers.py:770-776). Progress markers go to stderr;
results append to benchmarks/results.jsonl and print as JSON lines.

Run:  python benchmarks/progression.py [config ...]
Configs: kdv1024 shear512 rb256x64 rb2048x1024 sw_ell255 (default: all)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

T0 = time.time()


def mark(msg):
    print(f"[prog {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def build_kdv(N, dtype):
    import dedalus_tpu.public as d3
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=dtype)
    xbasis = d3.RealFourier(xcoord, size=N, bounds=(0, 10), dealias=3 / 2)
    u = dist.Field(name="u", bases=xbasis)
    a, b = 1e-4, 2e-4
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - a*dx(dx(u)) - b*dx(dx(dx(u))) = - u*dx(u)")
    solver = problem.build_solver(d3.SBDF2)
    x = dist.local_grids(xbasis)[0]
    n = 20
    u["g"] = np.log(1 + np.cosh(n) ** 2 / np.cosh(n * (x - 3)) ** 2) / (2 * n)
    return solver, 2e-3


def build_shear(N, dtype):
    import dedalus_tpu.public as d3
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=dtype)
    xbasis = d3.RealFourier(coords["x"], size=N, bounds=(0, 1), dealias=3 / 2)
    zbasis = d3.RealFourier(coords["z"], size=N, bounds=(-1, 1), dealias=3 / 2)
    p = dist.Field(name="p", bases=(xbasis, zbasis))
    s = dist.Field(name="s", bases=(xbasis, zbasis))
    u = dist.VectorField(coords, name="u", bases=(xbasis, zbasis))
    tau_p = dist.Field(name="tau_p")
    nu = 1 / 5e4
    D = nu
    x, z = dist.local_grids(xbasis, zbasis)
    problem = d3.IVP([u, s, p, tau_p], namespace=locals())
    problem.add_equation("dt(u) + grad(p) - nu*lap(u) = - u@grad(u)")
    problem.add_equation("dt(s) - D*lap(s) = - u@grad(s)")
    problem.add_equation("div(u) + tau_p = 0")
    problem.add_equation("integ(p) = 0")
    ug = np.zeros((2,) + np.broadcast_shapes((N, 1), (1, N)))
    ug[0] = 1 / 2 + 1 / 2 * (np.tanh((z - 0.5) / 0.1) - np.tanh((z + 0.5) / 0.1))
    ug[1] = (0.1 * np.sin(2 * np.pi * x) * np.exp(-(z - 0.5) ** 2 / 0.01)
             + 0.1 * np.sin(2 * np.pi * x) * np.exp(-(z + 0.5) ** 2 / 0.01))
    u["g"] = ug
    s["g"] = ug[0]
    solver = problem.build_solver(d3.RK222)
    # CFL-stable fixed step at 512^2 (u ~ 1, dx = 1/N, safety ~ 0.25)
    return solver, 0.25 / N


def build_rb(Nx, Nz, dtype, matsolver=None):
    from __graft_entry__ import _build_rb_solver
    if matsolver is not None:
        # route through the example builder with a forced matsolver
        from dedalus_tpu.tools.config import config
        old = config["linear algebra"].get("MATRIX_SOLVER", "auto")
        config["linear algebra"]["MATRIX_SOLVER"] = matsolver
        try:
            solver, b = _build_rb_solver(Nx, Nz, dtype)
        finally:
            config["linear algebra"]["MATRIX_SOLVER"] = old
    else:
        solver, b = _build_rb_solver(Nx, Nz, dtype)
    return solver, 0.01 if Nx <= 512 else 5e-5


def build_rb3d(Nx, Ny, Nz, dtype):
    """3D Rayleigh-Benard (Fourier^2 x Chebyshev) — BASELINE config 5's
    single-chip variant; the multi-chip version shards the pencil batch
    (see __graft_entry__.dryrun_multichip)."""
    import dedalus_tpu.public as d3
    coords = d3.CartesianCoordinates("x", "y", "z")
    dist = d3.Distributor(coords, dtype=dtype)
    xb = d3.RealFourier(coords["x"], size=Nx, bounds=(0, 4.0), dealias=3 / 2)
    yb = d3.RealFourier(coords["y"], size=Ny, bounds=(0, 4.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, 1.0), dealias=3 / 2)
    p = dist.Field(name="p", bases=(xb, yb, zb))
    b = dist.Field(name="b", bases=(xb, yb, zb))
    u = dist.VectorField(coords, name="u", bases=(xb, yb, zb))
    tau_p = dist.Field(name="tau_p")
    tau_b1 = dist.Field(name="tau_b1", bases=(xb, yb))
    tau_b2 = dist.Field(name="tau_b2", bases=(xb, yb))
    tau_u1 = dist.VectorField(coords, name="tau_u1", bases=(xb, yb))
    tau_u2 = dist.VectorField(coords, name="tau_u2", bases=(xb, yb))
    kappa = nu = 2.0e-6 ** 0.5
    x, y, z = dist.local_grids(xb, yb, zb)
    ex, ey, ez = coords.unit_vector_fields(dist)
    lift_basis = zb.derivative_basis(1)
    lift = lambda A: d3.Lift(A, lift_basis, -1)
    grad_u = d3.grad(u) + ez * lift(tau_u1)
    grad_b = d3.grad(b) + ez * lift(tau_b1)
    problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                     namespace=locals())
    problem.add_equation("trace(grad_u) + tau_p = 0")
    problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
    problem.add_equation(
        "dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = - u@grad(u)")
    problem.add_equation("b(z=0) = 1")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("b(z=1) = 0")
    problem.add_equation("u(z=1) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    b.fill_random("g", seed=42, distribution="normal", scale=1e-3)
    b["g"] += (1 - z)
    return solver, 1e-3


def build_shallow_water(Nphi, Ntheta, dtype, matsolver=None, min_q=None):
    from dedalus_tpu.tools.config import config as _cfg
    old_solver = _cfg["linear algebra"].get("MATRIX_SOLVER", "auto")
    old_q = _cfg["linear algebra"].get("BANDED_MIN_Q", "0")
    if matsolver is not None:
        _cfg["linear algebra"]["MATRIX_SOLVER"] = matsolver
    if min_q is not None:
        _cfg["linear algebra"]["BANDED_MIN_Q"] = str(min_q)
    try:
        return _build_shallow_water_inner(Nphi, Ntheta, dtype)
    finally:
        _cfg["linear algebra"]["MATRIX_SOLVER"] = old_solver
        _cfg["linear algebra"]["BANDED_MIN_Q"] = old_q


def _build_shallow_water_inner(Nphi, Ntheta, dtype):
    import dedalus_tpu.public as d3
    # Simulation units (reference: examples/ivp_sphere_shallow_water/
    # shallow_water.py:24-40): nondimensionalized so R = 1, hour = 1.
    # Raw SI units put the hyperdiffusion matrix entries (~ ell^4 / R^4
    # ~ 1e-36) at the f32 denormal boundary, where the factorization
    # flushes them to zero — the root cause of the round-3 sw_ell255
    # finite=false run (see BENCHMARKS.md).
    meter = 1 / 6.37122e6
    hour = 1
    second = hour / 3600
    R = 6.37122e6 * meter
    Omega = 7.292e-5 / second
    nu = 1e5 * meter ** 2 / second / 32 ** 2  # hyperdiffusion matched at ell=32
    g = 9.80616 * meter / second ** 2
    H = 1e4 * meter
    coords = d3.S2Coordinates("phi", "theta")
    dist = d3.Distributor(coords, dtype=dtype)
    basis = d3.SphereBasis(coords, shape=(Nphi, Ntheta), dtype=dtype,
                           radius=R, dealias=3 / 2)
    u = dist.VectorField(coords, name="u", bases=basis)
    h = dist.Field(name="h", bases=basis)
    zcross = lambda A: d3.MulCosine(d3.Skew(A))
    phi, theta = dist.local_grids(basis)
    lat = np.pi / 2 - theta + 0 * phi
    umax = 80 * meter / second  # reference: shallow_water.py:44
    lat0, lat1 = np.pi / 7, np.pi / 2 - np.pi / 7
    en = np.exp(-4 / (lat1 - lat0) ** 2)
    jet = (lat0 <= lat) * (lat <= lat1)
    u_jet = umax / en * np.exp(1 / ((lat[jet] - lat0) * (lat[jet] - lat1)))
    ug = np.zeros_like(np.broadcast_to(lat, (Nphi, Ntheta)))
    ug = np.array([ug, 0 * ug])
    ug[0][jet] = u_jet
    u["g"] = ug
    h["g"] = 120 * meter * np.cos(lat) * np.exp(-(phi / (1 / 3)) ** 2) \
        * np.exp(-((np.pi / 4 - lat) / (1 / 15)) ** 2)
    problem = d3.IVP([u, h], namespace=locals())
    problem.add_equation(
        "dt(u) + nu*lap(lap(u)) + g*grad(h) + 2*Omega*zcross(u) "
        "= - u@grad(u)")
    problem.add_equation("dt(h) + nu*lap(lap(h)) + H*div(u) = - div(u*h)")
    solver = problem.build_solver(d3.RK222)
    return solver, 300.0 * second


def build_rotconv_ivp(Nphi, Ntheta, Nr, dtype):
    """Rotating Boussinesq convection in a shell (IVP): the ell-coupled
    Coriolis NCC makes every per-m pencil a (theta x r)-coupled system on
    the flattened banded path — the 3D curvilinear flagship
    (reference formulation: examples/evp_shell_rotating_convection)."""
    import dedalus_tpu.public as d3
    Ri, Ro = 0.35, 1.0
    Ekman, Prandtl, Rayleigh = 1e-3, 1.0, 3e5
    coords = d3.SphericalCoordinates("phi", "theta", "r")
    dist = d3.Distributor(coords, dtype=dtype)
    shell = d3.ShellBasis(coords, shape=(Nphi, Ntheta, Nr), radii=(Ri, Ro),
                          dtype=dtype)
    sphere = shell.outer_surface
    phi, theta, r = dist.local_grids(shell)
    u = dist.VectorField(coords, name="u", bases=shell)
    p = dist.Field(name="p", bases=shell)
    T = dist.Field(name="T", bases=shell)
    tau_u1 = dist.VectorField(coords, bases=sphere)
    tau_u2 = dist.VectorField(coords, bases=sphere)
    tau_T1 = dist.Field(bases=sphere)
    tau_T2 = dist.Field(bases=sphere)
    tau_p = dist.Field()
    rvec = dist.VectorField(coords, bases=shell.meridional_basis)
    rvec["g"][2] = np.broadcast_to(r, rvec["g"][2].shape)
    ez = dist.VectorField(coords, bases=shell.meridional_basis)
    ez["g"][1] = -np.sin(theta)
    ez["g"][2] = np.cos(theta)
    lift_basis = shell.derivative_basis(1)
    lift = lambda A: d3.Lift(A, lift_basis, -1)
    grad_u = d3.grad(u) + rvec * lift(tau_u1)
    grad_T = d3.grad(T) + rvec * lift(tau_T1)
    problem = d3.IVP([p, u, T, tau_u1, tau_u2, tau_T1, tau_T2, tau_p],
                     namespace=locals())
    problem.add_equation("trace(grad_u) + tau_p = 0")
    problem.add_equation("dt(u) + (1/Ekman)*cross(ez, u) + grad(p) "
                         "- Rayleigh*T*rvec - div(grad_u) + lift(tau_u2) "
                         "= - u@grad(u)")
    problem.add_equation("dt(T) - dot(rvec,u)/Prandtl - div(grad_T)/Prandtl "
                         "+ lift(tau_T2) = - u@grad(T)")
    problem.add_equation("u(r=0.35) = 0")
    problem.add_equation("u(r=1.0) = 0")
    problem.add_equation("T(r=0.35) = 0")
    problem.add_equation("T(r=1.0) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver(d3.RK222)
    T.fill_random("g", seed=3, scale=1e-4)
    return solver, 1e-4


CONFIGS = {
    "kdv1024": lambda dt_: build_kdv(1024, dt_),
    "shear512": lambda dt_: build_shear(512, dt_),
    "rb256x64": lambda dt_: build_rb(256, 64, dt_),
    "rb512x128": lambda dt_: build_rb(512, 128, dt_),
    "rb2048x1024": lambda dt_: build_rb(2048, 1024, dt_, matsolver="banded"),
    "rb3d_128": lambda dt_: build_rb3d(128, 128, 64, dt_),
    "sw_ell255": lambda dt_: build_shallow_water(512, 256, dt_),
    # dense-forced twin: the banded path's sequential block scans may be
    # latency-bound on TPU at this shape (round-4: 29x mode-stages/s gap
    # vs the pure-matmul shear path); a (G,S,S) batched inverse turns
    # every stage solve into one MXU matmul at ~2.4 GB of HBM
    "sw_ell255_dense": lambda dt_: build_shallow_water(
        512, 256, dt_, matsolver="BatchedInverse"),
    # re-blocked banded twin: q>=128 cuts the solve scans to ~1/8 the
    # sequential steps (latency-bound on TPU; [linear algebra]
    # BANDED_MIN_Q)
    "sw_ell255_q128": lambda dt_: build_shallow_water(
        512, 256, dt_, matsolver="banded", min_q=128),
    "rotconv32": lambda dt_: build_rotconv_ivp(64, 32, 32, dt_),
}

# measured steps per config (big builds measure fewer)
MEASURE = {"rb2048x1024": 20, "rb3d_128": 20}


def run_config(name, warmup=5, measure=50):
    import jax
    backend = jax.default_backend()
    dtype = np.float32 if backend != "cpu" else np.float64
    measure = MEASURE.get(name, measure)
    mark(f"{name}: building (backend={backend}, dtype={np.dtype(dtype).name})")
    t_build = time.time()
    solver, dt = CONFIGS[name](dtype)
    build_s = time.time() - t_build
    G, S = solver.pencil_shape
    mark(f"{name}: built in {build_s:.1f}s; pencils (G={G}, S={S}), "
         f"ops={type(solver.ops).__name__}")
    mark(f"{name}: warmup {warmup} steps (first compiles)")
    t_c = time.time()
    for i in range(warmup):
        solver.step(dt)
        if i == 0:
            solver.X.block_until_ready()
            mark(f"{name}: first step done in {time.time() - t_c:.1f}s")
    solver.X.block_until_ready()
    finite_warmup = bool(np.all(np.isfinite(np.asarray(solver.X))))
    if not finite_warmup:
        mark(f"{name}: STATE NOT FINITE after {warmup} warmup steps — "
             "failing loudly (check dt stability / f32 dynamic range; "
             "see BENCHMARKS.md sw_ell255 root cause)")
    # block of `measure` steps in one device dispatch (compiles once)
    mark(f"{name}: compiling {measure}-step block")
    solver.step_many(measure, dt)
    solver.X.block_until_ready()
    mark(f"{name}: measuring {measure}-step block")
    t0 = time.time()
    solver.step_many(measure, dt)
    solver.X.block_until_ready()
    elapsed = time.time() - t0
    sps = measure / elapsed
    finite = bool(np.all(np.isfinite(np.asarray(solver.X))))
    stages = getattr(solver.timestepper, "stages", 1)
    record = {
        "config": name,
        "backend": backend,
        "dtype": np.dtype(dtype).name,
        "pencil_shape": [int(G), int(S)],
        "ops": type(solver.ops).__name__,
        "steps_per_sec": round(sps, 3),
        "mode_stages_per_sec": round(G * S * stages * sps, 1),
        "build_sec": round(build_s, 2),
        # cold-start split (host_assembly/structure/factor/compile seconds
        # + assembly-cache verdict; tools/metrics.BuildPhases)
        "build_phases": solver.build_phases.record(),
        "finite": finite,
        "finite_after_warmup": finite_warmup,
    }
    mark(f"{name}: {sps:.2f} steps/s, finite={finite}")
    return record


def main():
    from __graft_entry__ import _append_result
    names = sys.argv[1:] or list(CONFIGS)
    if len(names) > 1:
        # One subprocess per config: a config's device allocations (or a
        # wedged backend) must not poison the next — leftover HBM from an
        # OOM'd build previously surfaced as spurious RESOURCE_EXHAUSTED
        # on tiny later configs.
        import subprocess
        failures = 0
        for name in names:
            mark(f"--- spawning {name} ---")
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), name])
            if proc.returncode != 0:
                # a hard-killed child (OOM, segfault) writes no record of
                # its own — leave one so the sweep output stays complete
                failures += 1
                record = {"config": name,
                          "error": f"subprocess exit {proc.returncode}"}
                print(json.dumps(record), flush=True)
                _append_result(record)
        sys.exit(1 if failures else 0)
    failed = False
    for name in names:
        if name not in CONFIGS:
            mark(f"unknown config {name}; skipping")
            continue
        try:
            record = run_config(name)
        except Exception as e:
            record = {"config": name, "error": repr(e)}
            mark(f"{name}: FAILED {e!r}")
            failed = True
        print(json.dumps(record), flush=True)
        _append_result(record)
    # a recorded-error run must NOT look successful to the sweep (a
    # round-4 remote-compile outage marked rb2048x1024 done with no data)
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
