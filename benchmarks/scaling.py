"""
Weak-scaling benchmark for the overlapped distributed transpose pipeline.

Records the multi-chip scaling trajectory ROADMAP item 3 asked for, on
the 8-device virtual CPU mesh so the curve survives TPU chip outages
(bench.py `_attach_scaling` re-reports the newest row stale-stamped
every round; a claimed chip re-measures it for real). Per device count
d in {1, 2, 4, 8}:

  * a weak-scaled 2-D nonlinear diffusion IVP (Fourier x Chebyshev,
    Nx = 64*d so per-device work is constant) is built, distributed
    over a d-device pencil mesh, and stepped — steps/s recorded;
  * the compiled step's HLO is scanned: ZERO full-state all-gathers
    (the collective-placement assertion of tests/test_collectives.py,
    promoted to the chunked walk) and the all-to-all count recorded;
  * the transpose phase split is measured at the pipeline level
    (DistributedPencilPipeline round-trips): `transpose_exposed_sec` =
    communication the chunked walk still waits on,
    `transpose_overlapped_sec` = communication hidden under the
    interleaved chunk transforms (tools/metrics.py phase vocabulary).

Then, on the full 8-device mesh:

  * chunked-vs-monolithic guard: [distributed] TRANSPOSE_CHUNKS=auto vs
    =1 solvers must produce BIT-IDENTICAL states, and the chunked walk
    must hold >= 0.95x the monolithic steps/s (the overlap is upside,
    never a tax);
  * the 2048 x 1024 NORTH-STAR shape steps on the 8-device mesh
    (banded pencil solve), steps/s recorded;
  * a 2-D batch x pencil fleet (EnsembleSolver on Mesh(2, 4)) must
    bit-match the 1-D member-mesh fleet.

Appends ONE `weak_scaling` row to benchmarks/results.jsonl; exits
nonzero when any guard fails (gather found, bit-identity broken, ratio
< 0.95, non-finite north star, fleet mismatch).

Run: python benchmarks/scaling.py [--quick] [--skip-northstar]
  --quick          devices {1, 8}, shorter windows (CI smoke)
  --skip-northstar skip the 2048x1024 build (memory-constrained hosts)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The virtual pencil mesh must exist before jax initializes (conftest.py
# does the same for the test suite).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

T0 = time.time()


def mark(msg):
    print(f"[scaling {time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def build_diffusion2d(Nx, Nz, matsolver=None):
    """2-D nonlinear diffusion IVP (the tests/test_collectives.py step
    problem, resolution-parameterized): one variable + two tau lines, so
    the weak-scaled builds stay cheap while the step exercises the full
    transform walk + pencil solve."""
    import dedalus_tpu.public as d3
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=Nx, bounds=(0, 4.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, 1.0), dealias=3 / 2)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    kw = {"matsolver": matsolver} if matsolver else {}
    solver = problem.build_solver(d3.SBDF2, **kw)
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
    return solver, u


# shared with tests/test_collectives.py and the lint --programs census:
# ONE parser and ONE program handle behind every gather assertion
from dedalus_tpu.tools.lint.progcheck import collective_counts  # noqa: E402


def step_hlo(solver):
    """Compiled-HLO text of the solver's advance program (the
    tests/test_collectives.py probe)."""
    from dedalus_tpu.core.timesteppers import step_program_handle
    prog, args = step_program_handle(solver)
    return prog.lower(*args).compile().as_text()


def measure_steps(solver, dt, warmup, steps, reps=3):
    """Median steps/s over `reps` measured windows of `steps` steps."""
    import jax
    solver.step_many(warmup, dt)
    jax.block_until_ready(solver.X)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        solver.step_many(steps, dt)
        jax.block_until_ready(solver.X)
        walls.append(time.perf_counter() - t0)
    return steps / float(np.median(walls))


def median_wall(fn, reps=5):
    fn()  # compile
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        walls.append(time.perf_counter() - t0)
    return float(np.median(walls))


def transpose_split(domain, mesh, chunks):
    """Pipeline-level transpose phase split on `mesh`:
      t_chunk  chunked to_grid/to_coeff round-trip wall
      t_mono   monolithic (chunks=1) round-trip wall
      t_a2a    the bare transposes (all_to_all_transpose both ways)
    exposed = t_chunk - (t_mono - t_a2a)   [chunked wall minus compute]
    overlapped = t_a2a - exposed           [comm hidden under compute]
    both clamped at 0."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dedalus_tpu.parallel import (DistributedPencilPipeline,
                                      all_to_all_transpose)
    name = mesh.axis_names[0]
    pipe_c = DistributedPencilPipeline(domain, mesh, name, chunks=chunks)
    pipe_m = DistributedPencilPipeline(domain, mesh, name, chunks=1)
    shape = tuple(b.size for b in domain.bases)
    rng = np.random.default_rng(7)
    cdata = jax.device_put(rng.standard_normal(shape),
                           NamedSharding(mesh, P(name)))

    def roundtrip(pipe):
        prog_g = jax.jit(pipe.to_grid)
        prog_c = jax.jit(pipe.to_coeff)

        def run():
            jax.block_until_ready(prog_c(prog_g(cdata)))
        return run

    gdata = jax.jit(pipe_m.to_grid)(cdata)
    a2a_g = jax.jit(lambda d: all_to_all_transpose(d, 0, 1, mesh, name))
    a2a_c = jax.jit(lambda d: all_to_all_transpose(d, 1, 0, mesh, name))

    def bare_transposes():
        jax.block_until_ready(a2a_c(a2a_g(cdata)))

    t_chunk = median_wall(roundtrip(pipe_c))
    t_mono = median_wall(roundtrip(pipe_m))
    t_a2a = median_wall(bare_transposes)
    exposed = max(0.0, t_chunk - max(0.0, t_mono - t_a2a))
    overlapped = max(0.0, t_a2a - exposed)
    return {"transpose_total_sec": round(t_a2a, 6),
            "transpose_exposed_sec": round(exposed, 6),
            "transpose_overlapped_sec": round(overlapped, 6),
            "walk_chunked_sec": round(t_chunk, 6),
            "walk_monolithic_sec": round(t_mono, 6)}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--skip-northstar", action="store_true")
    args = parser.parse_args()

    import jax
    from jax.sharding import Mesh
    from dedalus_tpu.parallel import distribute_solver
    from dedalus_tpu.tools.config import config
    from dedalus_tpu.parallel.transposes import resolve_transpose_chunks
    from __graft_entry__ import _append_result

    n_dev = len(jax.devices())
    if n_dev < 8:
        mark(f"only {n_dev} devices visible; need 8")
        return 1
    chunks = resolve_transpose_chunks()
    device_counts = (1, 8) if args.quick else (1, 2, 4, 8)
    base_nx, nz = 64, 64
    warmup, steps = (3, 6) if args.quick else (4, 16)
    dt = 1e-4
    failures = []

    # ---------------------------------------------------- weak-scaling sweep
    sweep = []
    sweep_plan = None
    for d in device_counts:
        Nx = base_nx * d
        mark(f"weak point d={d}: {Nx}x{nz}")
        solver, _ = build_diffusion2d(Nx, nz)
        mesh = None
        if d > 1:
            mesh = Mesh(np.array(jax.devices()[:d]), ("x",))
            distribute_solver(solver, mesh)
        sps = measure_steps(solver, dt, warmup, steps)
        point = {"devices": d, "shape": [Nx, nz],
                 "steps_per_sec": round(sps, 4)}
        if d > 1:
            counts = collective_counts(step_hlo(solver))
            point.update(all_to_alls=counts["all-to-all"],
                         all_gathers=counts["all-gather"])
            if counts["all-gather"]:
                failures.append(
                    f"d={d}: {counts['all-gather']} full-state "
                    f"all-gathers in the sharded step")
            if counts["all-to-all"] < 2:
                failures.append(f"d={d}: transform transposes missing "
                                f"({counts})")
            point.update(transpose_split(solver.problem.variables[0].domain,
                                         mesh, chunks))
            # the widest sharded point's resolved plan stamps the row
            sweep_plan = solver.plan_provenance()
        sweep.append(point)
        mark(f"  {sps:.2f} steps/s")

    # -------------------------------------- chunked vs monolithic (8 devices)
    mark("chunked vs monolithic guard (8 devices)")
    mesh8 = Mesh(np.array(jax.devices()[:8]), ("x",))
    old = config["distributed"]["TRANSPOSE_CHUNKS"]
    Nx8 = base_nx * 8
    try:
        config["distributed"]["TRANSPOSE_CHUNKS"] = "1"
        mono, _ = build_diffusion2d(Nx8, nz)
        distribute_solver(mono, mesh8)
        config["distributed"]["TRANSPOSE_CHUNKS"] = old
        chunked, _ = build_diffusion2d(Nx8, nz)
        distribute_solver(chunked, mesh8)
    finally:
        config["distributed"]["TRANSPOSE_CHUNKS"] = old
    # interleaved windows: alternating the two walks inside one sweep
    # cancels host load drift that a sequential A-then-B comparison
    # would read as a regression
    import jax as _jax
    for s in (mono, chunked):
        s.step_many(warmup, dt)
        _jax.block_until_ready(s.X)
    walls = {"mono": [], "chunk": []}
    for _ in range(5):
        for key, s in (("mono", mono), ("chunk", chunked)):
            t0 = time.perf_counter()
            s.step_many(steps, dt)
            _jax.block_until_ready(s.X)
            walls[key].append(time.perf_counter() - t0)
    sps_mono = steps / float(np.median(walls["mono"]))
    sps_chunk = steps / float(np.median(walls["chunk"]))
    bit_identical = bool(
        (np.asarray(mono.X) == np.asarray(chunked.X)).all())
    ratio = sps_chunk / sps_mono if sps_mono else 0.0
    if not bit_identical:
        diff = np.abs(np.asarray(mono.X) - np.asarray(chunked.X)).max()
        failures.append(f"chunked walk not bit-identical to monolithic "
                        f"(max diff {diff:.3e})")
    if ratio < 0.95:
        failures.append(f"chunked walk regressed: {ratio:.3f}x < 0.95x "
                        f"monolithic steps/s")
    guard = {"chunks": chunks,
             "mono_steps_per_sec": round(sps_mono, 4),
             "chunked_steps_per_sec": round(sps_chunk, 4),
             "ratio": round(ratio, 4),
             "bit_identical": bit_identical}
    mark(f"  mono {sps_mono:.2f} vs chunked {sps_chunk:.2f} steps/s "
         f"({ratio:.3f}x), bit_identical={bit_identical}")

    # ------------------------------------------------- 2048x1024 north star
    northstar = None
    if not args.skip_northstar:
        mark("north-star shape 2048x1024 on 8 devices (banded)")
        try:
            ns, _ = build_diffusion2d(2048, 1024, matsolver="banded")
            distribute_solver(ns, mesh8)
            ns_steps = 2 if args.quick else 4
            t_build = time.time() - T0
            ns.step_many(2, 1e-5)   # compile + ramp
            jax.block_until_ready(ns.X)
            t0 = time.perf_counter()
            ns.step_many(ns_steps, 1e-5)
            jax.block_until_ready(ns.X)
            wall = time.perf_counter() - t0
            finite = bool(np.isfinite(np.asarray(ns.X)).all())
            northstar = {"shape": [2048, 1024], "devices": 8,
                         "steps_per_sec": round(ns_steps / wall, 4),
                         "finite": finite,
                         "build_sec": round(t_build, 1)}
            if not finite:
                failures.append("north-star state non-finite")
            mark(f"  {northstar['steps_per_sec']} steps/s, "
                 f"finite={finite}")
            del ns
        except MemoryError as exc:
            mark(f"  north-star skipped: {exc}")
            northstar = {"shape": [2048, 1024], "skipped": str(exc)}

    # ------------------------------------ 2-D batch x pencil fleet bit-match
    mark("2-D batch x pencil fleet vs 1-D fleet")
    members, fleet_steps = 4, 8

    def fleet_state(mesh):
        solver, u = build_diffusion2d(64, 16)
        x, z = solver.dist.local_grids(*u.domain.bases)
        fleet = solver.ensemble(members, mesh=mesh)

        def ics(i):
            u["g"] = np.sin(np.pi * z) * (
                1 + 0.1 * (i + 1) * np.cos(np.pi * x / 2))
        fleet.init_members(ics)
        fleet.step_many(fleet_steps, 1e-3)
        return np.asarray(fleet.X)[:members]

    X1 = fleet_state(Mesh(np.array(jax.devices()[:2]), ("batch",)))
    X2 = fleet_state(Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                          ("batch", "pencil")))
    fleet_match = bool((X1 == X2).all())
    if not fleet_match:
        failures.append(f"2-D fleet diverged from 1-D fleet "
                        f"(max diff {np.abs(X1 - X2).max():.3e})")
    mark(f"  bit_match={fleet_match}")

    row = {
        "config": "weak_scaling",
        "benchmark": "scaling",
        "backend": jax.default_backend(),
        "dtype": "float64",
        "chunks": chunks,
        "sweep": sweep,
        "chunked_vs_mono": guard,
        "fleet2d": {"members": members,
                    "mesh": [2, 4],
                    "bit_match_1d": fleet_match},
        "plan": sweep_plan,
        "finite": not failures,
        "quick": bool(args.quick),
    }
    if northstar is not None:
        row["northstar"] = northstar
    if failures:
        row["errors"] = failures
    _append_result(row)
    print(json.dumps(row, indent=2))
    if failures:
        mark("FAILURES: " + "; ".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
