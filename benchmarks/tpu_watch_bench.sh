#!/bin/bash
# TPU watcher: probe the chip every ~2.5 min; the moment it becomes
# claimable, run the BASELINE progression benchmarks (one hard-timeout,
# process-group-killed subprocess per config — round 2's wedge was a
# leaked chip-holding child) and record to benchmarks/results.jsonl.
# Stops after one successful sweep (marker file) or MAX_ITERS probes.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/auto_bench.log
MARKER=benchmarks/.auto_bench_done
MAX_ITERS=${MAX_ITERS:-250}

log() { echo "$(date +%H:%M:%S) $*" >> "$LOG"; }

probe() {
    timeout -k 5 90 setsid python -c \
        "import jax; d=jax.devices(); print('PROBE_OK', jax.default_backend(), len(d))" \
        2>/dev/null | grep -q PROBE_OK
}

run_config() {
    name=$1; tmo=$2
    # per-config marker: a sweep resumed after a mid-sweep chip loss must
    # not burn the window re-measuring (and re-recording) finished configs
    done_marker="benchmarks/.auto_bench_done_$name"
    if [ -f "$done_marker" ]; then
        log "skipping $name (already recorded)"
        return 0
    fi
    log "running $name (timeout ${tmo}s)"
    timeout -k 10 "$tmo" setsid python benchmarks/progression.py "$name" \
        >> "$LOG" 2>&1
    rc=$?
    log "$name finished rc=$rc"
    [ "$rc" -eq 0 ] && touch "$done_marker"
    # verify the chip survived (a wedged chip fails this and we stop
    # burning the window on configs that can only error)
    if ! probe; then
        log "chip unresponsive after $name; aborting sweep"
        return 1
    fi
    return 0
}

for i in $(seq 1 "$MAX_ITERS"); do
    [ -f "$MARKER" ] && exit 0
    if probe; then
        log "TPU CLAIMABLE (probe $i) — starting benchmark sweep"
        run_config rb256x64 1500 || continue
        run_config kdv1024 900 || continue
        run_config shear512 1500 || continue
        run_config sw_ell255 2400 || continue
        if [ ! -f benchmarks/.auto_bench_done_accuracy ]; then
            log "running tpu_accuracy (timeout 900s)"
            timeout -k 10 900 setsid python benchmarks/tpu_accuracy.py \
                >> "$LOG" 2>&1 && touch benchmarks/.auto_bench_done_accuracy
            probe || continue
        fi
        run_config rotconv32 2400 || continue
        run_config rb2048x1024 3600 || continue
        log "sweep complete"
        touch "$MARKER"
        exit 0
    else
        log "probe $i: unavailable"
    fi
    sleep 60
done
