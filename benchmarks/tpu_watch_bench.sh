#!/bin/bash
# TPU watcher: probe the chip (each cycle is ~60s sleep + up to 90s
# probe, so ~2.5 min while unavailable); the moment it becomes claimable,
# run the BASELINE progression benchmarks PRIZE-FIRST (rb2048x1024
# north-star, then sw_ell255, then rotconv32 — the three unproven
# configs — before refreshing the already-proven small ones). One
# hard-timeout, process-group-killed subprocess per config — round 2's
# wedge was a leaked chip-holding child. Records go to
# benchmarks/results.jsonl. The sweep-complete marker is only written
# when EVERY config has its own done marker, so a timed-out prize config
# is retried on the next claimable window. MAX_ITERS=600 ≈ 25h ceiling.
cd "$(dirname "$0")/.." || exit 1
LOG=benchmarks/auto_bench.log
MARKER=benchmarks/.auto_bench_done
MAX_ITERS=${MAX_ITERS:-600}

log() { echo "$(date +%H:%M:%S) $*" >> "$LOG"; }

probe() {
    timeout -k 5 90 setsid python -c \
        "import jax; d=jax.devices(); print('PROBE_OK', jax.default_backend(), len(d))" \
        2>/dev/null | grep -q PROBE_OK
}

ALL_NAMES="rb2048x1024 sw_ell255 sw_ell255_dense sw_profile rotconv32 rb256x64 kdv1024 shear512 accuracy rb3d_128"

all_done() {
    for n in $ALL_NAMES; do
        [ -f "benchmarks/.auto_bench_done_$n" ] || return 1
    done
    return 0
}

run_script() {
    name=$1; tmo=$2; shift 2
    # per-config marker: a sweep resumed after a mid-sweep chip loss must
    # not burn the window re-measuring (and re-recording) finished configs
    done_marker="benchmarks/.auto_bench_done_$name"
    if [ -f "$done_marker" ]; then
        log "skipping $name (already recorded)"
        return 0
    fi
    log "running $name (timeout ${tmo}s)"
    timeout -k 10 "$tmo" setsid "$@" >> "$LOG" 2>&1
    rc=$?
    log "$name finished rc=$rc"
    [ "$rc" -eq 0 ] && touch "$done_marker"
    # verify the chip survived (a wedged chip fails this and we stop
    # burning the window on configs that can only error)
    if ! probe; then
        log "chip unresponsive after $name; aborting sweep"
        return 1
    fi
    return 0
}

run_config() {
    run_script "$1" "$2" python benchmarks/progression.py "$1"
}

for i in $(seq 1 "$MAX_ITERS"); do
    [ -f "$MARKER" ] && exit 0
    if probe; then
        log "TPU CLAIMABLE (probe $i) — starting PRIZE-FIRST benchmark sweep"
        # --- the three unproven configs (VERDICT round-4 items 1, 2, 4) ---
        run_config rb2048x1024 4500 || continue
        run_config sw_ell255 2400 || continue
        run_config sw_ell255_dense 2400 || continue
        run_script sw_profile 1200 python benchmarks/profile_sw.py || continue
        run_config rotconv32 2400 || continue
        # --- refresh the proven configs with this-round timestamps ---
        run_config rb256x64 1500 || continue
        run_config kdv1024 900 || continue
        run_config shear512 1500 || continue
        run_script accuracy 1200 python benchmarks/tpu_accuracy.py || continue
        run_config rb3d_128 2400 || continue
        if all_done; then
            log "sweep complete (all configs recorded)"
            touch "$MARKER"
            exit 0
        fi
        log "sweep pass finished with unrecorded configs; will retry on next window"
    else
        log "probe $i: unavailable"
    fi
    sleep 60
done
