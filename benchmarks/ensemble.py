"""
Ensemble benchmark: fleet (vmapped + mesh-sharded) stepping vs N x serial.

Measures what core/ensemble.EnsembleSolver actually buys on the virtual
CPU mesh: member-steps per second for a fleet of N independent IVPs
advanced as ONE compiled, scanned program, against the strongest honest
serial baseline — a single already-built, already-compiled solver driven
through the same `step_many` scanned blocks (so the baseline amortizes
its own Python loop; the fleet win is batching, not a strawman).

Two problems:

  diffusion64_ensemble   1-D forced heat equation (64 modes) — the
                         dispatch-bound regime where per-member overhead
                         dominates; the acceptance bar (>= 4x at N=64)
                         is checked here.
  rb256x64_ensemble      the 2-D Rayleigh-Benard flagship (RK222) — the
                         compute-bound regime; the sweep records where
                         batching stops paying on 2 host cores.

For each N in the sweep the row records a per-phase breakdown:
  build_sec    template solver build (paid ONCE per fleet)
  init_sec     per-member IC/parameter installation + device_put
  compile_sec  first fleet dispatch (trace + XLA compile)
  loop_sec     measured stepping window (post-warmup)
plus ensemble_steps_per_sec, the serial baseline, and the speedup.

Appends one row per problem to benchmarks/results.jsonl and exits
nonzero when the diffusion N=64 speedup misses the 4x acceptance bar.

Run: python benchmarks/ensemble.py [--quick]
  --quick   trims the sweep to {1, 8} and shortens windows (CI smoke).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The virtual member mesh must exist before jax initializes (conftest.py
# does the same for the test suite); only forced when the backend is CPU
# and the caller has not already configured a device count.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

T0 = time.time()


def mark(msg):
    print(f"[ensemble {time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def build_diffusion(size=64):
    """1-D forced heat IVP with a per-member parameter field `a` (an RHS
    extra operand, so the sweep exercises batched NCC/parameter data,
    not just batched ICs)."""
    import dedalus_tpu.public as d3
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=np.float64)
    xb = d3.RealFourier(xc, size=size, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    problem = d3.IVP([u], namespace={"u": u, "a": a, "lap": d3.lap})
    problem.add_equation("dt(u) - lap(u) = a*u")
    solver = problem.build_solver(d3.SBDF2, warmup_iterations=2,
                                  enforce_real_cadence=0)
    x = dist.local_grid(xb)

    def member_init(i):
        u["g"] = np.sin((1 + i % 4) * x)
        a["g"] = 0.1 * (1 + i % 7) * np.cos(x)

    return solver, member_init


def build_rb():
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(256, 64, np.float64)
    solver.warmup_iterations = 2

    def member_init(i):
        b.fill_random("g", seed=100 + i, distribution="normal", scale=1e-3)
        b["g"] += (1.0 - b.dist.local_grids(*b.domain.bases)[1])

    return solver, member_init


def measure_serial(builder, dt, block, blocks):
    """Post-warmup steps/s of ONE solver through scanned `step_many`
    blocks — the per-member rate a user pays running the fleet serially
    (x N for the fleet-equivalent wall time)."""
    import jax
    t0 = time.perf_counter()
    solver, member_init = builder()
    build_sec = time.perf_counter() - t0
    member_init(0)
    t0 = time.perf_counter()
    solver.step_many(block, dt)           # trace + compile
    jax.block_until_ready(solver.X)
    compile_sec = time.perf_counter() - t0
    solver.step_many(block, dt)           # warm
    jax.block_until_ready(solver.X)
    t0 = time.perf_counter()
    for _ in range(blocks):
        solver.step_many(block, dt)
    jax.block_until_ready(solver.X)
    loop_sec = time.perf_counter() - t0
    steps = block * blocks
    return {
        "build_sec": round(build_sec, 4),
        "compile_sec": round(compile_sec, 4),
        "loop_sec": round(loop_sec, 4),
        "steps": steps,
        "steps_per_sec": round(steps / loop_sec, 2),
        "finite": bool(np.isfinite(np.asarray(solver.X)).all()),
    }


def measure_fleet(builder, N, dt, block, blocks, warm=True):
    """Post-warmup ensemble-steps/s (member-steps per wall second) of an
    N-member fleet on the auto mesh, with the per-phase breakdown.
    `warm=False` skips the extra post-compile warm block (the
    compute-bound RB fleet, where one block is minutes of wall time and
    the compile dispatch already warmed the program)."""
    import jax
    t0 = time.perf_counter()
    solver, member_init = builder()
    build_sec = time.perf_counter() - t0
    t0 = time.perf_counter()
    ens = solver.ensemble(N, mesh="auto")
    ens.init_members(member_init)
    init_sec = time.perf_counter() - t0
    t0 = time.perf_counter()
    ens.step_many(block, dt)              # trace + compile
    jax.block_until_ready(ens.X)
    compile_sec = time.perf_counter() - t0
    if warm:
        ens.step_many(block, dt)
        jax.block_until_ready(ens.X)
    t0 = time.perf_counter()
    for _ in range(blocks):
        ens.step_many(block)
    jax.block_until_ready(ens.X)
    loop_sec = time.perf_counter() - t0
    member_steps = N * block * blocks
    return {
        "members": N,
        "devices": ens.mesh.shape["batch"] if ens.mesh is not None else 1,
        "build_sec": round(build_sec, 4),
        "init_sec": round(init_sec, 4),
        "compile_sec": round(compile_sec, 4),
        "loop_sec": round(loop_sec, 4),
        "member_steps": member_steps,
        "ensemble_steps_per_sec": round(member_steps / loop_sec, 2),
        "finite": bool(np.isfinite(np.asarray(ens.X)).all()),
        # template solver's resolved plan == the whole fleet's (the
        # members share one compiled program); hoisted to the row by
        # run_problem
        "plan": solver.plan_provenance(),
    }


def run_problem(config, builder, dt, block, blocks, sweep, append,
                warm=True):
    mark(f"{config}: serial baseline ({block}-step blocks x {blocks})")
    serial = measure_serial(builder, dt, block, blocks)
    mark(f"{config}: serial {serial['steps_per_sec']} steps/s")
    row = {
        "config": config,
        "backend": os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0],
        "dt": dt,
        "block": block,
        "blocks": blocks,
        "serial": serial,
        "sweep": [],
    }
    for N in sweep:
        fleet = measure_fleet(builder, N, dt, block, blocks, warm=warm)
        row["plan"] = fleet.pop("plan")
        fleet["speedup_vs_serial"] = round(
            fleet["ensemble_steps_per_sec"] / serial["steps_per_sec"], 2)
        # setup amortization: one build+compile for the fleet vs N of them
        serial_setup = N * (serial["build_sec"] + serial["compile_sec"])
        fleet_setup = (fleet["build_sec"] + fleet["init_sec"]
                       + fleet["compile_sec"])
        fleet["setup_amortization"] = round(serial_setup / fleet_setup, 2) \
            if fleet_setup else None
        row["sweep"].append(fleet)
        mark(f"{config}: N={N} -> {fleet['ensemble_steps_per_sec']} "
             f"member-steps/s ({fleet['speedup_vs_serial']}x serial, "
             f"compile {fleet['compile_sec']}s)")
    n64 = next((f for f in row["sweep"] if f["members"] == 64), None)
    if n64 is not None:
        row["speedup_n64"] = n64["speedup_vs_serial"]
        row["meets_4x_n64"] = n64["speedup_vs_serial"] >= 4.0
    append(row)
    return row


def main():
    quick = "--quick" in sys.argv
    from __graft_entry__ import _append_result
    if quick:
        # smoke mode: no N=64 point, so nothing is appended to the
        # machine record (a quick row would shadow the full sweep in
        # bench.py's _attach_ensemble)
        _append_result = lambda record: None  # noqa: E731
    sweep = [1, 8] if quick else [1, 8, 64, 256]
    rows = [run_problem(
        "diffusion64_ensemble", build_diffusion, 1e-3,
        block=8 if quick else 32, blocks=2 if quick else 16,
        sweep=sweep, append=_append_result)]
    # RB: compute-bound on the host cores (a member-step is seconds of
    # wall time), so single-step blocks, a one-block measured window, and
    # no extra warm block; the sweep is still the full N list — nothing
    # silently dropped, the row just records a short window
    rows.append(run_problem(
        "rb256x64_ensemble", build_rb, 0.01,
        block=1, blocks=1, sweep=[1, 8] if quick else sweep,
        append=_append_result, warm=False))
    diffusion = rows[0]
    ok = quick or (diffusion.get("speedup_n64") or 0) >= 4.0
    for row in rows:
        print(json.dumps(row), flush=True)
    if not ok:
        mark("FAIL: diffusion N=64 ensemble-steps/s is not >= 4x serial")
        sys.exit(1)


if __name__ == "__main__":
    main()
