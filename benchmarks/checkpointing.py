"""
Checkpointing benchmark: per-checkpoint STEP-LOOP STALL by format
(synchronous HDF5 vs synchronous sharded vs asynchronous sharded) and
restore-after-fault wall time, on the RB 256x64 flagship (CPU).

The number that matters is the stall: the wall time one durable
checkpoint write holds the step loop. The synchronous HDF5 path gathers
the full state to host and blocks until h5py flushes; the synchronous
sharded path (tools/dcheckpoint.py) writes per-shard npy files with
checksums and a manifest-last commit (still blocking, but no handler/
transform machinery in the way); the ASYNC sharded path submits
immutable device references to a background writer and returns — the
acceptance bar is a >= 5x stall reduction async-sharded vs sync-HDF5,
with durability verified (everything submitted restores bit-identically
after a drain) so the speedup cannot come from dropped writes.

Restore-after-fault: the newest sharded checkpoint is silently
corrupted (chaos.corrupt_shard — post-commit byte damage the checksums
must catch) and the restore walks back to the previous manifest; the
measured wall is detection + quarantine + fallback + load.

Methodology: one solver, warmed past compile; per mode, N_CHECKPOINTS
writes interleaved with STEPS_BETWEEN steps (the loop keeps stepping
between writes, so async writers genuinely overlap IO with compute);
the recorded stall is the MEDIAN over writes of the wall time the
checkpoint call held the loop. Appends one `rb256x64_checkpoint` row to
benchmarks/results.jsonl and exits nonzero when the 5x bar is missed or
a durability/bit-identity check fails.

Run: python benchmarks/checkpointing.py [--quick]
  --quick   64x32 grid, fewer writes, no row appended (CI smoke).
"""

import argparse
import json
import os
import pathlib
import shutil
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

T0 = time.time()
RESULTS = pathlib.Path(__file__).parent / "results.jsonl"
N_CHECKPOINTS = 5
STEPS_BETWEEN = 3
DT = 0.01
ACCEPTANCE_X = 5.0


def mark(msg):
    print(f"[checkpointing {time.time() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


def build_solver(nx, nz):
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, b = build_rb_solver(nx, nz, np.float64, matsolver="banded")
    solver.stop_iteration = 10 ** 9
    for _ in range(3):          # past compile + warmup accounting
        solver.step(DT)
    return solver


def measure_mode(solver, workdir, fmt, async_write):
    """Median per-checkpoint stall for one (format, async) mode, stepping
    STEPS_BETWEEN steps between writes. Returns (median_stall, loop,
    host_X_at_last_write)."""
    from dedalus_tpu.tools.resilience import ResilientLoop
    loop = ResilientLoop(solver, dt=DT, checkpoint_dir=workdir,
                         checkpoint_format=fmt, checkpoint_async=async_write,
                         checkpoint_inflight=2, checkpoint_keep=N_CHECKPOINTS + 1,
                         install_signal_handlers=False,
                         flush_telemetry=False)
    stalls = []
    X_last = None
    for _ in range(N_CHECKPOINTS):
        for _ in range(STEPS_BETWEEN):
            solver.step(DT)
        t0 = time.perf_counter()
        loop.write_checkpoint()
        stalls.append(time.perf_counter() - t0)
        X_last = np.asarray(solver.X)
    if loop._checkpointer is not None:
        errors = loop._checkpointer.close()
        if errors:
            raise RuntimeError(f"async writer errors: {errors}")
    return statistics.median(stalls), loop, X_last


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="64x32 smoke run, no results row")
    args = parser.parse_args()
    nx, nz = (64, 32) if args.quick else (256, 64)
    config = "rb256x64_checkpoint" if not args.quick \
        else "rb64x32_checkpoint_quick"

    import jax
    from dedalus_tpu.tools import chaos as chaos_mod
    from dedalus_tpu.tools import dcheckpoint as dc

    work = pathlib.Path(__file__).parent / "_checkpoint_bench"
    shutil.rmtree(work, ignore_errors=True)
    work.mkdir(parents=True)
    mark(f"building RB {nx}x{nz} (banded, f64, CPU)")
    solver = build_solver(nx, nz)
    G, S = solver.pencil_shape
    mark(f"solver ready: pencil {G}x{S}")

    errors = []
    row = {
        "config": config,
        "ts": round(time.time(), 1),
        "backend": jax.default_backend(),
        "dtype": "float64",
        "nx": nx, "nz": nz,
        "checkpoints": N_CHECKPOINTS,
        "steps_between": STEPS_BETWEEN,
        "plan": solver.plan_provenance(),
        "finite": True,
    }

    # ---- 1. synchronous HDF5 (the PR-4 baseline)
    stall_hdf5, _, _ = measure_mode(solver, work / "hdf5", "hdf5", False)
    row["stall_sync_hdf5_sec"] = round(stall_hdf5, 6)
    mark(f"sync hdf5 stall: {stall_hdf5:.4f}s/checkpoint")

    # ---- 2. synchronous sharded
    stall_sharded, _, _ = measure_mode(solver, work / "sharded", "sharded",
                                       False)
    row["stall_sync_sharded_sec"] = round(stall_sharded, 6)
    mark(f"sync sharded stall: {stall_sharded:.4f}s/checkpoint")

    # ---- 3. asynchronous sharded, durability verified
    stall_async, loop, X_last = measure_mode(solver, work / "async",
                                             "sharded", True)
    row["stall_async_sharded_sec"] = round(stall_async, 6)
    event = dc.restore_latest(work / "async")
    durable = np.array_equal(event["arrays"]["X"], X_last)
    row["async_durable_bit_identical"] = bool(durable)
    if not durable:
        errors.append("async-written checkpoint does not bit-match the "
                      "state at its write")
    reduction = stall_hdf5 / stall_async if stall_async > 0 else float("inf")
    row["stall_reduction_async_vs_hdf5"] = round(reduction, 1)
    mark(f"async sharded stall: {stall_async:.4f}s/checkpoint "
         f"({reduction:.1f}x less than sync hdf5), durable+bit-identical="
         f"{durable}")
    if reduction < ACCEPTANCE_X:
        errors.append(f"async stall reduction {reduction:.1f}x under the "
                      f"{ACCEPTANCE_X}x acceptance bar")

    # ---- 4. restore-after-fault: silently corrupt the newest, time the
    #         checksum detection + quarantine + fallback + load
    prev = dc.list_checkpoints(work / "async")[-2]
    prev_arrays, _ = dc.load_checkpoint(prev)
    chaos_mod.corrupt_shard(dc.list_checkpoints(work / "async")[-1],
                            mode="garbage")
    t0 = time.perf_counter()
    event = dc.restore_latest(work / "async")
    restore_wall = time.perf_counter() - t0
    row["restore_after_fault_sec"] = round(restore_wall, 6)
    ok = (len(event["fallbacks"]) == 1
          and np.array_equal(event["arrays"]["X"], prev_arrays["X"]))
    row["restore_after_fault_bit_identical"] = bool(ok)
    if not ok:
        errors.append("restore-after-fault did not recover the previous "
                      "checkpoint bit-identically")
    mark(f"restore-after-fault: {restore_wall:.4f}s "
         f"(fallback to previous manifest, bit-identical={ok})")

    if errors:
        row["finite"] = False
        row["error"] = "; ".join(errors)
    shutil.rmtree(work, ignore_errors=True)

    if args.quick:
        mark("quick mode: no results row appended")
    else:
        with open(RESULTS, "a") as f:
            f.write(json.dumps(row) + "\n")
        mark(f"row appended to {RESULTS}")
    print(json.dumps(row, indent=2))
    if errors:
        for err in errors:
            mark(f"FAILED: {err}")
        sys.exit(1)


if __name__ == "__main__":
    main()
