"""
On-chip accuracy + emulated-f64 sweep, appended to benchmarks/results.jsonl.

Three tiers on the accelerator (reference is f64 end-to-end; BENCHMARKS.md
dtype policy; VERDICT round-4 item 3):
  1. native bench dtype (f32 on TPU): heat-decay error vs exact —
     demonstrates the spectral-convergence floor at the bench precision;
  2. native f64 (XLA:TPU software f64, where supported): same check;
  3. emulated f64 (double-double pair path, core/ddstep.DDIVPRunner):
     heat error + KdV mass drift at f64 grade, plus the measured
     slowdown factor vs the f32 path on the same problem.

Run: python benchmarks/tpu_accuracy.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

T0 = time.time()


def mark(msg):
    print(f"[acc {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def build_heat(N, dtype, scheme=None):
    import dedalus_tpu.public as d3
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=dtype)
    xb = d3.RealFourier(xcoord, size=N, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - dx(dx(u)) = 0")
    solver = problem.build_solver(scheme or d3.RK443)
    x = dist.local_grids(xb)[0]
    u["g"] = np.sin(3 * x) + 0.5 * np.cos(5 * x)
    return solver, u, x


def heat_exact(x, t):
    return (np.exp(-9 * t) * np.sin(3 * x)
            + 0.5 * np.exp(-25 * t) * np.cos(5 * x))


def heat_error(N, dtype, steps=200):
    import dedalus_tpu.public as d3  # noqa: F401
    solver, u, x = build_heat(N, dtype)
    dt = 1e-4
    solver.step_many(steps, dt)
    return float(np.abs(np.asarray(u["g"]) - heat_exact(x, steps * dt)).max())


def build_kdv(N, dtype):
    import dedalus_tpu.public as d3
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=dtype)
    xbasis = d3.RealFourier(xcoord, size=N, bounds=(0, 10), dealias=3 / 2)
    u = dist.Field(name="u", bases=xbasis)
    a, b = 1e-4, 2e-4
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - a*dx(dx(u)) - b*dx(dx(dx(u))) = - u*dx(u)")
    x = dist.local_grids(xbasis)[0]
    n = 20
    u["g"] = np.log(1 + np.cosh(n) ** 2 / np.cosh(n * (x - 3)) ** 2) / (2 * n)
    solver = problem.build_solver(d3.SBDF2)
    return solver, u


def dd_sweep(record):
    """Emulated-f64 (double-double) on-accelerator checks + timing."""
    import dedalus_tpu.public as d3
    from dedalus_tpu.core.ddstep import DDIVPRunner, maybe_dd_runner
    from dedalus_tpu.tools.config import config
    old = config["linear algebra"].get("MATRIX_SOLVER", "auto")
    config["linear algebra"]["MATRIX_SOLVER"] = "dense"
    try:
        # heat, MATCHED SCHEME: the dd trajectory against the native-f64
        # trajectory of the SAME scheme at the SAME dt. This isolates the
        # emulated-f64 arithmetic (target ~1e-10, like
        # tests/test_ddstep.py:77); the old `dd_heat_err_N64` number was
        # dd-vs-EXACT, i.e. dominated by the SBDF2 time-discretization
        # error (~4e-6), and is kept under its honest name
        # `dd_heat_timedisc_err_N64` as a sanity floor.
        N, dt, steps = 64, 1e-3, 200
        ref_solver, ref_u, ref_x = build_heat(N, np.float64, scheme=d3.SBDF2)
        for _ in range(steps):
            ref_solver.step(dt)
        X64 = np.asarray(ref_solver.X, dtype=np.float64)
        solver, u, x = build_heat(N, np.float64, scheme=d3.SBDF2)
        runner = maybe_dd_runner(solver) or DDIVPRunner(solver)
        runner.sync_state()   # ICs were set after build_solver
        for _ in range(steps):
            runner.step(dt)
        Xdd = runner.state_f64()
        scale = max(float(np.abs(X64).max()), 1e-300)
        record["dd_vs_f64_heat_N64"] = \
            float(np.abs(Xdd - X64).max()) / scale
        runner.push_state()
        err = float(np.abs(np.asarray(u["g"], np.float64)
                           - heat_exact(x, steps * dt)).max())
        record["dd_heat_timedisc_err_N64"] = err
        mark(f"dd heat N=64: dd-vs-f64 {record['dd_vs_f64_heat_N64']:.3e} "
             f"(matched SBDF2 dt={dt}); vs exact {err:.3e} "
             f"(time-discretization floor)")

        # KdV: mass conservation at f64 grade + dd-vs-f32 step cost
        N = 256
        solver64, u64 = build_kdv(N, np.float64)
        runner = maybe_dd_runner(solver64) or DDIVPRunner(solver64)
        runner.sync_state()   # ICs were set after build_solver
        mass0 = float(np.mean(u64["g"]))
        n_steps = 200
        runner.step(5e-4)            # compile (factor + step program)
        runner.step(5e-4)            # order-2 factor (ramp) before timing
        runner.step_many(n_steps, 5e-4)   # block compile
        import jax as _jax
        _jax.block_until_ready(runner.X.hi)
        t0 = time.time()
        runner.step_many(n_steps, 5e-4)
        _jax.block_until_ready(runner.X.hi)
        dd_sps = n_steps / (time.time() - t0)
        runner.push_state()
        mass1 = float(np.mean(u64["g"]))
        record["dd_kdv_mass_drift"] = abs(mass1 - mass0) / abs(mass0)
        record["dd_kdv_steps_per_sec"] = round(dd_sps, 2)
        mark(f"dd KdV mass drift {record['dd_kdv_mass_drift']:.3e}, "
             f"{dd_sps:.1f} steps/s")

        # f32 reference cost on the same problem/scheme, measured as the
        # same scan-block dispatch the dd runner uses (ramp consumed
        # BEFORE the warm-up block so the timed block's scan length
        # matches and no compile lands inside the timing window)
        solver32, _ = build_kdv(N, np.float32)
        solver32.step(5e-4)
        solver32.step(5e-4)
        solver32.step_many(n_steps, 5e-4)   # block compile
        solver32.X.block_until_ready()
        t0 = time.time()
        solver32.step_many(n_steps, 5e-4)
        solver32.X.block_until_ready()
        f32_sps = n_steps / (time.time() - t0)
        record["f32_kdv_steps_per_sec"] = round(f32_sps, 2)
        record["dd_slowdown_vs_f32"] = round(f32_sps / dd_sps, 2)
        mark(f"f32 KdV {f32_sps:.1f} steps/s -> dd slowdown "
             f"{record['dd_slowdown_vs_f32']}x")

        # flagship 2-D problem through the dd path (vector fields, taus,
        # LHS NCCs, DotProduct RHS, RK222)
        from dedalus_tpu.extras.bench_problems import build_rb_solver
        rb_solver, _b = build_rb_solver(64, 16, np.float64)
        rb_runner = maybe_dd_runner(rb_solver) or DDIVPRunner(rb_solver)
        rb_runner.sync_state()
        rb_runner.step(1e-3)
        rb_runner.step(1e-3)
        rb_steps = 50
        rb_runner.step_many(rb_steps, 1e-3)   # block compile
        import jax as _jax2
        _jax2.block_until_ready(rb_runner.X.hi)
        t0 = time.time()
        rb_runner.step_many(rb_steps, 1e-3)
        _jax2.block_until_ready(rb_runner.X.hi)
        record["dd_rb64_steps_per_sec"] = round(
            rb_steps / (time.time() - t0), 2)
        rb_finite = bool(np.all(np.isfinite(rb_runner.state_f64())))
        record["dd_rb64_finite"] = rb_finite
        mark(f"dd RB 64x16 {record['dd_rb64_steps_per_sec']} steps/s, "
             f"finite={rb_finite}")
    except Exception as exc:
        record["dd_error"] = repr(exc)[:300]
        mark(f"dd sweep failed: {exc!r}")
    finally:
        config["linear algebra"]["MATRIX_SOLVER"] = old


def main():
    import jax
    backend = jax.default_backend()
    dtype = np.float32 if backend != "cpu" else np.float64
    mark(f"backend={backend} dtype={np.dtype(dtype).name}")
    errs = {}
    for N in (32, 64, 128):
        errs[N] = heat_error(N, dtype)
        mark(f"N={N}: max err {errs[N]:.3e}")
    from __graft_entry__ import _append_result
    record = {
        "case": "tpu_heat_exact",
        "backend": backend,
        "dtype": np.dtype(dtype).name,
        **{f"err_N{N}": e for N, e in errs.items()},
    }
    if backend != "cpu":
        # native f64 on the accelerator (XLA software f64), where supported
        try:
            e64 = heat_error(64, np.float64)
            record["err_N64_f64_onchip"] = e64
            mark(f"native f64 on-chip N=64: max err {e64:.3e}")
        except Exception as exc:
            record["f64_onchip_error"] = repr(exc)[:200]
            mark(f"native f64 on-chip unsupported: {exc!r}")
    # emulated f64 (double-double): runs on every backend; on TPU this is
    # the dtype=np.float64 fast path (core/ddstep.maybe_dd_runner)
    dd_sweep(record)
    record["ts"] = round(time.time(), 1)
    _append_result(record)
    print(record)
    # resolution-independent floor: spectral convergence bottoms out at
    # the dtype roundoff, not a power law
    assert errs[128] < (2e-5 if dtype == np.float32 else 1e-8), errs
    # dd path must deliver f64-grade results wherever it ran: the
    # matched-scheme comparison isolates the arithmetic (f64-grade
    # agreement, far below the f32 floor of ~1e-7)
    if "dd_vs_f64_heat_N64" in record:
        assert record["dd_vs_f64_heat_N64"] < 1e-9, record
    if "dd_heat_timedisc_err_N64" in record:
        assert record["dd_heat_timedisc_err_N64"] < 1e-5, record
    if "dd_kdv_mass_drift" in record:
        assert record["dd_kdv_mass_drift"] < 1e-10, record
    # dd_error on an accelerator is recorded as a diagnostic (the sweep
    # must not retry a persistent backend limitation forever); on CPU it
    # is a regression and fails loudly
    if backend == "cpu":
        assert "dd_error" not in record, record


if __name__ == "__main__":
    main()
