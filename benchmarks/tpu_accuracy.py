"""
On-chip accuracy check: heat-equation decay vs the exact solution at the
bench dtype (f32 on TPU), appended to benchmarks/results.jsonl. Pairs
with benchmarks/accuracy_f32.py (which prices f32 vs f64 on CPU): this
script demonstrates the spectral-convergence floor ON the accelerator
itself (reference: f64 end-to-end; BENCHMARKS.md dtype policy).

Run: python benchmarks/tpu_accuracy.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

T0 = time.time()


def mark(msg):
    print(f"[acc {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def heat_error(N, dtype, steps=200):
    import dedalus_tpu.public as d3
    xcoord = d3.Coordinate("x")
    dist = d3.Distributor(xcoord, dtype=dtype)
    xb = d3.RealFourier(xcoord, size=N, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    dx = lambda A: d3.Differentiate(A, xcoord)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - dx(dx(u)) = 0")
    solver = problem.build_solver(d3.RK443)
    x = dist.local_grids(xb)[0]
    u["g"] = np.sin(3 * x) + 0.5 * np.cos(5 * x)
    dt = 1e-4
    solver.step_many(steps, dt)
    t = steps * dt
    exact = (np.exp(-9 * t) * np.sin(3 * x)
             + 0.5 * np.exp(-25 * t) * np.cos(5 * x))
    return float(np.abs(np.asarray(u["g"]) - exact).max())


def main():
    import jax
    backend = jax.default_backend()
    dtype = np.float32 if backend != "cpu" else np.float64
    mark(f"backend={backend} dtype={np.dtype(dtype).name}")
    errs = {}
    for N in (32, 64, 128):
        errs[N] = heat_error(N, dtype)
        mark(f"N={N}: max err {errs[N]:.3e}")
    from __graft_entry__ import _append_result
    record = {
        "case": "tpu_heat_exact",
        "backend": backend,
        "dtype": np.dtype(dtype).name,
        **{f"err_N{N}": e for N, e in errs.items()},
    }
    if backend != "cpu":
        # f64-on-accelerator opt-in: the Fourier transforms route through
        # the matrix-MMT path on TPU (no c128), so f64 runs on emulated
        # double-precision matmuls where the backend supports them —
        # demonstrating the reference's f64 spectral-convergence floor
        # on-chip (BENCHMARKS.md dtype policy; reference is f64-native).
        try:
            e64 = heat_error(64, np.float64)
            record["err_N64_f64_onchip"] = e64
            mark(f"f64-on-chip N=64: max err {e64:.3e}")
        except Exception as exc:
            record["f64_onchip_error"] = repr(exc)[:200]
            mark(f"f64-on-chip unsupported: {exc!r}")
    _append_result(record)
    print(record)
    # resolution-independent floor: spectral convergence bottoms out at
    # the dtype roundoff, not a power law
    assert errs[128] < (2e-5 if dtype == np.float32 else 1e-8), errs


if __name__ == "__main__":
    main()
