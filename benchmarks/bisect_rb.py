"""
Compile-time bisection for the RB 2048x1024 step on TPU: times the
compilation of each device program the IMEX step is made of (transforms,
eval_F, matvecs, chunked factor, solve) so a wedged TPU compile can be
attributed to one piece. Usage:

  python benchmarks/bisect_rb.py [fft|evalF|matvec|factor|all] [Nx Nz]
"""
import sys
import time
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

T0 = time.time()


def mark(m):
    print(f"[{time.time()-T0:7.1f}s] {m}", file=sys.stderr, flush=True)


def main():
    mark(f"backend={jax.default_backend()}")
    phase = sys.argv[1] if len(sys.argv) > 1 else "all"
    Nx = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    Nz = int(sys.argv[3]) if len(sys.argv) > 3 else 1024

    if phase == "fft":
        gx, gz = 3 * Nx // 2, 3 * Nz // 2
        for shape, axis in [((gx, gz), 0), ((gx, gz), 1), ((4, gx, gz), 2)]:
            x = jnp.zeros(shape, jnp.float32)
            t = time.time()
            jax.jit(lambda a, ax=axis: jnp.fft.rfft(a, axis=ax)).lower(x).compile()
            mark(f"rfft {shape} axis={axis}: compile {time.time()-t:.1f}s")
        return

    from __graft_entry__ import _build_rb_solver
    mark(f"building solver {Nx}x{Nz} (banded)")
    from dedalus_tpu.tools.config import config
    config["linear algebra"]["MATRIX_SOLVER"] = "banded"
    solver, b = _build_rb_solver(Nx, Nz, np.float32)
    mark(f"built; pencil={solver.pencil_shape} ops={type(solver.ops).__name__} "
         f"q={solver.structure.q} NB={solver.structure.NB} "
         f"t={solver.structure.t_pins}")
    rd = solver.real_dtype
    M, L = solver.M_mat, solver.L_mat
    X0 = solver.gather_fields()
    t0 = jnp.asarray(0.0, dtype=rd)
    dt = jnp.asarray(5e-5, dtype=rd)
    extra = solver.rhs_extra()
    from dedalus_tpu.tools.jitlift import lifted_jit
    ops = solver.ops

    if phase in ("evalF", "all"):
        mark("compiling eval_F alone")
        f = lifted_jit(lambda X, t, e: solver.eval_F(X, t, e))
        t = time.time()
        y = f(X0, t0, extra)
        y.block_until_ready()
        mark(f"eval_F compile+run {time.time()-t:.1f}s")

    if phase in ("matvec", "all"):
        mark("compiling matvecs")
        f = lifted_jit(lambda M, L, X: (ops.matvec(M, X), ops.matvec(L, X)))
        t = time.time()
        y = f(M, L, X0)
        y[0].block_until_ready()
        mark(f"matvec compile+run {time.time()-t:.1f}s")

    if phase in ("factor", "all"):
        mark("compiling chunked factor")
        ffac = lifted_jit(lambda M, L, dt: ops.factor_lincomb(
            jnp.asarray(1.0, rd), M, dt, L))
        t = time.time()
        aux = ffac(M, L, dt)
        jax.tree.leaves(aux)[0].block_until_ready()
        mark(f"factor compile+run {time.time()-t:.1f}s; chunks={ops._g_chunks}")

        mark("compiling solve")
        fs = lifted_jit(lambda aux, rhs, M, L: ops.solve(aux, rhs, mats=(M, L)))
        t = time.time()
        x = fs(aux, X0, M, L)
        x.block_until_ready()
        mark(f"solve compile+run {time.time()-t:.1f}s")

    mark("done")


if __name__ == "__main__":
    main()
