"""
Cold-start benchmark: RB 256x64 solver build time, cold vs warm caches.

Measures what the assembly cache (tools/assembly_cache.py) + persistent
XLA compile cache actually buy on CPU, in the three regimes that matter:

  cold                fresh process, EMPTY assembly + XLA cache dirs
  warm_same_process   second build inside the cold process
  warm_fresh_process  new process against the now-populated caches
                      (median of N runs; this box is noisy)

Each build is timed from entering the builder to the solver being ready
(the same window progression.py records as `build_sec`), with the
backend pre-warmed by a trivial dispatch first so jax runtime init is
not billed to the solver. The per-phase split
(host_assembly/structure/factor/compile, tools/metrics.BuildPhases)
rides along, `compile_sec` from a first `step()` timed separately.

Appends rows {"config": "rb256x64_coldstart", ...} to
benchmarks/results.jsonl and exits nonzero when the warm same-process
build fails the >= 3x target (the machine-checked acceptance bar).

Run: python benchmarks/coldstart.py [--keep-caches]
"""

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NX, NZ = 256, 64
FRESH_RUNS = 3
T0 = time.time()


def mark(msg):
    print(f"[cold {time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def _child():
    """One measured process: build (+ optional same-process rebuild and
    first-step compile) and print a JSON record on stdout."""
    import numpy as np
    import jax
    import dedalus_tpu.public  # noqa: F401  (configures caches from cfg)
    xla_dir = os.environ.get("COLDSTART_XLA_DIR")
    if xla_dir:
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    from dedalus_tpu.tools.config import config
    config["linear algebra"]["MATRIX_SOLVER"] = os.environ.get(
        "COLDSTART_MATSOLVER", "banded")
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    import jax.numpy as jnp
    # backend/runtime warmup: jax init is not solver cold-start
    jax.block_until_ready(jnp.zeros((8, 8)) @ jnp.zeros((8, 8)))

    def one_build():
        t0 = time.perf_counter()
        solver, b = build_rb_solver(NX, NZ, np.float64)
        return solver, time.perf_counter() - t0

    solver, build_sec = one_build()
    out = {
        "build_sec": round(build_sec, 4),
        "build_phases": solver.build_phases.record(),
        "ops": type(solver.ops).__name__,
        "pencil_shape": list(solver.pencil_shape),
    }
    if os.environ.get("COLDSTART_REBUILD"):
        solver2, warm_sec = one_build()
        out["build_sec_warm_same_process"] = round(warm_sec, 4)
        out["build_phases_warm_same_process"] = \
            solver2.build_phases.record()
        # solver2 never steps, so its compile phase is unmeasured — null,
        # not a measured zero
        out["build_phases_warm_same_process"]["compile_sec"] = None
    if os.environ.get("COLDSTART_STEP"):
        solver.step(0.01)
        jax.block_until_ready(solver.X)
        out["build_phases"] = solver.build_phases.record()  # + compile_sec
        out["finite"] = bool(np.isfinite(np.asarray(solver.X)).all())
    else:
        out["build_phases"]["compile_sec"] = None
    print(json.dumps(out), flush=True)


def _run_child(env, tag, timeout=1200):
    mark(f"running {tag} child")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, stdout=subprocess.PIPE, text=True, timeout=timeout)
    line = next((ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(f"{tag} child failed (rc={proc.returncode})")
    rec = json.loads(line)
    mark(f"{tag}: build {rec['build_sec']}s "
         f"(cache={rec['build_phases'].get('assembly_cache')})")
    return rec


def main():
    if "--child" in sys.argv:
        _child()
        return
    from __graft_entry__ import _append_result

    keep = "--keep-caches" in sys.argv
    tmp = tempfile.mkdtemp(prefix="dedalus_coldstart_")
    asm_dir = os.path.join(tmp, "assembly")
    xla_dir = os.path.join(tmp, "xla")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["DEDALUS_TPU_ASSEMBLY_CACHE"] = asm_dir
    env["COLDSTART_XLA_DIR"] = xla_dir
    env["COLDSTART_REBUILD"] = "1"
    env["COLDSTART_STEP"] = "1"

    mark(f"cold run (empty caches under {tmp})")
    cold = _run_child(env, "cold")

    env.pop("COLDSTART_REBUILD")
    env.pop("COLDSTART_STEP")
    warm_fresh = []
    for i in range(FRESH_RUNS):
        warm_fresh.append(_run_child(env, f"warm-fresh-{i + 1}"))
    warm_fresh_sec = statistics.median(
        r["build_sec"] for r in warm_fresh)
    warm_rec = min(warm_fresh, key=lambda r: abs(
        r["build_sec"] - warm_fresh_sec))

    cold_sec = cold["build_sec"]
    warm_same_sec = cold["build_sec_warm_same_process"]
    record = {
        "config": f"rb{NX}x{NZ}_coldstart",
        "backend": env.get("JAX_PLATFORMS", "cpu"),
        "matsolver": env.get("COLDSTART_MATSOLVER", "banded"),
        "build_sec_cold": cold_sec,
        "build_phases_cold": cold["build_phases"],
        "build_sec_warm_same_process": warm_same_sec,
        "build_phases_warm_same_process":
            cold["build_phases_warm_same_process"],
        "build_sec_warm_fresh_process": warm_fresh_sec,
        "build_phases_warm_fresh_process": warm_rec["build_phases"],
        "warm_fresh_runs": [r["build_sec"] for r in warm_fresh],
        "speedup_same_process": round(cold_sec / warm_same_sec, 2)
        if warm_same_sec else None,
        "speedup_fresh_process": round(cold_sec / warm_fresh_sec, 2)
        if warm_fresh_sec else None,
        "finite": cold.get("finite"),
        "ops": cold.get("ops"),
        "pencil_shape": cold.get("pencil_shape"),
    }
    ok = (record["speedup_same_process"] or 0) >= 3.0
    record["meets_3x_same_process"] = ok
    record["meets_3x_fresh_process"] = \
        (record["speedup_fresh_process"] or 0) >= 3.0
    _append_result(record)
    print(json.dumps(record), flush=True)
    mark(f"speedups: same-process {record['speedup_same_process']}x, "
         f"fresh-process {record['speedup_fresh_process']}x")
    if not keep:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    if not ok:
        mark("FAIL: warm same-process build is not >= 3x faster than cold")
        sys.exit(1)


if __name__ == "__main__":
    main()
