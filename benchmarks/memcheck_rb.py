"""
HBM budget audit for the north-star config (RB 2048x1024, banded path).

Builds the solver on CPU (f32), then accounts every persistent device
buffer (state, histories, M/L band stores, factorization aux) with both
its raw size and its TPU-tiled size ((8, 128) tiling of the two minor
dims — the padding that produced round 2's OOM shapes), and runs
jax.jit(...).lower().compile().memory_analysis() on the factor and step
programs to bound the transient footprint.

Run: JAX_PLATFORMS=cpu python benchmarks/memcheck_rb.py [Nx Nz]
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

T0 = time.time()


def mark(msg):
    print(f"[mem {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def tpu_padded_bytes(shape, itemsize):
    """Bytes under TPU (8, 128) tiling of the two minor dims."""
    if len(shape) == 0:
        return itemsize
    if len(shape) == 1:
        return int(np.ceil(shape[0] / 128)) * 128 * itemsize
    lead = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    sub = int(np.ceil(shape[-2] / 8)) * 8
    lane = int(np.ceil(shape[-1] / 128)) * 128
    return lead * sub * lane * itemsize


def fmt(nbytes):
    return f"{nbytes / 1e9:.3f}G" if nbytes > 1e8 else f"{nbytes / 1e6:.1f}M"


def audit_tree(name, tree, rows):
    total = padded = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if leaf is None or not hasattr(leaf, "shape"):
            continue
        raw = leaf.size * leaf.dtype.itemsize
        pad = tpu_padded_bytes(leaf.shape, leaf.dtype.itemsize)
        total += raw
        padded += pad
        rows.append((f"{name}{jax.tree_util.keystr(path)}", leaf.shape,
                     str(leaf.dtype), raw, pad))
    return total, padded


def main():
    Nx = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    Nz = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    from dedalus_tpu.tools.config import config
    config["linear algebra"]["MATRIX_SOLVER"] = "banded"
    from __graft_entry__ import _build_rb_solver

    mark(f"building RB {Nx}x{Nz} f32 banded on {jax.default_backend()}")
    solver, b = _build_rb_solver(Nx, Nz, np.float32)
    G, S = solver.pencil_shape
    ops = solver.ops
    mark(f"built: pencils (G={G}, S={S}), ops={type(ops).__name__}")
    if hasattr(ops, "q"):
        mark(f"structure: q={ops.q} NB={ops.NB} n_pad={ops.n_pad} "
             f"nd={ops.nd} kl={ops.kl} ku={ops.ku} t={ops.t}")
        mark(f"M dsel={len(solver.M_mat.dsel)} L dsel={len(solver.L_mat.dsel)}")

    rows = []
    audit_tree("X", solver.X, rows)
    audit_tree("M", solver.M_mat, rows)
    audit_tree("L", solver.L_mat, rows)

    # factor once (RK222 path: one dt)
    dt = 5e-5
    ts = solver.timestepper
    mark(f"split={ts._split}; factoring at dt={dt}")
    t1 = time.time()
    ts._ensure_factor(dt)
    jax.block_until_ready(ts._lhs_aux)
    mark(f"factor done in {time.time() - t1:.1f}s (chunks={ops._g_chunks})")
    seen = set()
    for i, aux in enumerate(ts._lhs_aux):
        leaves = jax.tree_util.tree_leaves(aux)
        key = tuple(id(x) for x in leaves)
        if key in seen:
            rows.append((f"aux[{i}] (aliased)", (), "-", 0, 0))
            continue
        seen.add(key)
        audit_tree(f"aux[{i}]", aux, rows)

    print(f"{'buffer':58s} {'shape':>24s} {'dtype':>8s} {'raw':>9s} {'tpu':>9s}")
    tot_raw = tot_pad = 0
    for name, shape, dt_, raw, pad in sorted(rows, key=lambda r: -r[3]):
        tot_raw += raw
        tot_pad += pad
        if raw > 1e6:
            print(f"{name:58s} {str(shape):>24s} {dt_:>8s} "
                  f"{fmt(raw):>9s} {fmt(pad):>9s}")
    print(f"{'TOTAL persistent':58s} {'':>24s} {'':>8s} "
          f"{fmt(tot_raw):>9s} {fmt(tot_pad):>9s}")

    # compiled-program memory analysis (CPU numbers: unpadded temps)
    rd = solver.real_dtype
    mark("lowering split-step programs for memory analysis")
    M, L, X = solver.M_mat, solver.L_mat, solver.X
    extra = solver.rhs_extra()

    def analyze(name, fn, *args, **kw):
        try:
            c = fn.lower(*args, **kw).compile()
            ma = c.memory_analysis()
            print(f"program {name:20s} temp={fmt(ma.temp_size_in_bytes)} "
                  f"out={fmt(ma.output_size_in_bytes)} "
                  f"args={fmt(ma.argument_size_in_bytes)}")
        except Exception as e:
            print(f"program {name:20s} analysis failed: {type(e).__name__}: {e}")

    dtj = jnp.asarray(dt, dtype=rd)
    analyze("factor", ts._factor_uniq, M, L, dtj)
    ti = jnp.asarray(0.0, dtype=rd)
    analyze("stage_eval", ts._stage_eval, M, L, X, ti, extra)
    LXi, Fi = ts._stage_eval(M, L, X, ti, extra)
    MX0 = ts._mx0(M, X)
    analyze("stage_solve", ts._stage_solve, 1, MX0, [Fi], [LXi], dtj,
            ts._lhs_aux[0], M, L)
    mark("done")


if __name__ == "__main__":
    main()
