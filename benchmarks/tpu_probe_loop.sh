#!/bin/bash
# Periodically probe the TPU (cheap, in a killed-on-timeout subprocess) and
# log when it becomes claimable. Never leaves children: timeout -k kills the
# whole probe process group.
LOG=/root/repo/benchmarks/tpu_probe.log
for i in $(seq 1 200); do
    ts=$(date +%H:%M:%S)
    out=$(timeout -k 5 90 setsid python -c "import jax; d=jax.devices(); print('PROBE_OK', jax.default_backend(), len(d), d[0].device_kind)" 2>&1 | tail -2)
    if echo "$out" | grep -q PROBE_OK; then
        echo "$ts OK: $out" >> "$LOG"
    else
        echo "$ts FAIL: $(echo $out | tail -c 200)" >> "$LOG"
    fi
    sleep 60
done
