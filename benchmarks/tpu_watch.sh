#!/bin/bash
# Probe the TPU tunnel; when it answers, run the benchmark progression
# (subprocess-isolated per config) and record results. One-shot: exits
# after a successful sweep (or after MAX_WAIT).
cd "$(dirname "$0")/.."
MAX_WAIT=${MAX_WAIT:-14400}
START=$(date +%s)
echo "[tpu_watch] start $(date)" >> benchmarks/tpu_watch.log
while true; do
    NOW=$(date +%s)
    if [ $((NOW - START)) -gt "$MAX_WAIT" ]; then
        echo "[tpu_watch] gave up after ${MAX_WAIT}s" >> benchmarks/tpu_watch.log
        exit 1
    fi
    if timeout 90 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
(x @ x).block_until_ready()
print('ok')
" > /dev/null 2>&1; then
        echo "[tpu_watch] TPU responsive at $(date); running progression" >> benchmarks/tpu_watch.log
        timeout 7200 python benchmarks/progression.py kdv1024 rb256x64 shear512 sw_ell255 rb2048x1024 \
            >> benchmarks/tpu_watch.log 2>&1
        echo "[tpu_watch] progression done rc=$? at $(date)" >> benchmarks/tpu_watch.log
        exit 0
    fi
    sleep 300
done
