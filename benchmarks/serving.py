"""
Serving benchmark: cold-miss vs warm-hit time-to-first-step, request
throughput, and overload behavior against a LIVE `python -m dedalus_tpu
serve` daemon subprocess — the served-latency numbers the warm pool
exists to buy, and the bounded-degradation numbers the admission
control exists to guarantee.

Three scenarios:

  rb256x64_serving      the 2-D Rayleigh-Benard flagship (compute-bound):
                        the acceptance bar — warm pool-hit
                        time-to-first-step >= 10x faster than a cold
                        fresh-process request — is checked here.
  diffusion64_serving   the 1-D forced heat equation (dispatch-bound):
                        ttfs plus a sequential request-throughput sweep.
  diffusion64_overload  a sustained closed-loop storm holding 2x the
                        daemon's in-system capacity outstanding against
                        a bounded queue: records the shed rate,
                        accepted-request p50/p95 latency (which must
                        stay under the (queue_depth+3) x single-request
                        bound — load shedding, not unbounded queueing),
                        and zero daemon restarts.
  diffusion64_batching  the continuous-batching multiplier: the same
                        closed-loop same-spec storm against the single-
                        executor baseline AND a `--batch` daemon whose
                        micro-batches coalesce it — requests/s, p50/p95
                        both modes, the speedup (>= 1.5x acceptance),
                        and the batch occupancy stats.
  router_scaling        the replica-fleet spec-locality multiplier: a
                        closed-loop MIXED-spec storm (6 distinct
                        problems, one pinned worker each) against
                        1/2/4-replica fleets behind the spec-hash
                        router (service/router.py), every replica
                        capped at --pool-size 3 so a lone replica
                        thrashes its warm pool on the mix while the
                        hash-partitioned fleet keeps every spec
                        resident — requests/s per fleet size, the 4v1
                        speedup (>= 2.5x acceptance), and the router's
                        forwarding overhead p50 (routed minus direct
                        warm request wall, 1-replica fleet).

Methodology: one fresh daemon per problem with an EMPTY private
assembly-cache directory, so the first request is a true cold
fresh-process request (host assembly + structure analysis + factor +
step compile all paid inside `time_to_first_step_sec`, which the server
measures dispatch -> first-step-complete). Subsequent identical requests
hit the warm pool; the warm ttfs is the median of WARM_RUNS requests.
All timings are the SERVER's served-latency fields (the client-observed
request wall rides along for context). Cold and warm runs use identical
initial conditions and the returned coefficient-layout fields are
compared bit-for-bit — the pool reset must reproduce the cold result
exactly or the speedup does not count.

Appends one row per problem to benchmarks/results.jsonl and exits
nonzero when the RB warm/cold ttfs ratio misses the 10x acceptance bar.

Run: python benchmarks/serving.py [--quick]
  --quick   diffusion only, fewer warm runs, no row appended (CI smoke).
"""

import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from dedalus_tpu.service.client import ServiceClient  # noqa: E402

T0 = time.time()
WARM_RUNS = 3
THROUGHPUT_REQUESTS = 10


def mark(msg):
    print(f"[serving {time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def start_daemon(workdir, *extra):
    """Fresh daemon subprocess with an empty private assembly cache (a
    true cold start) and a JSONL sink inside `workdir`. Returns
    (proc, client, sink_path, stderr_file)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["DEDALUS_TPU_ASSEMBLY_CACHE"] = os.path.join(workdir, "assembly")
    sink = os.path.join(workdir, "served.jsonl")
    stderr = open(os.path.join(workdir, "daemon.err"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dedalus_tpu", "serve", "--sink", sink,
         *extra],
        env=env, stdout=subprocess.PIPE, stderr=stderr, text=True)
    line = proc.stdout.readline()
    try:
        banner = json.loads(line)
    except ValueError:
        proc.kill()
        raise RuntimeError(f"daemon failed to start: {line!r} (see "
                           f"{stderr.name})")
    mark(f"daemon ready on port {banner['port']} (pid {banner['pid']})")
    return proc, ServiceClient(port=banner["port"], timeout=1200), sink, \
        stderr


def stop_daemon(proc, client, stderr):
    try:
        client.shutdown()
        proc.wait(timeout=120)
    except Exception:
        proc.kill()
    finally:
        stderr.close()


def one_request(client, spec, ics, dt, steps, tag):
    t0 = time.perf_counter()
    result = client.run(spec, ics=ics, dt=dt, stop_iteration=steps)
    wall = time.perf_counter() - t0
    serving = result.serving
    mark(f"{tag}: pool={serving['pool_verdict']} "
         f"ttfs={serving['time_to_first_step_sec']}s "
         f"(request wall {wall:.2f}s)")
    return {
        "pool_verdict": serving["pool_verdict"],
        "ttfs_sec": serving["time_to_first_step_sec"],
        "queue_sec": serving["queue_sec"],
        "build_sec": serving.get("build_sec"),
        "request_wall_sec": round(wall, 4),
        "fields": result.fields,
        "steps_per_sec": (result.record or {}).get("steps_per_sec"),
        # the daemon-resolved plan rides back in the flushed step record
        "plan": (result.record or {}).get("plan"),
    }


def run_problem(config, spec, ics, dt, steps, warm_runs,
                throughput_requests=0):
    workdir = tempfile.mkdtemp(prefix="dedalus_serving_")
    proc, client, sink, stderr = start_daemon(workdir)
    try:
        cold = one_request(client, spec, ics, dt, steps, f"{config} cold")
        if cold["pool_verdict"] != "cold":
            # a shared ambient cache leaked in; the number would flatter
            # nothing (warm-cache is FASTER than cold) but the row must
            # say what it measured
            mark(f"WARNING: first request verdict is "
                 f"{cold['pool_verdict']}, not cold")
        warm = [one_request(client, spec, ics, dt, steps,
                            f"{config} warm-{i + 1}")
                for i in range(warm_runs)]
        assert all(w["pool_verdict"] == "hit" for w in warm), \
            "warm request missed the pool"
        # bit-identity: every warm result must equal the cold one
        names = sorted(cold["fields"])
        bit_identical = all(
            np.array_equal(w["fields"][name][1], cold["fields"][name][1])
            for w in warm for name in names)
        warm_ttfs = statistics.median(w["ttfs_sec"] for w in warm)
        row = {
            "config": config,
            "backend": os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0],
            "dt": dt,
            "steps_per_request": steps,
            "cold_verdict": cold["pool_verdict"],
            "ttfs_cold_sec": round(cold["ttfs_sec"], 4),
            "ttfs_warm_sec": round(warm_ttfs, 4),
            "ttfs_warm_runs": [round(w["ttfs_sec"], 4) for w in warm],
            "ttfs_speedup": round(cold["ttfs_sec"] / warm_ttfs, 2)
            if warm_ttfs else None,
            "build_sec_cold": cold["build_sec"],
            "request_wall_cold_sec": cold["request_wall_sec"],
            "request_wall_warm_sec": round(statistics.median(
                w["request_wall_sec"] for w in warm), 4),
            "queue_sec_warm": round(statistics.median(
                w["queue_sec"] for w in warm), 6),
            "bit_identical_cold_warm": bool(bit_identical),
            "steps_per_sec_warm": warm[-1]["steps_per_sec"],
            "plan": warm[-1]["plan"] or cold["plan"],
        }
        if throughput_requests:
            mark(f"{config}: throughput sweep "
                 f"({throughput_requests} requests x {steps} steps)")
            t0 = time.perf_counter()
            for _ in range(throughput_requests):
                client.run(spec, ics=ics, dt=dt, stop_iteration=steps)
            wall = time.perf_counter() - t0
            row["throughput_requests"] = throughput_requests
            row["throughput_requests_per_sec"] = round(
                throughput_requests / wall, 2)
            row["throughput_member_steps_per_sec"] = round(
                throughput_requests * steps / wall, 1)
            mark(f"{config}: {row['throughput_requests_per_sec']} "
                 "requests/s")
        stats = client.stats()
        row["pool"] = {k: stats["pool"][k]
                       for k in ("hits", "misses", "evictions")}
        mark(f"{config}: ttfs cold {row['ttfs_cold_sec']}s -> warm "
             f"{row['ttfs_warm_sec']}s ({row['ttfs_speedup']}x), "
             f"bit-identical={row['bit_identical_cold_warm']}")
        return row
    finally:
        stop_daemon(proc, client, stderr)
        shutil.rmtree(workdir, ignore_errors=True)


def run_overload(config="diffusion64_overload", queue_depth=1,
                 storm_rate_x=2.0, rounds=8, steps=400):
    """Sustained over-capacity storm, CLOSED-LOOP: `storm_rate_x` times
    the daemon's in-system capacity (1 executing + queue_depth queued)
    in always-outstanding client workers, each re-submitting the moment
    its previous request resolves — so overload pressure is structural,
    not a product of timing calibration, and shedding MUST occur.
    Records the shed rate, accepted-request p50/p95 latency, the MAX
    live queue occupancy (a stats sampler polls the daemon's
    faults.queued throughout the storm — the direct no-unbounded-queue-
    growth observation), and that the daemon neither crashed nor
    restarted. Acceptance: max observed queue occupancy never exceeds
    queue_depth, shedding occurred, and accepted p95 stays under a
    1.5 x (queue_depth + 3) x single-request sanity bound (the
    admission bound caps the in-system population at queue_depth + 1
    service times; the headroom absorbs 2-core scheduling jitter
    between the daemon and the storm workers)."""
    import statistics as stats_mod
    import threading

    from dedalus_tpu.service.protocol import ServiceError

    spec = {"problem": "diffusion", "params": {"size": 64}}
    ics = diffusion_ics(64)
    capacity = queue_depth + 1
    workers = max(int(round(storm_rate_x * capacity)), capacity + 1)
    workdir = tempfile.mkdtemp(prefix="dedalus_overload_")
    proc, client, sink, stderr = start_daemon(
        workdir, "--queue-depth", str(queue_depth))
    try:
        # warm the pool (build + step compile + phase-sampler thunks),
        # then calibrate the single-request service time (median of 5)
        for _ in range(2):
            client.run(spec, ics=ics, dt=1e-3, stop_iteration=steps)
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            client.run(spec, ics=ics, dt=1e-3, stop_iteration=steps)
            samples.append(time.perf_counter() - t0)
        single = stats_mod.median(samples)
        mark(f"{config}: single request {single:.3f}s; closed-loop storm "
             f"of {workers} workers x {rounds} rounds "
             f"({storm_rate_x}x the {capacity}-deep in-system capacity)")
        accepted, shed, other = [], [], []
        outcome_lock = threading.Lock()
        # live queue-occupancy sampler: control requests are answered on
        # reader threads even while the executor is saturated, so the
        # max observed faults.queued IS the no-unbounded-growth check
        max_queued = [0]
        storm_over = threading.Event()

        def sample_queue():
            sclient = ServiceClient(port=client.port, timeout=30)
            while not storm_over.wait(0.2):
                try:
                    queued = sclient.stats()["faults"]["queued"]
                    max_queued[0] = max(max_queued[0], queued)
                except Exception:
                    pass

        def one_worker(i):
            wclient = ServiceClient(port=client.port, timeout=1200)
            done = 0
            while done < rounds:
                t_req = time.perf_counter()
                try:
                    wclient.run(spec, ics=ics, dt=1e-3,
                                stop_iteration=steps)
                    with outcome_lock:
                        accepted.append(time.perf_counter() - t_req)
                    done += 1
                except ServiceError as exc:
                    if exc.code == "overloaded":
                        with outcome_lock:
                            shed.append(exc.retry_after_sec)
                        # honor (a fraction of) the shed hint, then
                        # re-offer the load — sustained over-capacity
                        time.sleep(min(exc.retry_after_sec or 0.5,
                                       2.0) * 0.3)
                    else:
                        with outcome_lock:
                            other.append(exc.code)
                        done += 1
                except OSError as exc:
                    with outcome_lock:
                        other.append(f"oserror:{exc.errno}")
                    done += 1

        threads = [threading.Thread(target=one_worker, args=(i,),
                                    daemon=True) for i in range(workers)]
        sampler = threading.Thread(target=sample_queue, daemon=True)
        sampler.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1200)
        storm_over.set()
        sampler.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "storm worker hung"
        restarts = 0 if proc.poll() is None else 1
        alive = False
        try:
            alive = client.ping().get("kind") == "pong"
        except Exception:
            pass
        lats = sorted(accepted)
        p50 = lats[len(lats) // 2] if lats else None
        p95 = lats[min(int(len(lats) * 0.95), len(lats) - 1)] \
            if lats else None
        bound = 1.5 * (queue_depth + 3) * single
        # every issued request counts, so the row's fields stay mutually
        # consistent even when some workers hit non-shed errors
        total = len(accepted) + len(shed) + len(other)
        row = {
            "config": config,
            "backend": os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0],
            "queue_depth": queue_depth,
            "storm_rate_x": storm_rate_x,
            "storm_workers": workers,
            "steps_per_request": steps,
            "requests_sent": total,
            "accepted": len(accepted),
            "shed": len(shed),
            "other_errors": len(other),
            "shed_rate": round(len(shed) / total, 3) if total else None,
            "single_request_sec": round(single, 4),
            "accepted_p50_sec": round(p50, 4) if p50 else None,
            "accepted_p95_sec": round(p95, 4) if p95 else None,
            "latency_bound_sec": round(bound, 4),
            "latency_bounded": bool(lats) and p95 <= bound,
            "max_queued_observed": max_queued[0],
            "queue_bounded": max_queued[0] <= queue_depth,
            "shed_with_retry_hint": sum(1 for s in shed if s),
            "daemon_restarts": restarts,
            "daemon_alive_after": alive,
        }
        mark(f"{config}: {len(accepted)} accepted / {len(shed)} shed / "
             f"{len(other)} other, p50 {row['accepted_p50_sec']}s p95 "
             f"{row['accepted_p95_sec']}s (bound {row['latency_bound_sec']}"
             f"s), max queued {max_queued[0]}/{queue_depth}, "
             f"restarts={restarts}, alive={alive}")
        return row
    finally:
        stop_daemon(proc, client, stderr)
        shutil.rmtree(workdir, ignore_errors=True)


def run_batching(config="diffusion64_batching", clients=8, rounds=4,
                 steps=400):
    """Continuous-batching throughput: a CLOSED-LOOP storm of `clients`
    concurrent same-spec workers (each re-submitting the moment its
    previous request resolves, with per-worker ICs — the batched
    operands) against (a) the single-executor baseline daemon and (b) a
    `--batch` daemon whose micro-batches coalesce the storm. The queue
    is deep enough that nothing sheds — this measures throughput and
    accepted latency, not admission control (run_overload covers that).
    Records requests/s and p50/p95 for both modes plus the multiplier,
    and the batch daemon's occupancy stats (batches formed, late joins,
    peak seats). Exits nonzero when batching is not at least 1.5x the
    single-executor requests/s — the multiplier IS the feature."""
    import threading

    spec = {"problem": "diffusion", "params": {"size": 64}}
    x = np.linspace(0, 2 * np.pi, 64, endpoint=False)
    worker_ics = [{"u": ("g", np.sin((1 + i % 4) * x)),
                   "a": ("g", 0.05 * (1 + i) * np.cos(x))}
                  for i in range(clients)]

    def storm(port):
        lat, errors = [], []
        lock = threading.Lock()

        def one_worker(i):
            wclient = ServiceClient(port=port, timeout=1200)
            for _ in range(rounds):
                t_req = time.perf_counter()
                try:
                    wclient.run(spec, ics=worker_ics[i], dt=1e-3,
                                stop_iteration=steps)
                    with lock:
                        lat.append(time.perf_counter() - t_req)
                except Exception as exc:
                    with lock:
                        errors.append(str(exc))
        threads = [threading.Thread(target=one_worker, args=(i,),
                                    daemon=True) for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1200)
        wall = time.perf_counter() - t0
        assert not any(t.is_alive() for t in threads), "storm worker hung"
        lats = sorted(lat)
        return {
            "requests": len(lat),
            "errors": len(errors),
            "wall_sec": round(wall, 3),
            "requests_per_sec": round(len(lat) / wall, 3) if wall else 0,
            "p50_sec": round(lats[len(lats) // 2], 4) if lats else None,
            "p95_sec": round(lats[min(int(len(lats) * 0.95),
                                      len(lats) - 1)], 4)
            if lats else None,
        }

    out = {}
    for mode, extra in (("baseline", ()),
                        ("batched", ("--batch",
                                     "--batch-max", str(clients),
                                     "--batch-window", "0.02"))):
        workdir = tempfile.mkdtemp(prefix=f"dedalus_batching_{mode}_")
        proc, client, sink, stderr = start_daemon(
            workdir, "--queue-depth", str(2 * clients), *extra)
        try:
            # warm the pool (and, batched, the fleet programs) before
            # the measured storm
            for _ in range(2):
                client.run(spec, ics=worker_ics[0], dt=1e-3,
                           stop_iteration=steps)
            # occupancy is recorded as a STORM-ONLY delta: the daemon's
            # counters are cumulative and the two warmup requests formed
            # their own one-member batches
            pre = (client.stats()["serving"]["batching"]
                   if mode == "batched" else {})
            mark(f"{config}: {mode} storm ({clients} workers x {rounds} "
                 f"rounds x {steps} steps)")
            out[mode] = storm(client.port)
            if mode == "batched":
                post = client.stats()["serving"]["batching"]
                out["batch_stats"] = {
                    "batches": post["batches"] - pre["batches"],
                    "members": post["members"] - pre["members"],
                    "late_joins": post["late_joins"] - pre["late_joins"],
                    "peak_members": post["peak_members"],
                }
            out[mode]["daemon_crashed"] = proc.poll() is not None
            mark(f"{config}: {mode} {out[mode]['requests_per_sec']} "
                 f"requests/s (p50 {out[mode]['p50_sec']}s, p95 "
                 f"{out[mode]['p95_sec']}s, {out[mode]['errors']} errors)")
        finally:
            stop_daemon(proc, client, stderr)
            shutil.rmtree(workdir, ignore_errors=True)
    base_rps = out["baseline"]["requests_per_sec"] or 1e-9
    speedup = round(out["batched"]["requests_per_sec"] / base_rps, 2)
    batch_stats = out.get("batch_stats") or {}
    row = {
        "config": config,
        "backend": os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0],
        "clients": clients,
        "rounds": rounds,
        "steps_per_request": steps,
        "baseline_requests_per_sec": out["baseline"]["requests_per_sec"],
        "baseline_p50_sec": out["baseline"]["p50_sec"],
        "baseline_p95_sec": out["baseline"]["p95_sec"],
        "batched_requests_per_sec": out["batched"]["requests_per_sec"],
        "batched_p50_sec": out["batched"]["p50_sec"],
        "batched_p95_sec": out["batched"]["p95_sec"],
        "requests_speedup": speedup,
        "errors": out["baseline"]["errors"] + out["batched"]["errors"],
        "batches": batch_stats.get("batches"),
        "late_joins": batch_stats.get("late_joins"),
        "peak_batch_members": batch_stats.get("peak_members"),
        "meets_1p5x": speedup >= 1.5
        and not out["batched"]["daemon_crashed"],
    }
    mark(f"{config}: batching {row['batched_requests_per_sec']} vs "
         f"baseline {row['baseline_requests_per_sec']} requests/s = "
         f"{speedup}x ({row['batches']} batches, {row['late_joins']} "
         f"late joins, peak {row['peak_batch_members']} seats)")
    return row


def _balanced_specs(count=6, per_replica=2):
    """`count` distinct diffusion specs whose 4-replica ring assignment
    (deterministic: the ring depends only on names+vnodes) spreads at
    most `per_replica` specs per replica — so the row measures the
    LOCALITY multiplier, not one-off hash luck with an adversarial
    spec set that happens to pile onto a single member."""
    from dedalus_tpu.service.router import (ring_order, ring_points,
                                            route_digest)
    points = ring_points(["r0", "r1", "r2", "r3"], 64)
    chosen, load = [], {}
    for size in range(40, 400, 4):
        spec = {"problem": "diffusion", "params": {"size": size}}
        owner = ring_order(points, route_digest({"spec": spec}))[0]
        if load.get(owner, 0) >= per_replica:
            continue
        load[owner] = load.get(owner, 0) + 1
        chosen.append(spec)
        if len(chosen) == count:
            return chosen
    raise RuntimeError("could not assemble a balanced spec set")


def _start_router(n_replicas, workdir, pool_size, queue_depth):
    """An in-process RouterService fronting `n_replicas` spawned
    daemons. Returns (router, serve_thread)."""
    import io
    import threading

    from dedalus_tpu.service.router import RouterService

    router = RouterService(
        replicas=n_replicas, workdir=workdir,
        replica_args=["--pool-size", str(pool_size),
                      "--queue-depth", str(queue_depth)],
        probe_sec=0.5, probe_timeout=5.0, wedge_misses=8)
    thread = threading.Thread(
        target=router.serve_forever, kwargs={"ready_stream": io.StringIO()},
        daemon=True)
    thread.start()
    deadline = time.monotonic() + 600
    while router.port == 0 or router._listener is None \
            or len(router.fleet.routable()) < n_replicas:
        if not thread.is_alive() or time.monotonic() > deadline:
            raise RuntimeError(f"{n_replicas}-replica fleet failed to "
                               f"come up (see {workdir})")
        time.sleep(0.1)
    return router, thread


def _stop_router(router, thread):
    router.request_drain("benchmark done")
    thread.join(timeout=300)


def run_router_scaling(config="router_scaling", fleet_sizes=(1, 2, 4),
                       specs=6, rounds=3, steps=200, pool_size=3,
                       overhead_probes=10):
    """Spec-locality scaling behind the replica router: the same
    closed-loop mixed-spec storm (one pinned worker per spec, each
    re-submitting the moment its previous request resolves) against
    1/2/4-replica fleets. Every replica's warm pool holds `pool_size`
    solvers, fewer than the spec mix — a lone replica evicts and
    rebuilds on nearly every arrival, while the spec-hash ring gives
    each fleet member a subset that FITS, so the multiplier measures
    warm-pool residency bought by routing, not extra cores. Also
    records the router's forwarding overhead (routed minus direct warm
    request wall p50, measured on the 1-replica fleet where both paths
    hit the same warm pool). Acceptance: >= 2.5x requests/s at 4
    replicas vs 1."""
    import statistics as stats_mod
    import threading

    spec_list = _balanced_specs(count=specs, per_replica=pool_size - 1)
    ics_list = [diffusion_ics(s["params"]["size"]) for s in spec_list]
    workdir = tempfile.mkdtemp(prefix="dedalus_router_")
    # one private assembly cache shared by every topology: the storm
    # measures in-process warm-POOL residency, which the on-disk cache
    # cannot provide, and sharing keeps later topologies' warmup short
    saved_cache = os.environ.get("DEDALUS_TPU_ASSEMBLY_CACHE")
    os.environ["DEDALUS_TPU_ASSEMBLY_CACHE"] = os.path.join(
        workdir, "assembly")

    def storm(port):
        lat, errors = [], []
        lock = threading.Lock()

        def one_worker(i):
            wclient = ServiceClient(port=port, timeout=1200)
            for _ in range(rounds):
                t_req = time.perf_counter()
                try:
                    wclient.run(spec_list[i], ics=ics_list[i], dt=1e-3,
                                stop_iteration=steps)
                    with lock:
                        lat.append(time.perf_counter() - t_req)
                except Exception as exc:
                    with lock:
                        errors.append(str(exc))
        threads = [threading.Thread(target=one_worker, args=(i,),
                                    daemon=True)
                   for i in range(len(spec_list))]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1200)
        wall = time.perf_counter() - t0
        assert not any(t.is_alive() for t in threads), "storm worker hung"
        lats = sorted(lat)
        return {"requests": len(lat), "errors": errors,
                "wall_sec": round(wall, 3),
                "requests_per_sec": round(len(lat) / wall, 3)
                if wall else 0,
                "p50_sec": round(lats[len(lats) // 2], 4)
                if lats else None}

    per_fleet = {}
    overhead_ms = None
    try:
        for n in fleet_sizes:
            subdir = os.path.join(workdir, f"fleet{n}")
            os.makedirs(subdir, exist_ok=True)
            router, thread = _start_router(n, subdir, pool_size,
                                           queue_depth=2 * len(spec_list))
            try:
                mark(f"{config}: warming {len(spec_list)} specs on the "
                     f"{n}-replica fleet")
                for spec, ics in zip(spec_list, ics_list):
                    ServiceClient(port=router.port, timeout=1200).run(
                        spec, ics=ics, dt=1e-3, stop_iteration=steps)
                mark(f"{config}: {n}-replica storm ({len(spec_list)} "
                     f"pinned workers x {rounds} rounds x {steps} steps)")
                per_fleet[n] = storm(router.port)
                per_fleet[n]["forward_p50_ms"] = \
                    router.stats()["router"]["forward"]["p50_ms"]
                mark(f"{config}: {n} replica(s) -> "
                     f"{per_fleet[n]['requests_per_sec']} requests/s "
                     f"({len(per_fleet[n]['errors'])} errors)")
                if n == 1 and overhead_probes:
                    # routed vs direct warm request wall, same replica,
                    # same warm pool: the difference IS the router
                    host, port = router.fleet.endpoint(
                        router.fleet.routable()[0])
                    spec, ics = spec_list[0], ics_list[0]

                    def p50_wall(client):
                        samples = []
                        for _ in range(overhead_probes):
                            t0 = time.perf_counter()
                            client.run(spec, ics=ics, dt=1e-3,
                                       stop_iteration=steps)
                            samples.append(time.perf_counter() - t0)
                        return stats_mod.median(samples)

                    routed = p50_wall(ServiceClient(port=router.port,
                                                    timeout=1200))
                    direct = p50_wall(ServiceClient(host=host, port=port,
                                                    timeout=1200))
                    overhead_ms = round(max(routed - direct, 0.0) * 1e3,
                                        3)
                    mark(f"{config}: forward overhead p50 "
                         f"{overhead_ms} ms (routed {routed:.4f}s vs "
                         f"direct {direct:.4f}s)")
            finally:
                _stop_router(router, thread)
    finally:
        if saved_cache is None:
            os.environ.pop("DEDALUS_TPU_ASSEMBLY_CACHE", None)
        else:
            os.environ["DEDALUS_TPU_ASSEMBLY_CACHE"] = saved_cache
        shutil.rmtree(workdir, ignore_errors=True)

    biggest, smallest = max(per_fleet), min(per_fleet)
    base_rps = per_fleet[smallest]["requests_per_sec"] or 1e-9
    speedup = round(per_fleet[biggest]["requests_per_sec"] / base_rps, 2)
    row = {
        "config": config,
        "backend": os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0],
        # perfwatch-tracked measurement triplet: the 4-replica storm rate
        "metric": f"router_requests_per_sec_{biggest}r",
        "value": per_fleet[biggest]["requests_per_sec"],
        "unit": "requests/sec",
        "specs": len(spec_list),
        "clients": len(spec_list),
        "rounds": rounds,
        "steps_per_request": steps,
        "pool_size": pool_size,
        "replica_requests_per_sec": {
            str(n): per_fleet[n]["requests_per_sec"] for n in per_fleet},
        "replica_p50_sec": {str(n): per_fleet[n]["p50_sec"]
                            for n in per_fleet},
        f"requests_speedup_{biggest}v{smallest}": speedup,
        "forward_overhead_p50_ms": overhead_ms,
        "errors": sum(len(per_fleet[n]["errors"]) for n in per_fleet),
        "meets_2p5x": speedup >= 2.5
        and not any(per_fleet[n]["errors"] for n in per_fleet),
    }
    mark(f"{config}: " + ", ".join(
        f"{n}r={per_fleet[n]['requests_per_sec']}"
        for n in sorted(per_fleet)) +
        f" requests/s -> {speedup}x at {biggest} replicas "
        f"(forward overhead p50 {overhead_ms} ms)")
    return row


def diffusion_ics(size=64):
    x = np.linspace(0, 2 * np.pi, size, endpoint=False)
    return {"u": ("g", np.sin(3 * x)), "a": ("g", 0.1 * np.cos(x))}


def rb_ics(Nx=256, Nz=64):
    rng = np.random.default_rng(42)
    return {"b": ("g", 1e-3 * rng.standard_normal((Nx, Nz)))}


def main():
    quick = "--quick" in sys.argv
    from __graft_entry__ import _append_result
    if quick:
        # smoke mode appends nothing: a short-window quick row would
        # shadow the full measurement in bench.py's _attach_serving
        _append_result = lambda record: None  # noqa: E731

    rows = [run_problem(
        "diffusion64_serving",
        {"problem": "diffusion", "params": {"size": 64}},
        diffusion_ics(64), dt=1e-3, steps=25,
        warm_runs=2 if quick else WARM_RUNS,
        throughput_requests=4 if quick else THROUGHPUT_REQUESTS)]
    if not quick:
        rows.append(run_problem(
            "rb256x64_serving",
            # the headline RB configuration is the BANDED path (bench.py /
            # coldstart.py); the default-config dense fallback would make
            # the first step itself seconds of wall time and measure the
            # matsolver, not the pool
            {"problem": "rayleigh_benard",
             "params": {"Nx": 256, "Nz": 64, "matsolver": "banded"}},
            rb_ics(), dt=0.01, steps=3, warm_runs=WARM_RUNS))
    ok = True
    for row in rows:
        row["meets_10x"] = (row.get("ttfs_speedup") or 0) >= 10.0 \
            and row["bit_identical_cold_warm"]
        if row["config"].startswith("rb"):
            ok = row["meets_10x"]
        _append_result(row)
        print(json.dumps(row), flush=True)
    # the closed-loop storm holds 2x the in-system capacity outstanding,
    # so shedding is structural; quick mode just shrinks the rounds.
    # queue_depth=1 keeps the client-side thread count (2x capacity = 4
    # workers) small enough that benchmark-process contention does not
    # pollute the accepted-latency measurement on a 2-core box.
    overload = run_overload(rounds=3 if quick else 8,
                            steps=200 if quick else 400)
    overload["bounded_under_overload"] = (
        overload["latency_bounded"] and overload["queue_bounded"]
        and overload["daemon_restarts"] == 0
        and overload["shed"] > 0 and overload["daemon_alive_after"])
    _append_result(overload)
    print(json.dumps(overload), flush=True)
    # the continuous-batching multiplier: same-spec closed-loop storm,
    # single-executor baseline vs `--batch` micro-batching
    batching_row = run_batching(clients=4 if quick else 8,
                                rounds=2 if quick else 4,
                                steps=200 if quick else 400)
    _append_result(batching_row)
    print(json.dumps(batching_row), flush=True)
    # the replica-fleet spec-locality multiplier: mixed-spec closed-loop
    # storm against 1/2/4-replica fleets behind the spec-hash router
    scaling_row = run_router_scaling(
        fleet_sizes=(1, 4) if quick else (1, 2, 4),
        rounds=2 if quick else 3,
        steps=100 if quick else 200)
    _append_result(scaling_row)
    print(json.dumps(scaling_row), flush=True)
    if not quick and not scaling_row["meets_2p5x"]:
        mark("FAIL: 4-replica fleet is not >= 2.5x single-replica "
             "requests/s under the mixed-spec storm")
        sys.exit(1)
    if not quick and not batching_row["meets_1p5x"]:
        mark("FAIL: batched serving is not >= 1.5x single-executor "
             "requests/s under the same-spec storm")
        sys.exit(1)
    if not quick and not ok:
        mark("FAIL: RB warm pool-hit ttfs is not >= 10x faster than the "
             "cold fresh-process request (or results drifted)")
        sys.exit(1)
    if not quick and not overload["bounded_under_overload"]:
        mark("FAIL: overload storm was not bounded (accepted p95 over the "
             "bound, no shedding, or the daemon crashed)")
        sys.exit(1)


if __name__ == "__main__":
    main()
