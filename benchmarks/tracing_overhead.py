"""
Tracing-overhead benchmark: the observability layer's <1% claim, measured.

Request tracing (dedalus_tpu/tools/tracing.py) is host-side by contract —
the compiled step program is byte-identical with tracing on or off
(progcheck DTP107, `traced_step` census) — so the only costs it CAN have
are (a) whatever the per-step host path pays for having tracing enabled
and (b) span bookkeeping at the phase-sampling sites. This benchmark
prices both on the rb256x64 CPU headline configuration (the banded
Rayleigh-Benard step bench.py reports) and records their sum:

  * loop A/B — steps/s over many SHORT interleaved step_many windows,
    tracing disabled vs enabled, phase sampling quiesced so the probe
    re-execution (a ~2 step-time measurement burst with its own
    variance, identical in both modes) cannot drown a 1% signal. The
    window order alternates each round (off-on, on-off, ...) and the
    estimator is the MEDIAN OF PER-ROUND PAIRED fractions, so slow
    host-load drift — which a sequential comparison or a pooled median
    reads as overhead — cancels to common mode.
  * span path — the per-sample cost of the span recording a traced
    sample performs (one add_span per phase) is timed directly over
    thousands of iterations, then expressed as a fraction of step time
    at the PINNED cadence (every 5th step — 40x the shipped default of
    200, so the recorded fraction is an upper bound, not a flattering
    one).

Appends one `rb256x64_tracing` row to benchmarks/results.jsonl
(steps_per_sec off/on, loop + sampling + total overhead fractions,
span cost per sample, meets_1pct, resolved plan provenance) and exits
nonzero when the measured total reaches 1%. `--quick` shrinks the
round count and appends nothing.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _append_result, _mark as mark  # noqa: E402

PINNED_CADENCE = 5
SPAN_PHASES = ("matsolve", "rhs_eval", "transform", "transpose", "other")


def measure_interleaved(solver, dt, block, rounds):
    """Loop A/B: median steps/s per mode plus the drift-cancelled paired
    overhead fraction, over `rounds` alternating-order window pairs.
    Tracing state and sampling flag are restored on exit."""
    import jax
    from dedalus_tpu.tools import tracing
    was_on = tracing.enabled()
    was_sampling = solver.metrics.sampling
    solver.metrics.sampling = False
    walls = {"off": [], "on": []}
    try:
        for r in range(rounds):
            order = ("off", "on") if r % 2 == 0 else ("on", "off")
            for mode in order:
                (tracing.enable if mode == "on" else tracing.disable)()
                t0 = time.perf_counter()
                solver.step_many(block, dt)
                jax.block_until_ready(solver.X)
                walls[mode].append(time.perf_counter() - t0)
    finally:
        (tracing.enable if was_on else tracing.disable)()
        solver.metrics.sampling = was_sampling
    rates = {mode: round(block / float(np.median(w)), 3)
             for mode, w in walls.items()}
    paired = [(on - off) / off
              for off, on in zip(walls["off"], walls["on"])]
    return rates, float(np.median(paired))


def measure_span_cost(repeats=5000):
    """Per-sample cost of the span recording a traced phase sample
    performs (metrics.add_phase_sample: one add_span per phase)."""
    from dedalus_tpu.tools import tracing
    was_on = tracing.enabled()
    tracing.enable()
    try:
        for ph in SPAN_PHASES:                      # warm the path
            tracing.add_span("phase/" + ph, 1e-4)
        t0 = time.perf_counter()
        for _ in range(repeats):
            for ph in SPAN_PHASES:
                tracing.add_span("phase/" + ph, 1e-4)
        cost = (time.perf_counter() - t0) / repeats
    finally:
        (tracing.enable if was_on else tracing.disable)()
    return cost


def main():
    quick = "--quick" in sys.argv
    append = _append_result
    if quick:
        # smoke mode appends nothing: a short-window quick fraction is
        # noise, and would shadow the full measurement in report scans
        append = lambda record: None  # noqa: E731

    import jax
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    from dedalus_tpu.tools import tracing

    dt = 0.01
    # short windows: slow host-load drift moves BETWEEN windows, not
    # within one, so the paired estimator sees it as common mode
    block = 5
    rounds = 6 if quick else 24
    mark("building rb256x64 (banded, CPU headline config)")
    solver, _ = build_rb_solver(256, 64, np.float64, matsolver="banded")
    solver.metrics.sample_cadence = PINNED_CADENCE
    solver.metrics._gate.cadence = PINNED_CADENCE
    solver.metrics._gate.reset(int(solver.iteration))
    t0 = time.perf_counter()
    # warm with the SAME block size: step_many specializes on n, and a
    # different measurement block would recompile inside the first window
    solver.step_many(block, dt)
    jax.block_until_ready(solver.X)
    # warm the phase-sampling probes OUTSIDE the measured windows: the
    # first sample ever pays a one-time probe compile/warm (seconds on
    # this config) that would otherwise masquerade as tracing overhead
    solver._try_sample_phases()
    mark(f"compiled in {time.perf_counter() - t0:.1f}s; measuring "
         f"{rounds} interleaved round pairs x {block}-step windows")
    rates, loop_frac = measure_interleaved(solver, dt, block, rounds)
    span_cost = measure_span_cost(repeats=1000 if quick else 5000)
    step_sec = 1.0 / rates["off"] if rates["off"] else 1.0
    sampling_frac = span_cost / (PINNED_CADENCE * step_sec)
    overhead = loop_frac + sampling_frac
    finite = bool(np.isfinite(np.asarray(solver.X)).all())
    row = {
        "config": "rb256x64_tracing",
        "backend": jax.default_backend(),
        "dtype": "float64",
        "block": block,
        "rounds": rounds,
        "sample_cadence": PINNED_CADENCE,
        "steps_per_sec_untraced": rates["off"],
        "steps_per_sec_traced": rates["on"],
        "loop_overhead_frac": round(loop_frac, 5),
        "span_cost_per_sample_usec": round(span_cost * 1e6, 3),
        "sampling_overhead_frac": round(sampling_frac, 7),
        "overhead_frac": round(overhead, 5),
        "meets_1pct": bool(overhead < 0.01),
        "plan": solver.plan_provenance(),
        "finite": finite,
        "quick": quick,
        "ts": round(time.time(), 1),
    }
    mark(f"loop {loop_frac * 100:+.3f}% + sampling "
         f"{sampling_frac * 100:.5f}% (span path "
         f"{span_cost * 1e6:.1f} us/sample at cadence {PINNED_CADENCE}) "
         f"-> total {overhead * 100:+.3f}% (bar: <1%)")
    append(row)
    print(json.dumps(row), flush=True)
    if not finite:
        mark("FAIL: state non-finite after measurement")
        return 1
    if not quick and not row["meets_1pct"]:
        mark("FAIL: tracing overhead >= 1% on rb256x64")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
