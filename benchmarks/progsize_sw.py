"""
Program-size audit for sw_ell255 (BASELINE config 4): round 2's TPU attempt
died with HTTP 413 (remote-compile request body over the transport limit)
before RESOURCE_EXHAUSTED wedged the chip. This measures the lowered MLIR
text size of every device program the split step dispatches, so the
constant-lifting (tools/jitlift) can be verified to keep each program under
the transport limit (~10 MB observed OK, sw previously exceeded it).

Run: python benchmarks/progsize_sw.py [Nphi Ntheta]
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

T0 = time.time()


def mark(msg):
    print(f"[size {time.time() - T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def main():
    Nphi = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    Ntheta = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    from benchmarks.progression import build_shallow_water
    mark(f"building shallow water {Nphi}x{Ntheta} f32")
    solver, dt = build_shallow_water(Nphi, Ntheta, np.float32)
    G, S = solver.pencil_shape
    mark(f"built; pencils (G={G}, S={S}), ops={type(solver.ops).__name__}, "
         f"split={solver.timestepper._split}")
    ts = solver.timestepper
    rd = solver.real_dtype
    dtj = jnp.asarray(dt, dtype=rd)
    M, L, X = solver.M_mat, solver.L_mat, solver.X
    extra = solver.rhs_extra()

    def size_of(name, lowered):
        txt = lowered.as_text()
        mb = len(txt.encode()) / 1e6
        print(f"program {name:12s} lowered MLIR {mb:8.2f} MB")
        return mb

    total = 0
    total += size_of("factor", ts._factor_uniq.lower(M, L, dtj))
    ti = jnp.asarray(0.0, dtype=rd)
    total += size_of("stage_eval", ts._stage_eval.lower(M, L, X, ti, extra))
    mark("running one stage_eval to build solve inputs")
    LXi, Fi = ts._stage_eval(M, L, X, ti, extra)
    MX0 = ts._mx0(M, X)
    ts._ensure_factor(dt)
    total += size_of("stage_solve", ts._stage_solve.lower(
        1, MX0, [Fi], [LXi], dtj, ts._lhs_aux[0], M, L))
    print(f"TOTAL split-step programs: {total:.2f} MB "
          f"(remote-compile transport limit ~10 MB each)")
    # the FUSED programs (what the bench dispatches when split=False)
    t0 = jnp.asarray(0.0, dtype=rd)
    size_of("step(fused)", ts._step.lower(M, L, X, t0, dtj, extra,
                                          ts._lhs_aux))
    size_of("step_n(50)", ts._step_n.lower(M, L, X, t0, dtj, extra,
                                           ts._lhs_aux, 50))


if __name__ == "__main__":
    main()
