"""
sw_ell255 step-phase microbenchmark: where does the time go?

Round-4 finding (VERDICT weak #2): sw_ell255 ran at 18.6M mode-stages/s vs
541M for shear512 on the same chip — a ~29x gap with no profile to localize
it. This script times the step's constituent device programs separately
(the exact split-mode pieces the fused step composes, so the breakdown sums
to the step):

    mx0         M @ X batched banded matvec
    stage_eval  L @ X matvec + full RHS evaluation (SWSH transforms both
                ways + nonlinear products)
    stage_solve banded LU substitution sweeps + Woodbury correction
    step        the full RK222 step (2 stages) for reference

Appends {"case": "sw_profile", ...} to benchmarks/results.jsonl.

Run: python benchmarks/profile_sw.py [Nphi Ntheta]  (default 512 256)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

T0 = time.time()


def mark(msg):
    print(f"[swprof {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def time_fn(fn, *args, reps=30, warmup=3):
    """(median, iqr_spread) wall time of fn(*args) with device sync.

    `warmup` untimed passes absorb compile AND first-touch allocator/page
    effects (one pass was not enough: consecutive CPU runs ranked
    stage_solve vs rhs_only differently, VERDICT round-5 weak #2); the
    interquartile range rides along so a reader can tell a real ranking
    from noise (two medians closer than their spreads are a tie)."""
    import jax
    for _ in range(max(warmup, 1)):
        out = fn(*args)
        jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    q25, q50, q75 = np.percentile(times, [25, 50, 75])
    return float(q50), float(q75 - q25)


def main():
    import jax
    import jax.numpy as jnp
    from progression import build_shallow_water
    from __graft_entry__ import _append_result

    Nphi = int(sys.argv[1]) if len(sys.argv) > 2 else 512
    Ntheta = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    backend = jax.default_backend()
    dtype = np.float32 if backend != "cpu" else np.float64
    mark(f"building SW {Nphi}x{Ntheta} (backend={backend})")
    solver, dt = build_shallow_water(Nphi, Ntheta, dtype)
    G, S = solver.pencil_shape
    mark(f"built; pencils (G={G}, S={S}), ops={type(solver.ops).__name__}")

    # warmup steps compile + factor the LHS
    for _ in range(3):
        solver.step(dt)
    solver.X.block_until_ready()
    finite = bool(np.all(np.isfinite(np.asarray(solver.X))))
    mark(f"warmup done; finite={finite}")

    ts = solver.timestepper
    M, L, X = solver.M_mat, solver.L_mat, solver.X
    rd = solver.real_dtype
    extra = solver.rhs_extra()
    auxs = ts._lhs_aux
    if auxs is None:
        raise RuntimeError("timestepper has no factored LHS after warmup")
    dtj = jnp.asarray(float(dt), dtype=rd)
    tj = jnp.asarray(float(solver.sim_time), dtype=rd)

    res = {"case": "sw_profile", "backend": backend,
           "config": f"sw_{Nphi}x{Ntheta}",
           "pencil_shape": [int(G), int(S)],
           "ops": type(solver.ops).__name__}

    def timed(key, fn, *args):
        med, spread = time_fn(fn, *args)
        res[key] = 1e3 * med
        res[f"{key}_iqr"] = round(1e3 * spread, 3)

    mark("timing mx0 (M@X matvec)")
    timed("mx0_ms", ts._mx0, M, X)
    MX0 = ts._mx0(M, X)

    mark("timing stage_eval (L@X + RHS: transforms + nonlinear)")
    timed("stage_eval_ms", ts._stage_eval, M, L, X, tj, extra)
    LX, F = ts._stage_eval(M, L, X, tj, extra)

    mark("timing rhs_only (eval_F alone)")
    from dedalus_tpu.tools.jitlift import lifted_jit
    rhs_jit = lifted_jit(lambda X_, t_, e_: solver.eval_F(X_, t_, e_))
    timed("rhs_only_ms", rhs_jit, X, tj, extra)

    mark("timing stage_solve (banded substitution + Woodbury)")
    timed("stage_solve_ms", ts._stage_solve,
          1, MX0, [F], [LX], dtj, auxs[0], M, L)

    mark("timing full step (fused or split as configured)")
    n_steps = 10
    solver.step_many(n_steps, dt)   # block compile
    solver.X.block_until_ready()
    block_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        solver.step_many(n_steps, dt)
        solver.X.block_until_ready()
        block_times.append((time.perf_counter() - t0) / n_steps)
    q25, q50, q75 = np.percentile(block_times, [25, 50, 75])
    res["step_ms"] = 1e3 * float(q50)
    res["step_ms_iqr"] = round(1e3 * float(q75 - q25), 3)

    stages = getattr(ts, "stages", 2)
    accounted = (res["mx0_ms"]
                 + stages * (res["stage_eval_ms"] + res["stage_solve_ms"]))
    res["accounted_ms"] = round(accounted, 3)
    # Phase-sum check, fusion-aware: the split pieces above are timed as
    # SEPARATE dispatches, so with the fused step path active
    # (core/fusedstep.py) the one-dispatch step program legitimately
    # undercuts their sum — the elided per-dispatch boundaries ARE the
    # fusion win, not an undercounting bug. The check therefore only
    # flags a step that exceeds the accounted sum (pieces missing from
    # the breakdown), never a fused step that beats it; the resolved
    # fusion composition rides the record so a reader can tell the two
    # regimes apart.
    from dedalus_tpu.core.fusedstep import resolve_fusion
    plan = resolve_fusion()
    res["fusion"] = {"solve": plan.solve, "matvec": plan.matvec,
                     "transforms": plan.transforms, "donate": plan.donate,
                     "pallas": plan.pallas}
    gap = (res["step_ms"] - accounted) / max(accounted, 1e-9)
    res["accounted_gap_frac"] = round(gap, 4)
    # generous slack: CPU medians on a loaded box wobble ~20%
    res["phase_sum_ok"] = bool(gap < 0.5)
    for k in ("mx0_ms", "stage_eval_ms", "rhs_only_ms", "stage_solve_ms",
              "step_ms"):
        res[k] = round(res[k], 3)
    res["finite_after_warmup"] = finite
    res["ts"] = round(time.time(), 1)
    print(json.dumps(res), flush=True)
    _append_result(res)
    mark(f"breakdown: step={res['step_ms']}ms vs accounted={res['accounted_ms']}ms "
         f"(mx0={res['mx0_ms']}, eval={res['stage_eval_ms']} "
         f"[rhs {res['rhs_only_ms']}], solve={res['stage_solve_ms']} per stage; "
         f"IQR spreads eval={res['stage_eval_ms_iqr']} "
         f"solve={res['stage_solve_ms_iqr']} rhs={res['rhs_only_ms_iqr']})")


if __name__ == "__main__":
    main()
