"""
Fusion benchmark: fused vs unfused steps/s and per-phase breakdown on
diffusion64 + rb256x64, in ONE process (ISSUE-12 acceptance: >= 1.15x on
the rb256x64 CPU headline, recorded in results.jsonl).

For each problem the solver is built twice from identical initial
conditions — once with every [fusion] flag forced off (the exact legacy
step path), once at the shipped defaults (core/fusedstep.py resolve) —
and each build measures post-compile steps/s over scanned step_many
blocks (medians; this box's CPU timings wobble ~20%) plus the sampled
phase-probe breakdown. The two trajectories are compared after the same
number of steps: FUSED_MATVEC is bitwise, the precomposed-substitution
solve moves results at the eps*cond(block) level and the refinement
sweep polishes it back, so the recorded `state_rel_diff` documents the
fused-vs-unfused tolerance class alongside the speedup.

Appends `diffusion64_fusion` + `rb256x64_fusion` rows to
benchmarks/results.jsonl; bench.py `_attach_fusion` re-reports the
newest in-window row stale-stamped like the ensemble/serving/adjoint
rows. Exits nonzero when the rb256x64 speedup misses the 1.15x bar.

Run: python benchmarks/fusion.py [--quick]
  --quick   shortens windows (CI smoke; no rows appended, so a smoke
            run never shadows the full measurement).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-measured by design while the chip is unclaimable (ROADMAP platform
# note); an explicit JAX_PLATFORMS wins.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

T0 = time.time()


def mark(msg):
    print(f"[fusion {time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def set_fusion(mode):
    """Force every [fusion] flag ('off') or restore shipped defaults."""
    from dedalus_tpu.tools.config import config
    if not config.has_section("fusion"):
        config.add_section("fusion")
    if mode == "off":
        for key in ("FUSED_SOLVE", "FUSED_MATVEC", "FUSED_TRANSFORMS",
                    "DONATE_STEP", "PALLAS"):
            config["fusion"][key] = "off"
    else:
        for key in ("FUSED_SOLVE", "FUSED_MATVEC", "FUSED_TRANSFORMS",
                    "DONATE_STEP"):
            config["fusion"][key] = "auto"
        config["fusion"]["PALLAS"] = "off"
    set_solve()  # solve composition/precision back to shipped defaults


def set_solve(composition="auto", solve_dtype="auto", sweeps="auto",
              spike_chunks="auto"):
    """Pin the solve composition + precision ladder for one build
    (delegates to tools/autotune.py set_solve_config — the benchmark and
    the tuner pin cells through ONE code path). The tuner itself stays
    off in this process: the sweep must measure the pinned cells, not a
    cached decision."""
    from dedalus_tpu.tools.autotune import set_solve_config
    from dedalus_tpu.tools.config import config
    set_solve_config(composition=composition, solve_dtype=solve_dtype,
                     sweeps=sweeps, spike_chunks=spike_chunks)
    if not config.has_section("autotune"):
        config.add_section("autotune")
    config["autotune"]["MODE"] = "off"


def build_diffusion(size=64, dtype=np.float64):
    """The shared adjoint/fusion benchmark diffusion problem (ONE
    definition in extras so the cross-benchmark rows stay comparable)."""
    from dedalus_tpu.extras.bench_problems import build_diffusion_solver
    return build_diffusion_solver(size, dtype), 1e-3


def build_rb(dtype):
    from dedalus_tpu.extras.bench_problems import build_rb_solver
    solver, _b = build_rb_solver(256, 64, dtype, matsolver="banded")
    return solver, 0.01


def probe_phases(solver, reps=12):
    """Median wall ms of each compiled phase probe (rhs_eval / matsolve /
    fused_step when present), compile excluded."""
    import jax
    probes = solver.timestepper.phase_probes()
    if probes is None:
        return {}
    out = {}
    for name, (thunk, scale) in probes.items():
        jax.block_until_ready(thunk())
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(thunk())
            times.append(time.perf_counter() - t0)
        out[f"{name}_ms"] = round(1e3 * float(np.median(times))
                                  * float(scale), 3)
    return out


def measure(build, n_steps, block, blocks, solver_out=None):
    """Build, advance n_steps (trajectory checkpointing), then measure
    median steps/s over `blocks` scanned step_many blocks. The core
    machinery lives in tools/autotune.py `measure_build` (extracted in
    PR 20 so the tuner and this benchmark share ONE harness); this
    wrapper adds the per-phase breakdown the fusion rows report."""
    from dedalus_tpu.tools.autotune import measure_build
    holder = []
    result, state = measure_build(build, n_steps, block, blocks,
                                  solver_out=holder)
    solver = holder[0]
    if solver_out is not None:
        solver_out.append(solver)
    result["phases_ms"] = probe_phases(solver)
    return result, state


def run_case(name, build, dtype, n_steps, block, blocks):
    import jax
    from dedalus_tpu.core.fusedstep import resolve_fusion
    mark(f"{name}: building UNFUSED (all [fusion] flags off)")
    set_fusion("off")
    unfused, state_u = measure(build, n_steps, block, blocks)
    mark(f"{name}: unfused {unfused['steps_per_sec']} steps/s "
         f"(IQR {unfused['steps_per_sec_iqr']})")
    mark(f"{name}: building FUSED (shipped defaults)")
    set_fusion("auto")
    plan = resolve_fusion()
    fused_solver = []
    fused, state_f = measure(build, n_steps, block, blocks,
                             solver_out=fused_solver)
    mark(f"{name}: fused {fused['steps_per_sec']} steps/s "
         f"(IQR {fused['steps_per_sec_iqr']})")
    scale = float(np.max(np.abs(state_u))) or 1.0
    rel = float(np.max(np.abs(state_f - state_u)) / scale)
    speedup = (fused["steps_per_sec"] / unfused["steps_per_sec"]
               if unfused["steps_per_sec"] else 0.0)
    row = {
        "config": f"{name}_fusion",
        "backend": jax.default_backend(),
        # the dtype actually passed to the builds, not re-derived — row
        # provenance must track a future sweep/flag changing main()'s pick
        "dtype": str(np.dtype(dtype)),
        "steps_per_sec_unfused": unfused["steps_per_sec"],
        "steps_per_sec_fused": fused["steps_per_sec"],
        "steps_per_sec_iqr_unfused": unfused["steps_per_sec_iqr"],
        "steps_per_sec_iqr_fused": fused["steps_per_sec_iqr"],
        "fusion_speedup": round(speedup, 3),
        "meets_1p15x": bool(speedup >= 1.15),
        "phases_ms_unfused": unfused["phases_ms"],
        "phases_ms_fused": fused["phases_ms"],
        # fused-vs-unfused trajectory agreement after the same steps:
        # the documented tolerance class of the precomposed substitution
        # (FUSED_MATVEC alone is bitwise; see tests/test_fusion.py)
        "state_rel_diff": rel,
        "trajectory_steps": n_steps,
        "finite": bool(unfused["finite"] and fused["finite"]),
        "fusion": {"solve": plan.solve, "matvec": plan.matvec,
                   "transforms": plan.transforms, "donate": plan.donate,
                   "pallas": plan.pallas},
        # resolved-plan provenance for the FUSED build (the headline
        # number's configuration, machine-readable: docs/observability.md)
        "plan": fused_solver[0].plan_provenance(),
        "ts": round(time.time(), 1),
    }
    mark(f"{name}: speedup {row['fusion_speedup']}x "
         f"(state rel diff {rel:.3e})")
    print(json.dumps(row), flush=True)
    return row


def solve_residual(solver):
    """Achieved relative residual of one probe solve against the live
    LHS factorization (tools/autotune.py `probe_solve_residual` — one
    definition shared with the tuner's offline harness)."""
    from dedalus_tpu.tools.autotune import probe_solve_residual
    return probe_solve_residual(solver)


# The solve-composition x precision sweep (ISSUE-15): every cell builds
# at the shipped fused defaults plus the pinned composition/dtype and is
# compared against the sequential/f64 cell — the PR-12 fused baseline.
SOLVE_CELLS = (
    ("sequential", "f64"),
    ("ascan", "f64"),
    ("spike", "f64"),
    ("sequential", "f32"),
    ("ascan", "f32"),
    ("spike", "f32"),
)

# f64-class accuracy bar for the "unchanged accuracy" speedup claim: the
# PR-12 fused-vs-unfused tolerance class (tests/test_fusion.py)
F64_CLASS = 1e-12


def run_solve_sweep(name, build, dtype, n_steps, block, blocks):
    """Measure every solve composition x precision cell, record one
    `{name}_solvecomp` row: steps/s, state error vs the sequential-f64
    fused baseline, refinement sweep counts, achieved residuals."""
    import jax
    set_fusion("auto")
    sweep = []
    base = None
    base_state = None
    for comp, sdtype in SOLVE_CELLS:
        mark(f"{name}: solve composition {comp}/{sdtype}")
        set_solve(composition=comp,
                  solve_dtype="auto" if sdtype == "f64" else sdtype)
        holder = []
        res, state = measure(build, n_steps, block, blocks,
                             solver_out=holder)
        solver = holder[0]
        plan = solver._solve_plan
        cell = {
            "composition": comp,
            "solve_dtype": sdtype,
            "steps_per_sec": res["steps_per_sec"],
            "steps_per_sec_iqr": res["steps_per_sec_iqr"],
            "refine_sweeps": plan.sweeps if plan.sweeps is not None
            else getattr(solver.ops, "refine", None),
            "achieved_residual": solve_residual(solver),
            "finite": res["finite"],
        }
        if base is None:
            base = cell
            base_state = state
            base_plan = solver.plan_provenance()
            cell["baseline"] = True
            cell["state_rel_err"] = 0.0
        else:
            scale = float(np.max(np.abs(base_state))) or 1.0
            cell["state_rel_err"] = float(
                np.max(np.abs(state - base_state)) / scale)
            cell["speedup"] = round(
                cell["steps_per_sec"] / base["steps_per_sec"], 3) \
                if base["steps_per_sec"] else 0.0
        sweep.append(cell)
        mark(f"{name}: {comp}/{sdtype} {cell['steps_per_sec']} steps/s"
             f" (err {cell['state_rel_err']:.1e},"
             f" resid {cell['achieved_residual']})")
    set_fusion("auto")
    # best NEW cell at unchanged f64-class accuracy (the >=1.15x bar),
    # and the best f32 refinement-ladder cell (the <=1e-10 bar)
    accurate = [c for c in sweep if not c.get("baseline")
                and c["finite"] and c["state_rel_err"] <= F64_CLASS]
    best = max(accurate, key=lambda c: c["steps_per_sec"], default=None)
    ladder_cells = [c for c in sweep if c["solve_dtype"] == "f32"
                    and c["finite"]]
    ladder = max(ladder_cells, key=lambda c: c["steps_per_sec"],
                 default=None)
    import jax as _jax
    row = {
        "config": f"{name}_solvecomp",
        "benchmark": "solvecomp",
        "backend": _jax.default_backend(),
        "dtype": str(np.dtype(dtype)),
        "baseline_steps_per_sec": base["steps_per_sec"],
        "sweep": sweep,
        "best_f64_accurate": None if best is None else {
            "composition": best["composition"],
            "solve_dtype": best["solve_dtype"],
            "steps_per_sec": best["steps_per_sec"],
            "speedup": best["speedup"],
            "state_rel_err": best["state_rel_err"],
        },
        "meets_1p15x": bool(best is not None
                            and best.get("speedup", 0.0) >= 1.15),
        "ladder": None if ladder is None else {
            "composition": ladder["composition"],
            "solve_dtype": ladder["solve_dtype"],
            "steps_per_sec": ladder["steps_per_sec"],
            "speedup": ladder.get("speedup"),
            "state_rel_err": ladder["state_rel_err"],
            "refine_sweeps": ladder["refine_sweeps"],
            "achieved_residual": ladder["achieved_residual"],
        },
        "ladder_meets_1e10": bool(ladder is not None
                                  and ladder["state_rel_err"] <= 1e-10),
        "trajectory_steps": n_steps,
        "finite": all(c["finite"] for c in sweep),
        # baseline cell's resolved plan (per-cell compositions live in
        # the sweep itself)
        "plan": base_plan,
        "ts": round(time.time(), 1),
    }
    print(json.dumps(row), flush=True)
    return row


def main():
    quick = "--quick" in sys.argv
    from __graft_entry__ import _append_result
    if quick:
        _append_result = lambda record: None  # noqa: E731, F841
    import numpy as np  # noqa: F401,F811
    import jax
    dtype = np.float64 if jax.default_backend() == "cpu" else np.float32
    n_steps = 8 if quick else 20
    rows = [
        run_case("diffusion64",
                 lambda: build_diffusion(64, dtype),
                 dtype, n_steps, block=32 if quick else 200,
                 blocks=3 if quick else 7),
        run_case("rb256x64",
                 lambda: build_rb(dtype),
                 dtype, n_steps, block=8 if quick else 30,
                 blocks=3 if quick else 7),
    ]
    solve_rows = [
        run_solve_sweep("diffusion64",
                        lambda: build_diffusion(64, dtype),
                        dtype, n_steps, block=32 if quick else 200,
                        blocks=3 if quick else 7),
        run_solve_sweep("rb256x64",
                        lambda: build_rb(dtype),
                        dtype, n_steps, block=8 if quick else 20,
                        blocks=3 if quick else 5),
    ]
    ok = True
    for row in rows:
        if not row["finite"] or row["state_rel_diff"] > 1e-6:
            mark(f"FAIL: {row['config']} non-finite or fused trajectory "
                 f"off ({row['state_rel_diff']:.3e}); rows not recorded")
            ok = False
    for row in solve_rows:
        if not row["finite"]:
            mark(f"FAIL: {row['config']} non-finite; rows not recorded")
            ok = False
    if ok:
        for row in rows + solve_rows:
            _append_result(row)
    rb = rows[1]
    rb_solve = solve_rows[1]
    if not ok:
        sys.exit(1)
    if not rb["meets_1p15x"]:
        mark(f"FAIL: rb256x64 fusion speedup {rb['fusion_speedup']}x "
             "< 1.15x bar")
        sys.exit(1)
    if not rb_solve["meets_1p15x"]:
        best = rb_solve.get("best_f64_accurate")
        mark(f"FAIL: rb256x64 best f64-accurate solve composition "
             f"{best and best['speedup']}x < 1.15x bar")
        sys.exit(1)
    if not rb_solve["ladder_meets_1e10"]:
        ladder = rb_solve.get("ladder")
        mark(f"FAIL: rb256x64 f32 refinement ladder state error "
             f"{ladder and ladder['state_rel_err']} > 1e-10 bar")
        sys.exit(1)


if __name__ == "__main__":
    main()
