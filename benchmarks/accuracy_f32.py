"""
f32-vs-f64 accuracy study (BASELINE.md demands "identical spectral
convergence"; the TPU path runs f32, the CPU reference f64 — this script
prices that dtype change independently of hardware, on one backend).

Cases:
  1. Heat-equation decay vs EXACT solution at f64 and f32 (spectral +
     temporal convergence: the error floor shows the dtype's accuracy
     ceiling, the dt-sweep shows when truncation dominates roundoff).
  2. KdV-Burgers soliton: f32 state vs f64 state over 1000 steps
     (nonlinear cascade sensitivity), plus mass conservation drift.
  3. RB 256x64: f32 vs f64 buoyancy field over 500 steps from identical
     initial conditions; max relative state divergence and the total
     kinetic-energy trace difference.

Emits one JSON line per case (appended to benchmarks/results.jsonl by
--record) and a markdown table on stdout for BENCHMARKS.md.

Run: python benchmarks/accuracy_f32.py [--record]
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = os.environ.get("ACC_PLATFORM", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

if os.environ.get("ACC_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

T0 = time.time()
RESULTS = []


def mark(msg):
    print(f"[acc {time.time() - T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def heat_decay_error(dtype, N=64, dt_=1e-3, steps=200, k=3):
    """Max error vs exact exp(-k^2 t) decay (RK443)."""
    import dedalus_tpu.public as d3
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=dtype)
    xb = d3.RealFourier(xc, size=N, bounds=(0, 2 * np.pi), dealias=3 / 2)
    u = dist.Field(name="u", bases=xb)
    problem = d3.IVP([u], namespace=locals())
    problem.add_equation("dt(u) - lap(u) = 0")
    x = dist.local_grid(xb)
    u["g"] = np.sin(k * x).astype(dtype)
    solver = problem.build_solver(d3.RK443)
    for _ in range(steps):
        solver.step(dt_)
    exact = np.sin(k * x) * np.exp(-k * k * solver.sim_time)
    return float(np.abs(np.asarray(u["g"]) - exact).max())


def kdv_divergence(N=256, steps=1000, dt_=2e-3):
    """f32 vs f64 KdV-Burgers state divergence + mass drift."""
    import dedalus_tpu.public as d3

    def run(dtype):
        xc = d3.Coordinate("x")
        dist = d3.Distributor(xc, dtype=dtype)
        xb = d3.RealFourier(xc, size=N, bounds=(0, 10), dealias=3 / 2)
        u = dist.Field(name="u", bases=xb)
        a, bb = 1e-4, 2e-4
        dx = lambda A: d3.Differentiate(A, xc)
        problem = d3.IVP([u], namespace=locals())
        problem.add_equation(
            "dt(u) - a*dx(dx(u)) - bb*dx(dx(dx(u))) = - u*dx(u)")
        solver = problem.build_solver(d3.SBDF2)
        x = dist.local_grids(xb)[0]
        n = 20
        u["g"] = (np.log(1 + np.cosh(n) ** 2 / np.cosh(n * (x - 3)) ** 2)
                  / (2 * n)).astype(dtype)
        m0 = float(np.sum(np.asarray(u["g"], dtype=np.float64)))
        for _ in range(steps):
            solver.step(dt_)
        g = np.asarray(u["g"], dtype=np.float64)
        m1 = float(np.sum(g))
        return g, abs(m1 - m0) / abs(m0)

    g64, drift64 = run(np.float64)
    g32, drift32 = run(np.float32)
    scale = np.abs(g64).max()
    return float(np.abs(g64 - g32).max() / scale), drift64, drift32


def rb_divergence(Nx=256, Nz=64, steps=500, dt=0.01):
    """f32 vs f64 RB buoyancy divergence + KE-trace difference."""
    from __graft_entry__ import _build_rb_solver
    import dedalus_tpu.public as d3

    def run(dtype):
        solver, b = _build_rb_solver(Nx, Nz, dtype)
        u = solver.problem.namespace["u"] if hasattr(solver.problem, "namespace") else None
        ke = []
        for i in range(steps):
            solver.step(dt)
        bg = np.asarray(b["g"], dtype=np.float64)
        X = np.asarray(solver.X, dtype=np.float64)
        return bg, X

    b64, X64 = run(np.float64)
    b32, X32 = run(np.float32)
    bscale = np.abs(b64).max()
    Xscale = np.abs(X64).max()
    return (float(np.abs(b64 - b32).max() / bscale),
            float(np.abs(X64 - X32).max() / Xscale))


def main():
    record = "--record" in sys.argv
    rows = []

    mark("heat decay f64/f32")
    e64 = heat_decay_error(np.float64)
    e32 = heat_decay_error(np.float32)
    rows.append(("heat decay vs exact (RK443, 200 steps)", e64, e32))
    RESULTS.append({"case": "accuracy_heat_exact", "err_f64": e64,
                    "err_f32": e32})

    mark("kdv divergence (1000 steps)")
    div, drift64, drift32 = kdv_divergence()
    rows.append(("KdV f32-vs-f64 state (rel, 1000 steps)", "-", div))
    rows.append(("KdV mass drift (rel)", drift64, drift32))
    RESULTS.append({"case": "accuracy_kdv", "state_rel_div_f32": div,
                    "mass_drift_f64": drift64, "mass_drift_f32": drift32})

    mark("RB 256x64 divergence (500 steps)")
    bdiv, xdiv = rb_divergence()
    rows.append(("RB 256x64 f32-vs-f64 buoyancy (rel, 500 steps)", "-", bdiv))
    rows.append(("RB 256x64 f32-vs-f64 state (rel)", "-", xdiv))
    RESULTS.append({"case": "accuracy_rb256", "b_rel_div_f32": bdiv,
                    "state_rel_div_f32": xdiv})

    print("\n| Case | f64 | f32 |")
    print("|---|---|---|")
    for name, a, b in rows:
        fa = a if isinstance(a, str) else f"{a:.2e}"
        fb = b if isinstance(b, str) else f"{b:.2e}"
        print(f"| {name} | {fa} | {fb} |")
    for r in RESULTS:
        r["backend"] = jax.default_backend()
        print(json.dumps(r))
    if record:
        from __graft_entry__ import _append_result
        for r in RESULTS:
            _append_result(r)
    mark("done")


if __name__ == "__main__":
    main()
