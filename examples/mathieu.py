"""
Mathieu-equation characteristic values (reference:
examples/evp_1d_mathieu/mathieu_evp.py): a periodic EVP with a
nonconstant coefficient on a ComplexFourier basis,
    dx(dx(y)) + (a - 2*q*cos(2x)) * y = 0,  x in [0, 2*pi),
swept over the parameter q with matrix rebuilds (the cos(2x) NCC couples
Fourier modes, so each q gives a fresh pencil matrix).

At q=0 the spectrum is the plain Fourier one (n^2, doubly degenerate);
at q=5 the lowest characteristic values interleave even/odd families:
a_0 ~ -5.80004602, b_1 ~ -5.79008060, a_1 ~ 1.85818754, b_2 ~ 2.09946045
(Abramowitz & Stegun ch. 20).

Run: python examples/mathieu.py [--quick]
"""

import sys

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
N = 32
quick = "--quick" in sys.argv
q_list = np.linspace(0, 30, 8 if quick else 100)
dtype = np.complex128

# Basis
xcoord = d3.Coordinate('x')
dist = d3.Distributor(xcoord, dtype=dtype)
xbasis = d3.ComplexFourier(xcoord, size=N, bounds=(0, 2 * np.pi))
x = dist.local_grids(xbasis)[0]

# Fields
y = dist.Field(name='y', bases=xbasis)
a = dist.Field(name='a')
q = dist.Field(name='q')
cos_2x = dist.Field(name='cos_2x', bases=xbasis)
cos_2x['g'] = np.cos(2 * x)
dx = lambda A: d3.Differentiate(A, xcoord)

# Problem
problem = d3.EVP([y], eigenvalue=a, namespace=locals())
problem.add_equation("dx(dx(y)) + (a - 2*q*cos_2x)*y = 0")
solver = problem.build_solver()

# Parameter sweep: q enters the LHS as an NCC, so the pencil matrices are
# reassembled at each step (solve_dense(rebuild_matrices=True))
evals = []
for qi in q_list:
    q['g'] = qi
    solver.solve_dense(solver.subproblems[0], rebuild_matrices=True)
    evals.append(np.sort(solver.eigenvalues.real)[:10])
evals = np.array(evals)
logger.info(f"q={q_list[0]:.1f}: a[:4] = {evals[0][:4]}")
logger.info(f"q={q_list[-1]:.1f}: a[:4] = {evals[-1][:4]}")

if __name__ == "__main__" and not quick:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig = plt.figure(figsize=(6, 4))
    plt.plot(q_list, evals[:, 0::2], '.-', c='C0')
    plt.plot(q_list, evals[:, 1::2], '.-', c='C1')
    plt.xlim(q_list.min(), q_list.max())
    plt.ylim(-10, 30)
    plt.xlabel("q")
    plt.ylabel("characteristic value a")
    plt.title("Mathieu characteristic values")
    plt.tight_layout()
    plt.savefig("mathieu_eigenvalues.png", dpi=200)
