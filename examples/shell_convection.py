"""
Boussinesq convection in a spherical shell (first-order tau formulation)
(reference example: examples/ivp_shell_convection/shell_convection.py).

Non-dimensionalized with the shell thickness and freefall time:
    kappa = (Rayleigh * Prandtl)**(-1/2)
    nu = (Rayleigh / Prandtl)**(-1/2)

Run directly: python examples/shell_convection.py [--quick]
"""

import sys
import logging
import numpy as np

import dedalus_tpu.public as d3

logging.basicConfig(level=logging.INFO)
logger = logging.getLogger(__name__)

# Parameters (reference: shell_convection.py:44-50; reduced default size)
quick = "--quick" in sys.argv
Ri, Ro = 14.0, 15.0
Nphi, Ntheta, Nr = (16, 8, 6) if quick else (96, 48, 6)
Rayleigh = 3500
Prandtl = 1
dealias = 3 / 2
stop_iteration = 20 if quick else 400
timestep = 0.05
dtype = np.float64

# Bases
coords = d3.SphericalCoordinates("phi", "theta", "r")
dist = d3.Distributor(coords, dtype=dtype)
shell = d3.ShellBasis(coords, shape=(Nphi, Ntheta, Nr), radii=(Ri, Ro),
                      dealias=dealias, dtype=dtype)
sphere = shell.outer_surface

# Fields
p = dist.Field(name="p", bases=shell)
b = dist.Field(name="b", bases=shell)
u = dist.VectorField(coords, name="u", bases=shell)
tau_p = dist.Field(name="tau_p")
tau_b1 = dist.Field(name="tau_b1", bases=sphere)
tau_b2 = dist.Field(name="tau_b2", bases=sphere)
tau_u1 = dist.VectorField(coords, name="tau_u1", bases=sphere)
tau_u2 = dist.VectorField(coords, name="tau_u2", bases=sphere)

# Substitutions
kappa = (Rayleigh * Prandtl) ** (-1 / 2)
nu = (Rayleigh / Prandtl) ** (-1 / 2)
phi, theta, r = dist.local_grids(shell)
er = dist.VectorField(coords, name="er", bases=shell)
er["g"][2] = 1.0
rvec = dist.VectorField(coords, name="rvec", bases=shell)
rvec["g"][2] = np.broadcast_to(np.asarray(r), np.asarray(er["g"])[2].shape)
lift_basis = shell.derivative_basis(1)
lift = lambda A: d3.Lift(A, lift_basis, -1)
grad_u = d3.grad(u) + rvec * lift(tau_u1)  # First-order reduction
grad_b = d3.grad(b) + rvec * lift(tau_b1)

# Problem (reference: shell_convection.py:76-87)
problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                 namespace=locals())
problem.add_equation("trace(grad_u) + tau_p = 0")
problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
problem.add_equation("dt(u) - nu*div(grad_u) + grad(p) - b*er + lift(tau_u2) = - u@grad(u)")
problem.add_equation("b(r=Ri) = 1")
problem.add_equation("u(r=Ri) = 0")
problem.add_equation("b(r=Ro) = 0")
problem.add_equation("u(r=Ro) = 0")
problem.add_equation("integ(p) = 0")

# Solver
solver = problem.build_solver(d3.SBDF2)
solver.stop_iteration = stop_iteration

# Initial conditions: conductive profile + noise
b.fill_random("g", seed=42, distribution="normal", scale=1e-3)
b["g"] += (Ri - Ri * Ro / np.asarray(r)) / (Ri - Ro)

# Analysis
flow = d3.GlobalFlowProperty(solver, cadence=10)
flow.add_property(u @ u, name="u2")

# Main loop
if __name__ == "__main__":
    try:
        while solver.proceed:
            solver.step(timestep)
            if solver.iteration % 10 == 0:
                max_u2 = flow.max("u2")
                logger.info(f"Iteration={solver.iteration}, Time={solver.sim_time:.3f}, "
                            f"max(u2)={max_u2:.3e}")
    finally:
        solver.log_stats()
