"""
Librational instability in a disk: incompressible Navier-Stokes linearized
around a background librating flow (reference:
examples/ivp_disk_libration/libration.py). Demonstrates a disk IVP with a
time-dependent background entering through the parsing namespace.

Run: python examples/libration.py
"""

import numpy as np
import dedalus_tpu.public as d3
from scipy.special import jv
import logging
logger = logging.getLogger(__name__)

# Parameters (reference: libration.py:31-38)
Nphi, Nr = 32, 128
Ekman = 1 / 2 / 20 ** 2
Ro = 40
dealias = 3 / 2
stop_sim_time = 50
timestepper = d3.SBDF2
timestep = 1e-3
dtype = np.float64

# Bases
coords = d3.PolarCoordinates('phi', 'r')
dist = d3.Distributor(coords, dtype=dtype)
disk = d3.DiskBasis(coords, shape=(Nphi, Nr), radius=1, dealias=dealias,
                    dtype=dtype)
edge = disk.edge

# Fields
u = dist.VectorField(coords, name='u', bases=disk)
p = dist.Field(name='p', bases=disk)
tau_u = dist.VectorField(coords, name='tau_u', bases=edge)
tau_p = dist.Field(name='tau_p')

# Substitutions
phi, r = dist.local_grids(disk)
nu = Ekman
lift = lambda A: d3.Lift(A, disk, -1)

# Background librating flow (reference: libration.py:57-63)
u0_real = dist.VectorField(coords, bases=disk)
u0_imag = dist.VectorField(coords, bases=disk)
u0_real['g'][0] = Ro * np.real(jv(1, (1 - 1j) * r / np.sqrt(2 * Ekman))
                               / jv(1, (1 - 1j) / np.sqrt(2 * Ekman)))
u0_imag['g'][0] = Ro * np.imag(jv(1, (1 - 1j) * r / np.sqrt(2 * Ekman))
                               / jv(1, (1 - 1j) / np.sqrt(2 * Ekman)))
t = dist.Field()
u0 = np.cos(t) * u0_real - np.sin(t) * u0_imag

# Problem
problem = d3.IVP([p, u, tau_u, tau_p], time=t, namespace=locals())
problem.add_equation("div(u) + tau_p = 0")
problem.add_equation(
    "dt(u) - nu*lap(u) + grad(p) + lift(tau_u) = - u@grad(u0) - u0@grad(u)")
problem.add_equation("u(r=1) = 0")
problem.add_equation("integ(p) = 0")

# Solver
solver = problem.build_solver(timestepper)
solver.stop_sim_time = stop_sim_time

# Initial conditions
u.fill_random('g', seed=42, distribution='normal')
u.low_pass_filter(scales=0.25)

# Analysis
snapshots = solver.evaluator.add_file_handler('snapshots_libration',
                                              sim_dt=0.1, max_writes=10)
snapshots.add_task(-d3.div(d3.skew(u)), name='vorticity')
flow = d3.GlobalFlowProperty(solver, cadence=10)
flow.add_property(u @ u, name='u2')

# Main loop
if __name__ == "__main__":
    try:
        logger.info('Starting main loop')
        while solver.proceed:
            solver.step(timestep)
            if (solver.iteration - 1) % 10 == 0:
                max_u = np.sqrt(flow.max('u2'))
                logger.info(f"Iteration={solver.iteration}, "
                            f"Time={solver.sim_time:.3f}, dt={timestep:.3e}, "
                            f"max(u)={max_u:.3e}")
    except Exception:
        logger.error('Exception raised, triggering end of main loop.')
        raise
    finally:
        solver.log_stats()
