"""
Linear growth rates of no-slip Rayleigh-Benard convection over a range of
horizontal wavenumbers (reference:
examples/evp_1d_rayleigh_benard/rayleigh_benard_evp.py): a 1D sparse EVP
per kx, with dt -> -i*omega*... and a two-mode ComplexFourier carrier
whose fundamental IS the target wavenumber.

Physics check: the critical point of no-slip RB is Ra_c ~ 1707.762 at
kx_c ~ 3.117 — at Ra = 1710 the peak growth rate is barely positive.

Run: python examples/rayleigh_benard_evp.py [--quick]
"""

import sys

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)


def max_growth_rate(Rayleigh, Prandtl, kx, Nz, NEV=10, target=0):
    """Largest Im(omega) over NEV eigenvalues near `target`."""
    Lz = 1
    # minimal Fourier carrier whose k=+1 group is the prescribed kx
    # fundamental (size 4: the Nyquist slot is invalid here, so size 2
    # would leave no valid nonzero mode)
    Nx = 4
    Lx = 2 * np.pi / kx
    coords = d3.CartesianCoordinates('x', 'z')
    dist = d3.Distributor(coords, dtype=np.complex128)
    xbasis = d3.ComplexFourier(coords['x'], size=Nx, bounds=(0, Lx))
    zbasis = d3.ChebyshevT(coords['z'], size=Nz, bounds=(0, Lz))
    omega = dist.Field(name='omega')
    p = dist.Field(name='p', bases=(xbasis, zbasis))
    b = dist.Field(name='b', bases=(xbasis, zbasis))
    u = dist.VectorField(coords, name='u', bases=(xbasis, zbasis))
    tau_p = dist.Field(name='tau_p')
    tau_b1 = dist.Field(name='tau_b1', bases=xbasis)
    tau_b2 = dist.Field(name='tau_b2', bases=xbasis)
    tau_u1 = dist.VectorField(coords, name='tau_u1', bases=xbasis)
    tau_u2 = dist.VectorField(coords, name='tau_u2', bases=xbasis)
    kappa = (Rayleigh * Prandtl) ** (-1 / 2)
    nu = (Rayleigh / Prandtl) ** (-1 / 2)
    x, z = dist.local_grids(xbasis, zbasis)
    ex, ez = coords.unit_vector_fields(dist)
    lift_basis = zbasis.derivative_basis(1)
    lift = lambda A: d3.Lift(A, lift_basis, -1)
    grad_u = d3.grad(u) + ez * lift(tau_u1)
    grad_b = d3.grad(b) + ez * lift(tau_b1)
    dt = lambda A: -1j * omega * A
    problem = d3.EVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                     eigenvalue=omega, namespace=locals())
    problem.add_equation("trace(grad_u) + tau_p = 0")
    problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) - ez@u = 0")
    problem.add_equation("dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = 0")
    problem.add_equation("b(z=0) = 0")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("b(z=Lz) = 0")
    problem.add_equation("u(z=Lz) = 0")
    problem.add_equation("integ(p) = 0")
    solver = problem.build_solver()
    # group 1 = the kx fundamental (group 0 is the mean mode)
    sp = solver.subproblems_by_group[(1, None)]
    solver.solve_sparse(sp, NEV, target=target)
    return np.max(solver.eigenvalues.imag)


if __name__ == "__main__":
    quick = "--quick" in sys.argv
    Nz = 32 if quick else 64
    Rayleigh = 1710
    Prandtl = 1
    kx_list = np.linspace(3.0, 3.25, 5 if quick else 50)
    rates = np.array([max_growth_rate(Rayleigh, Prandtl, kx, Nz)
                      for kx in kx_list])
    for kx, rate in zip(kx_list, rates):
        logger.info(f"kx = {kx:.4f}: max growth rate = {rate:+.6f}")
    print(f"peak growth {rates.max():+.6f} at kx = {kx_list[np.argmax(rates)]:.4f}")
    assert rates.max() > 0, "Ra=1710 should be slightly supercritical"
    if not quick:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        plt.figure(figsize=(6, 4))
        plt.plot(kx_list, rates, '.-')
        plt.axhline(0, c='k', lw=0.5)
        plt.xlabel("kx")
        plt.ylabel("max Im(omega)")
        plt.title(f"RB growth rates (Ra={Rayleigh}, Pr={Prandtl})")
        plt.tight_layout()
        plt.savefig("rb_growth_rates.png", dpi=200)
