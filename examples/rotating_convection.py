"""
Linear stability eigenvalue problem for rotating Rayleigh-Benard
convection in a shell — the canonical colatitude-dependent-NCC problem:
the Coriolis vector ez = cos(theta) er - sin(theta) etheta varies along
theta, coupling spherical-harmonic degrees so each pencil spans all ell
at fixed azimuthal order m (reference:
examples/evp_shell_rotating_convection/rotating_convection.py; eigenvalue
targets from Marti, Calkins & Julien, G^3 2016, Table 1).

API-parity port of the reference script: the parameter block, field
names, and equation strings mirror the reference so d3 user scripts
translate unchanged; the solver machinery underneath is the TPU-native
ell-coupled assembly (dedalus_tpu/core/arithmetic.py
_sph_coupled_ncc_matrix) with lazy per-m sparse eigensolves.

Run: python examples/rotating_convection.py [--quick]
"""

import sys

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

quick = "--quick" in sys.argv

# Parameters (reference: rotating_convection.py:36-52)
Nphi = 28  # Critical mode has m=13
Ntheta = 32 if quick else 64
Nr = 32 if quick else 64
Ri = 0.35
Ro = 1
Prandtl = 1
Ekman = 1e-5
stress_free = True
dtype = np.complex128

# Critical Rayleigh numbers
if stress_free:
    Rayleigh = 2.1029e7
else:
    Rayleigh = 2.0732e7

# Bases
coords = d3.SphericalCoordinates('phi', 'theta', 'r')
dist = d3.Distributor(coords, dtype=dtype)
shell = d3.ShellBasis(coords, shape=(Nphi, Ntheta, Nr), radii=(Ri, Ro),
                      dtype=dtype)
sphere = shell.outer_surface
phi, theta, r = dist.local_grids(shell)

# Fields
om = dist.Field(name='om')
u = dist.VectorField(coords, name='u', bases=shell)
p = dist.Field(name='p', bases=shell)
T = dist.Field(name='T', bases=shell)
tau_u1 = dist.VectorField(coords, bases=sphere)
tau_u2 = dist.VectorField(coords, bases=sphere)
tau_T1 = dist.Field(bases=sphere)
tau_T2 = dist.Field(bases=sphere)
tau_p = dist.Field()

# Substitutions
dt = lambda A: -1j*om*A
rvec = dist.VectorField(coords, bases=shell.meridional_basis)
rvec['g'][2] = np.broadcast_to(r, rvec['g'][2].shape)
ez = dist.VectorField(coords, bases=shell.meridional_basis)
ez['g'][1] = -np.sin(theta)
ez['g'][2] = np.cos(theta)
lift_basis = shell.derivative_basis(1)
lift = lambda A: d3.Lift(A, lift_basis, -1)
grad_u = d3.grad(u) + rvec*lift(tau_u1)  # First-order reduction
grad_T = d3.grad(T) + rvec*lift(tau_T1)  # First-order reduction
strain_rate = d3.grad(u) + d3.transpose(d3.grad(u))

# Problem (reference: rotating_convection.py:89-105)
problem = d3.EVP([p, u, T, tau_u1, tau_u2, tau_T1, tau_T2, tau_p],
                 eigenvalue=om, namespace=locals())
problem.add_equation("trace(grad_u) + tau_p = 0")
problem.add_equation("dt(u) + (1/Ekman)*cross(ez, u) + grad(p) "
                     "- Rayleigh*T*rvec - div(grad_u) + lift(tau_u2) = 0")
problem.add_equation("Prandtl*dt(T) - dot(rvec,u) - div(grad_T) "
                     "+ lift(tau_T2) = 0")
if stress_free:
    problem.add_equation("radial(u(r=Ri)) = 0")
    problem.add_equation("radial(u(r=Ro)) = 0")
    problem.add_equation("angular(radial(strain_rate(r=Ri), 0), 0) = 0")
    problem.add_equation("angular(radial(strain_rate(r=Ro), 0), 0) = 0")
else:
    problem.add_equation("u(r=Ri) = 0")
    problem.add_equation("u(r=Ro) = 0")
problem.add_equation("T(r=Ri) = 0")
problem.add_equation("T(r=Ro) = 0")
problem.add_equation("integ(p) = 0")

# Solver
solver = problem.build_solver(ncc_cutoff=1e-10)

if __name__ == "__main__":
    # Select m=13 (group index = m for non-negative m in fftfreq order)
    subproblem = solver.subproblems_by_group[(13, None, None)]

    # Find 10 eigenvalues closest to the target
    if stress_free:
        target = 963.765
    else:
        target = 731.753
    solver.solve_sparse(subproblem, 10, target)

    logger.info(f"Predicted eigenvalue: {target+0j:f}")
    logger.info(f"Calculated eigenvalue: {solver.eigenvalues[0]:f}")
    logger.info("Ten eigenvalues closest to target:")
    logger.info(solver.eigenvalues)
    print("closest eigenvalue:", solver.eigenvalues[0])
