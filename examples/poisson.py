"""
2D Poisson LBVP with mixed boundary conditions (reference:
examples/lbvp_2d_poisson/poisson.py):
    lap(u) = f,  u(y=0) = g,  dy(u)(y=Ly) = h.

Run: python examples/poisson.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
Lx, Ly = 2 * np.pi, np.pi
Nx, Ny = 256, 128
dtype = np.float64

# Bases
coords = d3.CartesianCoordinates('x', 'y')
dist = d3.Distributor(coords, dtype=dtype)
xbasis = d3.RealFourier(coords['x'], size=Nx, bounds=(0, Lx))
ybasis = d3.ChebyshevT(coords['y'], size=Ny, bounds=(0, Ly))

# Fields
u = dist.Field(name='u', bases=(xbasis, ybasis))
tau_1 = dist.Field(name='tau_1', bases=xbasis)
tau_2 = dist.Field(name='tau_2', bases=xbasis)

# Forcing
x, y = dist.local_grids(xbasis, ybasis)
f = dist.Field(name='f', bases=(xbasis, ybasis))
g = dist.Field(name='g', bases=xbasis)
h = dist.Field(name='h', bases=xbasis)
f.fill_random('g', seed=40)
f.low_pass_filter(shape=(64, 32))
g['g'] = np.sin(8 * x) * 0.025
h['g'] = 0

# Substitutions
dy = lambda A: d3.Differentiate(A, coords['y'])
lift_basis = ybasis.derivative_basis(2)
lift = lambda A, n: d3.Lift(A, lift_basis, n)

# Problem
problem = d3.LBVP([u, tau_1, tau_2], namespace=locals())
problem.add_equation("lap(u) + lift(tau_1,-1) + lift(tau_2,-2) = f")
problem.add_equation("u(y=0) = g")
problem.add_equation("dy(u)(y=Ly) = h")

# Solver
solver = problem.build_solver()
solver.solve()

if __name__ == "__main__":
    ug = np.asarray(u['g'])
    logger.info(f"Solved Poisson: u range [{ug.min():.4f}, {ug.max():.4f}]")
    bc_err = np.abs(np.asarray(u(y=0).evaluate()['g']) - np.asarray(g['g'])).max()
    logger.info(f"Boundary error |u(y=0) - g|: {bc_err:.2e}")
