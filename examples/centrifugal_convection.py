"""
2D centrifugal convection in an annulus (reference example:
examples/ivp_annulus_centrifugal_convection/centrifugal_convection.py):
buoyancy driven radially outward (centrifugal gravity ~ r), heated outer
wall, with analysis outputs, CFL-adaptive stepping, and flow diagnostics.

Non-dimensionalized with the mean radius L = (Ri + Ro)/2 and freefall
time:
    kappa = (Rayleigh * Prandtl)**(-1/2)
    nu = (Rayleigh / Prandtl)**(-1/2)

Formulation note: the reference uses a first-order tau reduction with a
radial-vector lift (rvec*lift(tau)); here the second-order form with two
lift levels is used instead (capability-equivalent; polar tensor-valued
LHS NCCs are not implemented yet).

Run directly: python examples/centrifugal_convection.py [--quick]
"""

import sys
import logging
import numpy as np

import dedalus_tpu.public as d3

logging.basicConfig(level=logging.INFO)
logger = logging.getLogger(__name__)

# Parameters (reference: centrifugal_convection.py:36-46; reduced default)
quick = "--quick" in sys.argv
Nphi, Nr = (32, 16) if quick else (256, 64)
eta = 3
Rayleigh = 1e6
Prandtl = 1
dealias = 3 / 2
stop_iteration = 10 if quick else 2000
max_timestep = 0.125
dtype = np.float64

# Derived parameters: radii with mean radius 1
Ri = 2 / (1 + eta)
Ro = 2 * eta / (1 + eta)

# Bases
coords = d3.PolarCoordinates("phi", "r")
dist = d3.Distributor(coords, dtype=dtype)
annulus = d3.AnnulusBasis(coords, shape=(Nphi, Nr), radii=(Ri, Ro),
                          dealias=dealias, dtype=dtype)
edge = annulus.outer_edge

# Fields
p = dist.Field(name="p", bases=annulus)
b = dist.Field(name="b", bases=annulus)
u = dist.VectorField(coords, name="u", bases=annulus)
tau_p = dist.Field(name="tau_p")
tau_b1 = dist.Field(name="tau_b1", bases=edge)
tau_b2 = dist.Field(name="tau_b2", bases=edge)
tau_u1 = dist.VectorField(coords, name="tau_u1", bases=edge)
tau_u2 = dist.VectorField(coords, name="tau_u2", bases=edge)

# Substitutions
kappa = (Rayleigh * Prandtl) ** (-1 / 2)
nu = (Rayleigh / Prandtl) ** (-1 / 2)
phi, r = dist.local_grids(annulus)
rvec = dist.VectorField(coords, name="rvec", bases=annulus)
rvec["g"][1] = np.broadcast_to(np.asarray(r), rvec["g"][1].shape)
lift_basis = annulus.derivative_basis(2)
lift = lambda A, n: d3.Lift(A, lift_basis, n)
gravity = 2 * (eta - 1) / (eta + 1)
g = gravity * rvec

# Problem
problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                 namespace=locals())
problem.add_equation("div(u) + tau_p = 0")
problem.add_equation("dt(b) - kappa*lap(b) + lift(tau_b1, -1) + lift(tau_b2, -2) = - u@grad(b)")
problem.add_equation("dt(u) - nu*lap(u) + grad(p) + b*g + lift(tau_u1, -1) + lift(tau_u2, -2) = - u@grad(u)")
problem.add_equation("b(r=Ri) = 0")
problem.add_equation("u(r=Ri) = 0")
problem.add_equation("b(r=Ro) = 1")
problem.add_equation("u(r=Ro) = 0")
problem.add_equation("integ(p) = 0")  # Pressure gauge

# Solver
solver = problem.build_solver(d3.RK222)
solver.stop_iteration = stop_iteration

# Initial conditions: damped noise plus the conductive profile
b.fill_random("g", seed=42, distribution="normal", scale=1e-3)
b["g"] *= (r - Ri) * (Ro - r)
b["g"] += np.log(r / Ri) / np.log(Ro / Ri)

# Analysis
if not quick:
    snapshots = solver.evaluator.add_file_handler("snapshots", sim_dt=0.1,
                                                  max_writes=20)
    snapshots.add_task(-d3.div(d3.skew(u)), name="vorticity")
    snapshots.add_task(b, name="buoyancy")
    scalars = solver.evaluator.add_file_handler("scalars", sim_dt=0.01)
    scalars.add_task(d3.integ(0.5 * u @ u), name="KE")

# CFL
CFL = d3.CFL(solver, initial_dt=max_timestep, max_dt=max_timestep, safety=0.5,
             cadence=10, threshold=0.1, max_change=1.5, min_change=0.5)
CFL.add_velocity(u)

# Flow properties
flow = d3.GlobalFlowProperty(solver, cadence=10)
flow.add_property(np.sqrt(u @ u) / nu, name="Re")


def main():
    logger.info("Starting main loop")
    try:
        while solver.proceed:
            timestep = CFL.compute_timestep()
            solver.step(timestep)
            if (solver.iteration - 1) % 10 == 0:
                logger.info(f"Iteration={solver.iteration}, "
                            f"Time={solver.sim_time:.3e}, dt={timestep:.3e}, "
                            f"max(Re)={flow.max('Re'):f}")
    finally:
        solver.log_stats()
    assert np.isfinite(np.asarray(solver.X)).all()


if __name__ == "__main__":
    main()
