"""
Lane-Emden equation in the ball (reference:
examples/nlbvp_ball_lane_emden/lane_emden.py): the structure of a
polytropic star,
    lap(f) = -f^n,  f(r=1) = 0,
solved as an NLBVP with floating amplitude; the stellar radius follows as
R = f(0)^((n-1)/2) and matches Boyd's reference values.

Run: python examples/lane_emden.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
Nr = 64
n = 3.0
tolerance = 1e-10
dealias = 2
dtype = np.float64

# Bases
coords = d3.SphericalCoordinates('phi', 'theta', 'r')
dist = d3.Distributor(coords, dtype=dtype)
ball = d3.BallBasis(coords, (4, 2, Nr), radius=1, dtype=dtype,
                    dealias=dealias)

# Fields
f = dist.Field(name='f', bases=ball)
tau = dist.Field(name='tau', bases=ball.surface)

# Substitutions
lift = lambda A: d3.Lift(A, ball, -1)

# Problem
problem = d3.NLBVP([f, tau], namespace=locals())
problem.add_equation("lap(f) + lift(tau) = - f**n")
problem.add_equation("f(r=1) = 0")

# Initial guess
phi, theta, r = dist.local_grids(ball)
R0 = 5
f['g'] = R0 ** (2 / (n - 1)) * (1 - r ** 2) ** 2

# Solver
solver = problem.build_solver()
pert_norm = np.inf
while pert_norm > tolerance:
    solver.newton_iteration()
    pert_norm = solver.perturbation_norm()
    f0 = np.asarray(d3.Interpolate(f, coords['r'], 0.0).evaluate()['g']).ravel()[0]
    Ri = f0 ** ((n - 1) / 2)
    logger.info(f'Perturbation norm: {pert_norm:.3e}; R iterate: {Ri:.10f}')

# Compare to reference solutions from Boyd
R_ref = {0.0: np.sqrt(6),
         0.5: 2.752698054065,
         1.0: np.pi,
         1.5: 3.65375373621912608,
         2.0: 4.3528745959461246769735700,
         2.5: 5.355275459010779,
         3.0: 6.896848619376960375454528,
         3.25: 8.018937527,
         3.5: 9.535805344244850444,
         4.0: 14.971546348838095097611066,
         4.5: 31.836463244694285264}

if __name__ == "__main__":
    logger.info('-' * 20)
    logger.info(f'Iterations: {solver.iteration}')
    logger.info(f'Final R iteration: {Ri}')
    if n in R_ref:
        logger.info(f'Error vs reference: {Ri - R_ref[n]:.3e}')
