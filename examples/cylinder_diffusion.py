"""
Heat diffusion in a periodic cylinder (DirectProduct geometry: Fourier z x
disk), with an exact Fourier-Bessel decay check.

The initial temperature J0(j01 r / R) cos(kz z) is an exact eigenmode of
the Laplacian with homogeneous edge conditions, decaying at rate
kz^2 + (j01 / R)^2 — the cylinder analogue of the reference's heat-equation
oracle tests (no reference example exists for cylinders; geometry from
reference tests/test_cylinder_calculus.py).

Run: python examples/cylinder_diffusion.py
"""

import pathlib
import sys

import numpy as np
from scipy.special import j0, jn_zeros

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
import jax  # noqa: E402

# f64 end-to-end (do NOT probe jax.default_backend() here: backend init can
# be slow on tunneled TPUs; x64 is safe everywhere and f64 Fourier paths
# route through MMT matmuls on TPU automatically)
jax.config.update("jax_enable_x64", True)
import dedalus_tpu.public as d3  # noqa: E402

# Parameters
length, radius = 2.0, 1.5
Nz, Nphi, Nr = 16, 16, 32
dtype = np.float64
timestep = 2e-4
stop_iteration = 200

# Bases
cz = d3.Coordinate("z")
cp = d3.PolarCoordinates("phi", "r")
coords = d3.DirectProduct(cz, cp)
dist = d3.Distributor(coords, dtype=dtype)
zbasis = d3.RealFourier(cz, size=Nz, bounds=(0, length), dealias=3 / 2)
disk = d3.DiskBasis(cp, shape=(Nphi, Nr), dtype=dtype, radius=radius,
                    dealias=3 / 2)

# Fields
u = dist.Field(name="u", bases=(zbasis, disk))
tau = dist.Field(name="tau", bases=(zbasis, disk.edge))

# Problem: dt(u) - lap(u) + lift(tau) = 0 with u(r=R) = 0
lift = lambda A: d3.Lift(A, disk, -1)
problem = d3.IVP([u, tau], namespace=locals())
problem.add_equation("dt(u) - lap(u) + lift(tau) = 0")
problem.add_equation(f"u(r={radius}) = 0")

# Initial condition: exact eigenmode
solver = problem.build_solver(d3.RK443)
solver.stop_iteration = stop_iteration
z, phi, r = dist.local_grids(zbasis, disk)
kz = 2 * np.pi / length
j01 = jn_zeros(0, 1)[0]
u["g"] = j0(j01 * r / radius) * np.cos(kz * z) + 0 * phi
u0 = np.asarray(u["g"]).copy()

# Main loop
solver.dt = timestep
solver.evolve(log_cadence=50)

# Check against the exact decay rate
rate = kz ** 2 + (j01 / radius) ** 2
exact = u0 * np.exp(-rate * solver.sim_time)
err = np.abs(np.asarray(u["g"]) - exact).max() / np.abs(u0).max()
print(f"t = {solver.sim_time:.4f}: max relative error vs exact decay "
      f"= {err:.3e}")
assert err < 1e-6
