"""
1D Korteweg-de Vries / Burgers equation
(reference: examples/ivp_1d_kdv_burgers/kdv_burgers.py).

    dt(u) + u*dx(u) = a*dx(dx(u)) + b*dx(dx(dx(u)))

Run: python examples/kdv_burgers.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
Lx = 10
Nx = 1024
a = 1e-4
b = 2e-4
dealias = 3/2
stop_sim_time = 10
timestepper = d3.SBDF2
timestep = 2e-3
dtype = np.float64

# Bases
xcoord = d3.Coordinate('x')
dist = d3.Distributor(xcoord, dtype=dtype)
xbasis = d3.RealFourier(xcoord, size=Nx, bounds=(0, Lx), dealias=dealias)

# Fields
u = dist.Field(name='u', bases=xbasis)

# Substitutions
dx = lambda A: d3.Differentiate(A, xcoord)

# Problem
problem = d3.IVP([u], namespace=locals())
problem.add_equation("dt(u) - a*dx(dx(u)) - b*dx(dx(dx(u))) = - u*dx(u)")

# Initial conditions
x = dist.local_grid(xbasis)
n = 20
u['g'] = np.log(1 + np.cosh(n)**2/np.cosh(n*(x-0.2*Lx))**2) / (2*n)

# Solver
solver = problem.build_solver(timestepper)
solver.stop_sim_time = stop_sim_time

# Main loop
if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    u.change_scales(1)
    u_list = [np.copy(u['g'])]
    t_list = [solver.sim_time]
    while solver.proceed:
        solver.step(timestep)
        if solver.iteration % 100 == 0:
            logger.info(f'Iteration={solver.iteration}, Time={solver.sim_time:.3e}, dt={timestep:.1e}')
        if solver.iteration % 25 == 0:
            u.change_scales(1)
            u_list.append(np.copy(u['g']))
            t_list.append(solver.sim_time)
    solver.log_stats()
