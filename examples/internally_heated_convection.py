"""
Internally-heated Boussinesq convection in a full ball with stress-free
boundary conditions (reference example:
examples/ivp_ball_internally_heated_convection/internally_heated_convection.py).

Run directly: python examples/internally_heated_convection.py [--quick]
"""

import sys
import logging
import numpy as np

import dedalus_tpu.public as d3

logging.basicConfig(level=logging.INFO)
logger = logging.getLogger(__name__)

# Parameters (reference: internally_heated_convection.py:44-52; reduced size)
quick = "--quick" in sys.argv
Nphi, Ntheta, Nr = (16, 8, 12) if quick else (64, 32, 48)
Rayleigh = 1e4
Prandtl = 1
dealias = 3 / 2
stop_iteration = 20 if quick else 400
timestep = 0.01
dtype = np.float64

# Bases
coords = d3.SphericalCoordinates("phi", "theta", "r")
dist = d3.Distributor(coords, dtype=dtype)
ball = d3.BallBasis(coords, shape=(Nphi, Ntheta, Nr), radius=1,
                    dealias=dealias, dtype=dtype)
sphere = ball.surface

# Fields
u = dist.VectorField(coords, name="u", bases=ball)
p = dist.Field(name="p", bases=ball)
T = dist.Field(name="T", bases=ball)
tau_p = dist.Field(name="tau_p")
tau_u = dist.VectorField(coords, name="tau_u", bases=sphere)
tau_T = dist.Field(name="tau_T", bases=sphere)

# Substitutions
phi, theta, r = dist.local_grids(ball)
r_vec = dist.VectorField(coords, name="r_vec", bases=ball)
r_vec["g"][2] = np.broadcast_to(np.asarray(r), np.asarray(r_vec["g"])[2].shape)
T_source = 6
kappa = (Rayleigh * Prandtl) ** (-1 / 2)
nu = (Rayleigh / Prandtl) ** (-1 / 2)
lift = lambda A: d3.Lift(A, ball, -1)
strain_rate = d3.grad(u) + d3.trans(d3.grad(u))
shear_stress = d3.angular(d3.radial(strain_rate(r=1), index=1))

# Problem (reference: internally_heated_convection.py:79-88)
problem = d3.IVP([p, u, T, tau_p, tau_u, tau_T], namespace=locals())
problem.add_equation("div(u) + tau_p = 0")
problem.add_equation("dt(u) - nu*lap(u) + grad(p) - r_vec*T + lift(tau_u) = - cross(curl(u),u)")
problem.add_equation("dt(T) - kappa*lap(T) + lift(tau_T) = - u@grad(T) + kappa*T_source")
problem.add_equation("shear_stress = 0")  # stress free
problem.add_equation("radial(u(r=1)) = 0")  # no penetration
problem.add_equation("T(r=1) = 0")
problem.add_equation("integ(p) = 0")  # pressure gauge

# Solver
solver = problem.build_solver(d3.SBDF2)
solver.stop_iteration = stop_iteration

# Initial conditions
T.fill_random("g", seed=42, distribution="normal", scale=0.01)
T["g"] += 1 - np.asarray(r) ** 2  # conductive profile for T_source = 6

# Main loop
flow = d3.GlobalFlowProperty(solver, cadence=10)
flow.add_property(u @ u, name="u2")
if __name__ == "__main__":
    try:
        while solver.proceed:
            solver.step(timestep)
            if solver.iteration % 10 == 0:
                logger.info(f"Iteration={solver.iteration}, Time={solver.sim_time:.3f}, "
                            f"max(u2)={flow.max('u2'):.3e}")
    finally:
        solver.log_stats()
