"""
2D doubly-periodic shear flow with a passive tracer
(reference: examples/ivp_2d_shear_flow/shear_flow.py).

Run: python examples/shear_flow.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
Lx, Lz = 1, 2
Nx, Nz = 128, 256
Reynolds = 5e4
Schmidt = 1
dealias = 3/2
stop_sim_time = 20
timestepper = d3.RK222
max_timestep = 1e-2
dtype = np.float64

# Bases
coords = d3.CartesianCoordinates('x', 'z')
dist = d3.Distributor(coords, dtype=dtype)
xbasis = d3.RealFourier(coords['x'], size=Nx, bounds=(0, Lx), dealias=dealias)
zbasis = d3.RealFourier(coords['z'], size=Nz, bounds=(-Lz/2, Lz/2), dealias=dealias)

# Fields
p = dist.Field(name='p', bases=(xbasis, zbasis))
s = dist.Field(name='s', bases=(xbasis, zbasis))
u = dist.VectorField(coords, name='u', bases=(xbasis, zbasis))
tau_p = dist.Field(name='tau_p')

# Substitutions
nu = 1 / Reynolds
D = nu / Schmidt
x, z = dist.local_grids(xbasis, zbasis)
ex, ez = coords.unit_vector_fields(dist)

# Problem
problem = d3.IVP([u, s, p, tau_p], namespace=locals())
problem.add_equation("dt(u) + grad(p) - nu*lap(u) = - u@grad(u)")
problem.add_equation("dt(s) - D*lap(s) = - u@grad(s)")
problem.add_equation("div(u) + tau_p = 0")
problem.add_equation("integ(p) = 0")

# Initial conditions: shear layers + sinusoidal perturbation + tracer
ug = np.zeros((2,) + tuple(np.broadcast_shapes((Nx, 1), (1, Nz))))
ug[0] = 1/2 + 1/2 * (np.tanh((z-0.5)/0.1) - np.tanh((z+0.5)/0.1))
ug[1] = (0.1 * np.sin(2*np.pi*x/Lx) * np.exp(-(z-0.5)**2/0.01)
         + 0.1 * np.sin(2*np.pi*x/Lx) * np.exp(-(z+0.5)**2/0.01))
u['g'] = ug
s['g'] = 1/2 + 1/2 * (np.tanh((z-0.5)/0.1) - np.tanh((z+0.5)/0.1))

# Solver
solver = problem.build_solver(timestepper)
solver.stop_sim_time = stop_sim_time

# CFL
CFL = d3.CFL(solver, initial_dt=max_timestep, cadence=10, safety=0.2,
             threshold=0.1, max_change=1.5, min_change=0.5, max_dt=max_timestep)
CFL.add_velocity(u)

# Main loop
if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    try:
        logger.info('Starting main loop')
        while solver.proceed:
            timestep = CFL.compute_timestep()
            solver.step(timestep)
            if (solver.iteration - 1) % 100 == 0:
                logger.info(f'Iteration={solver.iteration}, Time={solver.sim_time:.3e}, dt={timestep:.1e}')
    finally:
        solver.log_stats()
