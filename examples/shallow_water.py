"""
Spherical rotating shallow water: an unstable mid-latitude jet develops
barotropic instability (reference: examples/ivp_sphere_shallow_water/
shallow_water.py, test case from Galewsky et al. 2004).

Run: python examples/shallow_water.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Simulation units (reference: shallow_water.py:24-27): nondimensionalize
# so the radius is 1 and an hour is 1 — raw SI values span enough orders
# that the hyperdiffusion entries underflow f32 on accelerators.
meter = 1 / 6.37122e6
hour = 1
second = hour / 3600

# Parameters (reference: shallow_water.py:28-40)
import sys
quick = "--quick" in sys.argv
Nphi, Ntheta = (64, 32) if quick else (256, 128)
dealias = 3 / 2
R = 6.37122e6 * meter
Omega = 7.292e-5 / second
nu = 1e5 * meter**2 / second / 32**2  # hyperdiffusion matched at ell = 32
g = 9.80616 * meter / second**2
H = 1e4 * meter
timestep = 600 * second
stop_sim_time = 10 * 600 * second if quick else 360 * hour
dtype = np.float64

# Bases
coords = d3.S2Coordinates('phi', 'theta')
dist = d3.Distributor(coords, dtype=dtype)
basis = d3.SphereBasis(coords, shape=(Nphi, Ntheta), dtype=dtype, radius=R,
                       dealias=dealias)

# Fields
u = dist.VectorField(coords, name='u', bases=basis)
h = dist.Field(name='h', bases=basis)

# Substitutions
zcross = lambda A: d3.MulCosine(d3.Skew(A))
phi, theta = dist.local_grids(basis)
lat = np.pi / 2 - theta + 0 * phi

# Initial conditions: zonal jet (Galewsky et al. 2004)
umax = 80 * meter / second
lat0 = np.pi / 7
lat1 = np.pi / 2 - lat0
en = np.exp(-4 / (lat1 - lat0) ** 2)
jet = (lat0 <= lat) * (lat <= lat1)
u_jet = umax / en * np.exp(1 / ((lat[jet] - lat0) * (lat[jet] - lat1)))
ug = np.zeros_like(np.broadcast_to(lat, (Nphi, Ntheta)))
ug = np.array([ug, 0 * ug])
ug[0][jet] = u_jet
u['g'] = ug

# Initial conditions: balanced height
c = dist.Field(name='c')
problem = d3.LBVP([h, c], namespace=locals())
problem.add_equation("g*lap(h) + c = - div(u@grad(u) + 2*Omega*zcross(u))")
problem.add_equation("ave(h) = 0")
solver = problem.build_solver()
solver.solve()

# Initial conditions: perturbation
lat2 = np.pi / 4
hpert = 120 * meter
alpha = 1 / 3
beta = 1 / 15
h['g'] += hpert * np.cos(lat) * np.exp(-(phi / alpha) ** 2) \
    * np.exp(-((lat2 - lat) / beta) ** 2)

# Problem (reference: shallow_water.py:63-66)
problem = d3.IVP([u, h], namespace=locals())
problem.add_equation(
    "dt(u) + nu*lap(lap(u)) + g*grad(h) + 2*Omega*zcross(u) = - u@grad(u)")
problem.add_equation("dt(h) + nu*lap(lap(h)) + H*div(u) = - div(u*h)")

# Solver
solver = problem.build_solver(d3.RK222)
solver.stop_sim_time = stop_sim_time

# Analysis
snapshots = solver.evaluator.add_file_handler(
    'snapshots_shallow_water', sim_dt=1 * hour, max_writes=10)
snapshots.add_task(h, name='height')
snapshots.add_task(-d3.div(d3.Skew(u)), name='vorticity')

# Main loop
if __name__ == "__main__":
    try:
        logger.info('Starting main loop')
        while solver.proceed:
            solver.step(timestep)
            if (solver.iteration - 1) % 10 == 0:
                logger.info(f'Iteration={solver.iteration}, '
                            f'Time={solver.sim_time:.3e}, dt={timestep:.3e}')
    except Exception:
        logger.error('Exception raised, triggering end of main loop.')
        raise
    finally:
        solver.log_stats()
