"""
Plot 2D snapshot files produced by the examples' file handlers
(reference workflow: examples/ivp_2d_rayleigh_benard/plot_snapshots.py).

Usage:
    python examples/plot_snapshots.py snapshots/*.h5 [--output=frames]
                                      [--tasks=buoyancy,vorticity]
"""

import pathlib
import sys

import h5py
import numpy as np
import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
from dedalus_tpu.extras import plot_tools  # noqa: E402


def plot_file(filename, output, tasks=None, dpi=150):
    output = pathlib.Path(output)
    output.mkdir(parents=True, exist_ok=True)
    saved = []
    with h5py.File(filename, "r") as f:
        names = tasks or list(f["tasks"])
        n_writes = f["tasks"][names[0]].shape[0]
        sim_time = np.asarray(f["scales"]["sim_time"])
        write_number = np.asarray(f["scales"]["write_number"])
        for index in range(n_writes):
            fig, axes = plt.subplots(len(names), 1,
                                     figsize=(6, 2.2 * len(names)),
                                     squeeze=False)
            for n, name in enumerate(names):
                plot_tools.plot_bot_3d(f["tasks"][name], 0, index,
                                       axes=axes[n][0], title=name,
                                       even_scale=True, visible_axes=False)
            fig.suptitle(f"t = {sim_time[index]:.3f}")
            savename = output / f"write_{int(write_number[index]):06d}.png"
            fig.savefig(savename, dpi=dpi)
            plt.close(fig)
            saved.append(savename)
    return saved


def main(argv):
    files = [a for a in argv if not a.startswith("--")]
    output = next((a.split("=", 1)[1] for a in argv
                   if a.startswith("--output=")), "frames")
    tasks = next((a.split("=", 1)[1].split(",") for a in argv
                  if a.startswith("--tasks=")), None)
    for fn in files:
        saved = plot_file(fn, output, tasks)
        print(f"{fn}: {len(saved)} frames -> {output}/")


if __name__ == "__main__":
    main(sys.argv[1:])
