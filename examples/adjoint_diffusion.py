"""
Inverse initial-condition recovery by adjoint gradient descent
(the DifferentiableIVP workload end to end, docs/differentiable.md).

Setup: a 1-D diffusion equation is stepped forward from a band-limited
"true" temperature field to produce a terminal observation. The inverse
problem — recover the initial field from that single terminal snapshot —
is then solved by gradient descent on

    J(u0) = || XT(u0) - X_obs ||^2

with dJ/du0 from `solver.differentiable(...)`: each optimizer iteration
is ONE compiled value-and-grad call (checkpointed adjoint backprop
through all n steps, adjoint pencil solves against the cached LHS
factors). Diffusion damps mode k by exp(-k^2 T), so the observation
window is kept short and the true field band-limited — the classic
ill-posedness of backward diffusion, visible here as slower recovery of
the higher modes.

Run: python examples/adjoint_diffusion.py
"""

import logging

import numpy as np
import jax.numpy as jnp

import dedalus_tpu.public as d3

logger = logging.getLogger(__name__)

# Parameters
Nx = 64
n_steps = 100
dt = 1e-4
iterations = 60
learning_rate = 0.45
dtype = np.float64

# Problem
xcoord = d3.Coordinate('x')
dist = d3.Distributor(xcoord, dtype=dtype)
xbasis = d3.RealFourier(xcoord, size=Nx, bounds=(0, 2 * np.pi))
u = dist.Field(name='u', bases=xbasis)
problem = d3.IVP([u], namespace={'u': u, 'lap': d3.lap})
problem.add_equation("dt(u) - lap(u) = 0")
x = dist.local_grid(xbasis)

# True initial condition (band-limited: modes the short window keeps
# observable) -> terminal observation, produced by the plain stepping
# loop BEFORE any differentiable program exists (the loss closes over
# X_obs, and compiled programs bake closure values in at trace time —
# a placeholder observation would be baked in permanently)
u['g'] = np.sin(x) + 0.5 * np.cos(2 * x) - 0.3 * np.sin(3 * x)
fwd_solver = problem.build_solver(d3.SBDF2, warmup_iterations=2,
                                  enforce_real_cadence=0)
X_true = np.asarray(fwd_solver.gather_fields()).copy()
for _ in range(n_steps):
    fwd_solver.step(dt)
X_obs = jnp.asarray(fwd_solver.X)

# Inverse problem: a fresh solver (clock at t=0) differentiated against
# the now-final observation
solver = problem.build_solver(d3.SBDF2, warmup_iterations=2,
                              enforce_real_cadence=0)
div = solver.differentiable(
    wrt=("initial_state",),
    loss=lambda X: jnp.sum((X - X_obs) ** 2),
    checkpoint_segments=10)

if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    # Gradient descent from a cold (zero) initial guess
    X_guess = np.zeros_like(X_true)
    for i in range(iterations):
        loss, grads = div.value_and_grad(n_steps, dt,
                                         initial_state=X_guess)
        X_guess = X_guess - learning_rate * np.asarray(
            grads["initial_state"])
        if i % 10 == 0 or i == iterations - 1:
            err = np.linalg.norm(X_guess - X_true) / np.linalg.norm(X_true)
            logger.info(f"iter {i:3d}: J = {loss:.3e}, "
                        f"|u0 - u0_true|/|u0_true| = {err:.3e}")
    record = div.flush_metrics()
    if record:
        adj = record["adjoint"]
        logger.info(f"adjoint: {adj['grad_calls']} grad calls, "
                    f"{adj['grad_steps_per_sec']} grad-steps/s, "
                    f"{adj['checkpoint_segments']} segments")
    final_err = np.linalg.norm(X_guess - X_true) / np.linalg.norm(X_true)
    logger.info(f"recovered initial field, relative error {final_err:.3e}")
    assert final_err < 1e-2, "inverse-IC recovery did not converge"
