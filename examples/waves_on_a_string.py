"""
Eigenmodes of waves on a clamped string (reference:
examples/evp_1d_waves_on_a_string/waves_on_a_string.py): Legendre EVP
    s*u + dx(dx(u)) = 0,  u(0) = u(Lx) = 0
with first-order tau reduction. Eigenvalues are s_n = (n pi / Lx)^2.

Run: python examples/waves_on_a_string.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
Lx = 1
Nx = 128
dtype = np.complex128

# Bases
xcoord = d3.Coordinate('x')
dist = d3.Distributor(xcoord, dtype=dtype)
xbasis = d3.Legendre(xcoord, size=Nx, bounds=(0, Lx))

# Fields
u = dist.Field(name='u', bases=xbasis)
tau_1 = dist.Field(name='tau_1')
tau_2 = dist.Field(name='tau_2')
s = dist.Field(name='s')

# Substitutions
dx = lambda A: d3.Differentiate(A, xcoord)
lift_basis = xbasis.derivative_basis(1)
lift = lambda A: d3.Lift(A, lift_basis, -1)
ux = dx(u) + lift(tau_1)   # First-order reduction
uxx = dx(ux) + lift(tau_2)

# Problem
problem = d3.EVP([u, tau_1, tau_2], eigenvalue=s, namespace=locals())
problem.add_equation("s*u + uxx = 0")
problem.add_equation("u(x=0) = 0")
problem.add_equation("u(x=Lx) = 0")

# Solve
solver = problem.build_solver()
solver.solve_dense(solver.subproblems[0])
# physical modes have the smallest magnitudes; spurious tau modes are huge
order = np.argsort(np.abs(solver.eigenvalues))
evals = solver.eigenvalues[order].real
n = 1 + np.arange(len(evals))
true = (n * np.pi / Lx) ** 2

if __name__ == "__main__":
    logger.info("First eigenvalues (computed vs (n pi/L)^2):")
    for i in range(8):
        rel = abs(evals[i] - true[i]) / abs(true[i])
        logger.info(f"  n={i+1}: {evals[i]:.6f} vs {true[i]:.6f} "
                    f"(rel err {rel:.2e})")
