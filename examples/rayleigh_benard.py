"""
2D horizontally-periodic Rayleigh-Benard convection
(reference: examples/ivp_2d_rayleigh_benard/rayleigh_benard.py).

Run: python examples/rayleigh_benard.py
"""

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
Lx, Lz = 4, 1
Nx, Nz = 256, 64
Rayleigh = 2e6
Prandtl = 1
dealias = 3/2
stop_sim_time = 50
timestepper = d3.RK222
max_timestep = 0.125
dtype = np.float64

# Bases
coords = d3.CartesianCoordinates('x', 'z')
dist = d3.Distributor(coords, dtype=dtype)
xbasis = d3.RealFourier(coords['x'], size=Nx, bounds=(0, Lx), dealias=dealias)
zbasis = d3.ChebyshevT(coords['z'], size=Nz, bounds=(0, Lz), dealias=dealias)

# Fields
p = dist.Field(name='p', bases=(xbasis, zbasis))
b = dist.Field(name='b', bases=(xbasis, zbasis))
u = dist.VectorField(coords, name='u', bases=(xbasis, zbasis))
tau_p = dist.Field(name='tau_p')
tau_b1 = dist.Field(name='tau_b1', bases=xbasis)
tau_b2 = dist.Field(name='tau_b2', bases=xbasis)
tau_u1 = dist.VectorField(coords, name='tau_u1', bases=xbasis)
tau_u2 = dist.VectorField(coords, name='tau_u2', bases=xbasis)

# Substitutions
kappa = (Rayleigh * Prandtl)**(-1/2)
nu = (Rayleigh / Prandtl)**(-1/2)
x, z = dist.local_grids(xbasis, zbasis)
ex, ez = coords.unit_vector_fields(dist)
lift_basis = zbasis.derivative_basis(1)
lift = lambda A: d3.Lift(A, lift_basis, -1)
grad_u = d3.grad(u) + ez*lift(tau_u1)  # First-order reduction
grad_b = d3.grad(b) + ez*lift(tau_b1)  # First-order reduction

# Problem
problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2], namespace=locals())
problem.add_equation("trace(grad_u) + tau_p = 0")
problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
problem.add_equation("dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = - u@grad(u)")
problem.add_equation("b(z=0) = Lz")
problem.add_equation("u(z=0) = 0")
problem.add_equation("b(z=Lz) = 0")
problem.add_equation("u(z=Lz) = 0")
problem.add_equation("integ(p) = 0")  # Pressure gauge

# Solver
solver = problem.build_solver(timestepper)
solver.stop_sim_time = stop_sim_time

# Initial conditions
b.fill_random('g', seed=42, distribution='normal', scale=1e-3)
b['g'] *= z * (Lz - z)
b['g'] += Lz - z

# Analysis
snapshots = solver.evaluator.add_file_handler('snapshots', sim_dt=0.25, max_writes=50)
snapshots.add_task(b, name='buoyancy')
snapshots.add_task(-d3.div(d3.skew(u)), name='vorticity')

# CFL
CFL = d3.CFL(solver, initial_dt=max_timestep, cadence=10, safety=0.5,
             threshold=0.05, max_change=1.5, min_change=0.5, max_dt=max_timestep)
CFL.add_velocity(u)

# Flow properties
flow = d3.GlobalFlowProperty(solver, cadence=10)
flow.add_property(np.sqrt(u@u)/nu, name='Re')

# Main loop
if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    try:
        logger.info('Starting main loop')
        while solver.proceed:
            timestep = CFL.compute_timestep()
            solver.step(timestep)
            if (solver.iteration - 1) % 10 == 0:
                max_Re = flow.max('Re')
                logger.info(f'Iteration={solver.iteration}, Time={solver.sim_time:.3e}, '
                            f'dt={timestep:.1e}, max(Re)={max_Re:.2f}')
    except Exception:
        logger.error('Exception raised, triggering end of main loop.')
        raise
    finally:
        solver.log_stats()
