"""
Linear stability of laminar pipe flow (reference example:
examples/evp_disk_pipe_flow/pipe_flow.py): an EVP in the periodic
cylinder — disk basis for the cross-section, parametrized axial
wavenumber kz, background w0 = 1 - r^2, no-slip walls. The background
advection terms (w0*dz(u), u@grad(w0)) exercise disk LHS NCCs.

Pipe flow is linearly stable at all Re: every eigenvalue decays (the
reference validates this setup against Vasil et al. 2016, JCP, Table 3).
The slowest-decaying (Re=1e4, kz=1, m=1) mode computed here converges in
resolution (Nr=48 and Nr=64 agree to 6 digits) to
    s ~ -0.0227050 - 0.9514810i.

Run: python examples/pipe_flow.py [--quick]
"""

import sys

import numpy as np
import dedalus_tpu.public as d3
import logging
logger = logging.getLogger(__name__)

# Parameters
quick = "--quick" in sys.argv
Re = 1e4
kz = 1
m = 1
Nphi = 2 * max(m, 4) + 2
Nr = 32 if quick else 64
dtype = np.complex128

# Bases
coords = d3.PolarCoordinates('phi', 'r')
dist = d3.Distributor(coords, dtype=dtype)
disk = d3.DiskBasis(coords, shape=(Nphi, Nr), radius=1, dtype=dtype)
phi, r = dist.local_grids(disk)

# Fields
s = dist.Field(name='s')
u = dist.VectorField(coords, name='u', bases=disk)
w = dist.Field(name='w', bases=disk)
p = dist.Field(name='p', bases=disk)
tau_u = dist.VectorField(coords, name='tau_u', bases=disk.edge)
tau_w = dist.Field(name='tau_w', bases=disk.edge)

# Substitutions
dt = lambda A: s * A
dz = lambda A: 1j * kz * A
lift_basis = disk.derivative_basis(2)
lift = lambda A: d3.Lift(A, lift_basis, -1)

# Background: laminar Poiseuille profile
w0 = dist.Field(name='w0', bases=disk)
w0['g'] = np.broadcast_to(np.asarray(1 - r ** 2),
                          np.broadcast_shapes(phi.shape, r.shape))

# Problem
problem = d3.EVP([u, w, p, tau_u, tau_w], eigenvalue=s, namespace=locals())
problem.add_equation("div(u) + dz(w) = 0")
problem.add_equation("dt(u) + w0*dz(u) + grad(p) - (1/Re)*(lap(u) + dz(dz(u))) + lift(tau_u) = 0")
problem.add_equation("dt(w) + w0*dz(w) + u@grad(w0) + dz(p) - (1/Re)*(lap(w) + dz(dz(w))) + lift(tau_w) = 0")
problem.add_equation("u(r=1) = 0")
problem.add_equation("w(r=1) = 0")

# Solver: dense solve of the m-th azimuthal pencil
solver = problem.build_solver()
sp = solver.subproblems_by_group[(m, None)]
solver.solve_dense(sp)
evals = solver.eigenvalues[np.isfinite(solver.eigenvalues)]
evals = evals[np.argsort(-evals.real)]
print(f"Slowest decaying mode: lambda = {evals[0]}")
assert evals[0].real < 0, "pipe flow must be linearly stable"
if not quick:
    expect = -0.0227050 - 0.9514810j
    match = evals[np.argmin(np.abs(evals - expect))]
    logger.info(f"closest to converged value {expect}: {match}")
    assert abs(match - expect) < 1e-4, match
