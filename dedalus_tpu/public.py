"""
User-facing API: `import dedalus_tpu.public as d3`
(reference: dedalus/public.py:4-14).
"""

import os as _os

if _os.environ.get("DEDALUS_PLATFORM"):
    # Authoritative backend selection for user scripts: some environments
    # force a platform at interpreter start (a PJRT-plugin sitecustomize
    # overrides JAX_PLATFORMS), and probing an unreachable accelerator can
    # hang; DEDALUS_PLATFORM=cpu pins the backend before any jax use.
    import jax as _jax
    _jax.config.update("jax_platforms", _os.environ["DEDALUS_PLATFORM"])

from .core.coords import (Coordinate, CartesianCoordinates, DirectProduct,
                          PolarCoordinates, S2Coordinates,
                          SphericalCoordinates)
from .core.distributor import Distributor
from .core.domain import Domain
from .core.basis import (Jacobi, ChebyshevT, ChebyshevU, ChebyshevV, Legendre,
                         Ultraspherical, RealFourier, ComplexFourier, Fourier)
from .core.polar import DiskBasis, AnnulusBasis
from .core.sphere import SphereBasis, MulCosine
from .core.spherical3d import ShellBasis, BallBasis
from .core.field import Field, LockedField
from .core.problems import IVP, LBVP, NLBVP, EVP
from .core.operators import (
    AdvectiveCFL,
    Differentiate, Convert, Interpolate, Integrate, Average,
    AzimuthalAverageFactory as AzimuthalAverage,
    LiftFactory as Lift, LiftTau,
    Gradient, Divergence, Laplacian, Curl, Trace, TransposeComponents,
    SkewFactory as Skew, Radial, Azimuthal, Angular, SphericalEllProduct,
    TimeDerivative, UnaryGridFunction, GeneralFunction, GridWrapper as Grid,
    CoeffWrapper as Coeff, dt)
from .core.arithmetic import Add, Multiply, DotProduct, CrossProduct, Power
from .core.timesteppers import (schemes, add_scheme, MultistepIMEX,
                                RungeKuttaIMEX, CNAB1, SBDF1, CNAB2, MCNAB2,
                                SBDF2, CNLF2, SBDF3, SBDF4, RK111, RK222,
                                RK443, RKSMR, RKGFY)
from .core.solvers import (InitialValueSolver, LinearBoundaryValueSolver,
                           NonlinearBoundaryValueSolver, EigenvalueSolver)
from .core.ensemble import EnsembleSolver
from .core.evaluator import Evaluator
from .extras.flow_tools import CFL, GlobalFlowProperty, GlobalArrayReducer
from .tools.exceptions import (CheckpointError, SilentCorruptionError,
                               SolverHealthError)
from .tools.health import HealthMonitor

# lowercase operator aliases (reference: core/operators.py aliases)
cross = CrossProduct
dot = DotProduct
trans = TransposeComponents

# long-form aliases (reference exports both spellings)
InitialValueProblem = IVP
LinearBoundaryValueProblem = LBVP
NonlinearBoundaryValueProblem = NLBVP
EigenvalueProblem = EVP
Chebyshev = ChebyshevT
Component = Radial  # reference Component(operand, index) defaults radial
RadialComponent = Radial
AzimuthalComponent = Azimuthal
AngularComponent = Angular


def VectorField(dist, *args, **kw):
    """Module-level field factories (reference: core/field.py exports);
    equivalent to the Distributor methods."""
    return dist.VectorField(*args, **kw)


def TensorField(dist, *args, **kw):
    return dist.TensorField(*args, **kw)


def ScalarField(dist, *args, **kw):
    return dist.Field(*args, **kw)


from .tools.post import load_tasks_to_xarray
grad = Gradient
div = Divergence
lap = Laplacian
curl = Curl
trace = Trace
transpose = TransposeComponents
skew = Skew
integ = Integrate
ave = Average
lift = Lift
interp = Interpolate
radial = Radial
azimuthal = Azimuthal
angular = Angular
# reference-parity aliases (reference: core/operators.py:1028 interpolate,
# :1449 convert; Transpose as the TransposeComponents shorthand)
Transpose = TransposeComponents
convert = Convert


def interpolate(arg, **positions):
    """Iterated interpolation: interpolate(f, x=0.5, z=1.0) (reference:
    core/operators.py:1028)."""
    for coord, position in positions.items():
        arg = Interpolate(arg, coord, position)
    return arg



# Warm-pool solver service (dedalus_tpu/service/; docs/serving.md): the
# lightweight blocking client for a `python -m dedalus_tpu serve` daemon.
# Imported last; the client touches none of the solver stack — the
# daemon owns all solver state and compilation.
from .service.client import ServiceClient
from .service.protocol import ServiceError, SpecError
