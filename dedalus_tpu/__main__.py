"""
Command-line interface (reference: dedalus/__main__.py:1-45), argparse
subcommands — `python -m dedalus_tpu <command> --help` documents each:

    test          run the tier-1 test suite
    cov           test suite under coverage
    bench         run the benchmark (bench.py)
    get_config    print the resolved configuration
    get_examples  print the examples directory
    report        summarize a metrics/results JSONL file
    postmortem    summarize a health post-mortem directory
    lint          static analysis: AST jit-hygiene rules, and the
                  compiled-program contract census under --programs
    serve         warm-pool solver daemon (dedalus_tpu/service/)
    submit        submit one run to a serve daemon
    route         spec-hash router fronting a replica fleet
    tune          pre-tune solve-plan decisions offline
                  (tools/autotune.py; docs/performance.md#autotuning)
"""

import argparse
import json
import pathlib
import sys


def test(args=None):
    import pytest
    # fail fast on a missing/stale lint baseline: tests/test_lint.py would
    # fail anyway, but only after the whole suite ran — and a stale
    # baseline usually means a fixed hazard whose grandfathering should be
    # dropped in the SAME commit
    from .tools.lint import check_baseline_fresh
    problems = check_baseline_fresh()
    if problems:
        for problem in problems:
            print(f"test: {problem}", file=sys.stderr)
        sys.exit(1)
    root = pathlib.Path(__file__).parent.parent
    # tier-1 semantics: slow-marked tests (long timing runs) are opt-in
    # via pytest directly; chaos-marked fault-injection tests
    # (tests/test_resilience.py) and service-marked daemon tests
    # (tests/test_service.py) are fast and run by default — recovery and
    # serving paths that are not exercised do not exist
    sys.exit(pytest.main([str(root / "tests"), "-q", "-m", "not slow"]))


def bench(args=None):
    import runpy
    root = pathlib.Path(__file__).parent.parent
    bench_path = root / "bench.py"
    if not bench_path.exists():
        print("bench.py not found next to the package", file=sys.stderr)
        sys.exit(1)
    runpy.run_path(str(bench_path), run_name="__main__")


def cov(args=None):
    """Test suite under coverage (reference: dedalus/tests/__init__.py:30
    cov). Requires the `coverage` package. Runs in a fresh interpreter so
    coverage measures modules imported by the package itself (starting
    coverage after this import would under-report __init__/tools)."""
    try:
        import coverage  # noqa: F401
    except ImportError:
        print("cov requires the 'coverage' package (pip install coverage)",
              file=sys.stderr)
        sys.exit(1)
    import subprocess
    root = pathlib.Path(__file__).parent.parent
    rc = subprocess.run(
        [sys.executable, "-m", "coverage", "run", "--source=dedalus_tpu",
         "-m", "pytest", str(root / "tests"), "-q", "-m", "not slow"],
        cwd=root).returncode
    subprocess.run([sys.executable, "-m", "coverage", "report"], cwd=root)
    sys.exit(rc)


def get_config(args=None):
    from .tools.config import config
    config.write(sys.stdout)


def get_examples(args=None):
    root = pathlib.Path(__file__).parent.parent / "examples"
    print(root)


def _format_plan(record):
    """One-line resolved-plan provenance for a metrics/bench row. Rows
    written before plan stamping existed (PR 16) carry no `plan` block
    and must still render — as the literal `plan=unversioned` — rather
    than crash or silently vanish."""
    plan = record.get("plan")
    if not isinstance(plan, dict):
        return "plan=unversioned"
    parts = []
    fusion = plan.get("fusion")
    if isinstance(fusion, dict):
        on = "+".join(k for k in ("solve", "matvec", "transforms",
                                  "donate", "pallas")
                      if fusion.get(k)) or "off"
        parts.append(f"fusion={on}")
    if plan.get("solve_composition"):
        solve = str(plan["solve_composition"])
        if plan.get("solve_dtype"):
            solve += f"/{plan['solve_dtype']}"
        parts.append(f"solve={solve}")
    if plan.get("refine_sweeps") is not None:
        parts.append(f"sweeps={plan['refine_sweeps']}")
    if plan.get("spike_chunks") is not None:
        parts.append(f"spike={plan['spike_chunks']}")
    if plan.get("transpose_chunks") is not None:
        parts.append(f"chunks={plan['transpose_chunks']}")
    if plan.get("solver_key"):
        parts.append(f"key={plan['solver_key']}")
    # how the plan was chosen (tools/autotune.py): tuned decisions name
    # their evidence kind + margin inline; rows from before plan_source
    # existed simply omit the column
    source = plan.get("plan_source")
    if source:
        cell = source
        tuning = plan.get("tuning")
        if source == "tuned" and isinstance(tuning, dict):
            detail = [str(tuning.get("evidence_kind") or "")]
            if tuning.get("margin") is not None:
                detail.append(f"{tuning['margin']}x")
            cell += f" ({', '.join(d for d in detail if d)})"
        parts.append(f"source={cell}")
    return (f"plan[v{plan.get('plan_version', '?')}]: "
            + (", ".join(parts) or "(empty)"))


def report(args):
    """Summarize a metrics JSONL file (tools/metrics.py records; bench rows
    from benchmarks/results.jsonl listed briefly; health post-mortem and
    service records get their own lines). Tolerates heterogeneous rows —
    records from before any given key existed print with defaults rather
    than crashing. `--last N` restricts to the N most recent parsable
    rows."""
    from .tools.metrics import format_phase_table
    path = pathlib.Path(args.jsonl)
    try:
        lines = path.read_text().splitlines()
    except OSError as exc:
        print(f"report: cannot read {path}: {exc}", file=sys.stderr)
        sys.exit(1)
    records = []
    n_bad = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            n_bad += 1
            continue
        if not isinstance(record, dict):
            n_bad += 1
            continue
        records.append(record)
    if args.last is not None:
        records = records[-args.last:] if args.last > 0 else []
    n_metrics = n_post = n_other = 0
    prev_ledger = {}      # (program, backend) -> previous ledger row
    for record in records:
        kind = record.get("kind")
        if kind == "step_metrics":
            n_metrics += 1
            ident = " ".join(
                f"{k}={record[k]}" for k in ("config", "backend", "dtype")
                if record.get(k) is not None)
            print(f"[{n_metrics}] {ident or 'step_metrics'}: "
                  f"{record.get('iterations', 0)} iters, "
                  f"{record.get('steps_per_sec', 0.0)} steps/s, "
                  f"{record.get('phase_samples', 0)} samples "
                  f"(cadence {record.get('sample_cadence', '?')})")
            # format_phase_table's first line repeats the sample count
            # already printed in the header above
            for tline in format_phase_table(record, indent="    ")[1:]:
                print(tline)
            health = record.get("health")
            if isinstance(health, dict):
                status = "ok" if health.get("ok", True) else \
                    f"FAILED: {health.get('reason', '?')}"
                print(f"    health: {status}, "
                      f"{health.get('checks', 0)} checks, "
                      f"{health.get('warnings', 0)} warnings")
            ensemble = record.get("ensemble")
            if isinstance(ensemble, dict):
                parts = [f"{ensemble.get('members', '?')} members",
                         f"{ensemble.get('active', '?')} active",
                         f"{ensemble.get('dropped', 0)} dropped"]
                if ensemble.get("rewinds"):
                    parts.append(f"{ensemble['rewinds']} rewinds")
                if ensemble.get("reshards"):
                    parts.append(f"{ensemble['reshards']} reshards")
                parts.append(
                    f"{ensemble.get('ensemble_steps_per_sec', 0.0)} "
                    f"member-steps/s")
                if ensemble.get("devices"):
                    parts.append(f"{ensemble['devices']} device(s)")
                print(f"    ensemble: {', '.join(parts)}")
                if ensemble.get("dropped_members"):
                    print(f"    dropped members: "
                          f"{ensemble['dropped_members']}")
            resilience = record.get("resilience")
            if isinstance(resilience, dict):
                parts = [f"{resilience.get('rewinds', 0)} rewinds",
                         f"{resilience.get('retries', 0)} retries"]
                if resilience.get("dt_limit") is not None:
                    parts.append(f"dt capped {resilience['dt_limit']}")
                if resilience.get("stopped_by"):
                    parts.append(f"stopped by {resilience['stopped_by']}")
                if resilience.get("resumed_from"):
                    parts.append(
                        f"resumed from {resilience['resumed_from']} "
                        f"(write {resilience.get('resume_write', '?')})")
                if resilience.get("sdc_checks") is not None:
                    # the SDC sentinel trajectory: checks run / silent
                    # corruptions caught (tools/resilience.py)
                    parts.append(f"sdc {resilience.get('sdc_detected', 0)}"
                                 f"/{resilience['sdc_checks']}")
                print(f"    resilience: {', '.join(parts)}")
                ckpt = resilience.get("checkpoint")
                if isinstance(ckpt, dict):
                    # durable-checkpoint stall column: format (+async),
                    # cumulative step-loop stall, writes landed
                    line = (f"    checkpoint: {ckpt.get('format', '?')}"
                            f"{'+async' if ckpt.get('async') else ''}, "
                            f"stall {ckpt.get('stall_sec', 0.0)}s")
                    if ckpt.get("written") is not None:
                        line += f", {ckpt['written']} written"
                    if ckpt.get("max_inflight"):
                        line += (f", max in-flight "
                                 f"{ckpt['max_inflight']}")
                    if ckpt.get("errors"):
                        line += f", {ckpt['errors']} ERRORS"
                    print(line)
            adjoint = record.get("adjoint")
            if isinstance(adjoint, dict):
                # differentiable-solve telemetry (core/adjoint.py):
                # grad throughput, remat segments, memory
                parts = [f"{adjoint.get('grad_calls', 0)} grad calls",
                         f"{adjoint.get('grad_steps_per_sec', '?')} "
                         f"grad-steps/s"]
                if adjoint.get("grad_forward_ratio") is not None:
                    parts.append(f"{adjoint['grad_forward_ratio']}x "
                                 "forward cost")
                if adjoint.get("checkpoint_segments") is not None:
                    parts.append(
                        f"{adjoint['checkpoint_segments']} segments")
                mem = adjoint.get("device_mem_peak_bytes")
                if mem:
                    parts.append(f"peak {mem / 1e9:.3f} GB")
                if adjoint.get("wrt"):
                    parts.append(f"wrt={','.join(adjoint['wrt'])}")
                print(f"    adjoint: {', '.join(parts)}")
            serving = record.get("serving")
            if isinstance(serving, dict):
                # served-latency columns (dedalus_tpu/service/): the pool
                # verdict and time-to-first-step ARE the serving story
                parts = [f"pool={serving.get('pool_verdict', '?')}",
                         f"queue={serving.get('queue_sec', '?')}s",
                         f"ttfs={serving.get('time_to_first_step_sec')}s"]
                if serving.get("build_sec"):
                    parts.append(f"build={serving['build_sec']}s")
                if serving.get("deadline_sec") is not None:
                    parts.append(f"deadline={serving['deadline_sec']}s")
                if serving.get("request_id"):
                    parts.append(f"request={serving['request_id']}")
                batch = serving.get("batch")
                if isinstance(batch, dict):
                    parts.append(
                        f"batch={batch.get('id', '?')}"
                        f"#{batch.get('seat', '?')}"
                        + (" (late join)" if batch.get("late_join")
                           else ""))
                print(f"    serving: {', '.join(parts)}")
            print(f"    {_format_plan(record)}")
        elif kind == "health_postmortem":
            n_post += 1
            resilience = record.get("resilience")
            lineage = ""
            if isinstance(resilience, dict) and resilience.get("retries"):
                lineage = (f" (retry {resilience['retries']}, "
                           f"{resilience.get('rewinds', 0)} rewinds)")
            print(f"(postmortem) iter={record.get('iteration', '?')} "
                  f"sim_time={record.get('sim_time', '?')}: "
                  f"{record.get('reason', '(no reason)')}{lineage}"
                  + (f" [{record.get('directory')}]"
                     if record.get("directory") else ""))
        elif kind == "service_stats":
            n_other += 1
            pool = record.get("pool") or {}
            print(f"(service) {record.get('requests_served', 0)} requests, "
                  f"{record.get('errors', 0)} errors, "
                  f"pool {pool.get('hits', 0)} hits / "
                  f"{pool.get('misses', 0)} misses / "
                  f"{pool.get('evictions', 0)} evictions, "
                  f"{len(pool.get('entries', []))} warm entr(ies), "
                  f"uptime {record.get('uptime_sec', '?')}s")
            faults = record.get("faults") or {}
            if faults:
                # the fault-tolerance trajectory (service/faults.py):
                # shed/deadline/watchdog/drop/replay + breaker counters
                breaker = faults.get("breaker") or {}
                line = (f"    faults: {faults.get('shed', 0)} shed, "
                        f"{faults.get('deadline_exceeded', 0)} "
                        "deadline-exceeded, "
                        f"{faults.get('watchdog_fires', 0)} watchdog, "
                        f"{faults.get('client_drops', 0)} client drops, "
                        f"{faults.get('replays', 0)} replays, "
                        f"breaker {breaker.get('opens', 0)} opens / "
                        f"{breaker.get('fastfails', 0)} fast-fails")
                if faults.get("mem_evictions"):
                    line += (f", {faults['mem_evictions']} "
                             "memory evictions")
                if breaker.get("open"):
                    line += f", OPEN circuits: {breaker['open']}"
                print(line)
                codes = faults.get("error_codes") or {}
                if codes:
                    # per-error-code refusal census (server._send_error):
                    # which failure mode dominates, at a glance
                    print("    error codes: "
                          + ", ".join(f"{v} {k}"
                                      for k, v in sorted(codes.items())))
            batching = (record.get("serving") or {}).get("batching") or {}
            if batching.get("enabled"):
                # continuous-batching occupancy (service/batching.py):
                # how full the micro-batches actually ran, and why
                # members left them
                det = batching.get("detached") or {}
                det_txt = ", ".join(f"{v} {k}"
                                    for k, v in sorted(det.items())) \
                    or "none"
                print(f"    batching: {batching.get('batches', 0)} "
                      f"batches, {batching.get('members', 0)} members "
                      f"({batching.get('late_joins', 0)} late joins), "
                      f"peak {batching.get('peak_members', 0)}"
                      f"/{batching.get('batch_max', '?')} seats, "
                      f"{batching.get('blocks', 0)} blocks, "
                      f"detached: {det_txt}")
                for ev in batching.get("recent_batches") or []:
                    det = ev.get("detached") or {}
                    det_txt = ", ".join(
                        f"{v} {k}" for k, v in sorted(det.items())) \
                        or "none"
                    print(f"      {ev.get('batch_id', '?')} "
                          f"[{ev.get('spec', '?')}]: "
                          f"{ev.get('members', 0)} members "
                          f"({ev.get('late_joins', 0)} late), peak "
                          f"{ev.get('peak_active', 0)} active, "
                          f"{ev.get('blocks', 0)} blocks, {det_txt}"
                          + (" [ABANDONED]" if ev.get("abandoned")
                             else ""))
        elif kind == "router_stats":
            n_other += 1
            router = record.get("router") or {}
            fleet = record.get("fleet") or {}
            forward = router.get("forward") or {}
            ring = router.get("ring_members") or []
            print(f"(router) {router.get('forwarded', 0)} forwarded, "
                  f"{router.get('failovers', 0)} failovers, "
                  f"{router.get('shed', 0)} shed, "
                  f"{router.get('refusals', 0)} refusals absorbed, "
                  f"ring [{', '.join(ring) or 'empty'}], "
                  f"forward p50 {forward.get('p50_ms', '?')} ms / "
                  f"p95 {forward.get('p95_ms', '?')} ms, "
                  f"uptime {record.get('uptime_sec', '?')}s")
            # fleet health census (service/fleet.py): one line per
            # replica so a wedged or flapping member reads off directly
            if fleet:
                print(f"    fleet: {fleet.get('restarts', 0)} restarts, "
                      f"{fleet.get('crashes', 0)} crashes, "
                      f"{fleet.get('wedges', 0)} wedges, "
                      f"{fleet.get('watchdog_fires', 0)} watchdog "
                      "postmortems")
                for name, rep in sorted(
                        (fleet.get("replicas") or {}).items()):
                    state = rep.get("state", "?")
                    if rep.get("draining"):
                        state += " (draining)"
                    print(f"      {name}: {state}, "
                          f"{rep.get('restarts', 0)} restarts, "
                          f"port {rep.get('port', '?')}"
                          + (f", pid {rep['pid']}"
                             if rep.get("pid") else ""))
            codes = router.get("error_codes") or {}
            if codes:
                print("    error codes: "
                      + ", ".join(f"{v} {k}"
                                  for k, v in sorted(codes.items())))
        elif kind == "trace":
            n_other += 1
            from .tools.tracing import summarize_trace
            summary = summarize_trace(record)
            print(f"(trace) {summary['trace_id']}: "
                  f"root {summary['root'] or '?'} "
                  f"{round((summary['root_sec'] or 0.0) * 1e3, 3)} ms, "
                  f"{summary['spans']} spans "
                  f"(`python -m dedalus_tpu trace` for the span tree)")
        elif kind == "watchdog_postmortem":
            n_post += 1
            stacks = record.get("stacks") or []
            print(f"(watchdog) request={record.get('request_id', '?')} "
                  f"stuck {record.get('stuck_sec', '?')}s "
                  f"(limit {record.get('watchdog_sec', '?')}s) at "
                  f"iter={record.get('iteration', '?')}, "
                  f"{len(stacks)} thread stack(s) recorded")
            # held-locks map beside the stacks: recorded only when the
            # daemon ran with the lock-order sanitizer on ([sanitize]
            # LOCK_ORDER) — on a deadlock postmortem this names the lock
            # each thread is blocked on, not just the frame it sits in
            for tname, locks in sorted(
                    (record.get("held_locks") or {}).items()):
                held = ", ".join(locks.get("held") or []) or "none"
                waiting = locks.get("waiting")
                print(f"    locks[{tname}]: held {held}"
                      + (f"; waiting on {waiting}" if waiting else ""))
        elif kind == "ledger":
            # resource-ledger rows (tools/lint/progcheck.py cost tier):
            # one line per census program with deltas against the
            # previous round of the same (program, backend) series, so
            # compile-cost creep reads off the report directly
            n_other += 1
            program = record.get("program") or "?"
            series = (program, record.get("backend"))
            prev = prev_ledger.get(series) or {}
            prev_ledger[series] = record
            if record.get("ledger_version") is None:
                # a row written before the cost tier versioned its
                # fields must render, not crash (mirrors the
                # plan=unversioned backfill rule)
                print(f"(ledger) {program}: ledger=unversioned")
                continue
            cells = []
            for key, label in (("flops", "flops"),
                               ("bytes_accessed", "bytes"),
                               ("peak_bytes", "peak_mem"),
                               ("hlo_instructions", "hlo"),
                               ("scan_max_length", "scan_depth")):
                value = record.get(key)
                if value is None:
                    continue
                cell = f"{label}={value:,}" if isinstance(value, int) \
                    else f"{label}={value}"
                before = prev.get(key)
                if isinstance(before, (int, float)) \
                        and not isinstance(before, bool) and before:
                    delta = 100.0 * (value - before) / before
                    cell += f" ({delta:+.1f}%)"
                cells.append(cell)
            print(f"(ledger) {program} "
                  f"[{record.get('backend') or '?'}]: "
                  + (", ".join(cells) or "no cost data"))
            print(f"    {_format_plan(record)}")
        elif kind == "autotune":
            # tuning-decision rows (tools/autotune.py run_tune): one line
            # per (backend, shape) decision — chosen plan, margin over
            # the runner-up, tuning wall, cache verdict — then the
            # per-cell evidence so a rejected candidate reads off the
            # report without opening the JSONL
            n_other += 1
            print(f"(autotune) {record.get('config', '?')} "
                  f"[{record.get('backend', '?')}"
                  f"/{record.get('device_kind', '?')}]: chosen "
                  f"{record.get('chosen_label', '?')} "
                  f"(margin {record.get('margin', '?')}x, "
                  f"wall {record.get('tuning_wall_sec', '?')}s, "
                  f"cache {record.get('cache', '?')}, "
                  f"{record.get('evidence_kind', '?')}, "
                  f"sig {str(record.get('signature', ''))[:12]})")
            for cell in record.get("cells") or []:
                if not isinstance(cell, dict):
                    continue
                label = (f"{cell.get('composition', '?')}/"
                         f"{cell.get('solve_dtype', '?')}"
                         + ("+pallas" if cell.get("pallas") else ""))
                if cell.get("skipped"):
                    print(f"    {label}: skipped ({cell['skipped']})")
                elif cell.get("error"):
                    print(f"    {label}: ERROR {cell['error']}")
                else:
                    rate = cell.get("steps_per_sec",
                                    cell.get("solves_per_sec", "?"))
                    unit = "steps/s" if "steps_per_sec" in cell \
                        else "solves/s"
                    line = f"    {label}: {rate} {unit}"
                    err = cell.get("rel_err")
                    if isinstance(err, (int, float)):
                        line += f", err {err:.1e}"
                    if cell.get("reference"):
                        line += " (reference)"
                    print(line)
        else:
            n_other += 1
            ident = record.get("metric") or record.get("config") or "record"
            val = record.get("value")
            unit = record.get("unit", "")
            extra = f" = {val} {unit}".rstrip() if val is not None else ""
            stale = " [stale]" if record.get("stale") else ""
            print(f"(other) {ident}{extra}{stale}")
            print(f"    {_format_plan(record)}")
            # ensemble benchmark rows (benchmarks/ensemble.py): one line
            # per sweep point so speedups read without opening the JSONL
            sweep = record.get("sweep")
            if isinstance(sweep, list) and sweep \
                    and isinstance(sweep[0], dict) \
                    and "ensemble_steps_per_sec" in sweep[0]:
                serial = record.get("serial") or {}
                if serial.get("steps_per_sec") is not None:
                    print(f"    serial baseline: "
                          f"{serial['steps_per_sec']} steps/s")
                for point in sweep:
                    print(f"    N={point.get('members', '?')}: "
                          f"{point.get('ensemble_steps_per_sec', '?')} "
                          f"member-steps/s "
                          f"({point.get('speedup_vs_serial', '?')}x serial,"
                          f" {point.get('devices', '?')} device(s))")
            # weak-scaling rows (benchmarks/scaling.py): steps/s per
            # device count with the transpose overlap phase split, the
            # chunked-vs-monolithic guard, north star, and the 2-D
            # batch x pencil fleet bit-match
            if record.get("benchmark") == "scaling" \
                    and isinstance(record.get("sweep"), list):
                for point in record["sweep"]:
                    line = (f"    d={point.get('devices', '?')} "
                            f"{'x'.join(str(s) for s in point.get('shape', []))}: "
                            f"{point.get('steps_per_sec', '?')} steps/s")
                    if point.get("transpose_exposed_sec") is not None:
                        line += (f", transpose exposed "
                                 f"{point['transpose_exposed_sec']}s / "
                                 f"overlapped "
                                 f"{point.get('transpose_overlapped_sec', '?')}s")
                    if point.get("all_gathers") is not None:
                        line += (f", {point.get('all_to_alls', '?')} a2a / "
                                 f"{point['all_gathers']} gathers")
                    print(line)
                guard = record.get("chunked_vs_mono")
                if isinstance(guard, dict):
                    print(f"    chunked({record.get('chunks', '?')}) vs "
                          f"mono: {guard.get('chunked_steps_per_sec', '?')} "
                          f"vs {guard.get('mono_steps_per_sec', '?')} "
                          f"steps/s ({guard.get('ratio', '?')}x, "
                          f"bit_identical="
                          f"{guard.get('bit_identical', '?')})")
                ns = record.get("northstar")
                if isinstance(ns, dict) and ns.get("steps_per_sec"):
                    print(f"    north star "
                          f"{'x'.join(str(s) for s in ns.get('shape', []))}"
                          f" on {ns.get('devices', '?')} devices: "
                          f"{ns['steps_per_sec']} steps/s "
                          f"(finite={ns.get('finite', '?')})")
                fleet = record.get("fleet2d")
                if isinstance(fleet, dict):
                    print(f"    2-D fleet {fleet.get('members', '?')} "
                          f"members on "
                          f"{'x'.join(str(s) for s in fleet.get('mesh', []))}"
                          f" batch x pencil: bit_match_1d="
                          f"{fleet.get('bit_match_1d', '?')}")
            # fusion benchmark rows (benchmarks/fusion.py): fused vs
            # unfused steps/s and the documented trajectory tolerance
            if record.get("fusion_speedup") is not None:
                plan = record.get("fusion") or {}
                on = "+".join(k for k in ("solve", "matvec", "transforms",
                                          "donate", "pallas")
                              if plan.get(k)) or "off"
                print(f"    fusion: "
                      f"{record.get('steps_per_sec_unfused', '?')} -> "
                      f"{record.get('steps_per_sec_fused', '?')} steps/s "
                      f"({record.get('fusion_speedup', '?')}x, {on}; "
                      f"state rel diff "
                      f"{record.get('state_rel_diff', '?')})")
            # solve-composition sweep rows (benchmarks/fusion.py
            # run_solve_sweep): per-cell steps/s + accuracy, and the
            # two acceptance bars in one summary line
            if record.get("benchmark") == "solvecomp" \
                    and isinstance(record.get("sweep"), list):
                for cell in record["sweep"]:
                    line = (f"    {cell.get('composition', '?')}/"
                            f"{cell.get('solve_dtype', '?')}: "
                            f"{cell.get('steps_per_sec', '?')} steps/s")
                    if cell.get("baseline"):
                        line += " (baseline)"
                    else:
                        line += (f" ({cell.get('speedup', '?')}x, err "
                                 f"{cell.get('state_rel_err', '?')})")
                    if cell.get("achieved_residual") is not None:
                        line += (f", resid {cell['achieved_residual']:.1e}"
                                 f" @ {cell.get('refine_sweeps', '?')} "
                                 "sweep(s)")
                    print(line)
                best = record.get("best_f64_accurate")
                ladder = record.get("ladder")
                if best:
                    print(f"    best f64-accurate: {best['composition']}/"
                          f"{best['solve_dtype']} {best.get('speedup', '?')}x"
                          f" (meets_1p15x={record.get('meets_1p15x', '?')})")
                if ladder:
                    print(f"    ladder: {ladder['composition']}/"
                          f"{ladder['solve_dtype']} "
                          f"{ladder.get('speedup', '?')}x, state err "
                          f"{ladder.get('state_rel_err', '?')} "
                          f"(meets_1e10="
                          f"{record.get('ladder_meets_1e10', '?')})")
            # serving benchmark rows (benchmarks/serving.py): the cold-
            # miss vs warm-hit time-to-first-step comparison in one line
            if record.get("ttfs_cold_sec") is not None \
                    or record.get("ttfs_warm_sec") is not None:
                line = (f"    serving: ttfs cold "
                        f"{record.get('ttfs_cold_sec', '?')}s -> warm "
                        f"{record.get('ttfs_warm_sec', '?')}s "
                        f"({record.get('ttfs_speedup', '?')}x)")
                if record.get("throughput_requests_per_sec") is not None:
                    line += (f", {record['throughput_requests_per_sec']} "
                             "requests/s")
                print(line)
            # adjoint benchmark rows (benchmarks/adjoint.py): the grad/
            # forward cost ratio and the segment-memory sweep in one block
            if record.get("grad_forward_ratio") is not None:
                line = (f"    adjoint: grad "
                        f"{record.get('grad_steps_per_sec', '?')} steps/s "
                        f"vs forward "
                        f"{record.get('forward_steps_per_sec', '?')} "
                        f"steps/s ({record['grad_forward_ratio']}x)")
                if record.get("fd_rel_err") is not None:
                    line += f", fd_rel={record['fd_rel_err']:.1e}"
                print(line)
                for point in record.get("segments_sweep") or []:
                    if point.get("error"):
                        print(f"      K={point.get('segments', '?')}: "
                              f"{point['error']}")
                        continue
                    rss = point.get("peak_rss_bytes")
                    line = (f"      K={point.get('segments', '?')}: "
                            f"{point.get('grad_steps_per_sec', '?')} "
                            f"grad-steps/s")
                    if rss:
                        line += f", peak RSS {rss / 1e6:.1f} MB"
                    print(line)
            # checkpoint benchmark rows (benchmarks/checkpointing.py):
            # per-checkpoint step-loop stall by mode + fault-restore wall
            if record.get("stall_async_sharded_sec") is not None:
                line = (f"    checkpoint: stall hdf5 "
                        f"{record.get('stall_sync_hdf5_sec', '?')}s / "
                        f"sharded {record.get('stall_sync_sharded_sec', '?')}"
                        f"s / async {record['stall_async_sharded_sec']}s"
                        f" ({record.get('stall_reduction_async_vs_hdf5', '?')}"
                        f"x less stall)")
                if record.get("restore_after_fault_sec") is not None:
                    line += (f", restore-after-fault "
                             f"{record['restore_after_fault_sec']}s")
                print(line)
            # continuous-batching benchmark rows (benchmarks/serving.py
            # run_batching): the requests/s multiplier in one line
            if record.get("requests_speedup") is not None:
                print(f"    batching: "
                      f"{record.get('batched_requests_per_sec', '?')} "
                      f"vs {record.get('baseline_requests_per_sec', '?')}"
                      f" requests/s ({record['requests_speedup']}x, "
                      f"{record.get('clients', '?')} clients, "
                      f"{record.get('batches', '?')} batches, "
                      f"{record.get('late_joins', '?')} late joins, "
                      f"peak {record.get('peak_batch_members', '?')} "
                      "seats)")
            # overload benchmark rows (benchmarks/serving.py storm): the
            # shed-rate and bounded-latency story in one line
            if record.get("shed_rate") is not None:
                shed_pct = round(100.0 * record["shed_rate"], 1)
                line = (f"    overload: {record.get('storm_rate_x', '?')}x "
                        f"capacity storm, {shed_pct}% shed, accepted p50 "
                        f"{record.get('accepted_p50_sec', '?')}s / p95 "
                        f"{record.get('accepted_p95_sec', '?')}s "
                        f"(bound {record.get('latency_bound_sec', '?')}s), "
                        f"{record.get('daemon_restarts', '?')} daemon "
                        "restarts")
                if record.get("max_queued_observed") is not None:
                    line += (f", max queued "
                             f"{record['max_queued_observed']}"
                             f"/{record.get('queue_depth', '?')}")
                print(line)
            # replica-fleet scaling rows (benchmarks/serving.py
            # run_router_scaling): aggregate requests/s per replica
            # count plus the routing tax, in one line
            if record.get("requests_speedup_4v1") is not None:
                sweep = record.get("replica_requests_per_sec") or {}
                sweep_txt = ", ".join(
                    f"{n}r={v}" for n, v in sorted(sweep.items()))
                print(f"    router: {sweep_txt} requests/s "
                      f"({record['requests_speedup_4v1']}x at 4 "
                      f"replicas, {record.get('specs', '?')} specs, "
                      f"{record.get('clients', '?')} clients, forward "
                      f"overhead p50 "
                      f"{record.get('forward_overhead_p50_ms', '?')} ms)")
    # perf-trajectory trend table (tools/perfwatch.py): only series with
    # enough history to analyze render, so short fixture files and fresh
    # sinks add nothing here
    try:
        from .tools import perfwatch
        trends = perfwatch.trend_lines(records)
    except Exception:
        trends = []
    if trends:
        print("perfwatch trends:")
        for tline in trends:
            print(f"    {tline}")
    print(f"{n_metrics} metrics record(s), {n_other} other, "
          f"{n_post} postmortem, {n_bad} unparsable")
    if n_metrics == 0 and n_other == 0 and n_post == 0:
        sys.exit(1)


def trace(args):
    """Inspect request traces (tools/tracing.py records, written by
    `serve --trace` or the metrics sink): indented span trees by default,
    `--chrome OUT` exports Chrome trace-event JSON for Perfetto /
    chrome://tracing, `--summary` one line per trace."""
    from .tools import tracing
    try:
        records = tracing.load_trace_records(args.jsonl)
    except OSError as exc:
        print(f"trace: cannot read {args.jsonl}: {exc}", file=sys.stderr)
        sys.exit(1)
    if args.trace_id:
        records = [r for r in records
                   if str(r.get("trace_id", "")).startswith(args.trace_id)]
    if args.last is not None:
        records = records[-args.last:] if args.last > 0 else []
    if not records:
        print("trace: no matching trace records", file=sys.stderr)
        sys.exit(1)
    if args.chrome:
        out = pathlib.Path(args.chrome)
        out.write_text(json.dumps(tracing.chrome_trace_from_records(records)))
        total = sum(len(r.get("spans", [])) for r in records)
        print(f"wrote {len(records)} trace(s), {total} span(s) -> {out}")
        return
    for record in records:
        if args.summary:
            summary = tracing.summarize_trace(record)
            top = ", ".join(
                f"{name} {sec * 1e3:.3f}ms"
                for name, sec in list(summary["by_name"].items())[:4])
            print(f"{summary['trace_id']}: "
                  f"root {summary['root'] or '?'} "
                  f"{(summary['root_sec'] or 0.0) * 1e3:.3f} ms, "
                  f"{summary['spans']} spans ({top})")
        else:
            for line in tracing.format_trace_tree(record):
                print(line)


def postmortem(args):
    """Summarize a health flight-recorder dump (tools/health.py): accepts
    the post-mortem directory or a record file inside it."""
    from .tools.health import read_postmortem, format_postmortem
    path = pathlib.Path(args.directory)
    try:
        record, ring = read_postmortem(path)
    except (OSError, ValueError) as exc:
        print(f"postmortem: cannot read {path}: {exc}", file=sys.stderr)
        sys.exit(1)
    for line in format_postmortem(record, ring):
        print(line)


def tune(args):
    """Pre-tune solve-plan decisions offline (tools/autotune.py): run
    the step-level candidate sweep for one benchmark problem, persist
    the winning decision in the assembly cache (warming every later
    build and the whole serving fleet sharing that cache), and append a
    `kind: autotune` evidence row to benchmarks/results.jsonl."""
    from .tools.autotune import run_tune
    sys.exit(run_tune(problem=args.problem, force=args.force,
                      quick=args.quick, as_json=args.json,
                      record=not args.no_record, steps=args.steps,
                      budget=args.budget))


def lint(argv):
    """Static analysis (tools/lint): the DTL AST rule set plus, under
    --programs, the DTP compiled-program contract census
    (tools/lint/progcheck.py — collective placement, donation aliasing,
    forbidden primitives, manual-region integrity over the lowered
    step/fleet/grad programs; CPU-only). Nonzero exit on findings not
    covered by the per-tier baseline."""
    from .tools.lint.cli import main as lint_main
    sys.exit(lint_main(argv))


def perfwatch(argv):
    """Perf-trajectory regression sentinel (tools/perfwatch.py): noise-
    banded trend analysis over benchmarks/results.jsonl; `--check` exits
    nonzero on an unwaived regression."""
    from .tools.perfwatch import main as perfwatch_main
    sys.exit(perfwatch_main(argv))


def serve(argv):
    """Warm-pool solver daemon (dedalus_tpu/service/server.py)."""
    from .service.server import main as serve_main
    sys.exit(serve_main(argv))


def submit(argv):
    """Submit one run to a serve daemon (dedalus_tpu/service/client.py)."""
    from .service.client import main as submit_main
    sys.exit(submit_main(argv))


def route(argv):
    """Spec-hash router fronting a SolverService replica fleet
    (dedalus_tpu/service/router.py; docs/serving.md#replica-fleet)."""
    from .service.router import main as route_main
    sys.exit(route_main(argv))


# Subcommands that own their whole argument surface (each has its own
# argparse parser, including --help): dispatched BEFORE the top-level
# parser sees the argv tail — argparse's REMAINDER does not reliably
# capture leading options like `--help`, so forwarding must bypass it.
PASSTHROUGH = {"lint": lint, "perfwatch": perfwatch, "serve": serve,
               "submit": submit, "route": route}


def build_parser():
    doc_lines = (__doc__ or "").strip().splitlines()
    parser = argparse.ArgumentParser(
        prog="python -m dedalus_tpu",
        # docstrings are stripped under -OO: fall back rather than index
        description=doc_lines[0] if doc_lines
        else "dedalus_tpu command-line interface")
    sub = parser.add_subparsers(dest="command", metavar="command",
                                required=True)
    sub.add_parser("test", help="run the tier-1 test suite "
                                "(slow-marked tests excluded)"
                   ).set_defaults(func=test)
    sub.add_parser("bench", help="run the benchmark (bench.py)"
                   ).set_defaults(func=bench)
    sub.add_parser("cov", help="test suite under coverage"
                   ).set_defaults(func=cov)
    sub.add_parser("get_config", help="print the resolved configuration"
                   ).set_defaults(func=get_config)
    sub.add_parser("get_examples", help="print the examples directory"
                   ).set_defaults(func=get_examples)
    p = sub.add_parser("report", help="summarize a metrics/results JSONL "
                                      "file (tools/metrics.py records)")
    p.add_argument("jsonl", help="path to the JSONL file")
    p.add_argument("--last", type=int, default=None, metavar="N",
                   help="only the N most recent parsable rows")
    p.set_defaults(func=report)
    p = sub.add_parser("trace", help="inspect request traces "
                                     "(span trees, Chrome JSON export)")
    p.add_argument("jsonl", help="trace/metrics JSONL file "
                                 "(serve --trace output or telemetry sink)")
    p.add_argument("--trace-id", default=None, metavar="PREFIX",
                   help="only traces whose id starts with PREFIX")
    p.add_argument("--last", type=int, default=None, metavar="N",
                   help="only the N most recent matching traces")
    p.add_argument("--chrome", default=None, metavar="OUT",
                   help="write Chrome trace-event JSON (Perfetto / "
                        "chrome://tracing) instead of printing trees")
    p.add_argument("--summary", action="store_true",
                   help="one line per trace instead of the span tree")
    p.set_defaults(func=trace)
    p = sub.add_parser("postmortem", help="summarize a health post-mortem "
                                          "dump (tools/health.py)")
    p.add_argument("directory", help="post-mortem directory or record file")
    p.set_defaults(func=postmortem)
    p = sub.add_parser("tune", help="pre-tune solve-plan decisions "
                                    "offline (tools/autotune.py; "
                                    "docs/performance.md#autotuning)")
    p.add_argument("--problem", default="rb256x64",
                   choices=("rb256x64", "rb64x32", "diffusion64"),
                   help="benchmark problem to tune (default rb256x64)")
    p.add_argument("--force", action="store_true",
                   help="re-measure and overwrite any cached decision")
    p.add_argument("--json", action="store_true",
                   help="print the decision row as JSON")
    p.add_argument("--quick", action="store_true",
                   help="reduced-budget smoke run (no results row)")
    p.add_argument("--steps", type=int, default=None, metavar="N",
                   help="override [autotune] TUNE_STEPS")
    p.add_argument("--budget", type=float, default=None, metavar="SEC",
                   help="override [autotune] TUNE_BUDGET_SEC")
    p.add_argument("--no-record", action="store_true",
                   help="do not append to benchmarks/results.jsonl")
    p.set_defaults(func=tune)
    # pass-through subcommands: listed here so the top-level --help names
    # them, but main() dispatches them before this parser ever runs
    for name, helptext in (
            ("lint", "static analysis (DTL AST rules; DTP program "
                     "contracts via --programs); see `lint --help`"),
            ("perfwatch", "perf-trajectory regression sentinel over "
                          "benchmarks/results.jsonl; see "
                          "`perfwatch --help`"),
            ("serve", "warm-pool solver daemon (docs/serving.md); "
                      "see `serve --help`"),
            ("submit", "submit one run to a serve daemon; "
                       "see `submit --help`"),
            ("route", "spec-hash router fronting a replica fleet "
                      "(docs/serving.md#replica-fleet); see "
                      "`route --help`")):
        sub.add_parser(name, help=helptext, add_help=False)
    return parser


def main():
    if len(sys.argv) > 1 and sys.argv[1] in PASSTHROUGH:
        PASSTHROUGH[sys.argv[1]](sys.argv[2:])
        return
    args = build_parser().parse_args()
    args.func(args)


if __name__ == "__main__":
    main()
