"""
Command-line interface (reference: dedalus/__main__.py:1-45):

    python -m dedalus_tpu test            # run the test suite
    python -m dedalus_tpu bench           # run the benchmark (bench.py)
    python -m dedalus_tpu get_config      # print the resolved configuration
    python -m dedalus_tpu get_examples    # print the examples directory
    python -m dedalus_tpu report F.jsonl [--last N]  # summarize metrics JSONL
    python -m dedalus_tpu postmortem DIR  # summarize a health post-mortem
    python -m dedalus_tpu lint [paths]    # jit-hygiene static analysis
"""

import json
import pathlib
import sys


def test():
    import pytest
    # fail fast on a missing/stale lint baseline: tests/test_lint.py would
    # fail anyway, but only after the whole suite ran — and a stale
    # baseline usually means a fixed hazard whose grandfathering should be
    # dropped in the SAME commit
    from .tools.lint import check_baseline_fresh
    problems = check_baseline_fresh()
    if problems:
        for problem in problems:
            print(f"test: {problem}", file=sys.stderr)
        sys.exit(1)
    root = pathlib.Path(__file__).parent.parent
    # tier-1 semantics: slow-marked tests (long timing runs) are opt-in
    # via pytest directly; chaos-marked fault-injection tests
    # (tests/test_resilience.py) are fast and run by default — recovery
    # paths that are not exercised do not exist
    sys.exit(pytest.main([str(root / "tests"), "-q", "-m", "not slow"]))


def bench():
    import runpy
    root = pathlib.Path(__file__).parent.parent
    bench_path = root / "bench.py"
    if not bench_path.exists():
        print("bench.py not found next to the package", file=sys.stderr)
        sys.exit(1)
    runpy.run_path(str(bench_path), run_name="__main__")


def cov():
    """Test suite under coverage (reference: dedalus/tests/__init__.py:30
    cov). Requires the `coverage` package. Runs in a fresh interpreter so
    coverage measures modules imported by the package itself (starting
    coverage after this import would under-report __init__/tools)."""
    try:
        import coverage  # noqa: F401
    except ImportError:
        print("cov requires the 'coverage' package (pip install coverage)",
              file=sys.stderr)
        sys.exit(1)
    import subprocess
    root = pathlib.Path(__file__).parent.parent
    rc = subprocess.run(
        [sys.executable, "-m", "coverage", "run", "--source=dedalus_tpu",
         "-m", "pytest", str(root / "tests"), "-q", "-m", "not slow"],
        cwd=root).returncode
    subprocess.run([sys.executable, "-m", "coverage", "report"], cwd=root)
    sys.exit(rc)


def get_config():
    from .tools.config import config
    config.write(sys.stdout)


def get_examples():
    root = pathlib.Path(__file__).parent.parent / "examples"
    print(root)


def report():
    """Summarize a metrics JSONL file (tools/metrics.py records; bench rows
    from benchmarks/results.jsonl listed briefly; health post-mortem
    records get their own line). Tolerates heterogeneous rows — records
    from before any given key existed print with defaults rather than
    crashing. `--last N` restricts to the N most recent parsable rows."""
    from .tools.metrics import format_phase_table
    args = sys.argv[2:]
    last = None
    if "--last" in args:
        i = args.index("--last")
        try:
            last = int(args[i + 1])
        except (IndexError, ValueError):
            print("report: --last requires an integer", file=sys.stderr)
            sys.exit(2)
        args = args[:i] + args[i + 2:]
    if not args:
        print("usage: python -m dedalus_tpu report <metrics.jsonl> "
              "[--last N]", file=sys.stderr)
        sys.exit(2)
    path = pathlib.Path(args[0])
    try:
        lines = path.read_text().splitlines()
    except OSError as exc:
        print(f"report: cannot read {path}: {exc}", file=sys.stderr)
        sys.exit(1)
    records = []
    n_bad = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            n_bad += 1
            continue
        if not isinstance(record, dict):
            n_bad += 1
            continue
        records.append(record)
    if last is not None:
        records = records[-last:] if last > 0 else []
    n_metrics = n_post = n_other = 0
    for record in records:
        kind = record.get("kind")
        if kind == "step_metrics":
            n_metrics += 1
            ident = " ".join(
                f"{k}={record[k]}" for k in ("config", "backend", "dtype")
                if record.get(k) is not None)
            print(f"[{n_metrics}] {ident or 'step_metrics'}: "
                  f"{record.get('iterations', 0)} iters, "
                  f"{record.get('steps_per_sec', 0.0)} steps/s, "
                  f"{record.get('phase_samples', 0)} samples "
                  f"(cadence {record.get('sample_cadence', '?')})")
            # format_phase_table's first line repeats the sample count
            # already printed in the header above
            for tline in format_phase_table(record, indent="    ")[1:]:
                print(tline)
            health = record.get("health")
            if isinstance(health, dict):
                status = "ok" if health.get("ok", True) else \
                    f"FAILED: {health.get('reason', '?')}"
                print(f"    health: {status}, "
                      f"{health.get('checks', 0)} checks, "
                      f"{health.get('warnings', 0)} warnings")
            ensemble = record.get("ensemble")
            if isinstance(ensemble, dict):
                parts = [f"{ensemble.get('members', '?')} members",
                         f"{ensemble.get('active', '?')} active",
                         f"{ensemble.get('dropped', 0)} dropped"]
                if ensemble.get("rewinds"):
                    parts.append(f"{ensemble['rewinds']} rewinds")
                parts.append(
                    f"{ensemble.get('ensemble_steps_per_sec', 0.0)} "
                    f"member-steps/s")
                if ensemble.get("devices"):
                    parts.append(f"{ensemble['devices']} device(s)")
                print(f"    ensemble: {', '.join(parts)}")
                if ensemble.get("dropped_members"):
                    print(f"    dropped members: "
                          f"{ensemble['dropped_members']}")
            resilience = record.get("resilience")
            if isinstance(resilience, dict):
                parts = [f"{resilience.get('rewinds', 0)} rewinds",
                         f"{resilience.get('retries', 0)} retries"]
                if resilience.get("dt_limit") is not None:
                    parts.append(f"dt capped {resilience['dt_limit']}")
                if resilience.get("stopped_by"):
                    parts.append(f"stopped by {resilience['stopped_by']}")
                if resilience.get("resumed_from"):
                    parts.append(
                        f"resumed from {resilience['resumed_from']} "
                        f"(write {resilience.get('resume_write', '?')})")
                print(f"    resilience: {', '.join(parts)}")
        elif kind == "health_postmortem":
            n_post += 1
            resilience = record.get("resilience")
            lineage = ""
            if isinstance(resilience, dict) and resilience.get("retries"):
                lineage = (f" (retry {resilience['retries']}, "
                           f"{resilience.get('rewinds', 0)} rewinds)")
            print(f"(postmortem) iter={record.get('iteration', '?')} "
                  f"sim_time={record.get('sim_time', '?')}: "
                  f"{record.get('reason', '(no reason)')}{lineage}"
                  + (f" [{record.get('directory')}]"
                     if record.get("directory") else ""))
        else:
            n_other += 1
            ident = record.get("metric") or record.get("config") or "record"
            val = record.get("value")
            unit = record.get("unit", "")
            extra = f" = {val} {unit}".rstrip() if val is not None else ""
            stale = " [stale]" if record.get("stale") else ""
            print(f"(other) {ident}{extra}{stale}")
            # ensemble benchmark rows (benchmarks/ensemble.py): one line
            # per sweep point so speedups read without opening the JSONL
            sweep = record.get("sweep")
            if isinstance(sweep, list) and sweep \
                    and isinstance(sweep[0], dict) \
                    and "ensemble_steps_per_sec" in sweep[0]:
                serial = record.get("serial") or {}
                if serial.get("steps_per_sec") is not None:
                    print(f"    serial baseline: "
                          f"{serial['steps_per_sec']} steps/s")
                for point in sweep:
                    print(f"    N={point.get('members', '?')}: "
                          f"{point.get('ensemble_steps_per_sec', '?')} "
                          f"member-steps/s "
                          f"({point.get('speedup_vs_serial', '?')}x serial,"
                          f" {point.get('devices', '?')} device(s))")
    print(f"{n_metrics} metrics record(s), {n_other} other, "
          f"{n_post} postmortem, {n_bad} unparsable")
    if n_metrics == 0 and n_other == 0 and n_post == 0:
        sys.exit(1)


def postmortem():
    """Summarize a health flight-recorder dump (tools/health.py): accepts
    the post-mortem directory or a record file inside it."""
    from .tools.health import read_postmortem, format_postmortem
    if len(sys.argv) < 3:
        print("usage: python -m dedalus_tpu postmortem <dir-or-record>",
              file=sys.stderr)
        sys.exit(2)
    path = pathlib.Path(sys.argv[2])
    try:
        record, ring = read_postmortem(path)
    except (OSError, ValueError) as exc:
        print(f"postmortem: cannot read {path}: {exc}", file=sys.stderr)
        sys.exit(1)
    for line in format_postmortem(record, ring):
        print(line)


def lint():
    """Jit-hygiene static analysis (tools/lint): DTL rule set, baseline,
    suppressions. Nonzero exit on findings not covered by the baseline."""
    from .tools.lint.cli import main as lint_main
    sys.exit(lint_main(sys.argv[2:]))


def main():
    commands = {"test": test, "bench": bench, "cov": cov,
                "get_config": get_config, "get_examples": get_examples,
                "report": report, "postmortem": postmortem, "lint": lint}
    if len(sys.argv) < 2 or sys.argv[1] not in commands:
        print(f"usage: python -m dedalus_tpu [{'|'.join(commands)}]",
              file=sys.stderr)
        sys.exit(2)
    commands[sys.argv[1]]()


if __name__ == "__main__":
    main()
