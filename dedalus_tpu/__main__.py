"""
Command-line interface (reference: dedalus/__main__.py:1-45):

    python -m dedalus_tpu test            # run the test suite
    python -m dedalus_tpu bench           # run the benchmark (bench.py)
    python -m dedalus_tpu get_config      # print the resolved configuration
    python -m dedalus_tpu get_examples    # print the examples directory
    python -m dedalus_tpu report F.jsonl  # summarize a metrics JSONL file
"""

import json
import pathlib
import sys


def test():
    import pytest
    root = pathlib.Path(__file__).parent.parent
    # tier-1 semantics: slow-marked tests (long timing runs) are opt-in
    # via pytest directly
    sys.exit(pytest.main([str(root / "tests"), "-q", "-m", "not slow"]))


def bench():
    import runpy
    root = pathlib.Path(__file__).parent.parent
    bench_path = root / "bench.py"
    if not bench_path.exists():
        print("bench.py not found next to the package", file=sys.stderr)
        sys.exit(1)
    runpy.run_path(str(bench_path), run_name="__main__")


def cov():
    """Test suite under coverage (reference: dedalus/tests/__init__.py:30
    cov). Requires the `coverage` package. Runs in a fresh interpreter so
    coverage measures modules imported by the package itself (starting
    coverage after this import would under-report __init__/tools)."""
    try:
        import coverage  # noqa: F401
    except ImportError:
        print("cov requires the 'coverage' package (pip install coverage)",
              file=sys.stderr)
        sys.exit(1)
    import subprocess
    root = pathlib.Path(__file__).parent.parent
    rc = subprocess.run(
        [sys.executable, "-m", "coverage", "run", "--source=dedalus_tpu",
         "-m", "pytest", str(root / "tests"), "-q", "-m", "not slow"],
        cwd=root).returncode
    subprocess.run([sys.executable, "-m", "coverage", "report"], cwd=root)
    sys.exit(rc)


def get_config():
    from .tools.config import config
    config.write(sys.stdout)


def get_examples():
    root = pathlib.Path(__file__).parent.parent / "examples"
    print(root)


def report():
    """Summarize a metrics JSONL file (tools/metrics.py records; bench rows
    from benchmarks/results.jsonl are listed briefly)."""
    from .tools.metrics import format_phase_table
    if len(sys.argv) < 3:
        print("usage: python -m dedalus_tpu report <metrics.jsonl>",
              file=sys.stderr)
        sys.exit(2)
    path = pathlib.Path(sys.argv[2])
    try:
        lines = path.read_text().splitlines()
    except OSError as exc:
        print(f"report: cannot read {path}: {exc}", file=sys.stderr)
        sys.exit(1)
    n_metrics = n_other = n_bad = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            n_bad += 1
            continue
        if record.get("kind") == "step_metrics":
            n_metrics += 1
            ident = " ".join(
                f"{k}={record[k]}" for k in ("config", "backend", "dtype")
                if record.get(k) is not None)
            print(f"[{n_metrics}] {ident or 'step_metrics'}: "
                  f"{record.get('iterations', 0)} iters, "
                  f"{record.get('steps_per_sec', 0.0)} steps/s, "
                  f"{record.get('phase_samples', 0)} samples "
                  f"(cadence {record.get('sample_cadence', '?')})")
            # format_phase_table's first line repeats the sample count
            # already printed in the header above
            for tline in format_phase_table(record, indent="    ")[1:]:
                print(tline)
        else:
            n_other += 1
            ident = record.get("metric") or record.get("config") or "record"
            val = record.get("value")
            unit = record.get("unit", "")
            extra = f" = {val} {unit}".rstrip() if val is not None else ""
            print(f"(other) {ident}{extra}")
    print(f"{n_metrics} metrics record(s), {n_other} other, "
          f"{n_bad} unparsable")
    if n_metrics == 0 and n_other == 0:
        sys.exit(1)


def main():
    commands = {"test": test, "bench": bench, "cov": cov,
                "get_config": get_config, "get_examples": get_examples,
                "report": report}
    if len(sys.argv) < 2 or sys.argv[1] not in commands:
        print(f"usage: python -m dedalus_tpu [{'|'.join(commands)}]",
              file=sys.stderr)
        sys.exit(2)
    commands[sys.argv[1]]()


if __name__ == "__main__":
    main()
