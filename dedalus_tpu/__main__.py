"""
Command-line interface (reference: dedalus/__main__.py:1-45):

    python -m dedalus_tpu test          # run the test suite
    python -m dedalus_tpu bench         # run the benchmark (bench.py)
    python -m dedalus_tpu get_config    # print the resolved configuration
    python -m dedalus_tpu get_examples  # print the examples directory
"""

import pathlib
import sys


def test():
    import pytest
    root = pathlib.Path(__file__).parent.parent
    sys.exit(pytest.main([str(root / "tests"), "-q"]))


def bench():
    import runpy
    root = pathlib.Path(__file__).parent.parent
    bench_path = root / "bench.py"
    if not bench_path.exists():
        print("bench.py not found next to the package", file=sys.stderr)
        sys.exit(1)
    runpy.run_path(str(bench_path), run_name="__main__")


def cov():
    """Test suite under coverage (reference: dedalus/tests/__init__.py:30
    cov). Requires the `coverage` package. Runs in a fresh interpreter so
    coverage measures modules imported by the package itself (starting
    coverage after this import would under-report __init__/tools)."""
    try:
        import coverage  # noqa: F401
    except ImportError:
        print("cov requires the 'coverage' package (pip install coverage)",
              file=sys.stderr)
        sys.exit(1)
    import subprocess
    root = pathlib.Path(__file__).parent.parent
    rc = subprocess.run(
        [sys.executable, "-m", "coverage", "run", "--source=dedalus_tpu",
         "-m", "pytest", str(root / "tests"), "-q"], cwd=root).returncode
    subprocess.run([sys.executable, "-m", "coverage", "report"], cwd=root)
    sys.exit(rc)


def get_config():
    from .tools.config import config
    config.write(sys.stdout)


def get_examples():
    root = pathlib.Path(__file__).parent.parent / "examples"
    print(root)


def main():
    commands = {"test": test, "bench": bench, "cov": cov,
                "get_config": get_config, "get_examples": get_examples}
    if len(sys.argv) < 2 or sys.argv[1] not in commands:
        print(f"usage: python -m dedalus_tpu [{'|'.join(commands)}]",
              file=sys.stderr)
        sys.exit(2)
    commands[sys.argv[1]]()


if __name__ == "__main__":
    main()
