"""
Plotting helpers for grid data (reference: dedalus/extras/plot_tools.py —
same public surface, original implementation).

Covers the reference's plotting toolkit so its example plot scripts port
unchanged:

  * `FieldWrapper` / `DimWrapper` — h5py-dataset facade over live Fields
  * `plot_bot`, `plot_bot_2d`, `plot_bot_3d` — quadmesh plots with a
    top-mounted colorbar, from h5py datasets or Fields
  * `MultiFigure`, `Box`, `Frame` — paper-layout figure grids with
    image/pad/margin arithmetic
  * `quad_mesh`, `get_1d_vertices`, `pad_limits`, `get_plane` — mesh and
    limit helpers for pcolormesh-style plotting

matplotlib is imported lazily so headless installs only pay for it when
plotting.
"""

import numpy as np


# ----------------------------------------------------------------------
# Field facade (mimic the h5py dataset interface)

class DimWrapper:
    """Dimension-scale facade for one axis of a Field
    (reference: extras/plot_tools.py DimWrapper)."""

    def __init__(self, field, axis):
        self.field = field
        self.axis = axis

    @property
    def label(self):
        tdim = len(self.field.tensorsig)
        if self.axis < tdim:
            return "component"
        coord_axis = self.axis - tdim
        basis = self.field.domain.bases[coord_axis]
        if basis is None:
            return f"const_{coord_axis}"
        sub = coord_axis - basis.first_axis
        if basis.dim == 1:
            return basis.coord.name
        return basis.cs.names[sub]

    def __getitem__(self, scale):
        """Grid points for this axis; `scale` may be 0 (natural scales) or
        a float scale factor."""
        tdim = len(self.field.tensorsig)
        if self.axis < tdim:
            return np.arange(self.field.tensorsig[self.axis].dim)
        coord_axis = self.axis - tdim
        basis = self.field.domain.bases[coord_axis]
        if basis is None:
            return np.zeros(1)
        factor = 1.0 if (scale == 0 or scale is None) else float(scale)
        sub = coord_axis - basis.first_axis
        if basis.dim == 1:
            return np.ravel(basis.global_grid(factor))
        grids = basis.global_grids((factor,) * basis.dim)
        return np.ravel(grids[sub])


class FieldWrapper:
    """h5py-dataset facade over a live Field, so the same plotting entry
    points accept Fields and datasets (reference: extras/plot_tools.py
    FieldWrapper)."""

    def __init__(self, field):
        self.field = field
        self.name = getattr(field, "name", "field")

    @property
    def shape(self):
        return np.asarray(self.field["g"]).shape

    @property
    def dims(self):
        return [DimWrapper(self.field, axis)
                for axis in range(len(self.shape))]

    def __getitem__(self, slices):
        return np.asarray(self.field["g"])[slices]


# ----------------------------------------------------------------------
# Mesh helpers

def get_1d_vertices(grid, cut_edges=False):
    """Vertices dividing a 1d grid: interior vertices at midpoints; edge
    vertices tight to the grid (cut_edges) or reflected past it
    (reference: extras/plot_tools.py get_1d_vertices)."""
    grid = np.asarray(grid)
    if grid.ndim != 1:
        raise ValueError("grid must be 1d array.")
    if grid.size == 1:
        return np.array([grid[0] - 0.5, grid[0] + 0.5])
    mid = 0.5 * (grid[:-1] + grid[1:])
    if cut_edges:
        lo, hi = grid[0], grid[-1]
    else:
        lo = grid[0] - (mid[0] - grid[0])
        hi = grid[-1] + (grid[-1] - mid[-1])
    return np.concatenate([[lo], mid, [hi]])


def quad_mesh(x, y, cut_x_edges=False, cut_y_edges=False):
    """(xmesh, ymesh) vertex arrays for plt.pcolormesh from cell-center
    grids: x along the LAST mesh axis, y along the first
    (reference: extras/plot_tools.py quad_mesh)."""
    xvert = get_1d_vertices(np.ravel(x), cut_edges=cut_x_edges)
    yvert = get_1d_vertices(np.ravel(y), cut_edges=cut_y_edges)
    xmesh = np.broadcast_to(xvert[None, :], (yvert.size, xvert.size)).copy()
    ymesh = np.broadcast_to(yvert[:, None], (yvert.size, xvert.size)).copy()
    return xmesh, ymesh


def pad_limits(xgrid, ygrid, xpad=0.0, ypad=0.0, square=None):
    """[x0, x1, y0, y1] plot limits with fractional padding; optionally
    extended to a square aspect within axes `square`
    (reference: extras/plot_tools.py pad_limits)."""
    xgrid = np.asarray(xgrid)
    ygrid = np.asarray(ygrid)
    dx = xgrid.max() - xgrid.min()
    dy = ygrid.max() - ygrid.min()
    x0, x1 = xgrid.min() - xpad * dx, xgrid.max() + xpad * dx
    y0, y1 = ygrid.min() - ypad * dy, ygrid.max() + ypad * dy
    if square is not None:
        axes = square
        pos = axes.get_position()
        ax_aspect = ((pos.height * axes.figure.get_figheight())
                     / (pos.width * axes.figure.get_figwidth()))
        im_w, im_h = (x1 - x0), (y1 - y0)
        if im_h / im_w > ax_aspect:
            extra = im_h / ax_aspect - im_w
            x0 -= extra / 2
            x1 += extra / 2
        else:
            extra = im_w * ax_aspect - im_h
            y0 -= extra / 2
            y1 += extra / 2
    return [x0, x1, y0, y1]


def get_plane(dset, xaxis, yaxis, slices, xscale=0, yscale=0, **kw):
    """
    (xmesh, ymesh, data) for one 2d plane of a dataset: grids sorted
    ascending, data arranged to (y, x)
    (reference: extras/plot_tools.py get_plane).
    """
    slices = tuple(slices)
    xgrid = np.asarray(dset.dims[xaxis][xscale])[slices[xaxis]]
    ygrid = np.asarray(dset.dims[yaxis][yscale])[slices[yaxis]]
    xsort = np.argsort(xgrid)
    ysort = np.argsort(ygrid)
    xmesh, ymesh = quad_mesh(xgrid[xsort], ygrid[ysort], **kw)
    data = np.asarray(dset[slices])
    if xaxis < yaxis:
        data = data.T
    data = data[ysort][:, xsort]
    return xmesh, ymesh, data


# ----------------------------------------------------------------------
# plot_bot family

def plot_bot(dset, image_axes, data_slices, image_scales=(0, 0), clim=None,
             even_scale=False, cmap="RdBu_r", axes=None, figkw={},
             title=None, func=None, visible_axes=True):
    """
    Quadmesh plot of a 2d slice of a dataset or Field, colorbar on top
    (reference: extras/plot_tools.py plot_bot — same parameters).

    image_axes: (xaxis, yaxis) data axes for the image x and y.
    data_slices: per-axis ints/slices selecting the plane.
    image_scales: per-axis grid scales (0 = natural, or scale factors).
    func: optional (xmesh, ymesh, data) -> (xmesh, ymesh, data) hook.
    """
    import matplotlib.pyplot as plt
    import matplotlib.ticker as mticker
    from ..core.field import Field
    if isinstance(dset, Field):
        dset = FieldWrapper(dset)
    xaxis, yaxis = image_axes
    xscale, yscale = image_scales
    xmesh, ymesh, data = get_plane(dset, xaxis, yaxis, data_slices,
                                   xscale, yscale)
    data = np.asarray(data).real
    if func is not None:
        xmesh, ymesh, data = func(xmesh, ymesh, data)
    if axes is None:
        fig = plt.figure(**figkw)
        axes = fig.add_subplot(1, 1, 1)
    # carve the parent axes into an image box and a thin top colorbar box
    pos = axes.get_position()
    fig = axes.figure

    def sub_rect(left, bottom, width, height):
        return [pos.x0 + left * pos.width, pos.y0 + bottom * pos.height,
                width * pos.width, height * pos.height]

    paxes = fig.add_axes(sub_rect(0.03, 0.0, 0.94, 0.94))
    caxes = fig.add_axes(sub_rect(0.03, 0.95, 0.94, 0.05))
    axes.set_axis_off()
    if clim is None:
        if even_scale:
            lim = max(abs(np.nanmin(data)), abs(np.nanmax(data))) or 1.0
            clim = (-lim, lim)
        else:
            clim = (np.nanmin(data), np.nanmax(data))
    im = paxes.pcolormesh(xmesh, ymesh, data, cmap=cmap, vmin=clim[0],
                          vmax=clim[1], zorder=1)
    paxes.axis(pad_limits(xmesh, ymesh))
    paxes.tick_params(length=0, width=0)
    cbar = fig.colorbar(im, cax=caxes, orientation="horizontal",
                        ticks=mticker.MaxNLocator(nbins=5))
    cbar.outline.set_visible(False)
    caxes.xaxis.set_ticks_position("top")
    if title is None:
        title = getattr(dset, "name", None)
        if title and "/" in str(title):
            title = str(title).rsplit("/", 1)[1]
    caxes.set_xlabel(title)
    caxes.xaxis.set_label_position("top")
    if visible_axes:
        paxes.set_xlabel(_dim_label(dset, xaxis))
        paxes.set_ylabel(_dim_label(dset, yaxis))
    else:
        paxes.set_xticks([])
        paxes.set_yticks([])
    return paxes, caxes


def _dim_label(dset, axis):
    dim = dset.dims[axis]
    label = getattr(dim, "label", "")
    return label or str(axis)


def plot_bot_2d(dset, transpose=False, **kw):
    """plot_bot for 2d datasets: full-extent slices, axes (0, 1) or
    transposed (reference: extras/plot_tools.py plot_bot_2d)."""
    image_axes = (1, 0) if transpose else (0, 1)
    data_slices = (slice(None), slice(None))
    return plot_bot(dset, image_axes, data_slices, **kw)


def plot_bot_3d(dset, normal_axis, normal_index, transpose=False, **kw):
    """plot_bot for 3d datasets: slice along `normal_axis` (int or dim
    label) at `normal_index` (reference: extras/plot_tools.py
    plot_bot_3d)."""
    from ..core.field import Field
    if isinstance(dset, Field):
        dset = FieldWrapper(dset)
    if isinstance(normal_axis, str):
        for i, dim in enumerate(dset.dims):
            if getattr(dim, "label", None) == normal_axis:
                normal_axis = i
                break
        else:
            raise ValueError(f"Axis name not found: {normal_axis!r}")
    image_axes = [0, 1, 2]
    image_axes.remove(normal_axis)
    if transpose:
        image_axes = image_axes[::-1]
    data_slices = [slice(None), slice(None), slice(None)]
    data_slices[normal_axis] = normal_index
    return plot_bot(dset, tuple(image_axes), tuple(data_slices), **kw)


# ----------------------------------------------------------------------
# Figure layout arithmetic

class Box:
    """2d extent vector for image layout arithmetic: supports +, scalar
    and elementwise *, /, and xbox/ybox projections
    (reference: extras/plot_tools.py Box)."""

    def __init__(self, x, y):
        self.x = float(x)
        self.y = float(y)

    @property
    def xbox(self):
        return Box(self.x, 0.0)

    @property
    def ybox(self):
        return Box(0.0, self.y)

    def __add__(self, other):
        if isinstance(other, Box):
            return Box(self.x + other.x, self.y + other.y)
        return NotImplemented

    def __radd__(self, other):
        return self.__add__(other)

    def __mul__(self, other):
        if isinstance(other, Box):
            return Box(self.x * other.x, self.y * other.y)
        return Box(self.x * other, self.y * other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __imul__(self, other):
        b = self * other
        self.x, self.y = b.x, b.y
        return self

    def __truediv__(self, other):
        if isinstance(other, Box):
            return Box(self.x / other.x, self.y / other.y)
        return Box(self.x / other, self.y / other)


class Frame:
    """Padding frame (top, bottom, left, right) combinable with boxes:
    frame + box = padded box (reference: extras/plot_tools.py Frame)."""

    def __init__(self, top, bottom, left, right):
        self.top = float(top)
        self.bottom = float(bottom)
        self.left = float(left)
        self.right = float(right)

    @property
    def bottom_left(self):
        return Box(self.left, self.bottom)

    @property
    def top_right(self):
        return Box(self.right, self.top)

    def __add__(self, other):
        if isinstance(other, Box):
            return Box(self.left + other.x + self.right,
                       self.bottom + other.y + self.top)
        return NotImplemented

    def __radd__(self, other):
        return self.__add__(other)

    def __mul__(self, scale):
        return Frame(self.top * scale, self.bottom * scale,
                     self.left * scale, self.right * scale)

    def __imul__(self, scale):
        self.top *= scale
        self.bottom *= scale
        self.left *= scale
        self.right *= scale
        return self


class MultiFigure:
    """
    Grid of image cells in one figure, sized from Box/Frame arithmetic
    (reference: extras/plot_tools.py MultiFigure — same parameters).

    nrows/ncols image cells of shape `image` (a Box), each wrapped in
    `pad` (a Frame), the whole array wrapped in `margin` (a Frame),
    all scaled so the figure dimensions come out integral.
    """

    def __init__(self, nrows, ncols, image, pad, margin, scale=1.0, **kw):
        import matplotlib.pyplot as plt
        subfig = pad + image
        fig = margin + nrows * subfig.ybox + ncols * subfig.xbox
        # integral figure dims: snap the height scale up, absorb the
        # leftover width into the margins
        intscale = np.ceil(scale * fig.y) / fig.y
        extra_w = np.ceil(intscale * fig.x) - intscale * fig.x
        image *= intscale
        pad *= intscale
        margin *= intscale
        margin.left += extra_w / 2
        margin.right += extra_w / 2
        subfig = pad + image
        fig = margin + nrows * subfig.ybox + ncols * subfig.xbox
        self.figure = plt.figure(figsize=(int(np.rint(fig.x)),
                                          int(np.rint(fig.y))), **kw)
        self.nrows = nrows
        self.ncols = ncols
        self.image = image
        self.pad = pad
        self.margin = margin
        self.fig = fig

    def add_axes(self, i, j, rect=(0, 0, 1, 1), **kw):
        """Axes within image cell (i, j); `rect` = (left, bottom, width,
        height) in fractions of the image box."""
        irev = self.nrows - 1 - i
        subfig = self.pad + self.image
        offset = (self.margin.bottom_left + irev * subfig.ybox
                  + j * subfig.xbox + self.pad.bottom_left)
        start = (offset + Box(rect[0], rect[1]) * self.image) / self.fig
        shape = Box(rect[2], rect[3]) * self.image / self.fig
        return self.figure.add_axes([start.x, start.y, shape.x, shape.y],
                                    **kw)
