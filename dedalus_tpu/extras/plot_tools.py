"""
Plotting helpers for grid data (reference: dedalus/extras/plot_tools.py).

A compact subset of the reference surface: quad-mesh edge construction
from basis grids, `plot_bot_2d` for fields/arrays, and a simple
`MultiFigure` axes grid. Requires matplotlib (imported lazily).
"""

import numpy as np


def quad_mesh(x, y):
    """Cell-edge meshes for pcolormesh from cell-center grids
    (reference: extras/plot_tools.py quad_mesh)."""
    x, y = np.asarray(x).ravel(), np.asarray(y).ravel()

    def edges(c):
        if c.size == 1:
            return np.array([c[0] - 0.5, c[0] + 0.5])
        mid = 0.5 * (c[:-1] + c[1:])
        return np.concatenate([[c[0] - (mid[0] - c[0])], mid,
                               [c[-1] + (c[-1] - mid[-1])]])

    xe, ye = edges(x), edges(y)
    return np.meshgrid(xe, ye, indexing="ij")


class MultiFigure:
    """Grid of axes with uniform padding
    (reference: extras/plot_tools.py MultiFigure)."""

    def __init__(self, nrows, ncols, width=4.0, height=3.0, pad=0.4):
        import matplotlib.pyplot as plt
        self.nrows, self.ncols = nrows, ncols
        self.figure, self.axes = plt.subplots(
            nrows, ncols, figsize=(ncols * width, nrows * height),
            squeeze=False)
        self.figure.subplots_adjust(wspace=pad, hspace=pad)

    def add_axes(self, i, j):
        return self.axes[i][j]


def plot_bot_3d(dset, normal_axis, index, axes=None, title=None,
                cmap="RdBu_r", even_scale=False, visible_axes=True, **kw):
    """
    pcolormesh of one slice of an h5py task dataset along `normal_axis`
    (typically 0 = the write/time axis), using the file's attached
    dimension scales for coordinates (reference:
    extras/plot_tools.py plot_bot_3d; our file handler attaches scales at
    dataset creation, core/evaluator.py)."""
    import matplotlib.pyplot as plt
    data = np.asarray(np.take(dset, index, axis=normal_axis))
    # coordinate grids from the remaining dims' attached scales
    grids = []
    for d in range(len(dset.shape)):
        if d == normal_axis:
            continue
        dim = dset.dims[d]
        if len(dim) and dim[0].shape[0] == dset.shape[d] and dset.shape[d] > 1:
            grids.append(np.asarray(dim[0]))
        elif dset.shape[d] > 1:
            grids.append(np.arange(dset.shape[d]))
    data = np.squeeze(data)
    if data.ndim != 2 or len(grids) < 2:
        raise ValueError("plot_bot_3d slice is not 2D.")
    x, y = grids[-2], grids[-1]
    if axes is None:
        _, axes = plt.subplots()
    xm, ym = quad_mesh(x, y)
    if even_scale:
        lim = np.abs(data).max() or 1.0
        kw.setdefault("vmin", -lim)
        kw.setdefault("vmax", lim)
    mesh = axes.pcolormesh(xm, ym, np.asarray(data).real, cmap=cmap, **kw)
    if title:
        axes.set_title(title)
    if not visible_axes:
        axes.set_xticks([])
        axes.set_yticks([])
    return mesh


def plot_bot_2d(field_or_data, x=None, y=None, axes=None, title=None,
                cmap="RdBu_r", **kw):
    """
    pcolormesh of a 2D field's grid data (reference:
    extras/plot_tools.py plot_bot / plot_bot_2d). Accepts a Field (grids
    inferred from its bases) or a plain array with x/y grids.
    """
    import matplotlib.pyplot as plt
    data = field_or_data
    if hasattr(field_or_data, "domain"):
        field = field_or_data
        field.change_scales(1)
        data = np.asarray(field["g"])
        bases = [b for b in field.domain.bases if b is not None]
        if x is None or y is None:
            grids = []
            seen = set()
            for b in bases:
                if id(b) in seen:
                    continue
                seen.add(id(b))
                if b.dim == 1:
                    grids.append(b.global_grid(1.0))
                else:
                    grids.extend(b.global_grids((1.0,) * b.dim))
            if len(grids) != 2:
                raise ValueError("plot_bot_2d requires a 2D field.")
            x, y = grids
    if axes is None:
        _, axes = plt.subplots()
    xm, ym = quad_mesh(x, y)
    mesh = axes.pcolormesh(xm, ym, np.asarray(data).real, cmap=cmap, **kw)
    plt.colorbar(mesh, ax=axes)
    if title:
        axes.set_title(title)
    return mesh
