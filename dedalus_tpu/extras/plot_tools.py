"""
Plotting helpers for grid data (reference: dedalus/extras/plot_tools.py).

A compact subset of the reference surface: quad-mesh edge construction
from basis grids, `plot_bot_2d` for fields/arrays, and a simple
`MultiFigure` axes grid. Requires matplotlib (imported lazily).
"""

import numpy as np


def quad_mesh(x, y):
    """Cell-edge meshes for pcolormesh from cell-center grids
    (reference: extras/plot_tools.py quad_mesh)."""
    x, y = np.asarray(x).ravel(), np.asarray(y).ravel()

    def edges(c):
        if c.size == 1:
            return np.array([c[0] - 0.5, c[0] + 0.5])
        mid = 0.5 * (c[:-1] + c[1:])
        return np.concatenate([[c[0] - (mid[0] - c[0])], mid,
                               [c[-1] + (c[-1] - mid[-1])]])

    xe, ye = edges(x), edges(y)
    return np.meshgrid(xe, ye, indexing="ij")


class MultiFigure:
    """Grid of axes with uniform padding
    (reference: extras/plot_tools.py MultiFigure)."""

    def __init__(self, nrows, ncols, width=4.0, height=3.0, pad=0.4):
        import matplotlib.pyplot as plt
        self.nrows, self.ncols = nrows, ncols
        self.figure, self.axes = plt.subplots(
            nrows, ncols, figsize=(ncols * width, nrows * height),
            squeeze=False)
        self.figure.subplots_adjust(wspace=pad, hspace=pad)

    def add_axes(self, i, j):
        return self.axes[i][j]


def plot_bot_2d(field_or_data, x=None, y=None, axes=None, title=None,
                cmap="RdBu_r", **kw):
    """
    pcolormesh of a 2D field's grid data (reference:
    extras/plot_tools.py plot_bot / plot_bot_2d). Accepts a Field (grids
    inferred from its bases) or a plain array with x/y grids.
    """
    import matplotlib.pyplot as plt
    data = field_or_data
    if hasattr(field_or_data, "domain"):
        field = field_or_data
        field.change_scales(1)
        data = np.asarray(field["g"])
        bases = [b for b in field.domain.bases if b is not None]
        if x is None or y is None:
            grids = []
            seen = set()
            for b in bases:
                if id(b) in seen:
                    continue
                seen.add(id(b))
                if b.dim == 1:
                    grids.append(b.global_grid(1.0))
                else:
                    grids.extend(b.global_grids((1.0,) * b.dim))
            if len(grids) != 2:
                raise ValueError("plot_bot_2d requires a 2D field.")
            x, y = grids
    if axes is None:
        _, axes = plt.subplots()
    xm, ym = quad_mesh(x, y)
    mesh = axes.pcolormesh(xm, ym, np.asarray(data).real, cmap=cmap, **kw)
    plt.colorbar(mesh, ax=axes)
    if title:
        axes.set_title(title)
    return mesh
