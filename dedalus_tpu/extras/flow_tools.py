"""
Flow diagnostics and adaptive timestep control
(reference: dedalus/extras/flow_tools.py).
"""

import logging
import numpy as np

logger = logging.getLogger(__name__)


def _axis_profile(values, axis, ndim):
    """Reshape a 1D per-axis profile for broadcasting over the grid."""
    shape = [1] * ndim
    shape[axis] = np.size(values)
    return np.reshape(values, shape)


def interval_cfl_spacing(basis):
    """
    Local grid spacing of an interval basis at dealias scales, rescaled
    by dealias so the frequency reflects the nominal resolution
    (reference: core/basis.py:6091 CartesianAdvectiveCFL.cfl_spacing).
    """
    from ..core.basis import Jacobi, FourierBase
    dealias = basis.dealias if np.isscalar(basis.dealias) else basis.dealias[0]
    grid = basis.global_grid(dealias)
    N = grid.size
    if isinstance(basis, FourierBase):
        # uniform: dealias * (2 pi / N_dealias) * stretch
        return np.full(N, dealias * 2 * np.pi / N * basis.COV.stretch)
    if isinstance(basis, Jacobi) and basis.a0 == -0.5 and basis.b0 == -0.5:
        # Chebyshev: analytic sin(theta) spacing
        theta = np.pi * (np.arange(N) + 0.5) / N
        return dealias * basis.COV.stretch * np.sin(theta) * np.pi / N
    return dealias * (np.gradient(grid) if N > 1 else np.array([np.inf]))


def advective_cfl_frequency(u, ug, xp=np):
    """
    Advective CFL frequency of velocity field `u` with grid data `ug` on
    the dealias grid, per geometry (reference: core/basis.py:6086-6215
    *AdvectiveCFL.cfl_spacing; component conventions: polar (phi, r),
    spherical (phi, theta, r)). `xp` selects numpy (host) or jax.numpy
    (traced, for the AdvectiveCFL operator); spacing profiles are static
    numpy constants either way.
    """
    from ..core import coords as cmod
    cs = u.tensorsig[0]
    dist = u.dist
    ndim = dist.dim

    def polar_frequency(polar_cs, u_az, u_r):
        basis = u.domain.bases[dist.get_axis(polar_cs.coords[1])]
        if basis is None:
            return 0.0  # velocity constant over the polar factor
        r_axis = basis.first_axis + 1
        r = np.ravel(basis.global_grids(basis.dealias)[1])
        mmax = max(basis.shape[0] // 2 - 1, 0)
        if mmax == 0:
            az = np.array([np.inf])
        elif hasattr(basis, "radii"):  # annulus: spacing r / mmax
            az = r / mmax
        else:  # disk: spacing R / mmax
            az = np.array([basis.radius / mmax])
        dr = basis.dealias[1] * (np.gradient(r) if r.size > 1
                                 else np.array([np.inf]))
        return (xp.abs(u_az) / _axis_profile(az, r_axis, ndim)
                + xp.abs(u_r) / _axis_profile(dr, r_axis, ndim))

    def interval_frequency(coord, u_c):
        axis = dist.get_axis(coord)
        basis = u.domain.bases[axis]
        if basis is None:
            return 0.0
        dx = interval_cfl_spacing(basis)
        return xp.abs(u_c) / _axis_profile(dx, axis, ndim)

    total = 0.0
    if isinstance(cs, cmod.PolarCoordinates):
        total = polar_frequency(cs, ug[0], ug[1])
    elif isinstance(cs, cmod.DirectProduct):
        # cylinder: straight factors get interval spacings, the polar
        # factor its (azimuth, radius) spacings on its component slice
        off = 0
        for sub in cs.coordsystems:
            if isinstance(sub, cmod.PolarCoordinates):
                total = total + polar_frequency(sub, ug[off], ug[off + 1])
            elif isinstance(sub, cmod.CurvilinearCoordinateSystem):
                # an S2/spherical factor must not fall into the polar
                # formula (it would read colatitude as radius, silently)
                raise NotImplementedError(
                    "CFL spacing for this DirectProduct factor.")
            else:
                for j, coord in enumerate(sub.coords):
                    total = total + interval_frequency(coord, ug[off + j])
            off += sub.dim
    elif isinstance(cs, cmod.S2Coordinates):
        basis = u.domain.bases[dist.get_axis(cs.coords[0])]
        u_mag = xp.sqrt(ug[0] ** 2 + ug[1] ** 2)
        Lmax = basis.Lmax
        k = np.sqrt(Lmax * (Lmax + 1)) if Lmax > 0 else 0.0
        total = u_mag * (k / basis.radius)
    elif isinstance(cs, cmod.SphericalCoordinates):
        basis = u.domain.bases[dist.get_axis(cs.coords[2])]
        r_axis = basis.first_axis + 2
        r = np.ravel(basis.global_grids(basis.dealias)[2])
        Lmax = basis.shape[1] - 1
        k = np.sqrt(Lmax * (Lmax + 1)) if Lmax > 0 else 0.0
        u_mag = xp.sqrt(ug[0] ** 2 + ug[1] ** 2)
        if hasattr(basis, "radii"):  # shell: angular spacing r / k
            ang = (k / _axis_profile(r, r_axis, ndim)) if k else 0.0
            total = u_mag * ang
        else:  # ball: angular spacing R / k
            total = u_mag * (k / basis.radius)
        dr = basis.dealias[2] * (np.gradient(r) if r.size > 1
                                 else np.array([np.inf]))
        total = total + xp.abs(ug[2]) / _axis_profile(dr, r_axis, ndim)
    else:
        # Cartesian: per-axis interval spacings
        for i, coord in enumerate(cs.coords):
            total = total + interval_frequency(coord, ug[i])
    if np.isscalar(total):
        total = xp.zeros(ug.shape[1:])
    return total


class GlobalArrayReducer:
    """Global reductions over grid data (reference: extras/flow_tools.py:15).
    Single-controller JAX arrays are already global; reductions are direct."""

    def __init__(self, comm=None, dtype=np.float64):
        self.dtype = dtype

    def reduce_scalar(self, local_scalar, mpi_reduce_op=None):
        return local_scalar

    def global_min(self, data, empty=np.inf):
        return np.min(data) if data.size else empty

    def global_max(self, data, empty=-np.inf):
        return np.max(data) if data.size else empty

    def global_mean(self, data):
        return np.mean(data)


class GlobalFlowProperty:
    """Scheduled scalar diagnostics of flow expressions
    (reference: extras/flow_tools.py:64)."""

    def __init__(self, solver, cadence=1):
        self.solver = solver
        self.cadence = cadence
        self.reducer = GlobalArrayReducer()
        self.properties = solver.evaluator.add_dictionary_handler(iter=cadence)

    def add_property(self, property, name):
        self.properties.add_task(property, name=name)

    def min(self, name):
        return self.reducer.global_min(self.properties[name])

    def max(self, name):
        return self.reducer.global_max(self.properties[name])

    def grid_average(self, name):
        return self.reducer.global_mean(self.properties[name])

    def volume_integral(self, name):
        # tasks are integrals already when requested via integ(...)
        return np.sum(self.properties[name])

    def report(self, names):
        """
        {name: {"max", "min", "avg"}} for the given property names —
        one dict consumable by the health sink (tools/health.py attaches
        it to flight-recorder dumps via `monitor.attach_flow(flow,
        names)`). Properties that have not evaluated yet are skipped.
        """
        out = {}
        for name in names:
            try:
                data = self.properties[name]
            except KeyError:
                continue
            out[name] = {"max": float(self.reducer.global_max(data)),
                         "min": float(self.reducer.global_min(data)),
                         "avg": float(self.reducer.global_mean(data))}
        return out


class CFL:
    """
    Adaptive timestep from advective CFL frequencies
    (reference: extras/flow_tools.py:139 CFL, core/operators.py:4306
    AdvectiveCFL). Frequencies |u_i| / dx_i are computed on the grid and
    reduced to a stable timestep with safety/threshold/bounds logic
    (reference: extras/flow_tools.py:191 compute_timestep).
    """

    def __init__(self, solver, initial_dt, cadence=1, safety=1.0,
                 max_dt=np.inf, min_dt=0.0, max_change=np.inf, min_change=0.0,
                 threshold=0.0, history_size=256):
        from collections import deque
        self.solver = solver
        self.initial_dt = initial_dt
        self.cadence = cadence
        self.safety = safety
        self.max_dt = max_dt
        self.min_dt = min_dt
        self.max_change = max_change
        self.min_change = min_change
        self.threshold = threshold
        self.velocities = []
        self.frequencies = []
        self.current_dt = initial_dt
        # bounded (iteration, dt, freq_max) trail: the flight recorder's
        # dt/CFL-frequency evidence (tools/health.py dt_history)
        self.history = deque(maxlen=max(int(history_size), 1))
        self._last_freq_max = None
        monitor = getattr(solver, "health", None)
        if monitor is not None and hasattr(monitor, "attach_dt_source"):
            monitor.attach_dt_source(self)

    def add_velocity(self, velocity):
        """Register a velocity vector field for CFL frequencies
        (evaluated through the AdvectiveCFL operator's compiled path when
        the velocity is an expression; plain fields use the host path)."""
        self.velocities.append(velocity)

    def add_frequency(self, freq):
        """Register an additional frequency expression."""
        self.frequencies.append(freq)

    def compute_max_frequency(self):
        freq_max = 0.0
        for u in self.velocities:
            u.change_scales(u.domain.dealias)
            ug = np.asarray(u["g"])
            total = advective_cfl_frequency(u, ug, xp=np)
            if total.size:
                freq_max = max(freq_max, np.max(total))
        for fexpr in self.frequencies:
            field = fexpr.evaluate()
            freq_max = max(freq_max, np.max(np.abs(np.asarray(field["g"]))))
        return freq_max

    def compute_timestep(self):
        iteration = self.solver.iteration
        if iteration % self.cadence == 0:
            freq_max = self.compute_max_frequency()
            self._last_freq_max = float(freq_max)
            if freq_max == 0.0:
                dt = self.max_dt
            else:
                dt = self.safety / freq_max
            dt = min(dt, self.max_dt)
            dt = max(dt, self.min_dt)
            # bounded relative change with threshold hysteresis
            if self.current_dt:
                change = dt / self.current_dt
                change = min(change, self.max_change)
                change = max(change, self.min_change)
                if abs(change - 1.0) > self.threshold:
                    self.current_dt = self.current_dt * change
            else:
                self.current_dt = dt
        self.history.append({"iteration": int(iteration),
                             "dt": float(self.current_dt),
                             "freq_max": self._last_freq_max})
        return self.current_dt
