"""
Flow diagnostics and adaptive timestep control
(reference: dedalus/extras/flow_tools.py).
"""

import logging
import numpy as np

logger = logging.getLogger(__name__)


class GlobalArrayReducer:
    """Global reductions over grid data (reference: extras/flow_tools.py:15).
    Single-controller JAX arrays are already global; reductions are direct."""

    def __init__(self, comm=None, dtype=np.float64):
        self.dtype = dtype

    def reduce_scalar(self, local_scalar, mpi_reduce_op=None):
        return local_scalar

    def global_min(self, data, empty=np.inf):
        return np.min(data) if data.size else empty

    def global_max(self, data, empty=-np.inf):
        return np.max(data) if data.size else empty

    def global_mean(self, data):
        return np.mean(data)


class GlobalFlowProperty:
    """Scheduled scalar diagnostics of flow expressions
    (reference: extras/flow_tools.py:64)."""

    def __init__(self, solver, cadence=1):
        self.solver = solver
        self.cadence = cadence
        self.reducer = GlobalArrayReducer()
        self.properties = solver.evaluator.add_dictionary_handler(iter=cadence)

    def add_property(self, property, name):
        self.properties.add_task(property, name=name)

    def min(self, name):
        return self.reducer.global_min(self.properties[name])

    def max(self, name):
        return self.reducer.global_max(self.properties[name])

    def grid_average(self, name):
        return self.reducer.global_mean(self.properties[name])

    def volume_integral(self, name):
        # tasks are integrals already when requested via integ(...)
        return np.sum(self.properties[name])


class CFL:
    """
    Adaptive timestep from advective CFL frequencies
    (reference: extras/flow_tools.py:139 CFL, core/operators.py:4306
    AdvectiveCFL). Frequencies |u_i| / dx_i are computed on the grid and
    reduced to a stable timestep with safety/threshold/bounds logic
    (reference: extras/flow_tools.py:191 compute_timestep).
    """

    def __init__(self, solver, initial_dt, cadence=1, safety=1.0,
                 max_dt=np.inf, min_dt=0.0, max_change=np.inf, min_change=0.0,
                 threshold=0.0):
        self.solver = solver
        self.initial_dt = initial_dt
        self.cadence = cadence
        self.safety = safety
        self.max_dt = max_dt
        self.min_dt = min_dt
        self.max_change = max_change
        self.min_change = min_change
        self.threshold = threshold
        self.velocities = []
        self.frequencies = []
        self.current_dt = initial_dt

    def add_velocity(self, velocity):
        """Register a velocity vector field for CFL frequencies."""
        self.velocities.append(velocity)

    def add_frequency(self, freq):
        """Register an additional frequency expression."""
        self.frequencies.append(freq)

    def _grid_spacings(self, domain):
        """Per-axis grid spacing arrays (broadcastable), dealias grids."""
        dist = self.solver.dist
        spacings = []
        for axis, basis in enumerate(domain.bases):
            if basis is None:
                spacings.append(None)
                continue
            grid = basis.global_grid(basis.dealias)
            if grid.size > 1:
                dx = np.gradient(grid)
            else:
                dx = np.array([np.inf])
            shape = [1] * dist.dim
            shape[axis] = dx.size
            spacings.append(dx.reshape(shape))
        return spacings

    def compute_max_frequency(self):
        freq_max = 0.0
        for u in self.velocities:
            cs = u.tensorsig[0]
            u.change_scales(u.domain.dealias)
            ug = np.asarray(u["g"])
            spacings = self._grid_spacings(u.domain)
            total = np.zeros(ug.shape[1:])
            for i, coord in enumerate(cs.coords):
                axis = u.dist.get_axis(coord)
                if spacings[axis] is not None:
                    total = total + np.abs(ug[i]) / spacings[axis]
            if total.size:
                freq_max = max(freq_max, np.max(total))
        for fexpr in self.frequencies:
            field = fexpr.evaluate()
            freq_max = max(freq_max, np.max(np.abs(np.asarray(field["g"]))))
        return freq_max

    def compute_timestep(self):
        iteration = self.solver.iteration
        if iteration % self.cadence == 0:
            freq_max = self.compute_max_frequency()
            if freq_max == 0.0:
                dt = self.max_dt
            else:
                dt = self.safety / freq_max
            dt = min(dt, self.max_dt)
            dt = max(dt, self.min_dt)
            # bounded relative change with threshold hysteresis
            if self.current_dt:
                change = dt / self.current_dt
                change = min(change, self.max_change)
                change = max(change, self.min_change)
                if abs(change - 1.0) > self.threshold:
                    self.current_dt = self.current_dt * change
            else:
                self.current_dt = dt
        return self.current_dt
