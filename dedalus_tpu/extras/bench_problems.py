"""
Shared benchmark/test problem builders (the 2-D Rayleigh-Benard flagship
configuration; reference: examples/ivp_2d_rayleigh_benard/
rayleigh_benard.py). Used by the driver entry (__graft_entry__),
benchmarks, and the emulated-f64 regression tests.
"""

import numpy as np


def build_diffusion_solver(size=64, dtype=np.float64):
    """1-D forced nonlinear heat IVP (SBDF2, dense pencil path): the
    shared small problem behind the adjoint and fusion benchmark rows —
    parameter field `a`, forcing `f`, and a Burgers term so the dealiased
    transform chain and per-step residual storage are both exercised.
    ONE definition so the cross-benchmark results.jsonl comparisons stay
    on the same physics."""
    import dedalus_tpu.public as d3
    xc = d3.Coordinate("x")
    dist = d3.Distributor(xc, dtype=dtype)
    xb = d3.RealFourier(xc, size=size, bounds=(0, 2 * np.pi))
    u = dist.Field(name="u", bases=xb)
    a = dist.Field(name="a", bases=xb)
    f = dist.Field(name="f", bases=xb)
    dx = lambda A: d3.Differentiate(A, xc)  # noqa: E731
    problem = d3.IVP([u], namespace={"u": u, "a": a, "f": f,
                                     "lap": d3.lap, "dx": dx})
    problem.add_equation("dt(u) - lap(u) = a*u + f - u*dx(u)")
    x = dist.local_grid(xb)
    u["g"] = np.sin(3 * x)
    a["g"] = 0.1 * np.cos(x)
    f["g"] = 0.05 * np.sin(2 * x)
    return problem.build_solver(d3.SBDF2, warmup_iterations=2,
                                enforce_real_cadence=0)


def build_rb_solver(Nx, Nz, dtype, mesh=None, matsolver=None):
    import dedalus_tpu.public as d3
    Lx, Lz = 4.0, 1.0
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=dtype, mesh=mesh)
    xbasis = d3.RealFourier(coords["x"], size=Nx, bounds=(0, Lx), dealias=3 / 2)
    zbasis = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, Lz), dealias=3 / 2)
    p = dist.Field(name="p", bases=(xbasis, zbasis))
    b = dist.Field(name="b", bases=(xbasis, zbasis))
    u = dist.VectorField(coords, name="u", bases=(xbasis, zbasis))
    tau_p = dist.Field(name="tau_p")
    tau_b1 = dist.Field(name="tau_b1", bases=xbasis)
    tau_b2 = dist.Field(name="tau_b2", bases=xbasis)
    tau_u1 = dist.VectorField(coords, name="tau_u1", bases=xbasis)
    tau_u2 = dist.VectorField(coords, name="tau_u2", bases=xbasis)
    kappa = nu = 2.0e-6 ** 0.5
    x, z = dist.local_grids(xbasis, zbasis)
    ex, ez = coords.unit_vector_fields(dist)
    lift_basis = zbasis.derivative_basis(1)
    lift = lambda A: d3.Lift(A, lift_basis, -1)
    grad_u = d3.grad(u) + ez * lift(tau_u1)
    grad_b = d3.grad(b) + ez * lift(tau_b1)
    problem = d3.IVP([p, b, u, tau_p, tau_b1, tau_b2, tau_u1, tau_u2],
                     namespace=locals())
    problem.add_equation("trace(grad_u) + tau_p = 0")
    problem.add_equation("dt(b) - kappa*div(grad_b) + lift(tau_b2) = - u@grad(b)")
    problem.add_equation("dt(u) - nu*div(grad_u) + grad(p) - b*ez + lift(tau_u2) = - u@grad(u)")
    problem.add_equation("b(z=0) = Lz")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("b(z=Lz) = 0")
    problem.add_equation("u(z=Lz) = 0")
    problem.add_equation("integ(p) = 0")
    # matsolver=None defers to [linear algebra] MATRIX_SOLVER; callers on
    # the headline banded configuration (bench/coldstart/serving) pass
    # "banded" explicitly so their numbers do not depend on ambient config
    solver = problem.build_solver(d3.RK222, matsolver=matsolver)
    b.fill_random("g", seed=42, distribution="normal", scale=1e-3)
    b["g"] += (Lz - z)
    return solver, b


def build_tau_ivp(Nx=16, Nz=8, cadence=100, matsolver=None,
                  timestepper=None):
    """2-D nonlinear heat IVP with tau lines (Fourier x Chebyshev): the
    shared small sharded-stepping configuration behind the collective-
    placement tests (tests/test_collectives.py, tests/test_distributed.py),
    the weak-scaling benchmark and the compiled-program contract census
    (tools/lint/progcheck.py). Returns (solver, u, x, z) undistributed;
    callers shard it with parallel.distribute_solver or fleet it with
    solver.ensemble. ONE definition so every gather/all-to-all assertion
    runs against the same program shape."""
    import dedalus_tpu.public as d3
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=Nx, bounds=(0, 4.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=Nz, bounds=(0, 1.0), dealias=3 / 2)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)  # noqa: E731
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    kw = {"matsolver": matsolver} if matsolver else {}
    solver = problem.build_solver(timestepper or d3.SBDF2,
                                  enforce_real_cadence=cadence, **kw)
    x, z = dist.local_grids(xb, zb)
    u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
    return solver, u, x, z
