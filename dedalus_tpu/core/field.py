"""
Operands and Fields (reference: dedalus/core/field.py).

`Operand` is the arithmetic-overload base: `+ - * / ** @` and calls build
symbolic expression nodes (reference: core/field.py:39-327). `Field` is the
concrete distributed data container: an immutable-by-convention jnp array
plus a current layout tag ('c' coefficient / 'g' grid) and grid scales.

TPU-native design: user-facing Fields behave like the reference's (mutable
layout walked on access), but all data lives on device as jnp arrays; the
solver hot loop never touches Fields — it closes over pure pytrees of
coefficient arrays (see solvers.py).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .domain import Domain
from ..tools.general import is_complex_dtype


# ------------------------------------------------------------------
# Transform pipeline: pure jnp, safe inside jit.
#
# Mesh-aware mode: inside `mesh_transforms(mesh)` the walk pins the
# intermediate shardings of the reference's layout chain
# (core/distributor.py:128-166: coeff keeps the first R axes distributed,
# transforming axis r first moves its blocks to axis r+1) via
# with_sharding_constraint, so GSPMD lowers the moves to all-to-all pencil
# transposes instead of gathering the full state (the reference's
# Alltoallv transposes, core/transposes.pyx:246). Host/setup paths run
# outside the context and are untouched.

import threading as _threading

from . import meshctx

_MESH_CTX = _threading.local()


class mesh_transforms:
    """Context manager activating sharded transform walks (trace-time).
    `mesh=None` INHERITS any active context instead of clearing it: an
    undistributed solver body traced inside an outer walk context (the
    2-D batch x pencil fleet, core/ensemble.py) keeps the outer mesh.
    `chunks` carries the solver's resolved transpose chunk count
    ([distributed] TRANSPOSE_CHUNKS) into the walk; None resolves from
    config at walk time."""

    def __init__(self, mesh, chunks=None):
        self.mesh = mesh
        self.chunks = chunks

    def __enter__(self):
        self.prev = getattr(_MESH_CTX, "mesh", None)
        self.prev_chunks = getattr(_MESH_CTX, "chunks", None)
        if self.mesh is not None:
            _MESH_CTX.mesh = self.mesh
            _MESH_CTX.chunks = self.chunks
        return getattr(_MESH_CTX, "mesh", None)

    def __exit__(self, *exc):
        _MESH_CTX.mesh = self.prev
        _MESH_CTX.chunks = self.prev_chunks


def _active_mesh(domain):
    """(mesh, axis_names) for the current transform walk, or (None, ()).
    Reserved ensemble batch axes are filtered out (meshctx.walk_axis_names):
    on a 2-D batch x pencil mesh the walk transposes over the pencil axes
    only."""
    mesh = getattr(_MESH_CTX, "mesh", None)
    if mesh is None:
        return None, ()
    names = meshctx.walk_axis_names(mesh)
    R = min(len(names), domain.dim - 1)
    if R < 1:
        return None, ()
    return mesh, names[:R]


def _active_chunks():
    """Transpose chunk count for the current walk: the solver's resolved
    value when its mesh_transforms context carried one, else resolved
    from [distributed] TRANSPOSE_CHUNKS."""
    chunks = getattr(_MESH_CTX, "chunks", None)
    if chunks is not None:
        return chunks
    from ..parallel.transposes import resolve_transpose_chunks
    return resolve_transpose_chunks()


def _constrain(data, mesh, layout):
    from jax.sharding import NamedSharding, PartitionSpec
    spec = [layout.get(d) for d in range(data.ndim)]
    return jax.lax.with_sharding_constraint(
        data, NamedSharding(mesh, PartitionSpec(*spec)))


def _walk_divisible(data, domain, scales, tdim, mesh, names):
    """Whether every stage of the sharded layout walk divides evenly: mesh
    axis r shards the coeff size of axis r and the grid size of axis r+1.
    Uneven stages (reduced tau fields, odd sizes) fall back to the plain
    global-view walk — correct, but GSPMD may gather; choose divisible
    resolutions for the distributed axes."""
    def size(axis, grid):
        basis = domain.bases[axis]
        if basis is None:
            return data.shape[tdim + axis]
        sub = axis - basis.first_axis
        if grid:
            return basis.sub_grid_size(sub, scales[axis])
        return basis.coeff_size(sub)

    for r, name in enumerate(names):
        n = mesh.shape[name]
        if size(r, grid=False) % n or size(r + 1, grid=True) % n:
            return False
    return True


def transform_to_coeff(data, domain, scales, tdim, library=None, tensorsig=()):
    """
    Full grid -> full coefficient transform. First axis first, so curvilinear
    azimuths are in coefficient (m) space before their m-dependent
    colatitude/radial transforms run (reference layout-walk direction:
    core/distributor.py:128-166).
    """
    def fwd(data, axis):
        basis = domain.bases[axis]
        if basis is None:
            return data
        return basis.forward_transform(data, tdim + axis, scales[axis],
                                       library, tensorsig=tensorsig,
                                       sub_axis=axis - basis.first_axis)

    mesh, names = _active_mesh(domain)
    if mesh is not None and not _walk_divisible(data, domain, scales, tdim,
                                                mesh, names):
        mesh = None
    if mesh is None:
        for axis in range(domain.dim):
            data = fwd(data, axis)
        return data
    R = len(names)
    chunks = _active_chunks()
    # grid layout: mesh axis r shards array dim r+1
    layout = {tdim + r + 1: names[r] for r in range(R)}
    prev = meshctx.set_walk(mesh, layout)
    try:
        data = _constrain(data, mesh, layout)
        for r in range(R):
            if chunks > 1 and data.shape[tdim + r + 1] % mesh.shape[names[r]] == 0:
                # overlapped chunked stage: transform + per-chunk
                # all_to_all interleaved inside one shard_map
                # (parallel/transposes.py; bit-identical to the
                # monolithic constraint-walk below)
                from ..parallel.transposes import overlapped_to_coeff_stage
                del layout[tdim + r + 1]
                data = overlapped_to_coeff_stage(
                    data, lambda x, _r=r: fwd(x, _r),
                    tdim + r + 1, tdim + r, mesh, names[r],
                    layout=layout, chunks=chunks)
                layout[tdim + r] = names[r]
                meshctx.set_walk(mesh, layout)
                data = _constrain(data, mesh, layout)
                continue
            data = fwd(data, r)                 # axis r is local in grid layout
            del layout[tdim + r + 1]
            layout[tdim + r] = names[r]
            meshctx.set_walk(mesh, layout)
            data = _constrain(data, mesh, layout)  # all-to-all: dim r+1 -> dim r
        for axis in range(R, domain.dim):
            data = fwd(data, axis)
        return _constrain(data, mesh, layout)
    finally:
        meshctx.restore_walk(prev)


def transform_to_grid(data, domain, scales, tdim, library=None, tensorsig=()):
    """Full coefficient -> full grid transform: last axis first."""
    def bwd(data, axis):
        basis = domain.bases[axis]
        if basis is None:
            return data
        return basis.backward_transform(data, tdim + axis, scales[axis],
                                        library, tensorsig=tensorsig,
                                        sub_axis=axis - basis.first_axis)

    mesh, names = _active_mesh(domain)
    if mesh is not None and not _walk_divisible(data, domain, scales, tdim,
                                                mesh, names):
        mesh = None
    if mesh is None:
        for axis in range(domain.dim - 1, -1, -1):
            data = bwd(data, axis)
        return data
    R = len(names)
    chunks = _active_chunks()
    # coeff layout: mesh axis r shards array dim r
    layout = {tdim + r: names[r] for r in range(R)}
    prev = meshctx.set_walk(mesh, layout)
    try:
        data = _constrain(data, mesh, layout)
        for axis in range(domain.dim - 1, R - 1, -1):
            data = bwd(data, axis)
        for r in range(R - 1, -1, -1):
            n = mesh.shape[names[r]]
            if chunks > 1 and data.shape[tdim + r + 1] % n == 0 \
                    and data.shape[tdim + r] % n == 0:
                # overlapped chunked stage (parallel/transposes.py):
                # chunk k+1's all_to_all rides under chunk k's backward
                # transform; bit-identical to the monolithic walk below
                from ..parallel.transposes import overlapped_to_grid_stage
                del layout[tdim + r]
                data = overlapped_to_grid_stage(
                    data, lambda x, _r=r: bwd(x, _r),
                    tdim + r, tdim + r + 1, mesh, names[r],
                    layout=layout, chunks=chunks)
                layout[tdim + r + 1] = names[r]
                meshctx.set_walk(mesh, layout)
                data = _constrain(data, mesh, layout)
                continue
            del layout[tdim + r]
            layout[tdim + r + 1] = names[r]
            meshctx.set_walk(mesh, layout)
            data = _constrain(data, mesh, layout)  # all-to-all: dim r -> dim r+1
            data = bwd(data, r)                 # axis r now local
        return data
    finally:
        meshctx.restore_walk(prev)


def _compiled_transform(direction, domain, scales, tdim, tensorsig):
    """
    Jit-compiled whole-field transform, cached per static signature. All
    host-facing layout changes go through here: eager per-op dispatch is both
    slow and fragile on remote-compile TPU backends (each new op shape is a
    round-trip through the backend compiler). The cache lives on the domain
    object, so its compiled executables share the domain's lifetime instead
    of pinning every domain in a global table.
    """
    per_domain = domain.__dict__.setdefault("_compiled_transforms", {})
    key = (direction, scales, tdim, tensorsig)
    fn = per_domain.get(key)
    if fn is None:
        if direction == "c":
            def fn(data):
                return transform_to_coeff(data, domain, scales, tdim,
                                          tensorsig=tensorsig)
        else:
            def fn(data):
                return transform_to_grid(data, domain, scales, tdim,
                                         tensorsig=tensorsig)
        from ..tools.jitlift import lifted_jit
        fn = per_domain[key] = lifted_jit(fn)
    return fn


class _FieldDataView(np.ndarray):
    """
    Host ndarray tied to a Field layout: item assignment writes the whole
    array back into the field, emulating the reference's live data views
    (reference: core/field.py:561 __getitem__ returning self.data).
    """

    def __new__(cls, arr, field, layout):
        obj = np.asarray(arr).view(cls)
        obj._field = field
        obj._field_layout = layout
        # Shared mutable cell tracking the field data epoch this view
        # mirrors: all slices of this view share it, so sequential writes
        # through any of them stay valid while external data changes (user
        # mutation OR solver updates) invalidate all.
        obj._view_version = [field._data_epoch]
        return obj

    def __array_finalize__(self, obj):
        # Memory-sharing views (slices) keep the backref so
        # `u['g'][2][...] = v` lands in the field; fresh arrays produced by
        # ufuncs drop it so `w = u['g']*2; w[0] = ...` does not.
        self._field = None
        self._field_layout = None
        self._view_version = None
        if obj is not None and getattr(obj, "_field", None) is not None:
            try:
                shared = np.shares_memory(self, obj)
            except Exception:
                shared = False
            if shared:
                self._field = obj._field
                self._field_layout = obj._field_layout
                self._view_version = obj._view_version

    def _writeback(self):
        field, layout = self._field, self._field_layout
        if field is None:
            return
        if field._data_epoch != self._view_version[0]:
            raise RuntimeError(
                "Writing through a stale field data view: the field's data "
                "changed (user assignment or solver step) after this view "
                f"was taken. Re-read the data (field['{layout}']) and apply "
                "the mutation to the fresh view.")
        root = self
        while isinstance(root.base, np.ndarray):
            root = root.base
        field[layout] = np.asarray(root)
        self._view_version[0] = field._data_epoch

    def __setitem__(self, key, value):
        np.ndarray.__setitem__(self, key, value)
        self._writeback()


def _inplace_with_writeback(name):
    base_op = getattr(np.ndarray, name)

    def op(self, other):
        out = base_op(self, other)
        self._writeback()
        return out
    op.__name__ = name
    return op


for _name in ("__iadd__", "__isub__", "__imul__", "__itruediv__",
              "__ifloordiv__", "__imod__", "__ipow__", "__iand__",
              "__ior__", "__ixor__", "__ilshift__", "__irshift__"):
    setattr(_FieldDataView, _name, _inplace_with_writeback(_name))


class Operand:
    """Base class for everything that can appear in symbolic expressions."""

    __array_priority__ = 100.0  # win dispatch against numpy arrays

    # ---- arithmetic overloads (lazy imports avoid circular deps) ----

    def __add__(self, other):
        from .arithmetic import Add
        if np.isscalar(other) and other == 0:
            return self
        return Add(self, other)

    def __radd__(self, other):
        from .arithmetic import Add
        if np.isscalar(other) and other == 0:
            return self
        return Add(other, self)

    def __sub__(self, other):
        return self + (-1) * other

    def __rsub__(self, other):
        return other + (-1) * self

    def __neg__(self):
        return (-1) * self

    def __mul__(self, other):
        from .arithmetic import Multiply
        return Multiply(self, other)

    def __rmul__(self, other):
        from .arithmetic import Multiply
        return Multiply(other, self)

    def __truediv__(self, other):
        from .arithmetic import Multiply, Power
        if np.isscalar(other):
            return Multiply(1.0 / other, self)
        return Multiply(self, Power(other, -1))

    def __rtruediv__(self, other):
        from .arithmetic import Multiply, Power
        return Multiply(other, Power(self, -1))

    def __pow__(self, other):
        from .arithmetic import Power
        return Power(self, other)

    def __matmul__(self, other):
        from .arithmetic import DotProduct
        return DotProduct(self, other)

    def __rmatmul__(self, other):
        from .arithmetic import DotProduct
        return DotProduct(other, self)

    def __call__(self, **positions):
        """Interpolation: f(x=0.5) (reference: core/field.py API)."""
        from .operators import Interpolate
        out = self
        for name, position in positions.items():
            coord = self._lookup_coord(name)
            out = Interpolate(out, coord, position)
        return out

    def _lookup_coord(self, name):
        return self.dist.get_coord(name)

    def __array_ufunc__(self, ufunc, method, *inputs, **kw):
        """Dispatch numpy ufuncs on operands to symbolic nodes
        (reference: core/field.py:44)."""
        from .arithmetic import Add, Multiply, Power, DotProduct
        from .operators import UnaryGridFunction
        if method != "__call__":
            return NotImplemented
        binary = {np.add: Add, np.multiply: Multiply, np.matmul: DotProduct}
        if ufunc in binary and len(inputs) == 2:
            return binary[ufunc](*inputs)
        if ufunc is np.subtract and len(inputs) == 2:
            return inputs[0] - inputs[1]
        if ufunc is np.true_divide and len(inputs) == 2:
            a, b = inputs
            if isinstance(a, Operand):
                return a / b
            return a * Power(b, -1)
        if ufunc is np.power and len(inputs) == 2:
            return Power(*inputs)
        if ufunc is np.negative:
            return -inputs[0]
        if len(inputs) == 1:
            return UnaryGridFunction(ufunc, inputs[0])
        return NotImplemented

    # ---- symbolic tree API (overridden by Future) ----

    def atoms(self, *types):
        return set()

    def has(self, *operands):
        return any(self is op for op in operands)

    def replace(self, old, new):
        return new if self is old else self

    @staticmethod
    def cast(arg, dist):
        if isinstance(arg, Operand):
            return arg
        raise TypeError(f"Cannot cast {arg!r} to an Operand")


_zeros_cache = {}
_zeros_cache_bytes = 0
_zeros_cache_lock = _threading.Lock()
# device memory pinned by interned zeros is bounded in BYTES, not entry
# count: a resolution scan would otherwise accumulate dead large buffers
# (scarce HBM on TPU) for shapes no live field references
_ZEROS_CACHE_MAX_BYTES = 64 * 1024 * 1024


def _shared_zeros(shape, dtype):
    """Interned zero arrays for field initialization: jax arrays are
    immutable, so every field of one (shape, dtype) can alias a single
    zeros buffer — writes replace `field.data` wholesale. Saves one eager
    dispatch per field on cold starts (a dozen fields is ~0.2 s).
    Locked: fields are constructed from worker threads (ASSEMBLY_WORKERS),
    and the pop-reinsert recency refresh races without it."""
    global _zeros_cache_bytes
    key = (tuple(shape), np.dtype(dtype).str)
    with _zeros_cache_lock:
        out = _zeros_cache.get(key)
        if out is not None:
            # refresh recency: move the hit to the back of the eviction
            # order
            _zeros_cache[key] = _zeros_cache.pop(key)
            return out
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        if nbytes > _ZEROS_CACHE_MAX_BYTES:
            return jnp.zeros(shape, dtype=dtype)   # too large to pin
        # evict least-recently-used (hits reinsert, so dict order is LRU)
        while _zeros_cache and \
                _zeros_cache_bytes + nbytes > _ZEROS_CACHE_MAX_BYTES:
            old = _zeros_cache.pop(next(iter(_zeros_cache)))
            _zeros_cache_bytes -= old.size * old.dtype.itemsize
        out = _zeros_cache[key] = jnp.zeros(shape, dtype=dtype)
        _zeros_cache_bytes += nbytes
    return out


class Field(Operand):
    """
    Distributed spectral field (reference: core/field.py:32 Field/ScalarField,
    with VectorField/TensorField as tensorsig variants).
    """

    def __init__(self, dist, bases=None, name=None, tensorsig=(), dtype=None):
        self.dist = dist
        self.name = name
        self.tensorsig = tuple(tensorsig)
        self.dtype = np.dtype(dtype or dist.dtype)
        self.domain = Domain(dist, dist.expand_bases(bases))
        if self.domain.coeff_dtype_is_complex and not is_complex_dtype(self.dtype):
            raise ValueError("ComplexFourier bases require a complex dtype.")
        self.scales = dist.remedy_scales(1)
        self.layout = "c"
        self.data = _shared_zeros(self.coeff_shape, self.coeff_dtype)
        # Solver synchronization: `_version` counts user mutations;
        # `_data_epoch` counts ALL data changes (including solver updates,
        # for data-view staleness detection); `_pull`
        # is a deferred fetch installed by solvers after a step so field data
        # is only scattered from the device state when actually accessed.
        self._version = 0
        self._data_epoch = 0
        self._pull = None

    def atoms(self, *types):
        if not types or isinstance(self, types):
            return {self}
        return set()

    # ---- shapes & dtypes ----

    @property
    def tshape(self):
        return tuple(cs.dim for cs in self.tensorsig)

    @property
    def tdim(self):
        return len(self.tshape)

    @property
    def coeff_dtype(self):
        return self.dtype

    @property
    def grid_dtype(self):
        return self.dtype

    @property
    def coeff_shape(self):
        return self.tshape + self.domain.coeff_shape

    def grid_shape(self, scales=None):
        scales = self.dist.remedy_scales(scales if scales is not None else self.scales)
        return self.tshape + self.domain.grid_shape(scales)

    def __repr__(self):
        return f"Field(name={self.name!r}, bases={self.domain.bases})"

    def __str__(self):
        return self.name or f"F{id(self)%10000}"

    # ---- layout management ----

    def _sync(self):
        if self._pull is not None:
            pull, self._pull = self._pull, None
            pull()

    def require_coeff_space(self):
        self._sync()
        if self.layout == "g":
            fn = _compiled_transform("c", self.domain, tuple(self.scales),
                                     self.tdim, self.tensorsig)
            self.data = fn(self.data)
            self.layout = "c"
        return self.data

    def require_grid_space(self, scales=None):
        self._sync()
        if scales is not None:
            self.change_scales(scales)
        if self.layout == "c":
            fn = _compiled_transform("g", self.domain, tuple(self.scales),
                                     self.tdim, self.tensorsig)
            self.data = fn(self.data)
            self.layout = "g"
        return self.data

    def change_scales(self, scales):
        scales = self.dist.remedy_scales(scales)
        if scales != self.scales:
            self.require_coeff_space()
            self.scales = scales

    def change_layout(self, layout):
        if layout in ("c", 0, "coeff"):
            self.require_coeff_space()
        else:
            self.require_grid_space()

    def __getitem__(self, layout):
        # Return a host view that writes back on item assignment, so the
        # reference idiom `u['g'][2] = ...` works (reference fields expose
        # their live buffers; here device arrays are immutable, so the view
        # pushes mutations back through __setitem__).
        if layout in ("c", 0, "coeff"):
            return _FieldDataView(np.array(self.require_coeff_space()),
                                  self, "c")
        elif layout in ("g", 1, "grid"):
            return _FieldDataView(np.array(self.require_grid_space()),
                                  self, "g")
        raise KeyError(f"Unknown layout: {layout}")

    def __setitem__(self, layout, value):
        if layout in ("c", 0, "coeff"):
            new_layout = "c"
            shape, dtype = self.coeff_shape, self.coeff_dtype
        elif layout in ("g", 1, "grid"):
            new_layout = "g"
            shape, dtype = self.grid_shape(), self.grid_dtype
        else:
            raise KeyError(f"Unknown layout: {layout}")
        data = jnp.broadcast_to(jnp.asarray(value, dtype=dtype), shape)
        # Only after validation: discard pending solver data, count mutation.
        self._pull = None
        self._version += 1
        self._data_epoch += 1
        self.layout = new_layout
        self.data = data

    # Solver-facing accessors -------------------------------------------------

    def coeff_data(self):
        """Device coefficient array (triggers transform if needed)."""
        return self.require_coeff_space()

    def preset_coeff(self, array):
        """Install device coefficient data directly (solver scatter).
        Does not count as a user mutation (no version bump, but existing
        data views become stale); the grid-scale selection is preserved
        (coefficient data is scale-independent)."""
        self.data = array
        self.layout = "c"
        self._data_epoch += 1

    def mark_modified(self):
        self._version += 1

    def install_pull(self, pull):
        """Install a lazy solver-data pull; any outstanding data views
        become stale immediately (the field's data is now solver-owned)."""
        self._pull = pull
        self._data_epoch += 1

    # ---- utilities ----

    def copy(self):
        self._sync()
        out = Field(self.dist, bases=self.domain.bases, name=self.name,
                    tensorsig=self.tensorsig, dtype=self.dtype)
        out.data = self.data
        out.layout = self.layout
        out.scales = self.scales
        return out

    def evaluate(self):
        return self

    def fill_random(self, layout="g", seed=None, distribution="normal", **kw):
        """
        Deterministic random fill (reference: core/field.py:847 fill_random).
        Uses a global-shape numpy RNG so results are independent of sharding
        (reference's ChunkedRandomArray guarantees the same property).
        """
        rng = np.random.default_rng(seed)
        if layout in ("g", 1, "grid"):
            shape, dtype = self.grid_shape(), self.grid_dtype
        else:
            shape, dtype = self.coeff_shape, self.coeff_dtype
        scale = kw.pop("scale", 1)
        if distribution in ("normal", "standard_normal"):
            data = rng.standard_normal(shape)
            if is_complex_dtype(dtype):
                data = data + 1j * rng.standard_normal(shape)
        elif distribution == "uniform":
            data = rng.uniform(size=shape, **{k: kw[k] for k in ("low", "high") if k in kw})
        else:
            data = getattr(rng, distribution)(size=shape)
        self[layout] = scale * data.astype(dtype)

    def low_pass_filter(self, shape=None, scales=None):
        """Zero coefficients above a per-axis mode cutoff
        (reference: core/field.py API). `scales` gives cutoffs as fractions
        of each axis size; `shape` gives them as mode counts."""
        from .basis import RealFourier, ComplexFourier
        if shape is None and scales is None:
            return self
        coeff_shape = self.domain.coeff_shape
        if shape is None:
            scales = self.dist.remedy_scales(scales)
            shape = [1 if b is None else int(s * n)
                     for b, s, n in zip(self.domain.bases, scales, coeff_shape)]
        data = np.asarray(self.require_coeff_space())
        mask = np.ones_like(data, dtype=bool)
        for axis, (basis, cutoff) in enumerate(zip(self.domain.bases, shape)):
            if basis is None:
                continue
            n = coeff_shape[axis]
            if isinstance(basis, RealFourier):
                # interleaved (cos, -sin) pairs: cutoff counts coefficients
                keep = np.arange(n) < cutoff
            elif isinstance(basis, ComplexFourier):
                # FFT ordering: keep |k| < cutoff/2 on both branches
                k = np.abs(np.fft.fftfreq(n, d=1.0 / n))
                keep = k < cutoff / 2
            else:
                keep = np.arange(n) < cutoff
            view = [np.newaxis] * data.ndim
            view[self.tdim + axis] = slice(None)
            mask = mask & keep[tuple(view)]
        self.data = jnp.asarray(data * mask)
        self._version += 1
        return self

    def allreduce_data_norm(self, layout="c", order=2):
        data = np.asarray(self[layout])
        if order == np.inf:
            return np.max(np.abs(data))
        return np.linalg.norm(data.ravel(), ord=order)

    def allgather_data(self, layout="g"):
        return np.asarray(self[layout])

    # Problem-layer helpers ---------------------------------------------------

    def frechet_differential(self, variables, perturbations):
        """
        Symbolic Frechet differential of this field viewed as an expression
        (trivial for a bare field; see Future.frechet_differential).
        """
        for var, pert in zip(variables, perturbations):
            if self is var:
                return pert
        return 0


def ScalarField(dist, *args, **kw):
    return dist.Field(*args, **kw)


def VectorField(dist, coordsys, *args, **kw):
    return dist.VectorField(coordsys, *args, **kw)


def TensorField(dist, coordsys, *args, **kw):
    return dist.TensorField(coordsys, *args, **kw)


class LockedField(Field):
    """Field with locked layout (reference: core/field.py:952)."""

    def lock_to_layouts(self, *layouts):
        self._locked = tuple(layouts)

    def lock_scales(self):
        pass
