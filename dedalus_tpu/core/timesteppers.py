"""
IMEX timesteppers (reference: dedalus/core/timesteppers.py).

Schemes integrate M.dt(X) + L.X = F with implicit L and explicit F.

Multistep form (reference: core/timesteppers.py:22 MultistepIMEX):
    sum_j a_j M.X(n-j) + sum_j b_j L.X(n-j) = sum_{j>=1} c_j F(n-j)
with variable-timestep coefficients. The SBDF family generates its
coefficients from Lagrange derivative/extrapolation weights (equivalent to
the reference's closed forms from Wang & Ruuth 2008, JCM 26).

IMEX Runge-Kutta form (reference: core/timesteppers.py:486 RungeKuttaIMEX,
tableaux from Ascher, Ruuth & Spiteri 1997):
    M.X(i) - M.X(0) = dt * sum_j [ A[i,j] F(j) - H[i,j] L.X(j) ]

Device design: each step is ONE jitted call (gather -> F evaluation with
transforms -> batched LU solve -> scatter); the LHS factorization
(a0*M + b0*L or M + dt*H[i,i]*L) is recomputed only when the leading
coefficients change (reference: core/timesteppers.py:123-128,160-168).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..libraries import pencilops
from ..tools.jitlift import lifted_jit
from ..tools.config import config

schemes = {}


def _mesh_pin(solver):
    """
    Pencil-sharding pin for step-program intermediates: when the solver is
    distributed (parallel/sharding.distribute_solver recorded a mesh on the
    distributor), XLA's sharding propagation alone does NOT keep the
    factor/solve boundary sharded — the factored LHS comes back replicated
    and every solve then all-gathers its RHS (observed on the virtual CPU
    mesh). Returns pin(tree, lead=0): constrains every array leaf whose
    `lead` axis is the pencil-group axis (length G) onto the mesh's first
    axis; identity when no mesh is active, so unsharded runs trace zero
    extra ops. Resolved at trace time (closure over the solver) so the
    same step bodies serve both the unsharded and post-distribute traces.
    """
    mesh = getattr(solver.dist, "mesh", None)
    if mesh is None:
        return lambda tree, lead=0: tree
    from jax.sharding import NamedSharding, PartitionSpec
    name = mesh.axis_names[0]
    n = mesh.shape[name]
    G = solver.pencil_shape[0]

    def pin(tree, lead=0):
        def one(a):
            ndim = getattr(a, "ndim", None)
            # only pencil-batched leaves: chunked banded factors (leading
            # chunk axis) and scalars pass through unconstrained
            if ndim is None or ndim <= lead or a.shape[lead] != G or G % n:
                return a
            spec = [None] * ndim
            spec[lead] = name
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, PartitionSpec(*spec)))
        return jax.tree.map(one, tree)

    return pin


def _use_split_step(solver):
    """
    Whether to compile the step as SEVERAL small device programs (per-stage
    eval/solve dispatches) instead of one fused program. Monolithic step
    programs at very large pencil counts have wedged the TPU AOT compiler;
    above the mode threshold the ~ms of extra per-step dispatch latency is
    negligible against the per-step device time.
    """
    mode = config["execution"].get("STEP_PROGRAM", "auto").lower()
    if mode in ("fused", "split"):
        return mode == "split"
    G, S = solver.pencil_shape
    threshold = int(config["execution"].get("STEP_SPLIT_MODES", str(1 << 22)))
    return G * S > threshold


def add_scheme(cls):
    schemes[cls.__name__] = cls
    return cls


def _lagrange_derivative_weights(nodes):
    """Weights w: sum_j w_j p(nodes_j) = p'(0) for all deg < len(nodes)."""
    n = len(nodes)
    V = np.vander(np.asarray(nodes, dtype=float), n, increasing=True).T
    d = np.zeros(n)
    if n > 1:
        d[1] = 1.0
    return np.linalg.solve(V, d)


def _lagrange_extrapolation_weights(nodes):
    """Weights e: sum_j e_j p(nodes_j) = p(0)."""
    n = len(nodes)
    V = np.vander(np.asarray(nodes, dtype=float), n, increasing=True).T
    d = np.zeros(n)
    d[0] = 1.0
    return np.linalg.solve(V, d)


def _past_times(dt_hist, s):
    """[0, -k0, -(k0+k1), ...] for s+1 time levels."""
    times = [0.0]
    acc = 0.0
    for j in range(s):
        acc += dt_hist[j]
        times.append(-acc)
    return times


class MultistepIMEX:
    """Base multistep IMEX integrator (reference: core/timesteppers.py:22)."""

    steps = None
    stages = 1

    def __init__(self, solver):
        self.solver = solver
        G, S = solver.pencil_shape
        s = self.steps
        # fused-step plan: the one the SOLVER resolved at build start
        # (core/solvers.py), so a mid-build/mid-run config edit can
        # never split one scheme across two compositions; donation
        # applies to the fused (non-split) step programs only
        from .fusedstep import resolve_fusion
        self._fusion = getattr(solver, "_fusion_plan", None) \
            or resolve_fusion()
        self._split = _use_split_step(solver)
        self.donates_histories = self._fusion.donate and not self._split
        # three DISTINCT zero buffers: the donating step program aliases
        # each history input to its output, so sharing one interned zeros
        # array across the three would alias two donated params
        self.F_hist = jnp.zeros((s, G, S), dtype=solver.pencil_dtype)
        self.MX_hist = jnp.zeros((s, G, S), dtype=solver.pencil_dtype)
        self.LX_hist = jnp.zeros((s, G, S), dtype=solver.pencil_dtype)
        self.dt_hist = []
        self._lhs_key = None
        self._lhs_aux = None
        self.iteration = 0
        # per-run state lives in the block above; reset_run() must mirror
        # any addition here or pooled served runs stop bit-matching fresh
        # solves (tests/test_service.py::test_pool_reset_bit_identity)

        eval_F = solver.eval_F
        from ..tools.jitlift import device_constant
        mask_np, mask_dt = solver.valid_row_mask, solver.real_dtype
        # resolved inside each trace so the (G, S) mask is lifted to a
        # program argument instead of an inline constant
        mask = lambda: device_constant(mask_np, dtype=mask_dt)
        ops = solver.ops

        # M and L are explicit arguments (not closure constants) so the
        # compiled HLO stays small and the arrays live as device buffers.
        def _factor_body(M, L, a0, b0):
            # pinned + shard_map-routed: an unconstrained factor replicates
            # under GSPMD (forcing an all-gather into every solve), and the
            # pivoted-LU custom calls are unpartitionable without the
            # pencil_mesh shard_map routing (libraries/pencilops.py)
            with pencilops.pencil_mesh(getattr(solver.dist, "mesh", None)):
                return _mesh_pin(solver)(ops.factor_lincomb(a0, M, b0, L))
        _factor_jit = lifted_jit(_factor_body)
        G = solver.pencil_shape[0]
        itemsize = np.dtype(solver.pencil_dtype).itemsize

        def _factor(M, L, a0, b0):
            # very large factor outputs go chunk-by-chunk in separate
            # dispatches (caps the transient HBM peak; pencilops)
            if (hasattr(ops, "use_incremental_factor")
                    and ops.use_incremental_factor(G, itemsize)):
                return ops.factor_lincomb_incremental(a0, M, L, b_scale=b0)
            return _factor_jit(M, L, a0, b0)

        # the fused step body composes the same two pieces the split mode
        # dispatches separately, so the numerics cannot drift between modes
        pair = (self._fusion.matvec and hasattr(ops, "matvec_pair"))

        def eval_parts(M, L, X, t, extra):
            pin = _mesh_pin(solver)
            if pair:
                # one-pass M/L pair (bitwise-identical components;
                # core/fusedstep.py FUSED_MATVEC)
                MXn, LXn = ops.matvec_pair(M, L, X)
            else:
                MXn, LXn = ops.matvec(M, X), ops.matvec(L, X)
            return pin((eval_F(X, t, extra) * mask(), MXn, LXn))

        def update_solve(Fn, MXn, LXn, F_hist, MX_hist, LX_hist, a, b, c,
                         lhs_aux, M, L):
            pin = _mesh_pin(solver)
            F_hist = jnp.concatenate([Fn[None], F_hist[:-1]])
            MX_hist = jnp.concatenate([MXn[None], MX_hist[:-1]])
            LX_hist = jnp.concatenate([LXn[None], LX_hist[:-1]])
            RHS = (jnp.tensordot(c, F_hist, axes=1)
                   - jnp.tensordot(a[1:], MX_hist, axes=1)
                   - jnp.tensordot(b[1:], LX_hist, axes=1))
            with pencilops.pencil_mesh(getattr(solver.dist, "mesh", None)):
                Xn = pin(ops.solve(lhs_aux, RHS, mats=(M, L)))
            return Xn, pin(F_hist, lead=1), pin(MX_hist, lead=1), \
                pin(LX_hist, lead=1)

        def advance_body(M, L, X, t, extra, F_hist, MX_hist, LX_hist, a, b, c,
                         lhs_aux):
            with jax.named_scope("dedalus/step/advance"):
                Fn, MXn, LXn = eval_parts(M, L, X, t, extra)
                return update_solve(Fn, MXn, LXn, F_hist, MX_hist, LX_hist,
                                    a, b, c, lhs_aux, M, L)

        def _advance_n(M, L, X, t, extra, F_hist, MX_hist, LX_hist, a, b, c,
                       n, dt, lhs_aux):
            # n constant-coefficient steps in one lax.scan dispatch
            def body(carry, _):
                X, t, Fh, MXh, LXh = carry
                Xn, Fh, MXh, LXh = advance_body(M, L, X, t, extra, Fh, MXh,
                                                LXh, a, b, c, lhs_aux)
                return (Xn, t + dt, Fh, MXh, LXh), None
            carry, _ = jax.lax.scan(body, (X, t, F_hist, MX_hist, LX_hist),
                                    None, length=n)
            Xn, _, F_hist, MX_hist, LX_hist = carry
            return Xn, F_hist, MX_hist, LX_hist

        self._factor = _factor
        # the fused whole-step programs donate the history buffers
        # (args 5-7: F/MX/LX) when DONATE_STEP is on, so XLA rolls the
        # histories in place instead of allocating fresh ones each step;
        # cross-step reference holders (snapshot ring, async checkpoint
        # capture, the probe cache below) copy under donates_histories
        donate = (5, 6, 7) if self.donates_histories else ()
        self._advance = lifted_jit(advance_body, donate_argnums=donate)
        self._advance_n = lifted_jit(_advance_n, static_argnums=(11,),
                                     donate_argnums=donate)
        # non-donating twin for the fused-phase probe: a donating program
        # would consume the probe cache's snapshot inputs on first use
        # (compiled once at warmup end, outside measured windows)
        self._advance_probe = self._advance if not donate \
            else lifted_jit(advance_body)
        # ensemble hook (core/ensemble.py): the raw, un-jitted step body,
        # vmapped over a leading member axis by EnsembleSolver — the same
        # composition the fused program compiles, so fleet numerics cannot
        # drift from the serial step
        self.advance_body = advance_body

        # split-step pieces: the SAME bodies the fused program composes,
        # compiled as separate (smaller) device programs for very large
        # systems (see _use_split_step; self._split set in __init__ ahead
        # of the donation wiring)
        self._eval_parts = lifted_jit(eval_parts)
        self._update_solve = lifted_jit(update_solve)

    def compute_coefficients(self, dt_hist, order):
        """Return (a[0..order], b[0..order], c[1..order])."""
        raise NotImplementedError

    def _pad_coeffs(self, a, b, c):
        """Pad (a, b, c) to the stationary lengths (s+1, s+1, s) that
        advance_body consumes, exactly as step() does."""
        s = self.steps
        a = np.concatenate([a, np.zeros(s + 1 - len(a))])
        b = np.concatenate([b, np.zeros(s + 1 - len(b))])
        c = np.concatenate([c, np.zeros(s - len(c))])
        return a, b, c

    def coefficient_schedule(self, dt, n):
        """
        Host-side constant-dt coefficient schedule for an n-step run from
        a FRESH history (zero F/MX/LX hists), replaying exactly what n
        calls of step(dt) would produce: the startup ramp's per-step
        padded (a, b, c) triples (orders 1..min(s-1, n)) followed by the
        stationary triple covering every later step. The differentiable
        scan (core/adjoint.py) consumes this so adjoint forward passes
        are bit-identical to the stepping loop.
        """
        s = self.steps
        dt = float(dt)
        ramp = []
        for it in range(1, min(s - 1, int(n)) + 1):
            a, b, c = self.compute_coefficients([dt] * it, it)
            ramp.append(self._pad_coeffs(a, b, c))
        a, b, c = self.compute_coefficients([dt] * s, s)
        return ramp, self._pad_coeffs(a, b, c)

    def reset_run(self):
        """Rewind per-run state to just-constructed values IN PLACE (the
        warm-pool service's between-request reset, service/pool.py) —
        the instance survives because it owns the compiled step
        programs. The multistep ramp restarts; the LHS factorization
        cache (_lhs_key/_lhs_aux) is deliberately KEPT: it is a pure
        function of (M, L, scheme coefficients, dt history), all
        request-invariant on one pooled solver, and step() re-keys it
        whenever the dt pattern differs — exactly the check a fresh
        solver performs."""
        solver = self.solver
        G, S = solver.pencil_shape
        # distinct buffers: see __init__ (donated inputs must not alias)
        self.F_hist = jnp.zeros((self.steps, G, S),
                                dtype=solver.pencil_dtype)
        self.MX_hist = jnp.zeros((self.steps, G, S),
                                 dtype=solver.pencil_dtype)
        self.LX_hist = jnp.zeros((self.steps, G, S),
                                 dtype=solver.pencil_dtype)
        self.dt_hist = []
        self.iteration = 0

    def step(self, dt, wall_time=None):
        solver = self.solver
        s = self.steps
        self.dt_hist = [float(dt)] + self.dt_hist[:s - 1]
        self.iteration += 1
        order = min(s, self.iteration)
        a, b, c = self._pad_coeffs(
            *self.compute_coefficients(self.dt_hist, order))
        key = (round(float(a[0]), 14), round(float(b[0]), 14))
        rd = self.solver.real_dtype
        if key != self._lhs_key:
            self._lhs_key = key
            self._lhs_aux = self._factor(solver.M_mat, solver.L_mat,
                                         jnp.asarray(a[0], dtype=rd),
                                         jnp.asarray(b[0], dtype=rd))
        if self._split:
            Fn, MXn, LXn = self._eval_parts(
                solver.M_mat, solver.L_mat, solver.X,
                jnp.asarray(solver.sim_time, dtype=rd), solver.rhs_extra())
            X, self.F_hist, self.MX_hist, self.LX_hist = self._update_solve(
                Fn, MXn, LXn, self.F_hist, self.MX_hist, self.LX_hist,
                jnp.asarray(a, dtype=rd), jnp.asarray(b, dtype=rd),
                jnp.asarray(c, dtype=rd), self._lhs_aux,
                solver.M_mat, solver.L_mat)
        else:
            X, self.F_hist, self.MX_hist, self.LX_hist = self._advance(
                solver.M_mat, solver.L_mat, solver.X,
                jnp.asarray(solver.sim_time, dtype=rd), solver.rhs_extra(),
                self.F_hist, self.MX_hist, self.LX_hist, jnp.asarray(a, dtype=rd),
                jnp.asarray(b, dtype=rd), jnp.asarray(c, dtype=rd), self._lhs_aux)
        solver.X = X
        solver.sim_time = float(solver.sim_time) + float(dt)

    def step_many(self, n, dt):
        """
        n constant-dt steps in one device dispatch. The startup ramp (order
        build-up) and any dt change run as single steps until the multistep
        coefficients are stationary; the remainder scans on device.
        """
        solver = self.solver
        s = self.steps
        n = int(n)
        if self._split:
            # split mode targets huge systems where per-step device time
            # dominates dispatch latency; no need for the scanned block
            for _ in range(n):
                self.step(dt)
            return
        while n > 0 and not (self.iteration >= s
                             and len(self.dt_hist) == s
                             and all(abs(k - float(dt)) < 1e-15 * abs(dt)
                                     for k in self.dt_hist)):
            self.step(dt)
            n -= 1
        if n == 0:
            return
        rd = solver.real_dtype
        a, b, c = self.compute_coefficients(self.dt_hist, s)
        key = (round(float(a[0]), 14), round(float(b[0]), 14))
        if key != self._lhs_key:
            self._lhs_key = key
            self._lhs_aux = self._factor(solver.M_mat, solver.L_mat,
                                         jnp.asarray(a[0], dtype=rd),
                                         jnp.asarray(b[0], dtype=rd))
        X, self.F_hist, self.MX_hist, self.LX_hist = self._advance_n(
            solver.M_mat, solver.L_mat, solver.X,
            jnp.asarray(solver.sim_time, dtype=rd), solver.rhs_extra(),
            self.F_hist, self.MX_hist, self.LX_hist,
            jnp.asarray(a, dtype=rd), jnp.asarray(b, dtype=rd),
            jnp.asarray(c, dtype=rd), n, jnp.asarray(float(dt), dtype=rd),
            self._lhs_aux)
        solver.X = X
        solver.sim_time = float(solver.sim_time) + n * float(dt)
        self.iteration += n

    def phase_probes(self):
        """Measurement thunks re-running the already-compiled step pieces
        (eval vs. solve) on a snapshot of the current state — no state
        mutation: {name: (thunk, per-step scale)}. None until the first
        step has factored the LHS. Probe inputs are cached per LHS key:
        dense/banded compute time is value-independent, so stale values
        time the same programs without re-deriving fresh stage inputs each
        sample — but a dt/coefficient change drops the cache so the
        superseded factorization (the largest device allocation) is not
        pinned by the thunk closures. The cache does pin a handful of
        state-sized buffers (X snapshot, eval parts, the history tuple)
        for the run — a few (G, S) arrays, small next to the factors and
        band/dense stores."""
        if self._lhs_aux is None or not self.dt_hist:
            return None
        cache = getattr(self, "_probe_cache", None)
        if cache is not None and cache[0] != self._lhs_key:
            cache = None
        if cache is None:
            solver = self.solver
            rd = solver.real_dtype
            s = self.steps
            M, L, X = solver.M_mat, solver.L_mat, solver.X
            t = jnp.asarray(float(solver.sim_time), dtype=rd)
            extra = solver.rhs_extra()
            a, b, c = self._pad_coeffs(*self.compute_coefficients(
                self.dt_hist, min(s, max(self.iteration, 1))))
            aj, bj, cj = (jnp.asarray(v, dtype=rd) for v in (a, b, c))
            Fn, MXn, LXn = self._eval_parts(M, L, X, t, extra)
            # probe-input warm: runs once per LHS key under the metrics
            # cadence gate, never in the measured step path
            jax.block_until_ready((Fn, MXn, LXn))  # dedalus-lint: disable=DTL001
            # the probe cache holds cross-step references: copy under
            # donation (the shared contract lives in guard_histories)
            from .fusedstep import guard_histories
            hists = guard_histories(self)
            lhs_aux = self._lhs_aux

            def eval_thunk():
                return self._eval_parts(M, L, X, t, extra)

            def solve_thunk():
                return self._update_solve(Fn, MXn, LXn, *hists,
                                          aj, bj, cj, lhs_aux, M, L)

            probes = {"rhs_eval": (eval_thunk, 1.0),
                      "matsolve": (solve_thunk, 1.0)}
            if not self._split:
                # the whole fused step program (transform -> solve in one
                # dispatch), probed via the non-donating twin: the
                # `fused` row of the sampled phase table (tools/metrics)
                def fused_thunk():
                    return self._advance_probe(M, L, X, t, extra, *hists,
                                               aj, bj, cj, lhs_aux)

                probes["fused_step"] = (fused_thunk, 1.0)
            cache = self._probe_cache = (self._lhs_key, probes)
        return cache[1]


@add_scheme
class CNAB1(MultistepIMEX):
    """Crank-Nicolson / Adams-Bashforth 1 (reference: core/timesteppers.py:179)."""
    steps = 1

    def compute_coefficients(self, dt_hist, order):
        k0 = dt_hist[0]
        return np.array([1/k0, -1/k0]), np.array([0.5, 0.5]), np.array([1.0])


@add_scheme
class SBDF1(MultistepIMEX):
    """1st-order semi-implicit BDF / backward Euler (reference: :212)."""
    steps = 1

    def compute_coefficients(self, dt_hist, order):
        k0 = dt_hist[0]
        return np.array([1/k0, -1/k0]), np.array([1.0, 0.0]), np.array([1.0])


class SBDFBase(MultistepIMEX):
    """Variable-step SBDF via Lagrange weights."""

    def compute_coefficients(self, dt_hist, order):
        p = min(order, self.steps)
        times = _past_times(dt_hist, p)
        a = _lagrange_derivative_weights(times)
        b = np.zeros(p + 1)
        b[0] = 1.0
        c = _lagrange_extrapolation_weights(times[1:])
        return a, b, c


@add_scheme
class SBDF2(SBDFBase):
    """2nd-order SBDF (reference: core/timesteppers.py:321)."""
    steps = 2


@add_scheme
class SBDF3(SBDFBase):
    """3rd-order SBDF (reference: core/timesteppers.py:398)."""
    steps = 3


@add_scheme
class SBDF4(SBDFBase):
    """4th-order SBDF (reference: core/timesteppers.py:439)."""
    steps = 4


@add_scheme
class CNAB2(MultistepIMEX):
    """Crank-Nicolson / Adams-Bashforth 2 (reference: :244)."""
    steps = 2

    def compute_coefficients(self, dt_hist, order):
        if order == 1:
            return CNAB1.compute_coefficients(self, dt_hist, order)
        k0, k1 = dt_hist[0], dt_hist[1]
        w = k0 / k1
        a = np.array([1/k0, -1/k0, 0.0])
        b = np.array([0.5, 0.5, 0.0])
        c = np.array([1 + w/2, -w/2])
        return a, b, c


@add_scheme
class MCNAB2(MultistepIMEX):
    """Modified CNAB2 (Wang & Ruuth 2008; reference: :282)."""
    steps = 2

    def compute_coefficients(self, dt_hist, order):
        if order == 1:
            return CNAB1.compute_coefficients(self, dt_hist, order)
        k0, k1 = dt_hist[0], dt_hist[1]
        w = k0 / k1
        a = np.array([1/k0, -1/k0, 0.0])
        b = np.array([(8 + 1/w)/16, (7 - 1/w)/16, 1/16])  # Wang 2008 eqn 2.10
        c = np.array([1 + w/2, -w/2])
        return a, b, c


@add_scheme
class CNLF2(MultistepIMEX):
    """Crank-Nicolson leapfrog (reference: core/timesteppers.py:359)."""
    steps = 2

    def compute_coefficients(self, dt_hist, order):
        if order == 1:
            return CNAB1.compute_coefficients(self, dt_hist, order)
        k0, k1 = dt_hist[0], dt_hist[1]
        w = k0 / k1
        # Wang 2008 eqn 2.11 (variable-step leapfrog + wide Crank-Nicolson)
        a = np.array([1/((1 + w)*k0), (w - 1)/k0, -w**2/((1 + w)*k0)])
        b = np.array([1/(2*w), (1 - 1/w)/2, 0.5])
        c = np.array([1.0, 0.0])
        return a, b, c


class RungeKuttaIMEX:
    """IMEX Runge-Kutta base (reference: core/timesteppers.py:486)."""

    stages = None
    A = None  # explicit tableau (s+1, s+1)
    H = None  # implicit tableau (s+1, s+1)
    c = None  # stage times (s+1,)
    steps = 1

    def __init__(self, solver):
        self.solver = solver
        self.iteration = 0
        self._lhs_key = None
        self._lhs_aux = None
        # RK stages carry no cross-step history buffers: nothing to
        # donate (the fused-solve/matvec layers of core/fusedstep.py
        # apply through solver.ops regardless; plan kept for
        # introspection parity with MultistepIMEX)
        from .fusedstep import resolve_fusion
        self._fusion = getattr(solver, "_fusion_plan", None) \
            or resolve_fusion()
        self.donates_histories = False

        eval_F = solver.eval_F  # (reset_run mirrors the per-run state)
        rd = solver.real_dtype
        from ..tools.jitlift import device_constant
        mask_np = solver.valid_row_mask
        mask = lambda: device_constant(mask_np, dtype=rd)
        A = jnp.asarray(self.A, dtype=rd)
        H = jnp.asarray(self.H, dtype=rd)
        c = jnp.asarray(self.c, dtype=rd)
        s = self.stages
        ops = solver.ops
        one = jnp.asarray(1.0, dtype=rd)

        # M and L are explicit arguments (not closure constants): keeps the
        # compiled HLO small and shares one device buffer across calls.
        # Stages with equal implicit diagonal coefficients H[i,i] share one
        # factorization (all ARS tableaux here have constant diagonals, so
        # typically a single LHS factor serves every stage).
        H_diag = [float(self.H[i, i]) for i in range(1, s + 1)]
        uniq = sorted(set(H_diag))
        stage_slot = [uniq.index(h) for h in H_diag]

        # one factorization per UNIQUE implicit diagonal; the per-stage list
        # is assembled OUTSIDE the jit so stages sharing a factor alias the
        # same device buffers instead of duplicating the jit's outputs
        def _factor_uniq(M, L, dt):
            # pinned + shard_map-routed: see MultistepIMEX._factor_body
            pin = _mesh_pin(solver)
            with pencilops.pencil_mesh(getattr(solver.dist, "mesh", None)):
                return [pin(ops.factor_lincomb(one, M, dt * h, L))
                        for h in uniq]
        _factor_uniq = lifted_jit(_factor_uniq)
        G = solver.pencil_shape[0]
        itemsize = np.dtype(solver.pencil_dtype).itemsize

        def _factor(M, L, dt):
            # very large factor outputs go chunk-by-chunk in separate
            # dispatches (caps the transient HBM peak; pencilops)
            if (hasattr(ops, "use_incremental_factor")
                    and ops.use_incremental_factor(G, itemsize)):
                auxs = [ops.factor_lincomb_incremental(one, M, L,
                                                       b_scale=dt * h)
                        for h in uniq]
            else:
                auxs = _factor_uniq(M, L, dt)
            return [auxs[j] for j in stage_slot]
        self._factor_uniq = _factor_uniq

        # the fused step body composes the same per-stage pieces the split
        # mode dispatches separately, so the numerics cannot drift
        def stage_eval(M, L, Xi, ti, extra):
            pin = _mesh_pin(solver)
            return pin((ops.matvec(L, Xi), eval_F(Xi, ti, extra) * mask()))

        def stage_solve(i, MX0, Fs, LXs, dt, lhs_aux, M, L):
            RHS = MX0
            for j in range(i):
                RHS = RHS + dt * (A[i, j] * Fs[j] - H[i, j] * LXs[j])
            with pencilops.pencil_mesh(getattr(solver.dist, "mesh", None)):
                return _mesh_pin(solver)(ops.solve(lhs_aux, RHS,
                                                   mats=(M, L)))

        def step_body(M, L, X0, t0, dt, extra, lhs_auxs):
            MX0 = ops.matvec(M, X0)
            LXs = []
            Fs = []
            Xi = X0
            for i in range(1, s + 1):
                with jax.named_scope(f"dedalus/step/stage{i}"):
                    LXi, Fi = stage_eval(M, L, Xi, t0 + c[i - 1] * dt, extra)
                    LXs.append(LXi)
                    Fs.append(Fi)
                    Xi = stage_solve(i, MX0, Fs, LXs, dt, lhs_auxs[i - 1],
                                     M, L)
            return Xi

        def _step_n(M, L, X0, t0, dt, extra, lhs_auxs, n):
            # n device steps in one lax.scan: one dispatch per block
            # instead of per step (small problems are host-latency bound)
            def body(carry, _):
                X, t = carry
                Xn = step_body(M, L, X, t, dt, extra, lhs_auxs)
                return (Xn, t + dt), None
            (Xn, _), _ = jax.lax.scan(body, (X0, t0), None, length=n)
            return Xn

        self._factor = _factor
        self._step = lifted_jit(step_body)
        self._step_n = lifted_jit(_step_n, static_argnums=(7,))
        # ensemble hooks (core/ensemble.py): the raw step body for member
        # vmapping, plus the unique-implicit-diagonal bookkeeping so the
        # per-member-dt mode can vmap its own factorization
        self.step_body = step_body
        self.uniq_H_diag = uniq
        self.stage_slot = stage_slot

        # split-step pieces: the SAME per-stage bodies the fused program
        # composes, compiled as separate device programs (see _use_split_step)
        self._split = _use_split_step(solver)
        self._mx0 = lifted_jit(lambda M, X0: ops.matvec(M, X0))
        self._stage_eval = lifted_jit(stage_eval)
        self._stage_solve = lifted_jit(stage_solve, static_argnums=(0,))

    def _step_split(self, dt):
        solver = self.solver
        rd = solver.real_dtype
        M, L = solver.M_mat, solver.L_mat
        extra = solver.rhs_extra()
        dtj = jnp.asarray(float(dt), dtype=rd)
        t0 = float(solver.sim_time)
        MX0 = self._mx0(M, solver.X)
        Fs, LXs = [], []
        Xi = solver.X
        for i in range(1, self.stages + 1):
            # stage time in rd arithmetic (t0 + c*dt term-by-term), exactly
            # matching the fused body's on-device rd computation
            ti = jnp.asarray(rd.type(t0)
                             + rd.type(self.c[i - 1]) * rd.type(dt), dtype=rd)
            LXi, Fi = self._stage_eval(M, L, Xi, ti, extra)
            LXs.append(LXi)
            Fs.append(Fi)
            Xi = self._stage_solve(i, MX0, Fs, LXs, dtj,
                                   self._lhs_aux[i - 1], M, L)
        return Xi

    def reset_run(self):
        """Per-run reset (see MultistepIMEX.reset_run): RK schemes carry
        no ramp history, only the step count; the LHS factorization
        cache is deliberately kept — _ensure_factor re-keys on dt."""
        self.iteration = 0

    def _ensure_factor(self, dt):
        solver = self.solver
        key = round(float(dt), 14)
        if key != self._lhs_key:
            self._lhs_key = key
            self._lhs_aux = self._factor(
                solver.M_mat, solver.L_mat,
                jnp.asarray(float(dt), dtype=solver.real_dtype))

    def step(self, dt, wall_time=None):
        solver = self.solver
        rd = solver.real_dtype
        self._ensure_factor(dt)
        if self._split:
            solver.X = self._step_split(dt)
        else:
            solver.X = self._step(solver.M_mat, solver.L_mat, solver.X,
                                  jnp.asarray(solver.sim_time, dtype=rd),
                                  jnp.asarray(float(dt), dtype=rd),
                                  solver.rhs_extra(), self._lhs_aux)
        solver.sim_time = float(solver.sim_time) + float(dt)
        self.iteration += 1

    def step_many(self, n, dt):
        """n constant-dt steps in one device dispatch (lax.scan); split
        mode steps singly (dispatch latency is negligible at that size)."""
        solver = self.solver
        rd = solver.real_dtype
        self._ensure_factor(dt)
        if self._split:
            for _ in range(int(n)):
                self.step(dt)
            return
        solver.X = self._step_n(solver.M_mat, solver.L_mat, solver.X,
                                jnp.asarray(solver.sim_time, dtype=rd),
                                jnp.asarray(float(dt), dtype=rd),
                                solver.rhs_extra(), self._lhs_aux, int(n))
        solver.sim_time = float(solver.sim_time) + n * float(dt)
        self.iteration += n

    def phase_probes(self):
        """Measurement thunks re-running one already-compiled stage (eval
        vs. solve) on a snapshot of the current state — no state mutation:
        {name: (thunk, per-step scale)}, scale = stages. None until the
        first step has factored the LHS. Stage inputs are cached per LHS
        key (stage compute time is value-independent); a dt change drops
        the cache so the superseded factorization is not pinned. The
        cache does pin a few state-sized buffers (X snapshot, one stage's
        MX0/LX/F) for the run — small next to the factors."""
        if self._lhs_aux is None:
            return None
        cache = getattr(self, "_probe_cache", None)
        if cache is not None and cache[0] != self._lhs_key:
            cache = None
        if cache is None:
            solver = self.solver
            rd = solver.real_dtype
            M, L, X = solver.M_mat, solver.L_mat, solver.X
            t = jnp.asarray(float(solver.sim_time), dtype=rd)
            dtj = jnp.asarray(float(self._lhs_key or 0.0), dtype=rd)
            extra = solver.rhs_extra()
            s = float(self.stages)
            MX0 = self._mx0(M, X)
            LX1, F1 = self._stage_eval(M, L, X, t, extra)
            # probe-input warm: runs once per LHS key under the metrics
            # cadence gate, never in the measured step path
            jax.block_until_ready((MX0, LX1, F1))  # dedalus-lint: disable=DTL001
            aux0 = self._lhs_aux[0]

            def eval_thunk():
                return self._stage_eval(M, L, X, t, extra)

            def solve_thunk():
                return self._stage_solve(1, MX0, [F1], [LX1], dtj, aux0,
                                         M, L)

            probes = {"rhs_eval": (eval_thunk, s),
                      "matsolve": (solve_thunk, s)}
            if not self._split:
                # the whole fused step program (all stages in one
                # dispatch); non-mutating — step_body returns a fresh X
                lhs_auxs = self._lhs_aux

                def fused_thunk():
                    return self._step(M, L, X, t, dtj, extra, lhs_auxs)

                probes["fused_step"] = (fused_thunk, 1.0)
            cache = self._probe_cache = (self._lhs_key, probes)
        return cache[1]


@add_scheme
class RK111(RungeKuttaIMEX):
    """1st-order 1-stage IMEX RK (reference: core/timesteppers.py:636)."""
    stages = 1
    A = np.array([[0., 0.], [1., 0.]])
    H = np.array([[0., 0.], [0., 1.]])
    c = np.array([0., 1.])


@add_scheme
class RK222(RungeKuttaIMEX):
    """2nd-order 2-stage IMEX RK, ARS(2,2,2) (reference: :651)."""
    stages = 2
    _gamma = (2. - np.sqrt(2.)) / 2.
    _delta = 1. - 1. / (2. * _gamma)
    A = np.array([[0., 0., 0.],
                  [_gamma, 0., 0.],
                  [_delta, 1. - _delta, 0.]])
    H = np.array([[0., 0., 0.],
                  [0., _gamma, 0.],
                  [0., 1. - _gamma, _gamma]])
    c = np.array([0., _gamma, 1.])


@add_scheme
class RKSMR(RungeKuttaIMEX):
    """(3-eps)-order 3-stage DIRK+ERK scheme of Spalart, Moser & Rogers
    (1991, Appendix); coefficients are the published constants
    (reference: core/timesteppers.py:692 RKSMR)."""
    stages = 3
    _a1, _a2, _a3 = (29/96, -3/40, 1/6)
    _b1, _b2, _b3 = (37/160, 5/24, 1/6)
    _g1, _g2, _g3 = (8/15, 5/12, 3/4)
    _z2, _z3 = (-17/60, -5/12)
    A = np.array([[0., 0., 0., 0.],
                  [_g1, 0., 0., 0.],
                  [_g1 + _z2, _g2, 0., 0.],
                  [_g1 + _z2, _g2 + _z3, _g3, 0.]])
    H = np.array([[0., 0., 0., 0.],
                  [_a1, _b1, 0., 0.],
                  [_a1, _b1 + _a2, _b2, 0.],
                  [_a1, _b1 + _a2, _b2 + _a3, _b3]])
    c = np.array([0., 8/15, 2/3, 1.])


@add_scheme
class RK443(RungeKuttaIMEX):
    """3rd-order 4-stage IMEX RK, ARS(4,4,3) (reference: :671)."""
    stages = 4
    A = np.array([[0., 0., 0., 0., 0.],
                  [1/2, 0., 0., 0., 0.],
                  [11/18, 1/18, 0., 0., 0.],
                  [5/6, -5/6, 1/2, 0., 0.],
                  [1/4, 7/4, 3/4, -7/4, 0.]])
    H = np.array([[0., 0., 0., 0., 0.],
                  [0., 1/2, 0., 0., 0.],
                  [0., 1/6, 1/2, 0., 0.],
                  [0., -1/2, 1/2, 1/2, 0.],
                  [0., 3/2, -3/2, 1/2, 1/2]])
    c = np.array([0., 1/2, 2/3, 1/2, 1.])


@add_scheme
class RKGFY(RungeKuttaIMEX):
    """2nd-order 2-stage IMEX RK of Hollerbach & Marti (published
    tableau; reference keeps it unregistered at core/timesteppers.py:715
    — registered here for completeness)."""
    stages = 2
    A = np.array([[0., 0., 0.],
                  [1., 0., 0.],
                  [0.5, 0.5, 0.]])
    H = np.array([[0., 0., 0.],
                  [0.5, 0.5, 0.],
                  [0.5, 0., 0.5]])
    c = np.array([0., 1., 1.])


def step_program_handle(solver, dt=1e-3):
    """(program, args) of the solver's compiled single-step program — the
    shared inspection handle behind the compiled-program contract checker
    (tools/lint/progcheck.py), the collective-placement tests
    (tests/test_collectives.py) and benchmarks/scaling.py. `program` is
    the lifted_jit wrapper the step loop actually dispatches (multistep
    `_advance` / RK `_step`), so `program.lower(*args)` reproduces the
    executing program text — including the donate_argnums aliasing
    contract — and `program.jaxpr(*args)` its primitive structure.
    Requires a factored solver (one `solver.step(dt)` builds the LHS
    factorization); raises RuntimeError otherwise rather than lowering a
    program the step loop would never run.
    """
    ts = solver.timestepper
    if getattr(ts, "_lhs_aux", None) is None:
        raise RuntimeError(
            "step_program_handle needs a factored solver: call "
            "solver.step(dt) once before lowering the step program")
    rd = solver.real_dtype
    if isinstance(ts, MultistepIMEX):
        s = ts.steps + 1
        a = b = jnp.zeros(s, dtype=rd)
        c = jnp.zeros(ts.steps, dtype=rd)
        args = (solver.M_mat, solver.L_mat, solver.X,
                jnp.asarray(0.0, dtype=rd), solver.rhs_extra(),
                ts.F_hist, ts.MX_hist, ts.LX_hist, a, b, c, ts._lhs_aux)
        return ts._advance, args
    args = (solver.M_mat, solver.L_mat, solver.X,
            jnp.asarray(0.0, dtype=rd), jnp.asarray(float(dt), dtype=rd),
            solver.rhs_extra(), ts._lhs_aux)
    return ts._step, args
