"""
3D spherical bases (shell; ball in its own section) and the spherical tensor
calculus in regularity components
(reference: dedalus/core/basis.py:3682 ShellRadialBasis, :4336 ShellBasis,
dedalus/core/operators.py:3078 SphericalEllOperator family).

Design (TPU-first):
  * Coefficient layout is rectangular (Nphi, Ntheta, Nr). BOTH angular axes
    are separable: every spherical operator is block-diagonal over (m, ell)
    groups, so the pencil is the radial direction and the implicit solve is
    one batched matmul/LU over all (m, ell) pairs — the reference's
    per-subproblem SuperLU loop (core/solvers.py:683) becomes an MXU batch.
  * Tensor components in coefficient space are REGULARITY components: for
    each ell, the orthogonal intertwiner Q(ell) maps spin components to the
    combinations with radial character r^(ell+sum(reg))
    (reference: core/basis.py:3545 radial_recombinations,
    libraries/dedalus_sphere/spin_operators.py:276 Intertwiner). The
    recombination is one batched einsum over the ell axis.
  * In regularity components every calculus operator is RADIAL-ONLY, with
    per-(ell, regularity) matrices: gradient/divergence/curl are xi-weighted
    ladders D+ = d/dr - l/r, D- = d/dr + (l+1)/r at l = ell + regtotal
    (reference: core/operators.py:3245-3260 SphericalGradient radial
    matrices). On the shell these live in the weighted Jacobi spaces of
    core/weighted_jacobi.py, so each is (A + c*B)/dR with shared A, B.
"""

import numpy as np
import jax.numpy as jnp
from itertools import product as iter_product

from ..tools.cache import CachedMethod, cached_function
from ..tools import jacobi as jacobi_tools
from ..tools.array import match_precision
from ..libraries import sphere as swsh
from ..libraries import zernike
from ..libraries.spin_intertwiners import (regularity_to_spin,
                                           valid_regularities)
from .basis import Basis, AffineCOV
from .weighted_jacobi import WeightedJacobiRadial
from .coords import SphericalCoordinates
from .sphere import SphereBasis
from .domain import Domain
from ..tools.general import is_complex_dtype

REG_ORDERING = (-1, +1, 0)  # index 0 = '-', 1 = '+', 2 = '0' (radial)


# ----------------------------------------------------------------------
# Regularity component helpers

@cached_function
def reg_tuples(rank):
    return tuple(iter_product(REG_ORDERING, repeat=rank))


@cached_function
def reg_totals(rank):
    return np.array([sum(t) for t in reg_tuples(rank)], dtype=int) \
        if rank else np.zeros(1, dtype=int)


@cached_function
def q_stack(Ntheta, rank):
    """(Ntheta, 3^rank, 3^rank): Q(ell) regularity->spin, per ell."""
    return np.stack([regularity_to_spin(ell, rank) for ell in range(Ntheta)])


def spherical_rank(tensorsig, cs):
    """Number of tensor indices over `cs`; mixed signatures are rejected
    (reference restriction: core/basis.py:3551)."""
    rank = 0
    for tcs in tensorsig:
        if tcs == cs:
            rank += 1
        else:
            raise NotImplementedError(
                "3D spherical bases support tensors over the spherical "
                f"coordinate system only, got index {tcs!r}.")
    return rank


def apply_regularity_recombination(data, tdim, theta_data_axis, stack, forward):
    """
    Batched per-ell component recombination: forward maps spin->regularity
    (Q^T), backward regularity->spin (Q). `stack` is (L, ncomp, ncomp);
    the theta axis of `data` must be in ell space.
    """
    tshape = data.shape[:tdim]
    ncomp = int(np.prod(tshape, dtype=int)) if tdim else 1
    spatial = data.shape[tdim:]
    flat = data.reshape((ncomp,) + spatial)
    stack = match_precision(stack, data.dtype)
    a = 1 + (theta_data_axis - tdim)
    moved = jnp.moveaxis(flat, a, 1)  # (ncomp, L, rest...)
    if forward:
        out = jnp.einsum("lji,jl...->il...", stack, moved)
    else:
        out = jnp.einsum("lij,jl...->il...", stack, moved)
    out = jnp.moveaxis(out, 1, a)
    return out.reshape(tshape + spatial)


def xi(mu, l):
    """Normalized derivative factors: xi(-1,l)^2 + xi(+1,l)^2 = 1
    (reference: libraries/dedalus_sphere/spin_operators.py:260)."""
    l = np.asarray(l, dtype=float)
    return np.sqrt(np.maximum(l + (mu + 1) // 2, 0.0)
                   / np.maximum(2 * l + 1, 1.0))


# ----------------------------------------------------------------------
# Shell basis

class ShellBasis(WeightedJacobiRadial, Basis):
    """
    Spherical-shell basis: SWSH angular x weighted-Jacobi radius on [Ri, Ro]
    (reference: dedalus/core/basis.py:4336 ShellBasis).
    """

    dim = 3
    radial_sub_axis = 2
    regularity = True

    def __init__(self, coordsystem, shape, dtype=np.float64, radii=(1.0, 2.0),
                 k=0, alpha=(-0.5, -0.5), dealias=(1, 1, 1),
                 azimuth_library=None, colatitude_library=None,
                 radius_library=None):
        if not isinstance(coordsystem, SphericalCoordinates):
            raise ValueError("Shell coordsys must be SphericalCoordinates.")
        radii = tuple(map(float, radii))
        if min(radii) <= 0:
            raise ValueError("Shell radii must be positive.")
        if radii[0] >= radii[1]:
            raise ValueError("Shell radii must be increasing.")
        self.coordsystem = self.cs = coordsystem
        self.coord = coordsystem.coords[0]
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.radii = radii
        self.k = int(k)
        if np.isscalar(alpha):
            alpha = (alpha, alpha)
        self.alpha = tuple(map(float, alpha))
        if np.isscalar(dealias):
            dealias = (dealias,) * 3
        self.dealias = tuple(map(float, dealias))
        self.volume = 4 / 3 * np.pi * (radii[1] ** 3 - radii[0] ** 3)
        self.dR = radii[1] - radii[0]
        self.rho = (radii[1] + radii[0]) / self.dR
        self.radial_COV = AffineCOV((-1.0, 1.0), radii)
        Nphi, Ntheta, Nr = self.shape
        self.Nphi, self.Ntheta, self.Nr = Nphi, Ntheta, Nr
        self.Lmax = Ntheta - 1
        self.complex = is_complex_dtype(self.dtype)
        self.sphere_basis = SphereBasis(
            coordsystem.S2coordsys, (Nphi, Ntheta), dtype=dtype,
            radius=radii[1], dealias=self.dealias[:2],
            azimuth_library=azimuth_library,
            colatitude_library=colatitude_library, ell_separable=True)
        self.azimuth_basis = self.sphere_basis.azimuth_basis
        self.radius_library = radius_library
        self.inner_surface = self.S2_basis(radii[0])
        self.outer_surface = self.S2_basis(radii[1])

    def __repr__(self):
        return f"ShellBasis({self.shape}, radii={self.radii}, k={self.k})"

    def S2_basis(self, radius=None):
        """Sphere basis for boundary (tau/BC) fields
        (reference: core/basis.py ShellBasis.S2_basis)."""
        if radius is None:
            radius = self.radii[1]
        return SphereBasis(
            self.coordsystem.S2coordsys, (self.Nphi, self.Ntheta),
            dtype=self.dtype, radius=radius, dealias=self.dealias[:2],
            ell_separable=True)

    @property
    def meridional_basis(self):
        """Basis for NCC fields varying along (theta, r) only (reference:
        core/basis.py ShellBasis.meridional_basis). Here NCC angular
        structure is detected from field DATA rather than the declared
        basis, so this aliases the full basis; phi-constancy is validated
        at assembly (grid memory for the extra phi dim is negligible at
        NCC-construction scales)."""
        return self

    @property
    def radial_basis(self):
        """Basis for radius-only NCC fields (reference: core/basis.py
        ShellBasis.radial_basis); aliases the full basis — see
        `meridional_basis`."""
        return self

    # ------------------------------------------------------------ structure

    @property
    def first_axis(self):
        return self.coordsystem.first_axis

    @property
    def family_key(self):
        return (type(self).__name__, self.shape, self.radii, self.alpha,
                self.dtype)

    def coeff_size(self, sub_axis):
        return self.shape[sub_axis]

    def sub_grid_size(self, sub_axis, scale):
        return int(np.ceil(scale * self.shape[sub_axis]))

    def sub_separable(self, sub_axis):
        return sub_axis in (0, 1)

    def sub_group_shape(self, sub_axis):
        if sub_axis == 0:
            return 1 if self.complex else 2
        return 1

    def sub_n_groups(self, sub_axis):
        if sub_axis == 0:
            return self.Nphi if self.complex else self.Nphi // 2
        if sub_axis == 1:
            return self.Ntheta
        return 1

    def group_m(self):
        return self.sphere_basis.group_m()

    def clone_with(self, **changes):
        args = dict(coordsystem=self.coordsystem, shape=self.shape,
                    dtype=self.dtype, radii=self.radii, k=self.k,
                    alpha=self.alpha, dealias=self.dealias)
        args.update(changes)
        return ShellBasis(**args)

    def derivative_basis(self, order=1):
        return self.clone_with(k=self.k + order)

    # --------------------------------------------------------------- grids

    def global_grids(self, scales=(1, 1, 1)):
        return (self.sphere_basis.azimuth_grid(scales[0]),
                self.sphere_basis.colatitude_grid(scales[1]),
                self.radial_grid(scales[2]))

    # ---------------------------------------------------------- validity

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """(ncomp, gs_az, 1, Nr) at one (m, ell) group: regularity component
        valid iff ell >= |m| and the regularity tuple is allowed at ell
        (reference: core/basis.py:3183 regularity_allowed)."""
        rank = spherical_rank(tensorsig, self.cs)
        ncomp = 3 ** rank
        az_axis = self.first_axis
        colat_axis = az_axis + 1
        gs = self.sub_group_shape(0)
        if az_axis not in sep_widths:
            raise NotImplementedError(
                "Shell azimuth must be a pencil (group) axis.")
        ms = self.group_m()
        m = ms[group[az_axis]]
        if colat_axis in sep_widths:
            ells = np.array([group[colat_axis]])
        else:
            # layout-coupled colatitude (theta-dependent NCC): all ell
            # slots live in one per-m pencil
            ells = np.arange(self.Ntheta)
        comp_ok = np.stack([valid_regularities(int(ell), rank)
                            & (ell >= abs(m)) for ell in ells], axis=1)
        mask = np.broadcast_to(comp_ok[:, None, :, None],
                               (ncomp, gs, ells.size, self.Nr)).copy()
        if self.complex and group[az_axis] == self.Nphi // 2:
            mask[:] = False  # Nyquist
        if (not self.complex) and rank <= 1:
            # Drop msin slots at ell == 0 for real scalars and vectors
            # (reference: core/basis.py:4301)
            mask[:, 1, ells == 0, :] = False
        return mask

    # ----------------------------------------------------------- transforms

    def forward_transform(self, gdata, axis, scale, library=None,
                          tensorsig=(), sub_axis=0):
        if sub_axis in (0, 1):
            return self.sphere_basis.forward_transform(
                gdata, axis, scale, library, tensorsig=tensorsig,
                sub_axis=sub_axis)
        tdim = len(tensorsig)
        rank = spherical_rank(tensorsig, self.cs)
        out = gdata
        if rank:
            stack = q_stack(self.Ntheta, rank)
            out = apply_regularity_recombination(out, tdim, axis - 1, stack,
                                                 forward=True)
        return self._radial_matmul(out, axis, scale, forward=True)

    def backward_transform(self, cdata, axis, scale, library=None,
                           tensorsig=(), sub_axis=0):
        if sub_axis in (0, 1):
            return self.sphere_basis.backward_transform(
                cdata, axis, scale, library, tensorsig=tensorsig,
                sub_axis=sub_axis)
        tdim = len(tensorsig)
        rank = spherical_rank(tensorsig, self.cs)
        out = self._radial_matmul(cdata, axis, scale, forward=False)
        if rank:
            stack = q_stack(self.Ntheta, rank)
            out = apply_regularity_recombination(out, tdim, axis - 1, stack,
                                                 forward=False)
        return out

    # ------------------------------------------------- radial matrix stacks
    # All stacks are (Ntheta, Nr, Nr), indexed by the ell group.

    def _ell_l(self, regtotal):
        """l = ell + regtotal per ell slot, with invalid (l < 0) flagged."""
        ell = np.arange(self.Ntheta)
        l = ell + int(regtotal)
        return l, l >= 0

    @CachedMethod
    def dplus_stack(self, regtotal):
        """D+ = d/dr - l/r at l = ell + regtotal, k -> k+1."""
        l, ok = self._ell_l(regtotal)
        A, B = self._ladder_parts()
        stack = (A[None] - l[:, None, None] * B[None]) / self.dR
        stack[~ok] = 0.0
        return stack

    @CachedMethod
    def dminus_stack(self, regtotal):
        """D- = d/dr + (l+1)/r at l = ell + regtotal, k -> k+1."""
        l, ok = self._ell_l(regtotal)
        A, B = self._ladder_parts()
        stack = (A[None] + (l + 1)[:, None, None] * B[None]) / self.dR
        stack[~ok] = 0.0
        return stack

    @CachedMethod
    def laplacian_reg_stack(self, regtotal):
        """L = D-(l+1) @ D+(l) at l = ell + regtotal, k -> k+2
        (reference: core/basis.py:3855 operator_matrix 'L')."""
        l, ok = self._ell_l(regtotal)
        up = self.dplus_stack(regtotal)
        k1 = self.clone_with(k=self.k + 1)
        A1, B1 = k1._ladder_parts()
        down = (A1[None] + (l + 2)[:, None, None] * B1[None]) / self.dR
        stack = np.einsum("gij,gjk->gik", down, up)
        stack[~ok] = 0.0
        return stack

    def lift_column(self, index):
        col = np.zeros((self.Nr, 1))
        col[index, 0] = 1.0
        return col

    @CachedMethod
    def interp_stack(self, regtotal, position):
        """(Ntheta, 1, Nr): boundary evaluation rows (ell-independent on the
        shell; per-ell on the ball)."""
        return np.tile(self.radial_interpolation_row(position),
                       (self.Ntheta, 1, 1))

    def scalar_radial_coeffs(self, profile_grid_values, l_env=0):
        """Level-k radial coefficients of a radial profile on the scale-1
        grid (the envelope degree is irrelevant on the shell)."""
        return self._radial_forward_matrix(1.0) @ profile_grid_values

    def ncc_radial_matrix(self, f_radial_coeffs, f_k, R_in, R_out, ell,
                          k_out=0, l_env=0):
        """Radial NCC multiplication on the shell is independent of ell and
        regularity (no origin singularity): one quadrature matrix."""
        return self.radial_multiplication_matrix(f_radial_coeffs, f_k, k_out)

    @property
    def constant_angular_mode_value(self):
        """Grid value of the lowest angular mode (Y_00 for SWSH): the factor
        between (m=0, ell=0) coefficients and the radial profile they carry."""
        return float(swsh.harmonics(self.Lmax, 0, 0, np.array([0.5]))[0, 0])

    def constant_component_descr(self, sub_axis, device):
        if sub_axis == 0:
            if device:
                col = np.zeros((self.Nphi, 1))
                col[0, 0] = 1.0
                return ("full", col)
            return ("blocks", self.azimuth_basis.constant_blocks())
        if sub_axis == 1:
            Y00 = self.constant_angular_mode_value
            col = np.zeros((self.Ntheta, 1))
            col[0, 0] = 1.0 / Y00
            if device:
                return ("full", col)
            # separable axis: per-ell 1x1 blocks embedding into ell = 0
            blocks = np.zeros((self.Ntheta, 1, 1))
            blocks[0, 0, 0] = 1.0 / Y00
            return ("blocks", blocks)
        return ("full", self.radial_constant_column())

    # ---------------------------------------------------- conversion terms

    def conversion_terms(self, target, tensorsig, tshape):
        """k -> k+dk conversion: regularity/ell-independent single radial
        matrix (reference: core/basis.py:3877 conversion_matrix)."""
        if not isinstance(target, ShellBasis) or target.shape != self.shape \
                or target.radii != self.radii:
            raise ValueError(f"No conversion from {self} to {target}.")
        dk = target.k - self.k
        if dk == 0:
            return [(None, {})]
        if dk < 0:
            raise ValueError("Cannot convert to lower k.")
        r_axis = self.first_axis + 2
        return [(None, {r_axis: ("full", self._conversion_matrix_total(dk))})]


# ----------------------------------------------------------------------
# Ball basis

class BallBasis(Basis):
    """
    Solid-ball basis: SWSH angular x generalized-Zernike radius
    (reference: dedalus/core/basis.py:4568 BallBasis, :3920 BallRadialBasis).

    TPU-native design mirrors ShellBasis, with two differences rooted in the
    origin regularity:
      * each regularity component expands in Zernike polynomials at
        generalized degree l = ell + regtotal, so the radial transforms and
        operator matrices are (Ntheta, Nr, Nr) stacks over the ell groups
        applied as ONE batched matmul (the reference loops per ell:
        core/transforms.py:1451 BallRadialTransform);
      * triangular truncation: radial slot n at harmonic degree ell is valid
        for n >= nmin(ell) = ell // 2, enforced as masking on rectangular
        arrays (reference: core/basis.py:4086 _nmin).
    """

    dim = 3
    radial_sub_axis = 2
    regularity = True

    def __init__(self, coordsystem, shape, dtype=np.float64, radius=1.0,
                 k=0, alpha=0, dealias=(1, 1, 1), azimuth_library=None,
                 colatitude_library=None, radius_library=None):
        if not isinstance(coordsystem, SphericalCoordinates):
            raise ValueError("Ball coordsys must be SphericalCoordinates.")
        self.coordsystem = self.cs = coordsystem
        self.coord = coordsystem.coords[0]
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.radius = float(radius)
        self.k = int(k)
        self.alpha = float(alpha)
        if np.isscalar(dealias):
            dealias = (dealias,) * 3
        self.dealias = tuple(map(float, dealias))
        self.volume = 4 / 3 * np.pi * radius ** 3
        self.radial_COV = AffineCOV((0.0, 1.0), (0.0, radius))
        Nphi, Ntheta, Nr = self.shape
        self.Nphi, self.Ntheta, self.Nr = Nphi, Ntheta, Nr
        self.Lmax = Ntheta - 1
        self.complex = is_complex_dtype(self.dtype)
        self.sphere_basis = SphereBasis(
            coordsystem.S2coordsys, (Nphi, Ntheta), dtype=dtype,
            radius=radius, dealias=self.dealias[:2],
            azimuth_library=azimuth_library,
            colatitude_library=colatitude_library, ell_separable=True)
        self.azimuth_basis = self.sphere_basis.azimuth_basis
        self.radius_library = radius_library
        self.surface = self.S2_basis(radius)

    def __repr__(self):
        return f"BallBasis({self.shape}, radius={self.radius}, k={self.k})"

    def S2_basis(self, radius=None):
        if radius is None:
            radius = self.radius
        return SphereBasis(
            self.coordsystem.S2coordsys, (self.Nphi, self.Ntheta),
            dtype=self.dtype, radius=radius, dealias=self.dealias[:2],
            ell_separable=True)

    @property
    def meridional_basis(self):
        """See ShellBasis.meridional_basis: aliases the full basis (NCC
        angular structure is detected from data)."""
        return self

    @property
    def radial_basis(self):
        """See ShellBasis.radial_basis: aliases the full basis."""
        return self

    # ------------------------------------------------------------ structure

    @property
    def first_axis(self):
        return self.coordsystem.first_axis

    @property
    def family_key(self):
        return (type(self).__name__, self.shape, self.radius, self.alpha,
                self.dtype)

    @property
    def a_k(self):
        """Absolute Zernike weight parameter."""
        return self.alpha + self.k

    @staticmethod
    def _nmin(ell):
        return int(ell) // 2

    def coeff_size(self, sub_axis):
        return self.shape[sub_axis]

    def sub_grid_size(self, sub_axis, scale):
        return int(np.ceil(scale * self.shape[sub_axis]))

    def sub_separable(self, sub_axis):
        return sub_axis in (0, 1)

    def sub_group_shape(self, sub_axis):
        if sub_axis == 0:
            return 1 if self.complex else 2
        return 1

    def sub_n_groups(self, sub_axis):
        if sub_axis == 0:
            return self.Nphi if self.complex else self.Nphi // 2
        if sub_axis == 1:
            return self.Ntheta
        return 1

    def group_m(self):
        return self.sphere_basis.group_m()

    def clone_with(self, **changes):
        args = dict(coordsystem=self.coordsystem, shape=self.shape,
                    dtype=self.dtype, radius=self.radius, k=self.k,
                    alpha=self.alpha, dealias=self.dealias)
        args.update(changes)
        return BallBasis(**args)

    def derivative_basis(self, order=1):
        return self.clone_with(k=self.k + order)

    # --------------------------------------------------------------- grids

    def radial_grid(self, scale=1.0):
        Ng = self.sub_grid_size(2, scale)
        return self.radius * zernike.grid(3, Ng, self.alpha)

    def global_grids(self, scales=(1, 1, 1)):
        return (self.sphere_basis.azimuth_grid(scales[0]),
                self.sphere_basis.colatitude_grid(scales[1]),
                self.radial_grid(scales[2]))

    # ---------------------------------------------------------- validity

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """(ncomp, gs_az, 1, Nr): regularity validity at (m, ell) plus the
        radial triangular truncation n >= nmin(ell)."""
        rank = spherical_rank(tensorsig, self.cs)
        ncomp = 3 ** rank
        az_axis = self.first_axis
        colat_axis = az_axis + 1
        gs = self.sub_group_shape(0)
        if az_axis not in sep_widths:
            raise NotImplementedError(
                "Ball azimuth must be a pencil (group) axis.")
        ms = self.group_m()
        m = ms[group[az_axis]]
        if colat_axis in sep_widths:
            ells = np.array([group[colat_axis]])
        else:
            # layout-coupled colatitude (theta-dependent NCC)
            ells = np.arange(self.Ntheta)
        n = np.arange(self.Nr)
        mask = np.zeros((ncomp, gs, ells.size, self.Nr), dtype=bool)
        for i, ell in enumerate(ells):
            comp_ok = valid_regularities(int(ell), rank) & (ell >= abs(m))
            n_ok = n >= self._nmin(int(ell))
            mask[:, :, i, :] = (comp_ok[:, None, None]
                                & n_ok[None, None, :])
        if self.complex and group[az_axis] == self.Nphi // 2:
            mask[:] = False  # Nyquist
        if (not self.complex) and rank <= 1:
            # Drop msin slots at ell == 0 for real scalars and vectors
            # (reference: core/basis.py:4301)
            mask[:, 1, ells == 0, :] = False
        return mask

    # ------------------------------------------------- radial matrix stacks
    # (Ntheta, rows, cols) stacks over the ell groups; slot dimensions are
    # right-aligned at nmin(ell).

    def _build_ell_stack(self, build, rows, cols, align_rows=True,
                         align_cols=True):
        out = np.zeros((self.Ntheta, rows, cols))
        for ell in range(self.Ntheta):
            nmin = self._nmin(ell)
            n = self.Nr - nmin
            if n <= 0:
                continue
            mat = build(ell, n)
            if mat.size == 0:
                continue
            r0 = nmin if align_rows else 0
            c0 = nmin if align_cols else 0
            out[ell, r0:r0 + mat.shape[0], c0:c0 + mat.shape[1]] = mat
        return out

    @CachedMethod
    def radial_forward_stack(self, regtotal, scale=1.0):
        """(Ntheta, Nr, Ngr): grid -> aligned Zernike coefficients at
        l = ell + regtotal (reference: core/transforms.py:1451)."""
        Ngr = self.sub_grid_size(2, scale)
        z, w = zernike.quadrature(3, Ngr, self.alpha)
        extra = ((1 - z) / 2) ** self.k if self.k else 1.0

        def build(ell, n):
            l = ell + int(regtotal)
            if l < 0:
                return np.zeros((n, Ngr))
            Q = zernike.polynomials(3, n, self.a_k, l, z)
            Q = Q * w * extra
            dN = l // 2
            Q[max(Ngr - dN, 0):] = 0
            return Q
        return self._build_ell_stack(build, self.Nr, Ngr, align_cols=False)

    @CachedMethod
    def radial_backward_stack(self, regtotal, scale=1.0):
        """(Ntheta, Ngr, Nr): coefficients -> grid values."""
        Ngr = self.sub_grid_size(2, scale)
        z, _ = zernike.quadrature(3, Ngr, self.alpha)

        def build(ell, n):
            l = ell + int(regtotal)
            if l < 0:
                return np.zeros((Ngr, n))
            Q = zernike.polynomials(3, n, self.a_k, l, z)
            dN = l // 2
            Q[max(Ngr - dN, 0):] = 0
            return Q.T
        return self._build_ell_stack(build, Ngr, self.Nr, align_rows=False)

    @CachedMethod
    def dplus_stack(self, regtotal):
        """D+ = d/dr - l/r at l = ell + regtotal, k -> k+1, problem units."""
        def build(ell, n):
            l = ell + int(regtotal)
            if l < 0:
                return np.zeros((n, n))
            M = zernike.ladder_matrix(3, n, self.a_k, l, l + 1, l, +1)
            return np.sqrt(2) * M / self.radius
        return self._build_ell_stack(build, self.Nr, self.Nr)

    @CachedMethod
    def dminus_stack(self, regtotal):
        """D- = d/dr + (l+1)/r at l = ell + regtotal, k -> k+1."""
        def build(ell, n):
            l = ell + int(regtotal)
            if l < 1:
                # l = 0: D- output degree -1 does not exist
                return np.zeros((n, n))
            M = zernike.ladder_matrix(3, n, self.a_k, l, l - 1, -(l + 1), +1)
            return np.sqrt(2) * M / self.radius
        return self._build_ell_stack(build, self.Nr, self.Nr)

    @CachedMethod
    def laplacian_reg_stack(self, regtotal):
        """L = D-(l+1) @ D+(l), k -> k+2."""
        up = self.dplus_stack(regtotal)
        k1 = self.clone_with(k=self.k + 1)

        def build_down(ell, n):
            l = ell + int(regtotal)
            if l < 0:
                return np.zeros((n, n))
            M = zernike.ladder_matrix(3, n, k1.a_k, l + 1, l, -(l + 2), +1)
            return np.sqrt(2) * M / self.radius
        down = self._build_ell_stack(build_down, self.Nr, self.Nr)
        return np.einsum("gij,gjk->gik", down, up)

    @CachedMethod
    def interp_stack(self, regtotal, position):
        """(Ntheta, 1, Nr): evaluate regtotal components at problem radius
        `position`."""
        r0 = self.radial_COV.native_coord(position)

        def build(ell, n):
            l = ell + int(regtotal)
            if l < 0:
                return np.zeros((1, n))
            return zernike.interpolation_row(3, n, self.a_k, l, r0)
        return self._build_ell_stack(build, 1, self.Nr, align_rows=False)

    def lift_column(self, index):
        col = np.zeros((self.Nr, 1))
        col[index, 0] = 1.0
        return col

    @property
    def constant_angular_mode_value(self):
        return float(swsh.harmonics(self.Lmax, 0, 0, np.array([0.5]))[0, 0])

    @CachedMethod
    def radial_integration_row(self, power=2):
        """(1, Nr): integral against r^power dr for the (m=0, ell=0,
        regtotal=0) group, in problem units. Gauss-Jacobi with the r^(power-1)
        envelope folded into the weight, exact for any power > 0."""
        if power == 2:
            row = zernike.integration_row(3, self.Nr, self.a_k, 0)
        else:
            # int_0^1 Q_n(r) r^p dr = (1/4) int Q_n(z) ((1+z)/2)^((p-1)/2) dz
            b_env = (power - 1) / 2
            Nq = self.Nr + self.k + 4
            z = jacobi_tools.build_grid(Nq, 0, b_env)
            w = jacobi_tools.build_weights(Nq, 0, b_env)
            Q = zernike.polynomials(3, self.Nr, self.a_k, 0, z)
            row = ((Q * w) @ np.ones(Nq))[None, :] / 4
        return row * self.radius ** (power + 1)

    def radial_constant_column(self):
        """(Nr, 1): level-k coefficients of the constant 1 at l = 0."""
        Ngr = self.Nr + self.k + 2
        z, w = zernike.quadrature(3, Ngr, self.alpha)
        extra = ((1 - z) / 2) ** self.k if self.k else 1.0
        Q = zernike.polynomials(3, self.Nr, self.a_k, 0, z)
        col = (Q * w * extra) @ np.ones(Ngr)
        return col[:, None]

    def constant_component_descr(self, sub_axis, device):
        if sub_axis == 0:
            if device:
                col = np.zeros((self.Nphi, 1))
                col[0, 0] = 1.0
                return ("full", col)
            return ("blocks", self.azimuth_basis.constant_blocks())
        if sub_axis == 1:
            Y00 = self.constant_angular_mode_value
            col = np.zeros((self.Ntheta, 1))
            col[0, 0] = 1.0 / Y00
            if device:
                return ("full", col)
            blocks = np.zeros((self.Ntheta, 1, 1))
            blocks[0, 0, 0] = 1.0 / Y00
            return ("blocks", blocks)
        return ("full", self.radial_constant_column())

    # ----------------------------------------------------------- transforms

    def forward_transform(self, gdata, axis, scale, library=None,
                          tensorsig=(), sub_axis=0):
        if sub_axis in (0, 1):
            return self.sphere_basis.forward_transform(
                gdata, axis, scale, library, tensorsig=tensorsig,
                sub_axis=sub_axis)
        tdim = len(tensorsig)
        rank = spherical_rank(tensorsig, self.cs)
        out = gdata
        if rank:
            stack = q_stack(self.Ntheta, rank)
            out = apply_regularity_recombination(out, tdim, axis - 1, stack,
                                                 forward=True)
        return self._radial_reg_apply(out, tdim, axis, rank, scale,
                                      forward=True)

    def backward_transform(self, cdata, axis, scale, library=None,
                           tensorsig=(), sub_axis=0):
        if sub_axis in (0, 1):
            return self.sphere_basis.backward_transform(
                cdata, axis, scale, library, tensorsig=tensorsig,
                sub_axis=sub_axis)
        tdim = len(tensorsig)
        rank = spherical_rank(tensorsig, self.cs)
        out = self._radial_reg_apply(cdata, tdim, axis, rank, scale,
                                     forward=False)
        if rank:
            stack = q_stack(self.Ntheta, rank)
            out = apply_regularity_recombination(out, tdim, axis - 1, stack,
                                                 forward=False)
        return out

    def _radial_reg_apply(self, data, tdim, r_axis, rank, scale, forward):
        """Apply per-regtotal radial stacks, batched over the ell axis
        (group axis = colatitude, width 1)."""
        from .curvilinear import apply_group_stack
        totals = reg_totals(rank)
        ncomp = 3 ** rank
        tshape = data.shape[:tdim]
        flat = data.reshape((ncomp,) + data.shape[tdim:])
        colat_axis = r_axis - 1
        pieces = [None] * ncomp
        for R in np.unique(totals):
            if forward:
                stack = self.radial_forward_stack(int(R), scale)
            else:
                stack = self.radial_backward_stack(int(R), scale)
            idx = np.flatnonzero(totals == R)
            sub = flat[idx]
            sub = apply_group_stack(sub, stack, 1 + colat_axis - tdim,
                                    1 + r_axis - tdim, 1)
            for j, i in enumerate(idx):
                pieces[i] = sub[j]
        out = jnp.stack(pieces, axis=0) if ncomp > 1 else pieces[0][None]
        return out.reshape(tshape + out.shape[1:])

    # ---------------------------------------------------- conversion terms

    def conversion_terms(self, target, tensorsig, tshape):
        """k -> k+dk conversion: per-(ell, regtotal) Zernike connection
        stacks (reference: core/basis.py:4057 conversion_matrix)."""
        if not isinstance(target, BallBasis) or target.shape != self.shape \
                or target.radius != self.radius:
            raise ValueError(f"No conversion from {self} to {target}.")
        dk = target.k - self.k
        if dk == 0:
            return [(None, {})]
        if dk < 0:
            raise ValueError("Cannot convert to lower k.")
        rank = spherical_rank(tensorsig, self.cs)
        totals = reg_totals(rank)
        ncomp = 3 ** rank
        colat = self.first_axis + 1
        r_axis = self.first_axis + 2
        terms = []
        for R in np.unique(totals):
            sel = np.diag((totals == R).astype(float)) if ncomp > 1 else None
            stack = self.conversion_reg_stack(int(R), int(dk))
            terms.append((sel, {r_axis: ("gblocks", colat, stack)}))
        return terms

    @CachedMethod
    def conversion_reg_stack(self, regtotal, dk):
        def build(ell, n):
            l = ell + int(regtotal)
            if l < 0:
                return np.zeros((n, n))
            M = np.eye(n)
            for dki in range(dk):
                M = zernike.conversion_matrix(3, n, self.a_k + dki, l) @ M
            return M
        return self._build_ell_stack(build, self.Nr, self.Nr)

    # ------------------------------------------------------- NCC products

    def scalar_radial_coeffs(self, profile_grid_values, l_env=0):
        """Project a radial profile (on the scale-1 grid) onto Zernike
        coefficients at envelope degree l_env (the all-radial component of a
        rank-r NCC carries an r^r envelope, so odd profiles like r*er stay
        exact; reference: core/basis.py:4110 b_ncc = regtotal + 1/2)."""
        profile = np.asarray(profile_grid_values, dtype=np.float64)
        Ngr = profile.shape[-1]
        z, w = zernike.quadrature(3, Ngr, self.alpha)
        extra = ((1 - z) / 2) ** self.k if self.k else 1.0
        Q = zernike.polynomials(3, self.Nr, self.a_k, l_env, z)
        return (Q * (w * extra)) @ profile

    def ncc_radial_matrix(self, f_radial_coeffs, f_k, R_in, R_out, ell,
                          k_out=0, l_env=0):
        """(Nr, Nr): per-(ell, regularity) multiplication by the radial NCC
        with level-f_k l=0 coefficients, mapping regtotal R_in components at
        harmonic ell to R_out components at level k_out
        (reference: core/basis.py:4101 _last_axis_component_ncc_matrix)."""
        nmin = self._nmin(ell)
        n = self.Nr - nmin
        l_in = ell + int(R_in)
        l_out = ell + int(R_out)
        if n <= 0 or l_in < 0 or l_out < 0:
            return np.zeros((self.Nr, self.Nr))
        f_coeffs = np.asarray(f_radial_coeffs, dtype=np.float64)
        Nf = f_coeffs.shape[-1]
        a_f = self.alpha + f_k

        def values(z):
            fvals = f_coeffs @ zernike.polynomials(3, Nf, a_f, l_env, z)
            return fvals * zernike.polynomials(3, n, self.a_k, l_in, z)

        M = zernike._project(3, n, self.alpha + k_out, l_out, values, n,
                             extra=Nf + 16)
        out = np.zeros((self.Nr, self.Nr))
        out[nmin:, nmin:] = M
        return out

    def ncc_radial_pair_matrix(self, f_radial_coeffs, f_k, f_lenv, t_in,
                               t_out, ell_in, ell_out, k_out=0):
        """
        (Nr, Nr): multiplication by one angular mode's radial profile
        (Zernike coefficients `f_radial_coeffs` at envelope degree
        `f_lenv`, level k of this basis), mapping regtotal-`t_in`
        components at harmonic `ell_in` to regtotal-`t_out` components at
        harmonic `ell_out`, level `k_out`. The ell-COUPLED generalization
        of `ncc_radial_matrix` needed by theta-dependent NCC products
        (reference: the l-coupled Zernike Clenshaw couplings of
        core/basis.py:4101 + core/arithmetic.py:359-406).
        """
        nmin_in = self._nmin(int(ell_in))
        nmin_out = self._nmin(int(ell_out))
        n_in = self.Nr - nmin_in
        n_out = self.Nr - nmin_out
        l_in = int(ell_in) + int(t_in)
        l_out = int(ell_out) + int(t_out)
        if n_in <= 0 or n_out <= 0 or l_in < 0 or l_out < 0:
            return np.zeros((self.Nr, self.Nr))
        f_coeffs = np.asarray(f_radial_coeffs)
        if not np.iscomplexobj(f_coeffs):
            f_coeffs = f_coeffs.astype(np.float64)
        Nf = f_coeffs.shape[-1]

        def values(z):
            fvals = f_coeffs @ zernike.polynomials(3, Nf, self.alpha + f_k,
                                                   int(f_lenv), z)
            return fvals * zernike.polynomials(3, n_in, self.a_k, l_in, z)

        M = zernike._project(3, n_out, self.alpha + k_out, l_out, values,
                             n_in, extra=Nf + self.Nr + 16)
        out = np.zeros((self.Nr, self.Nr), dtype=M.dtype)
        out[nmin_out:, nmin_in:] = M
        return out


# ----------------------------------------------------------------------
# Spherical calculus operators (regularity components, ell-diagonal)

from .operators import LinearOperator  # noqa: E402 (cycle-safe)
from .future import ev  # noqa: E402


class SphericalEllOperator(LinearOperator):
    """Base for ell-diagonal spherical operators over shell/ball bases
    (reference: core/operators.py:3078 SphericalEllOperator)."""

    def _basis(self, operand=None):
        operand = operand or self.operand
        for b in operand.domain.bases:
            if getattr(b, "regularity", False):
                return b
        raise ValueError("Operand has no 3D spherical basis.")

    def _axes(self, basis):
        first = basis.first_axis
        return first, first + 1, first + 2


class SphericalGradient(SphericalEllOperator):
    """Gradient: prepends a regularity index; each input component maps to
    the '-' and '+' branches through xi-weighted ladders
    (reference: core/operators.py:3210 SphericalGradient)."""

    name = "Grad"

    def __init__(self, operand, cs):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalGradient(new_args[0], self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(1))
        self.tensorsig = (self.cs,) + tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        rank = spherical_rank(operand.tensorsig, basis.cs)
        ncomp = 3 ** rank
        totals = reg_totals(rank)
        dim = operand.domain.dim
        ell = np.arange(basis.Ntheta)
        terms = []
        for sigma_idx, sign in ((0, -1), (1, +1)):
            for R in np.unique(totals):
                sel = np.zeros((3 * ncomp, ncomp))
                for j in np.flatnonzero(totals == R):
                    sel[sigma_idx * ncomp + j, j] = 1.0
                l = ell + int(R)
                if sign == -1:
                    stack = basis.dminus_stack(int(R)) \
                        * xi(-1, l)[:, None, None]
                else:
                    stack = basis.dplus_stack(int(R)) \
                        * xi(+1, l)[:, None, None]
                descrs = [None] * dim
                descrs[rad] = ("gblocks", colat, stack)
                terms.append((sel, descrs))
        return terms


class SphericalDivergence(SphericalEllOperator):
    """Divergence: contracts the leading regularity index; only the '-' and
    '+' branches contribute (reference: core/operators.py:3516)."""

    name = "Div"

    def __init__(self, operand, index=0):
        if index != 0:
            raise NotImplementedError("Divergence only supports index=0.")
        self.cs = operand.tensorsig[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalDivergence(new_args[0])

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(1))
        self.tensorsig = tuple(operand.tensorsig[1:])
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        rank_rest = spherical_rank(operand.tensorsig[1:], basis.cs)
        nrest = 3 ** rank_rest
        rest_totals = reg_totals(rank_rest)
        dim = operand.domain.dim
        ell = np.arange(basis.Ntheta)
        terms = []
        for a_idx, a_reg in ((0, -1), (1, +1)):
            for Rb in np.unique(rest_totals):
                regtotal_in = int(Rb + a_reg)
                sel = np.zeros((nrest, 3 * nrest))
                for j in np.flatnonzero(rest_totals == Rb):
                    sel[j, a_idx * nrest + j] = 1.0
                l = ell + regtotal_in
                if a_reg == -1:
                    stack = basis.dplus_stack(regtotal_in) \
                        * xi(-1, l + 1)[:, None, None]
                else:
                    stack = basis.dminus_stack(regtotal_in) \
                        * xi(+1, l - 1)[:, None, None]
                descrs = [None] * dim
                descrs[rad] = ("gblocks", colat, stack)
                terms.append((sel, descrs))
        return terms


class SphericalCurl(SphericalEllOperator):
    """Curl on the leading index (reference: core/operators.py:3808)."""

    name = "Curl"

    def __init__(self, operand, index=0):
        if index != 0:
            raise NotImplementedError("Curl only supports index=0.")
        self.cs = operand.tensorsig[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalCurl(new_args[0])

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(1))
        self.tensorsig = (self.cs,) + tuple(operand.tensorsig[1:])
        self.dtype = operand.dtype

    def terms(self):
        from .polar import _expand_complex_terms
        operand = self.operand
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        rank_rest = spherical_rank(operand.tensorsig[1:], basis.cs)
        nrest = 3 ** rank_rest
        rest_totals = reg_totals(rank_rest)
        dim = operand.domain.dim
        ell = np.arange(basis.Ntheta)
        raw = []
        # (in regindex0, out regindex0, factor sign, ladder, xi args)
        # reference: core/operators.py:3855 SphericalCurl._radial_matrix
        for Rb in np.unique(rest_totals):
            comps = np.flatnonzero(rest_totals == Rb)

            def add(in_idx, out_idx, coeff, stack):
                sel = np.zeros((3 * nrest, 3 * nrest), dtype=complex)
                for j in comps:
                    sel[out_idx * nrest + j, in_idx * nrest + j] = coeff
                descrs = [None] * dim
                descrs[rad] = ("gblocks", colat, stack)
                raw.append((sel, descrs))

            t_m = int(Rb - 1)  # regtotal of ('-',) + b
            l = ell + t_m
            add(0, 2, -1j, basis.dplus_stack(t_m) * xi(+1, l + 1)[:, None, None])
            t_p = int(Rb + 1)
            l = ell + t_p
            add(1, 2, +1j, basis.dminus_stack(t_p) * xi(-1, l - 1)[:, None, None])
            t_0 = int(Rb)
            l = ell + t_0
            add(2, 0, -1j, basis.dminus_stack(t_0) * xi(+1, l)[:, None, None])
            add(2, 1, +1j, basis.dplus_stack(t_0) * xi(-1, l)[:, None, None])
        return _expand_complex_terms(raw, az, basis.sub_n_groups(0),
                                     basis.complex)


class SphericalLaplacian(SphericalEllOperator):
    """Laplacian: diagonal over regularity components
    (reference: core/operators.py:4073)."""

    name = "Lap"

    def __init__(self, operand, cs=None):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalLaplacian(new_args[0], self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(2))
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        rank = spherical_rank(operand.tensorsig, basis.cs)
        ncomp = 3 ** rank
        totals = reg_totals(rank)
        dim = operand.domain.dim
        terms = []
        for R in np.unique(totals):
            sel = np.diag((totals == R).astype(float)) if ncomp > 1 else None
            descrs = [None] * dim
            descrs[rad] = ("gblocks", colat, basis.laplacian_reg_stack(int(R)))
            terms.append((sel, descrs))
        return terms


class SphericalTrace(SphericalEllOperator):
    """Trace of the two leading indices in regularity components: the
    spin-frame metric row pulled through Q(ell) x Q(ell)
    (reference: core/operators.py:1756 SphericalTrace)."""

    name = "Trace"
    natural_layout = "g"

    def _build_metadata(self):
        operand = self.args[0]
        if len(operand.tensorsig) < 2:
            raise ValueError("Trace requires two tensor indices.")
        self.cs = operand.tensorsig[0]
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig[2:])
        self.dtype = operand.dtype

    @staticmethod
    @cached_function
    def _trace_rows(Ntheta):
        """(Ntheta, 9): trace functional on rank-2 regularity components:
        the spin metric row through the (coupled, non-kron) rank-2
        intertwiner."""
        t_spin = np.zeros(9)
        t_spin[1] = 1.0  # (-,+)
        t_spin[3] = 1.0  # (+,-)
        t_spin[8] = 1.0  # (0,0)
        Q2 = q_stack(Ntheta, 2)
        return np.stack([t_spin @ Q2[l] for l in range(Ntheta)])

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        rank_rest = len(operand.tensorsig) - 2
        nrest = 3 ** rank_rest
        dim = operand.domain.dim
        rows = self._trace_rows(basis.Ntheta)  # (L, 9)
        terms = []
        for j in range(9):
            if not np.any(rows[:, j]):
                continue
            row = np.zeros((1, 9))
            row[0, j] = 1.0
            factor = np.kron(row, np.identity(nrest))
            blocks = rows[:, j].reshape(-1, 1, 1)
            descrs = [None] * dim
            descrs[colat] = ("blocks", blocks)
            terms.append((factor, descrs))
        return terms

    def ev_impl(self, ctx):
        # Grid-space trace: coordinate components contract with delta.
        data = ev(self.operand, ctx, "g")
        return jnp.einsum("ii...->...", data)


class SphericalTransposeComponents(LinearOperator):
    """
    Index transpose for tensors on shell/ball (regularity-component)
    bases. The regularity intertwiner Q(ell) is NOT a kron over tensor
    indices, so a plain component permutation is wrong; the transpose in
    coefficient space is the per-ell sandwich Q(ell)^T P_swap Q(ell)
    with P_swap the index swap in the (kron-structured) spin frame
    (reference: core/operators.py:1870 TransposeComponents with
    radial_basis intertwiners). Entry-decomposed into one-hot tensor
    factors with per-ell colatitude blocks, like SphericalLift.
    """

    name = "TransposeComponents"
    natural_layout = "g"

    def __init__(self, operand, indices=(0, 1)):
        self.indices = indices
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalTransposeComponents(new_args[0], self.indices)

    def _basis(self, operand):
        for b in operand.domain.bases:
            if getattr(b, "regularity", False):
                return b
        raise ValueError("Operand has no 3D spherical basis.")

    def _build_metadata(self):
        operand = self.args[0]
        i, j = self.indices
        ts = list(operand.tensorsig)
        ts[i], ts[j] = ts[j], ts[i]
        self.domain = operand.domain
        self.tensorsig = tuple(ts)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az = basis.first_axis
        colat = az + 1
        rank = spherical_rank(operand.tensorsig, basis.cs)
        ncomp = 3 ** rank
        tshape = operand.tshape
        perm = np.arange(ncomp).reshape(tshape)
        perm = np.swapaxes(perm, *self.indices).ravel()
        P = np.zeros((ncomp, ncomp))
        P[np.arange(ncomp), perm] = 1.0
        Q = q_stack(basis.Ntheta, rank)          # (Ntheta, spin, reg)
        M = np.einsum("lsi,st,ltj->lij", Q, P, Q)  # Q^T P Q per ell
        dim = operand.domain.dim
        terms = []
        for i in range(ncomp):
            for j in range(ncomp):
                col = M[:, i, j]
                if not np.any(np.abs(col) > 1e-14):
                    continue
                factor = np.zeros((ncomp, ncomp))
                factor[i, j] = 1.0
                descrs = [None] * dim
                descrs[colat] = ("blocks", col.reshape(-1, 1, 1))
                terms.append((factor, descrs))
        return terms

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "g")
        i, j = self.indices
        return jnp.swapaxes(data, i, j)


class SphericalSpinTrace(LinearOperator):
    """Trace of rank-2 spherical-signature tensors on S2 (boundary) bases,
    where components are stored in the 3D spin frame: the spin metric
    contracts (-,+), (+,-), and (0,0) with constant coefficients."""

    name = "Trace"
    natural_layout = "g"

    def _build_metadata(self):
        operand = self.args[0]
        if len(operand.tensorsig) < 2:
            raise ValueError("Trace requires two tensor indices.")
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig[2:])
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        rest = int(np.prod(operand.tshape[2:], dtype=int)) \
            if operand.tshape[2:] else 1
        row = np.zeros((1, 9))
        row[0, 1] = 1.0  # (-,+)
        row[0, 3] = 1.0  # (+,-)
        row[0, 8] = 1.0  # (0,0)
        factor = np.kron(row, np.identity(rest))
        return [(factor, [None] * operand.domain.dim)]

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "g")
        return jnp.einsum("ii...->...", data)


class SphericalInterpolate(SphericalEllOperator):
    """Radial interpolation onto a bounding sphere: regularity -> spin
    recombination Q(ell) folded into per-ell blocks
    (reference: core/operators.py:1037 Interpolate + RegularityBasis
    recombination)."""

    name = "interp"

    def __init__(self, operand, position):
        self.position = position
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalInterpolate(new_args[0], self.position)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        sphere = basis.S2_basis(self.position)
        bases = list(operand.domain.bases)
        bases[az] = sphere
        bases[colat] = sphere
        bases[rad] = None
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        rank = spherical_rank(operand.tensorsig, basis.cs)
        ncomp = 3 ** rank
        totals = reg_totals(rank)
        dim = operand.domain.dim
        Q = q_stack(basis.Ntheta, rank)  # (L, ncomp, ncomp) reg->spin
        terms = []
        for i in range(ncomp):
            for j in range(ncomp):
                if not np.any(Q[:, i, j]):
                    continue
                factor = np.zeros((ncomp, ncomp))
                factor[i, j] = 1.0
                # fold the per-ell Q scalar into the per-ell radial rows
                rows = basis.interp_stack(int(totals[j]), self.position)
                stack = Q[:, i, j, None, None] * rows
                descrs = [None] * dim
                descrs[rad] = ("gblocks", colat, stack)
                terms.append((factor if ncomp > 1 else None, descrs))
        return terms


class SphericalLift(SphericalEllOperator):
    """Lift a sphere (S2) tau field into the shell via radial mode `n`:
    spin -> regularity recombination Q(ell)^T folded into per-ell blocks
    (reference: core/operators.py:4228 Lift)."""

    name = "Lift"

    def __init__(self, operand, basis, n):
        self.basis = basis
        self.n = n
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalLift(new_args[0], self.basis, self.n)

    def _basis(self, operand=None):
        return self.basis

    def _build_metadata(self):
        operand = self.args[0]
        basis = self.basis
        az, colat, rad = self._axes(basis)
        if operand.domain.bases[rad] is not None:
            raise ValueError("Lift operand must be constant along the radius.")
        bases = list(operand.domain.bases)
        bases[az] = basis
        bases[colat] = basis
        bases[rad] = basis
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        basis = self.basis
        az, colat, rad = self._axes(basis)
        rank = spherical_rank(self.operand.tensorsig, basis.cs)
        ncomp = 3 ** rank
        dim = self.operand.domain.dim
        index = self.n if self.n >= 0 else basis.Nr + self.n
        col = basis.lift_column(index)
        Q = q_stack(basis.Ntheta, rank)
        terms = []
        for i in range(ncomp):      # output regularity component
            for j in range(ncomp):  # input spin component
                if not np.any(Q[:, j, i]):
                    continue
                factor = np.zeros((ncomp, ncomp))
                factor[i, j] = 1.0
                blocks = Q[:, j, i].reshape(-1, 1, 1)
                descrs = [None] * dim
                descrs[colat] = ("blocks", blocks)
                descrs[rad] = ("full", col)
                terms.append((factor if ncomp > 1 else None, descrs))
        return terms


class SphericalIntegrate(SphericalEllOperator):
    """Integral of a scalar over the shell volume
    (reference: core/operators.py:1120 Integrate)."""

    name = "integ"

    def _build_metadata(self):
        operand = self.args[0]
        if operand.tensorsig:
            raise NotImplementedError("Shell integration of tensors not supported.")
        basis = self._basis(operand)
        az, colat, rad = self._axes(basis)
        bases = list(operand.domain.bases)
        bases[az] = bases[colat] = bases[rad] = None
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = ()
        self.dtype = operand.dtype

    @CachedMethod
    def _colat_row(self):
        basis = self._basis(self.operand)
        z, w = swsh.quadrature(basis.Lmax)
        Y = swsh.harmonics(basis.Lmax, 0, 0, z)
        return Y @ w  # (Ntheta,)

    def terms(self):
        basis = self._basis(self.operand)
        az, colat, rad = self._axes(basis)
        dim = self.operand.domain.dim
        G = basis.sub_n_groups(0)
        gs = basis.sub_group_shape(0)
        az_blocks = np.zeros((G, gs, gs))
        az_blocks[0, 0, 0] = 2 * np.pi
        col_row = self._colat_row()
        col_blocks = col_row.reshape(-1, 1, 1)
        descrs = [None] * dim
        descrs[az] = ("blocks", az_blocks)
        descrs[colat] = ("blocks", col_blocks)
        descrs[rad] = ("full", basis.radial_integration_row(power=2))
        return [(None, descrs)]

    def device_terms(self):
        basis = self._basis(self.operand)
        az, colat, rad = self._axes(basis)
        dim = self.operand.domain.dim
        row_az = np.zeros((1, basis.Nphi))
        row_az[0, 0] = 2 * np.pi
        descrs = [None] * dim
        descrs[az] = ("full", row_az)
        descrs[colat] = ("full", self._colat_row()[None, :])
        descrs[rad] = ("full", basis.radial_integration_row(power=2))
        return [(None, descrs)]


class SphericalComponent(LinearOperator):
    """
    Radial/angular component extraction on sphere-basis (S2 boundary)
    fields, where spin storage makes the selection a constant matrix in both
    layouts (reference: core/operators.py:2160-2283 RadialComponent/
    AngularComponent). Interior shell/ball fields store regularity
    components, so LHS extraction there is not a constant selection; use it
    on boundary fields or on the RHS.
    """

    name = "Comp"

    def __init__(self, operand, which, index=0):
        self.which = which  # 'radial' | 'angular'
        self.index = index
        super().__init__(operand)

    def rebuild(self, new_args):
        return SphericalComponent(new_args[0], self.which, self.index)

    def _build_metadata(self):
        operand = self.args[0]
        cs = operand.tensorsig[self.index]
        if not isinstance(cs, SphericalCoordinates):
            raise ValueError("Component extraction needs a spherical index.")
        for b in operand.domain.bases:
            if getattr(b, "regularity", False):
                raise ValueError(
                    "Radial/angular extraction has no constant coefficient "
                    "matrix on shell/ball interiors (regularity storage); "
                    "apply it to boundary (S2) fields or on the RHS.")
        self.cs = cs
        self.domain = operand.domain
        ts = list(operand.tensorsig)
        if self.which in ("radial", "azimuthal"):
            ts.pop(self.index)
        else:
            ts[self.index] = cs.S2coordsys
        self.tensorsig = tuple(ts)
        self.dtype = operand.dtype

    def _factor(self):
        before = int(np.prod([c.dim for c in self.operand.tensorsig[:self.index]],
                             dtype=int)) if self.index else 1
        after_sig = self.operand.tensorsig[self.index + 1:]
        after = int(np.prod([c.dim for c in after_sig], dtype=int)) \
            if after_sig else 1
        if self.which == "radial":
            row = np.array([[0.0, 0.0, 1.0]])  # spin/coordinate index 2
        else:
            row = np.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]])
        return np.kron(np.kron(np.identity(before), row), np.identity(after))

    def terms(self):
        if self.which == "azimuthal":
            # u_phi alone is not a smooth spin-weighted scalar: spin-(+-1)
            # SWSH coefficients cannot map to scalar SWSH coefficients with
            # a constant matrix. Grid-space (RHS) use only.
            raise ValueError(
                "Azimuthal extraction on spherical fields has no "
                "coefficient-space matrix; use angular()/radial() in "
                "boundary conditions, or azimuthal() on the RHS.")
        dim = self.operand.domain.dim
        return [(self._factor(), [None] * dim)]

    def ev_impl(self, ctx):
        if self.which == "azimuthal":
            # NOTE: u_phi of a smooth vector is not a smooth scalar on S2;
            # storing the result in a scalar field projects it onto scalar
            # SWSH with only algebraic convergence. Pointwise use only.
            data = ev(self.operand, ctx, "g")
            index = [slice(None)] * self.index + [0]
            return data[tuple(index)]
        return super().ev_impl(ctx)

    @property
    def natural_layout(self):
        return "g" if self.which == "azimuthal" else "c"


# ----------------------------------------------------------------------
# Factory wiring helpers (used by core.operators dispatchers)

def spherical_basis_of(operand):
    for b in operand.domain.bases:
        if b is not None and getattr(b, "regularity", False):
            return b
    return None
