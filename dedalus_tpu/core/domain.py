"""
Domains: cached direct products of bases (reference: dedalus/core/domain.py:17).

A Domain is a tuple of bases indexed by distributor axis, with `None` marking
axes along which fields are constant (size-1 in both layouts).
"""

import numpy as np

from ..tools.cache import CachedClass


class Domain(metaclass=CachedClass):

    def __init__(self, dist, bases):
        bases = tuple(bases)
        if len(bases) != dist.dim:
            raise ValueError("Domain needs one basis (or None) per distributor axis.")
        self.dist = dist
        self.bases = bases

    @property
    def full_bases(self):
        return self.bases

    def get_basis(self, coord):
        for basis in self.bases:
            if basis is None:
                continue
            if basis.coord is coord:
                return basis
            if getattr(coord, "coords", None) and basis.coord in coord.coords:
                return basis
            cs = getattr(basis, "coordsystem", None)
            if cs is not None and (coord is cs or coord in cs.coords):
                return basis
        return None

    @property
    def constant(self):
        return tuple(b is None for b in self.bases)

    @property
    def dim(self):
        return self.dist.dim

    @property
    def coeff_shape(self):
        return tuple(1 if b is None else b.coeff_size(axis - b.first_axis)
                     for axis, b in enumerate(self.bases))

    def grid_shape(self, scales):
        scales = self.dist.remedy_scales(scales)
        return tuple(1 if b is None else b.sub_grid_size(axis - b.first_axis, s)
                     for axis, (b, s) in enumerate(zip(self.bases, scales)))

    @property
    def dealias(self):
        out = []
        for axis, b in enumerate(self.bases):
            if b is None:
                out.append(1.0)
            elif isinstance(b.dealias, tuple):
                out.append(b.dealias[axis - b.first_axis])
            else:
                out.append(b.dealias)
        return tuple(out)

    @property
    def coeff_dtype_is_complex(self):
        from .basis import ComplexFourier
        return any(isinstance(b, ComplexFourier) for b in self.bases)

    def substitute_basis(self, old_basis, new_basis):
        bases = tuple(new_basis if b is old_basis else b for b in self.bases)
        return Domain(self.dist, bases)

    def __repr__(self):
        return f"Domain({self.bases})"
