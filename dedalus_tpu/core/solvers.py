"""
Solvers (reference: dedalus/core/solvers.py).

  InitialValueSolver        — IMEX timestepping, one jitted device step
  LinearBoundaryValueSolver — batched pencil solve of L.X = F
  NonlinearBoundaryValueSolver — Newton-Kantorovich iteration
  EigenvalueSolver          — dense/sparse generalized eigensolves per pencil

TPU-native design: the solver holds the state as ONE device array X of shape
(G, S) (all pencils batched); fields are synchronized at step boundaries so
user code sees reference-like Field semantics while the hot loop stays on
device (reference hot loop anatomy: core/solvers.py:683-711 + SURVEY.md §3.2).
"""

import os
import pathlib
import time as time_mod
import logging
import numpy as np
import scipy.linalg
import jax
import jax.numpy as jnp

from .subsystems import (PencilLayout, build_subproblems, build_matrices,
                         assemble_group_coos, MatrixStructure,
                         build_banded_arrays, gather_state, scatter_state,
                         row_valid_masks, merge_conditional_equations,
                         active_member, state_key)
from .future import EvalContext, ev
from . import timesteppers as timesteppers_mod
from ..libraries import pencilops
from ..tools import assembly_cache
from ..tools import health as health_mod
from ..tools import metrics as metrics_mod
from ..tools import retrace as retrace_mod
from ..tools.config import config
from ..tools.general import is_complex_dtype

logger = logging.getLogger(__name__)


class SolverBase:
    """Shared setup: pencil layout, subproblems, device matrices
    (reference: core/solvers.py:31 SolverBase)."""

    matrices = ("L",)
    lazy_ok = False   # EVP: per-group on-demand assembly at large sizes
    cache_ok = True   # NLBVP: Jacobian rebuilds churn the persistent cache

    def __init__(self, problem, matsolver=None, ncc_cutoff=None,
                 matrix_coupling=None, **kw):
        self.problem = problem
        self.dist = problem.dist
        self.variables = self.matrix_variables(problem)
        if matsolver is None:
            matsolver = config["linear algebra"].get("MATRIX_SOLVER", "auto")
        self.matsolver = matsolver
        # API-parity kwarg (reference: solvers accept ncc_cutoff for
        # Clenshaw truncation). NCC matrices here are quadrature-built and
        # sparsified at fixed tolerances (arithmetic.NCC_ANGULAR_CUTOFF,
        # sparsify defaults), so the value is accepted but currently
        # unused.
        self.ncc_cutoff = ncc_cutoff
        self.layout = PencilLayout(self.dist, self.variables,
                                   problem.equations,
                                   matrix_coupling=matrix_coupling)
        self.equations = merge_conditional_equations(problem.equations,
                                                     self.dist, self.layout)
        self.subproblems = build_subproblems(self.layout)
        self._lazy = False
        # cold-start accounting: host_assembly/structure/factor/compile
        # wall seconds + assembly-cache verdict (tools/metrics.BuildPhases)
        self.build_phases = metrics_mod.BuildPhases()
        self._build_pencil_system()
        self.valid_row_mask = row_valid_masks(self.layout, self.equations)

    def _build_pencil_system(self):
        """
        Assemble the pencil matrices and pick the device representation:
        dense (G, S, S) for small systems, banded-interior + Schur border
        for large single-coupled-axis systems (reference: ScipyBanded +
        Woodbury, libraries/matsolvers.py:186-194,285-316). Sets
        self._matrices (host arrays), self.ops, self.structure.

        Assembly itself goes through the group-batched kron-term path
        (core/batched_assembly.py) whenever the expression tree supports
        it — O(1) tree walks instead of O(G) — falling back to the
        per-group scipy walk otherwise.
        """
        names = self.matrices
        # consult the empirical autotuner FIRST (tools/autotune.py): a
        # tuned decision — warm from the memo/assembly cache (zero
        # probes) or measured once here under the [autotune] budget —
        # feeds the three plan resolutions below, so the plan is still
        # resolved exactly ONCE per build, BEFORE solver_key seals it
        # into the cache/pool keys. [autotune] itself is validated at
        # every build (bad MODE fails loud even when off); explicit
        # solve knobs disable the tuned path (`plan_source: config`)
        from ..tools import autotune
        atp = autotune.resolve_autotune()
        tuned = autotune.consult(self, atp) \
            if (self.cache_ok and not self.lazy_ok) else None
        # resolve the [fusion] composition ONCE, before anything keys on
        # or compiles under it: solver_key's fusion token, BandedOps'
        # switches, the timestepper's donation contract and the eval plan
        # all read THIS plan, so a config mutation mid-build (tests and
        # benchmarks flip flags in-process) can never split one solver
        # across two compositions
        from . import fusedstep
        self._fusion_plan = fusedstep.resolve_fusion(decision=tuned)
        # resolve the [distributed] transpose chunking ONCE too, for the
        # same reason: the chunk structure shapes every compiled sharded
        # walk, and solver_key/pool_key token it so pooled compiled
        # programs can never alias across chunk configs (a bad config
        # value fails the build here, not mid-trace)
        from ..parallel.transposes import resolve_transpose_chunks
        self._transpose_chunks = resolve_transpose_chunks(decision=tuned)
        # resolve the solve composition + precision ladder ONCE as well
        # ([fusion] SOLVE_COMPOSITION/SPIKE_CHUNKS + the [precision]
        # section, libraries/solvecomp.py): the composition restructures
        # the compiled substitution and the ladder changes the factor
        # store dtype, so both token the assembly/pool keys; a bad
        # config value fails the build here, not mid-trace
        from ..libraries import solvecomp
        self._solve_plan = solvecomp.resolve_solve_plan(decision=tuned)
        # provenance: how THIS build's plan was chosen, stamped into
        # plan_provenance() so every results row names its selector
        if tuned is not None:
            self._plan_source = "tuned"
            self._tuning = tuned.provenance()
        else:
            self._plan_source = ("config"
                                 if solvecomp.solve_knobs_pinned()
                                 else "default")
            self._tuning = None
        G, S = self.pencil_shape
        dense_bytes = G * S * S * np.dtype(self.pencil_dtype).itemsize
        lazy_bytes = int(config["linear algebra"].get(
            "EVP_LAZY_BYTES", str(1 << 28)))
        if self.lazy_ok and dense_bytes > lazy_bytes:
            # EVP at scale (e.g. ell-coupled rotating convection): skip the
            # full (G, S, S) batched store entirely; solve_dense/solve_sparse
            # assemble the requested group on demand, sparse end-to-end
            # (reference: per-subproblem sparse assembly + SuperLU,
            # core/solvers.py:225 solve_sparse)
            logger.info(
                f"EVP pencil system: lazy per-group assembly "
                f"(G={G}, S={S}; dense store would be "
                f"{dense_bytes / 1e9:.2f} GB)")
            self._lazy = True
            self._batched = None
            self._matrices = None
            self.structure = None
            self.ops = None
            return
        # persistent assembly cache (tools/assembly_cache.py): on a hit the
        # symbolic walk, scipy kron folds and banded structural analysis are
        # all skipped — the COO/banded stores load from disk
        cache = assembly_cache.resolve() if self.cache_ok else None
        ckey = None
        if cache is not None:
            ckey = assembly_cache.solver_key(self, names)
        # content identity of this pencil system, stashed for consumers
        # that key on it after the build (the warm-pool service's
        # assembly_cache.pool_key); None when the cache is disabled or
        # the graph is unfingerprintable — pool_key then recomputes
        self.assembly_key = ckey
        if ckey is not None:
            payload = cache.load(ckey)
            if payload is not None:
                try:
                    installed = assembly_cache.install_payload(
                        self, names, payload)
                except Exception as exc:
                    # parseable but internally inconsistent (missing
                    # array, drifted structure state): quarantine and
                    # assemble fresh — same contract as load-time
                    # corruption, which must never abort solver builds
                    installed = False
                    logger.warning(
                        f"assembly cache payload {ckey[:12]} failed to "
                        f"install ({exc!r}); quarantined, assembling fresh")
                    cache.discard(ckey)
                if installed:
                    self.build_phases.cache = "hit"
                    logger.info(
                        f"Pencil system: assembly cache hit "
                        f"({payload['meta']['kind']}, key {ckey[:12]})")
                    return
            self.build_phases.cache = "miss"
        self._assemble_batched(names)
        spec = self.matsolver if isinstance(self.matsolver, str) else ""
        forced = spec.lower() if spec.lower() in ("banded", "dense") else None
        cutoff_bytes = int(config["linear algebra"].get(
            "BANDED_CUTOFF_BYTES", str(1 << 30)))
        # An explicitly named dense matsolver (or solver class) is always
        # honored; only 'auto' lets the size heuristic pick the banded path.
        auto = isinstance(self.matsolver, str) and spec.lower() == "auto"
        try_banded = (forced == "banded"
                      or (auto and dense_bytes > cutoff_bytes))
        self.structure = None
        if try_banded:
            result = self._try_banded(names, S)
            if result is True:
                self._cache_store(cache, ckey, names)
                return
            if forced == "banded":
                raise ValueError("Banded solve forced but not applicable: "
                                 f"{self._banded_reason}")
            msg = (f"Banded path not applicable ({self._banded_reason}); "
                   f"using dense ({dense_bytes / 1e9:.2f} GB)")
            if dense_bytes > 4 * cutoff_bytes:
                # e.g. a Chebyshev x Chebyshev problem (two coupled axes):
                # O(G S^2) memory and O(G S^3) factor work with no banded
                # escape hatch yet — make the scale cost loud (reference
                # handles arbitrary coupled sets with sparse LU,
                # core/subsystems.py:493-598)
                logger.warning(
                    msg + " — this exceeds the banded cutoff 4x; consider "
                    "lowering the coupled-axis resolution or making more "
                    "axes separable (Fourier).")
            else:
                logger.info(msg)
            # reuse the already-assembled COO matrices for the dense fallback
            with self.build_phases.scope("host_assembly"):
                self._matrices = self._densify_coo_store(result, names, S)
        elif self._batched is not None:
            with self.build_phases.scope("host_assembly"):
                self._matrices = self._dense_from_batched(names)
        else:
            with self.build_phases.scope("host_assembly"):
                self._matrices = build_matrices(
                    self.subproblems, self.equations, self.variables,
                    names=names)
        self.ops = pencilops.DenseOps(
            self._dense_matsolver(),
            solve_plan=getattr(self, "_solve_plan", None))
        self._cache_store(cache, ckey, names)

    def _cache_store(self, cache, ckey, names):
        """Persist the freshly built pencil system (miss path only)."""
        if cache is None or ckey is None:
            return
        try:
            exported = assembly_cache.export_payload(self, names)
            if exported is not None:
                cache.store(ckey, *exported)
        except Exception as exc:
            logger.warning(f"assembly cache store failed: {exc!r}")

    def _assemble_batched(self, names):
        """Attempt group-batched assembly; sets self._batched to the shared
        COO pattern result (rows, cols, {name: (G, nnz) vals}, row_valid,
        col_valid) or None when the expression tree requires the per-group
        walk. Runs in PARTIAL mode (per-expression fallback onto the
        shared pattern) so a single unbatchable expression never forces
        the whole system onto the per-group walk."""
        from .batched_assembly import batched_system_coos, BatchUnsupported
        with self.build_phases.scope("host_assembly"):
            # PARTIAL mode directly: with zero per-expression fallbacks it
            # produces the full-mode output, and a system with one
            # unbatchable term late in the tree would otherwise pay full
            # assembly of every preceding expression twice (once in a
            # doomed non-partial pass, again in the retry)
            try:
                self._batched = batched_system_coos(
                    self.layout, self.equations, self.variables, names,
                    subproblems=self.subproblems, partial=True)
            except BatchUnsupported as exc:
                logger.debug(f"Batched assembly unavailable ({exc}); "
                             "using per-group assembly.")
                self._batched = None

    def _dense_from_batched(self, names):
        """Scatter the shared-pattern COO store into dense (G, S, S) arrays
        with the enumeration-order validity closure on the last name."""
        pr, pc, vals, row_valid, col_valid = self._batched
        G, S = self.pencil_shape
        out = {}
        for name in names:
            dense = np.zeros((G, S, S), dtype=vals[name].dtype)
            dense[:, pr, pc] = vals[name]
            out[name] = dense
        last = names[-1]
        for g in range(G):
            inv_rows = np.flatnonzero(~row_valid[g])
            inv_cols = np.flatnonzero(~col_valid[g])
            out[last][g, inv_rows, inv_cols] = 1.0
        return out

    def _densify_coo_store(self, store, names, S):
        """Scatter (coo_store, masks) from a failed banded attempt into the
        dense (G, S, S) arrays, applying the enumeration-order closure the
        dense path uses."""
        coo_store, masks = store
        cplx = any(is_complex_dtype(v.dtype) for v in self.variables)
        dtype = np.complex128 if cplx else np.float64
        G = len(coo_store)
        out = {name: np.zeros((G, S, S), dtype=dtype) for name in names}
        for g, (coos, (row_valid, col_valid)) in enumerate(zip(coo_store, masks)):
            for name in names:
                rows, cols, vals = coos[name]
                out[name][g][rows, cols] = vals
            inv_rows = np.flatnonzero(~row_valid)
            inv_cols = np.flatnonzero(~col_valid)
            out[names[-1]][g][inv_rows, inv_cols] = 1.0
        return out

    def _try_banded(self, names, S):
        """
        Attempt the banded + pinned representation: assemble real
        (pre-closure) entries per group, run the structural analysis, place
        the validity closure on the matched diagonal, and extract banded
        storage. Returns True on success (with self._matrices and self.ops
        set), else (coo_store, masks) for the dense fallback, with
        self._banded_reason set.
        """
        from .subsystems import PatternAccumulator, compute_group_closure
        # Relative drop tolerance for the PATTERN only (band detection /
        # matching); stored matrix values are never filtered, so the banded
        # and dense paths solve the same operator up to sub-tol out-of-band
        # entries dropped at fill time.
        tol = float(config["linear algebra"].get("BAND_DETECT_CUTOFF", "1e-14"))
        equations = self.equations
        coo_store = []
        masks = []
        acc = PatternAccumulator(S)
        scale = 0.0
        if self._batched is not None:
            pr, pc, bvals, row_valid_b, col_valid_b = self._batched
            for g in range(len(self.subproblems)):
                coo_store.append({name: (pr, pc, bvals[name][g])
                                  for name in names})
                masks.append((row_valid_b[g], col_valid_b[g]))
            scale = max((np.abs(bvals[name]).max() if bvals[name].size else 0.0)
                        for name in names)
        else:
            from .subsystems import map_groups
            with self.build_phases.scope("host_assembly"):
                results = map_groups(
                    lambda sp: assemble_group_coos(
                        sp, equations, self.variables, names, closure=False),
                    self.subproblems)
            for coos, row_valid, col_valid in results:
                coo_store.append(coos)
                masks.append((row_valid, col_valid))
                scale = max(scale, max((np.abs(v).max() if len(v) else 0.0
                                        for _, _, v in coos.values()), default=0.0))
        tol_abs = tol * (scale or 1.0)
        # Per-ROW relative significance, scaled to the pencil precision:
        # f32-sourced data breaks exact cancellations at ~eps32-relative
        # levels, leaving junk far below its row's real structure yet
        # above the GLOBAL cutoff when one term (e.g. a Rayleigh-scaled
        # buoyancy) inflates the global scale. Row-relative filtering
        # separates the two cleanly in both precisions.
        eps_p = np.finfo(self.real_dtype).eps
        row_frac = max(tol, 10.0 * eps_p)
        with self.build_phases.scope("structure"):
            for coos, (row_valid, col_valid) in zip(coo_store, masks):
                rowmax = np.zeros(S)
                for r, c, v in coos.values():
                    if len(r):
                        np.maximum.at(rowmax, r, np.abs(v))
                pat = {}
                for k, (r, c, v) in coos.items():
                    # row-significant AND above the global assembly-dirt
                    # floor (dirt-only rows would otherwise self-certify)
                    keep = (np.abs(v) >= row_frac * rowmax[r]) \
                        & (np.abs(v) > tol_abs)
                    pat[k] = (r[keep], c[keep], v[keep])
                acc.add_group(pat, row_valid, col_valid)
            structure = MatrixStructure(self.layout, self.variables,
                                        equations)
            row_valid_all = np.array([m[0] for m in masks])
            col_valid_all = np.array([m[1] for m in masks])
            spec = self.matsolver if isinstance(self.matsolver, str) else ""
            structure.finalize(acc.union, acc.qualified(), row_valid_all,
                               col_valid_all, vmax=acc.vmax,
                               allow_uneconomic=(spec.lower() == "banded"))
            if not structure.ok:
                self._banded_reason = structure.reason
                return (coo_store, masks)
            # validity closure aligned with the matching (passed separately
            # to build_banded_arrays so the shared COO pattern stays shared
            # and the scatter can vectorize over the whole group batch)
            closures = []
            for coos, (row_valid, col_valid) in zip(coo_store, masks):
                closure = compute_group_closure(structure, row_valid,
                                                col_valid)
                if closure is None:
                    self._banded_reason = \
                        "validity closure misaligned with matching"
                    return (coo_store, masks)
                closures.append(closure)
        host_dtype = (np.complex128 if is_complex_dtype(self.pencil_dtype)
                      else np.float64)
        try:
            with self.build_phases.scope("host_assembly"):
                self._matrices = build_banded_arrays(
                    coo_store, structure, names, host_dtype,
                    drop_tol=max(tol_abs, row_frac * (scale or 1.0)),
                    closures=closures)
        except ValueError as exc:
            self._banded_reason = str(exc)
            return (coo_store, masks)
        self.structure = structure
        self.ops = pencilops.BandedOps(
            structure, fusion=getattr(self, "_fusion_plan", None),
            solve_plan=getattr(self, "_solve_plan", None))
        logger.info(
            f"Pencil system: banded path (S={structure.S}, "
            f"pins={structure.t_pins}, kl={structure.kl}, "
            f"ku={structure.ku}, q={structure.q})")
        return True

    def _dense_matsolver(self):
        """Resolve the dense batched matsolver name (config MATRIX_SOLVER)."""
        spec = self.matsolver
        if not isinstance(spec, str) or spec.lower() not in ("auto", "banded", "dense"):
            return spec
        # TPU: triangular solves are sequential (slow); a precomputed
        # batched inverse makes every solve one MXU matmul (~65x faster
        # on v5e). TPU LuDecomposition only implements F32/C64, so
        # 64-bit problems factor in 32-bit + iterative refinement.
        # Elsewhere (CPU/GPU): LU is accurate and fast.
        if jax.default_backend() in ("tpu", "axon"):
            small = all(np.dtype(v.dtype) in (np.dtype(np.float32),
                                              np.dtype(np.complex64))
                        for v in self.variables)
            return "BatchedInverse" if small else "BatchedInverseRefined"
        return "BatchedLUFactorized"

    def matrix_variables(self, problem):
        return problem.variables

    @property
    def pencil_shape(self):
        S = sum(self.layout.slot_size(v.domain, v.tensorsig) for v in self.variables)
        return (self.layout.n_groups, S)

    @property
    def subproblems_by_group(self):
        """Subproblems keyed by their group tuple (reference:
        core/solvers.py SolverBase.subproblems_by_group)."""
        return {sp.group: sp for sp in self.subproblems}

    @property
    def pencil_dtype(self):
        """Device working dtype: 32-bit when every variable is 32-bit."""
        cplx = any(is_complex_dtype(v.dtype) for v in self.variables)
        bits32 = all(np.dtype(v.dtype) in (np.dtype(np.float32), np.dtype(np.complex64))
                     for v in self.variables)
        if cplx:
            return np.dtype(np.complex64) if bits32 else np.dtype(np.complex128)
        return np.dtype(np.float32) if bits32 else np.dtype(np.float64)

    @property
    def real_dtype(self):
        return np.dtype(np.float32) if self.pencil_dtype in (np.dtype(np.float32), np.dtype(np.complex64)) else np.dtype(np.float64)

    @property
    def state(self):
        return self.problem.variables

    # ---------------------------------------------------------------- fields

    def gather_fields(self, fields=None):
        """One jitted program per field set (memoized): eager per-op
        dispatch of the reshape/transpose chain costs ~0.5 s of every cold
        start, while a single traced program is one dispatch AND lands in
        the persistent XLA cache for the next process."""
        fields = fields or self.variables
        arrays = {state_key(v): v.coeff_data() for v in fields}
        key = tuple(state_key(v) for v in fields)
        programs = self.__dict__.setdefault("_gather_programs", {})
        fn = programs.get(key)
        if fn is None:
            from ..tools.jitlift import lifted_jit
            layout = self.layout
            fields = list(fields)
            # memoized in _gather_programs just above (cache-subscript
            # guard the static pass cannot see)
            fn = programs[key] = lifted_jit(  # dedalus-lint: disable=DTL003
                lambda arrs: gather_state(layout, fields, arrs))
        return fn(arrays)

    def scatter_fields(self, X, fields=None):
        """Eager scatter: counts as a mutation so a co-resident IVP solver's
        dirty tracking re-gathers this data."""
        fields = fields or self.variables
        arrays = scatter_state(self.layout, fields, X)
        for v in fields:
            v.preset_coeff(arrays[state_key(v)])
            v.mark_modified()

    def defer_scatter(self, X):
        """
        Install lazy pulls: fields fetch their slice of X only when accessed
        (keeps the no-IO stepping loop free of per-step scatter work).
        """
        cache = {}
        layout, variables = self.layout, self.variables

        def make_pull(var):
            def pull():
                if "arrays" not in cache:
                    cache["arrays"] = scatter_state(layout, variables, X)
                var.preset_coeff(cache["arrays"][state_key(var)])
            return pull

        for v in variables:
            v.install_pull(make_pull(v))

    def snapshot_versions(self):
        self._field_versions = {v.name: v._version for v in self.variables}

    def fields_dirty(self):
        versions = getattr(self, "_field_versions", None)
        if versions is None:
            return True
        return any(v._version != versions.get(v.name) for v in self.variables)

    # ------------------------------------------------------------------ RHS

    def _member_masks(self):
        """Per-block, per-member group-activity masks (None when always
        active); computed once — conditions are static per problem."""
        if getattr(self, "_member_masks_cache", None) is None:
            groups = list(self.layout.groups())
            out = []
            for eq in self.equations:
                out.append([None if cond is None
                            else np.array([float(cond(g)) for g in groups])
                            for _, cond in eq["members"]])
            self._member_masks_cache = out
        return self._member_masks_cache

    def build_rhs_evaluator(self, key="F", time_field=None, get_expr=None):
        """
        Build `eval_F(X, t=None, extra_arrays=None) -> (G, S)` evaluating the
        per-equation expressions selected by `get_expr` (default: the member's
        `key` entry). X=None skips the variable scatter (residual-style
        evaluation over non-variable fields only).
        """
        problem = self.problem
        layout = self.layout
        variables = self.variables
        equations = self.equations
        dim = self.dist.dim
        dtype = self.pencil_dtype
        if get_expr is None:
            get_expr = lambda member: member.get(key)

        # per-block member selection masks for conditioned equations
        member_masks = self._member_masks()

        # Non-variable fields feeding the RHS (parameters, forcings) become
        # explicit inputs of the compiled evaluator, so callers that thread
        # `extra_arrays` (see rhs_extra) pick up user updates to those fields
        # without retracing; a None leaves them baked as trace-time constants.
        from .field import Field as _Field
        from .future import Future as _Future
        extra = set()
        for eq in equations:
            for member, cond in eq["members"]:
                expr = get_expr(member)
                if isinstance(expr, (_Field, _Future)):
                    extra |= expr.atoms(_Field)
        extra -= set(variables)
        if time_field is not None:
            extra.discard(time_field)
        extra_fields = sorted(extra, key=lambda f: (f.name or "", id(f)))

        def eval_F(X, t=None, extra_arrays=None):
            from .field import mesh_transforms
            with mesh_transforms(self.dist.mesh,
                                 chunks=self._transpose_chunks):
                return eval_F_body(X, t, extra_arrays)

        def eval_F_body(X, t=None, extra_arrays=None):
            with metrics_mod.trace_scope("evaluator", "rhs"):
                return eval_F_inner(X, t, extra_arrays)

        def eval_F_inner(X, t=None, extra_arrays=None):
            subs = {}
            if X is not None:
                arrays = scatter_state(layout, variables, X)
                subs = {var: arrays[state_key(var)] for var in variables}
            if time_field is not None:
                subs[time_field] = jnp.reshape(jnp.asarray(t, dtype=self.real_dtype),
                                               (1,) * dim)
            if extra_arrays is not None:
                subs.update(zip(extra_fields, extra_arrays))
            ctx = EvalContext(subs)
            # fused operator-chain composites ride into the traced
            # evaluator (read per trace: the plan is built after this
            # evaluator, at solver construction)
            ctx.fusion = getattr(self, "_fused_eval_plan", None)
            parts = []
            for eq, masks in zip(equations, member_masks):
                size = layout.slot_size(eq["domain"], eq["tensorsig"])
                total = None
                for (member, cond), mask in zip(eq["members"], masks):
                    expr = get_expr(member)
                    if expr is None:
                        continue
                    data = ev(expr, ctx, "c")
                    part = layout.gather(data, eq["domain"], eq["tensorsig"])
                    if mask is not None:
                        part = part * jnp.asarray(mask, dtype=self.real_dtype)[:, None]
                    total = part if total is None else total + part
                if total is None:
                    total = jnp.zeros((layout.n_groups, size), dtype=dtype)
                parts.append(total)
            return jnp.concatenate(parts, axis=1).astype(dtype)

        eval_F.extra_fields = extra_fields
        return eval_F

    def rhs_extra(self):
        """Current data of the RHS's non-variable field inputs (ordered to
        match eval_F.extra_fields)."""
        return [f.coeff_data() for f in self.eval_F.extra_fields]


class InitialValueSolver(SolverBase):
    """IVP solver (reference: core/solvers.py:503 InitialValueSolver)."""

    matrices = ("M", "L")

    def __init__(self, problem, timestepper, matsolver=None,
                 enforce_real_cadence=100, warmup_iterations=10,
                 profile=None, profile_directory=None, metrics=None,
                 metrics_file=None, sample_cadence=None, health=None,
                 health_cadence=None, postmortem_dir=None, **kw):
        init_t0 = time_mod.time()
        super().__init__(problem, matsolver=matsolver, **kw)
        with self.build_phases.scope("factor"):
            self.M_mat = self.ops.to_device(self._matrices["M"],
                                            self.pencil_dtype)
            self.L_mat = self.ops.to_device(self._matrices["L"],
                                            self.pencil_dtype)
        self.eval_F = self.build_rhs_evaluator("F", time_field=problem.time)
        # fused RHS operator chains (core/fusedstep.py FUSED_TRANSFORMS):
        # foldable linear-operator nodes get host-precomposed
        # backward-MMT @ operator composite GEMMs, persisted through the
        # assembly cache; None when transform fusion is off or nothing
        # folds. Read at trace time via EvalContext.fusion.
        from . import fusedstep
        self._fused_eval_plan = fusedstep.build_eval_plan(self)
        # timestepping state
        self.sim_time = 0.0
        self.initial_sim_time = 0.0
        self.iteration = 0
        self.initial_iteration = 0
        self.stop_sim_time = np.inf
        self.stop_wall_time = np.inf
        self.stop_iteration = np.inf
        self.warmup_iterations = warmup_iterations
        self.enforce_real_cadence = enforce_real_cadence
        self.start_time = self.init_time = time_mod.time()
        self.warmup_time = None
        self.X = self.gather_fields()
        if isinstance(timestepper, str):
            timestepper = timesteppers_mod.schemes[timestepper]
        self.timestepper = timestepper(self)
        from .evaluator import Evaluator
        self.evaluator = Evaluator(self)
        self.dt = None
        self._project_state = None
        # float64 on an accelerator: route stepping through the emulated-
        # f64 (double-double) path where the problem is supported — XLA's
        # native software f64 has no MXU path, so the dd runner's int8
        # Ozaki matmuls + f32-factor/dd-refined solves are the fast f64
        # (config [execution] EMULATED_F64 = auto|never; core/ddstep.py)
        self._dd = None
        if (np.dtype(self.pencil_dtype) == np.dtype(np.float64)
                and jax.default_backend() in ("tpu", "axon")
                and config["execution"].get(
                    "EMULATED_F64", "auto").lower() != "never"):
            from .ddstep import DDIVPRunner, DDUnsupportedError
            try:
                self._dd = DDIVPRunner(self)
                logger.info("float64 on accelerator: emulated-f64 "
                            "(double-double) step path active")
            except DDUnsupportedError as exc:
                logger.info(f"float64 on accelerator: dd path unavailable "
                            f"({exc}); stepping in native XLA f64")
        # Profiling (reference: core/solvers.py:546-561,780-806 cProfile
        # phases; here a jax.profiler trace of the run phase + per-phase
        # wall times dumped at log_stats)
        if profile is None:
            profile = config["profiling"].getboolean("PROFILE_DEFAULT",
                                                     fallback=False)
        self.profile = bool(profile)
        self.profile_directory = pathlib.Path(
            profile_directory
            or config["profiling"].get("PROFILE_DIRECTORY", "profiles"))
        # Step-loop metrics (tools/metrics.py): counters + sampled phase
        # timers + memory watermark; default-on per [profiling] config,
        # cadence-gated so off-cadence steps never sync the device.
        self.metrics = metrics_mod.resolve(
            metrics, sink=metrics_file, cadence=sample_cadence,
            meta={"backend": jax.default_backend(),
                  "dtype": str(np.dtype(self.pencil_dtype)),
                  "pencil_shape": list(self.pencil_shape)})
        self._metrics_warm_pending = False
        # Abnormal-exit telemetry: an interrupted run (exception, SIGTERM)
        # still flushes one complete results.jsonl record (atexit + the
        # chaining signal hook; tools/metrics.py)
        metrics_mod.register_exit_flush(self)
        # Retrace sentinel (tools/retrace.py): armed at warmup end; a
        # post-warmup recompile of any step program warns and bumps the
        # dedalus/retrace counter on this metrics instance.
        retrace_mod.sentinel.subscribe(self.metrics)
        # Numerical-health monitor (tools/health.py): cadence-gated fused
        # NaN/growth/tail-energy probe + divergence flight recorder.
        # Default-on per [health] config; a disabled monitor compiles
        # nothing (zero-overhead path) but keeps the structured
        # invalid-dt error path available.
        self.health = health_mod.resolve(
            health, solver=self, cadence=health_cadence,
            postmortem_dir=postmortem_dir)
        self._health_error = None
        self._setup_time = time_mod.time() - init_t0
        self._trace_active = False

    @property
    def health_error(self):
        """The SolverHealthError that halted the run (None while healthy)."""
        return self._health_error

    @property
    def proceed(self):
        """Whether to keep iterating (reference: core/solvers.py:618)."""
        if self._health_error is not None:
            # logged once at detection (health monitor); graceful halt
            return False
        if self.sim_time >= self.stop_sim_time:
            logger.info("Simulation stop time reached.")
            return False
        if self.iteration >= self.stop_iteration:
            logger.info("Simulation stop iteration reached.")
            return False
        if (time_mod.time() - self.start_time) >= self.stop_wall_time:
            logger.info("Simulation stop wall time reached.")
            return False
        return True

    def enforce_hermitian_symmetry(self):
        """
        Re-project the state through a dealiased grid roundtrip
        (reference: core/solvers.py:675-692 enforce_hermitian_symmetry).
        Real-dtype storage makes Hermitian drift structurally impossible
        here (RealFourier keeps real arrays end-to-end), but the roundtrip
        still projects accumulated drift out of non-representable modes
        (curvilinear triangular truncation, Nyquist slots).
        """
        self.X = self._ensure_project()(self.X)

    def _ensure_project(self):
        """The jitted dealiased-roundtrip projection of the state (shared
        by enforce_hermitian_symmetry and the transform phase probe)."""
        if self._project_state is None:
            from .field import (transform_to_grid, transform_to_coeff,
                                mesh_transforms)
            layout, variables = self.layout, self.variables

            from ..tools.jitlift import lifted_jit

            def project(X):
                with mesh_transforms(self.dist.mesh,
                                     chunks=self._transpose_chunks):
                    arrays = scatter_state(layout, variables, X)
                    out = {}
                    for v in variables:
                        scales = tuple(v.domain.dealias)
                        tdim = len(v.tensorsig)
                        g = transform_to_grid(arrays[state_key(v)], v.domain,
                                              scales,
                                              tdim, tensorsig=v.tensorsig)
                        out[state_key(v)] = transform_to_coeff(g, v.domain, scales,
                                                         tdim,
                                                         tensorsig=v.tensorsig)
                    return gather_state(layout, variables, out)

            # ensemble hook: the raw projection body (core/ensemble.py
            # vmaps it over the member axis for the fleet's Hermitian/
            # valid-mode re-projection cadence)
            self._project_body = project
            self._project_state = lifted_jit(project)
        return self._project_state

    def _dd_advance(self, n, dt):
        """Advance n steps on the emulated-f64 (double-double) path: sync
        user field edits into the dd state, step, and install lazy field
        pulls that materialize f64 data on access. The f32 Hermitian
        re-projection cadence is skipped here — a f32 grid roundtrip would
        truncate the dd state (the dd-supported problem set is Cartesian
        real-storage, which has no Hermitian drift to project out)."""
        dd = self._dd
        if self.fields_dirty():
            # user edit or checkpoint restart: re-gather state AND restart
            # the multistep ramp from the solver's clock (histories predate
            # the new state; load_state also resets sim_time/iteration)
            dd.X = dd._gather_dd()
            dd.reset_history(self.sim_time)
        elif dd.sim_time != self.sim_time:
            dd.sim_time = self.sim_time
        if n > 1:
            dd.step_many(n, dt)   # one lax.scan dispatch per block
        else:
            dd.step(dt)
        self.X = dd.X.hi   # f32 view: finite checks, harness inspection
        self.sim_time = dd.sim_time
        layout, variables = self.layout, self.variables
        Xdd = dd.X
        cache = {}

        def make_pull(var):
            def pull():
                if "arrays" not in cache:
                    his = scatter_state(layout, variables, Xdd.hi)
                    los = scatter_state(layout, variables, Xdd.lo)
                    cache["arrays"] = {
                        k: (np.asarray(his[k], np.float64)
                            + np.asarray(los[k], np.float64))
                        for k in his}
                var.preset_coeff(jnp.asarray(cache["arrays"][state_key(var)]))
            return pull

        for v in variables:
            v.install_pull(make_pull(v))
        self.snapshot_versions()
        self.problem.sim_time = self.sim_time
        self.iteration += n
        self.dt = dt
        self.metrics.observe_steps(n)   # dd path: counters only, no probes
        self.health.tick(n)             # probes the f32 view (dd.X.hi)
        if self._health_error is None:
            self.evaluator.evaluate_scheduled(
                iteration=self.iteration,
                wall_time=time_mod.time() - self.start_time,
                sim_time=self.sim_time, timestep=dt)

    def _stop_trace(self):
        if self._trace_active:
            jax.profiler.stop_trace()
            self._trace_active = False
            logger.info(f"Profiler trace written to {self.profile_directory}")

    def _end_warmup(self):
        """Record warmup completion; start the profiler trace if enabled."""
        # Compile + first-run the phase probes BEFORE stamping warmup_time:
        # probe compilation stays out of the run window (log_stats rate) and
        # out of any externally measured post-warmup block. step_many-only
        # drivers hit this before the first block has factored the LHS
        # (no probes yet): defer the warm sample — and the loop-window
        # anchor — past that first, compile-bearing block.
        self._metrics_warm_pending = False
        if self.metrics.sampling and self._dd is None:
            if not self._try_sample_phases():
                self._metrics_warm_pending = self.metrics.sampling
        # health probe compiles here too (one baseline record), keeping
        # its compile out of measured windows like the phase probes
        self.health.warm(self.X)
        self.metrics.reset_loop()
        self.warmup_time = time_mod.time()
        # warmup compiled (or deferred-compiles) every step program; any
        # later retrace is a hygiene regression worth a structured warning
        retrace_mod.sentinel.arm()
        if self.profile and not self._trace_active:
            import atexit
            os.makedirs(self.profile_directory, exist_ok=True)
            jax.profiler.start_trace(str(self.profile_directory))
            self._trace_active = True
            # the trace must be closed even if the run dies before
            # log_stats (exception, NaN abort) — stop_trace is global
            # profiler state and a leaked session poisons later runs
            atexit.register(self._stop_trace)

    def step(self, dt, wall_time=None):
        """Advance the system by one timestep (reference: core/solvers.py:683)."""
        dt = float(dt)
        if not np.isfinite(dt):
            # structured health-error path: names iteration/sim_time and
            # dumps the flight recorder, so a CFL-produced NaN timestep
            # leaves the same post-mortem evidence as a NaN state
            raise self.health.invalid_dt(dt)
        if self.iteration == self.warmup_iterations:
            self._end_warmup()
        if self._dd is not None:
            self._dd_advance(1, dt)
            return
        # pick up user modifications of the state fields (version-tracked)
        if self.fields_dirty():
            self.X = self.gather_fields()
        # Hermitian/valid-mode re-projection cadence (reference:
        # core/solvers.py:688-692 — enforced for timestepper.steps
        # consecutive iterations so the multistep history stays consistent)
        if self.enforce_real_cadence:
            if self.iteration % self.enforce_real_cadence < self.timestepper.steps:
                self.enforce_hermitian_symmetry()
        first = "compile" not in self.build_phases.seconds
        t_first = time_mod.perf_counter() if first else None
        with metrics_mod.annotate("dedalus/step"):
            self.timestepper.step(dt)
        if first:
            # trace + lower + XLA compile of the step program dominates the
            # first dispatch; recorded as the cold-start `compile` phase
            jax.block_until_ready(self.X)
            self.build_phases.add(
                "compile", time_mod.perf_counter() - t_first)
        self.defer_scatter(self.X)
        self.snapshot_versions()
        self.problem.sim_time = self.sim_time
        self.iteration += 1
        self.dt = dt
        self._metrics_tick(1)
        self.health.tick(1)
        if self._health_error is None:
            # a poisoned step must not flow into scheduled outputs (no
            # NaN-filled checkpoint written as a "good" write)
            self.evaluator.evaluate_scheduled(
                iteration=self.iteration,
                wall_time=time_mod.time() - self.start_time,
                sim_time=self.sim_time, timestep=dt)

    def step_many(self, n, dt):
        """
        Advance n constant-dt steps with ONE device dispatch (lax.scan over
        the jitted step). Small problems are host-latency bound at one
        dispatch per step; blocking amortizes it. Scheduled handlers are
        evaluated once at the END of the block, so per-step output cadences
        inside a block coarsen to the block boundary; the Hermitian
        re-projection runs at the block start when the block crosses its
        cadence. Use step() when per-step cadences or adaptive dt matter.
        """
        n = int(n)
        dt = float(dt)
        if not np.isfinite(dt):
            raise self.health.invalid_dt(dt)
        if n <= 0:
            return
        if self.iteration <= self.warmup_iterations < self.iteration + n:
            self._end_warmup()
        if self._dd is not None:
            self._dd_advance(n, dt)   # blocked via DDIVPRunner.step_many
            return
        if self.fields_dirty():
            self.X = self.gather_fields()
        cadence = self.enforce_real_cadence
        if cadence:
            r = self.iteration % cadence
            if (n >= cadence or r < self.timestepper.steps
                    or (cadence - r) < n):
                self.enforce_hermitian_symmetry()
        first = "compile" not in self.build_phases.seconds
        t_first = time_mod.perf_counter() if first else None
        with metrics_mod.annotate("dedalus/step_many"):
            self.timestepper.step_many(n, dt)
        if first:
            jax.block_until_ready(self.X)
            self.build_phases.add(
                "compile", time_mod.perf_counter() - t_first)
        self.defer_scatter(self.X)
        self.snapshot_versions()
        self.problem.sim_time = self.sim_time
        self.iteration += n
        self.dt = dt
        self.metrics.inc("step_many_blocks")
        self._metrics_tick(n)
        self.health.tick(n)
        if self._health_error is None:
            self.evaluator.evaluate_scheduled(
                iteration=self.iteration,
                wall_time=time_mod.time() - self.start_time,
                sim_time=self.sim_time, timestep=dt)

    # -------------------------------------------------------------- metrics

    def _metrics_tick(self, n):
        """Per-step metrics hook: count iterations (non-blocking) and run
        the cadence-gated phase sample (the only point that syncs the
        device, and only every SAMPLE_CADENCE-th post-warmup iteration)."""
        m = self.metrics
        if not m.enabled:
            return
        m.observe_steps(n)
        if not (m.sampling and self._dd is None
                and self.warmup_time is not None):
            return
        if getattr(self, "_metrics_warm_pending", False):
            # deferred warm compile (step_many-only driver): sample now and
            # re-anchor the loop window — the block just finished carried
            # the step jit compile and must stay out of per-step rates
            self._metrics_warm_pending = False
            self._try_sample_phases()
            m.reset_loop()
            return
        if m.due():
            self._try_sample_phases()

    def _try_sample_phases(self):
        """_sample_phases with a telemetry firewall: probe failure disables
        sampling (with a warning) instead of killing the simulation.
        Returns whether a sample was recorded."""
        try:
            return self._sample_phases()
        except Exception as exc:
            logger.warning(f"metrics phase sampling disabled: {exc}")
            self.metrics.sampling = False
            return False

    def _sample_phases(self):
        """
        One phase sample: drain outstanding dispatches, then wall-time the
        already-compiled step pieces (timestepper phase probes + the
        dealiased transform roundtrip) on the current state, bracketing
        `block_until_ready`. The transform share of the RHS evaluation is
        measured by the roundtrip probe and subtracted out so
        transform/evaluator/matsolve/transpose sum to ~one step. On fused
        multi-device steps the all_to_all collectives execute inside the
        eval/solve probes, so their cost rides in evaluator/matsolve and
        `transpose` stays 0 — profiler traces (dedalus/transpose/...)
        are the per-collective attribution tool there. Returns True when
        a sample was recorded (False: probes not available yet).
        """
        m = self.metrics
        probes = self.timestepper.phase_probes()
        if probes is None:
            return False
        with metrics_mod.annotate("dedalus/metrics/sample"):
            jax.block_until_ready(self.X)
            scale = float(getattr(self.timestepper, "stages", 1) or 1)
            proj = self._ensure_project()
            times = {name: m.time_thunk(name, thunk) * s
                     for name, (thunk, s) in probes.items()}
            trans = m.time_thunk("transform", lambda: proj(self.X)) * scale
            rhs = times.get("rhs_eval", 0.0)
            trans = min(trans, rhs) if rhs else trans
            sample = {
                "transform": trans,
                "evaluator": max(rhs - trans, 0.0),
                "matsolve": times.get("matsolve", 0.0),
                "transpose": times.get("transpose", 0.0),
            }
            if "fused_step" in times:
                # the whole fused step program re-measured as its own row:
                # an ALTERNATIVE whole-step attribution that OVERLAPS the
                # split rows above, so metrics excludes it from the phase
                # sum (SUM_PHASES) — fused < sum(split) is the fusion win
                sample["fused"] = times["fused_step"]
            m.add_phase_sample(sample)
        return True

    def flush_metrics(self, extra=None):
        """Block on the state (so the loop window covers the device tail of
        the final dispatch) and flush one telemetry record — appended to
        the JSONL sink when one is configured. Health summary (checks,
        warnings, ok/failed) rides along under the `health` key. Returns
        the record dict."""
        try:
            jax.block_until_ready(self.X)
        except Exception:
            pass
        health_summary = self.health.summary()
        extra = dict(extra or {})
        if health_summary is not None:
            extra.setdefault("health", health_summary)
        resilience = getattr(self, "resilience", None)
        if resilience is not None:
            extra.setdefault("resilience", resilience.summary())
        # retrace-sentinel verdict rides in every telemetry record so the
        # perf trajectory shows compile-hygiene regressions in place
        extra.setdefault("retraces_post_warmup",
                         retrace_mod.sentinel.post_arm_retraces)
        # cold-start phase split (host_assembly/structure/factor/compile
        # seconds + assembly-cache verdict)
        extra.setdefault("build_phases", self.build_phases.record())
        # non-default solve composition / precision ladder: record the
        # resolved plan + the achieved residual of one probe solve (a
        # flush-time dispatch, off the step loop) so every telemetry
        # record carries the accuracy its speedup was bought at
        plan = getattr(self, "_solve_plan", None)
        if plan is not None and (plan.dtype != "native"
                                 or plan.composition != "sequential"):
            extra.setdefault("precision", self._precision_summary())
        # resolved plan provenance: every flushed record names the plan
        # that produced its numbers (ROADMAP item 2; `report` renders
        # pre-provenance rows as plan=unversioned)
        extra.setdefault("plan", self.plan_provenance())
        return self.metrics.flush(extra=extra)

    def plan_provenance(self):
        """The resolved execution plan this solver was built under, as one
        flat telemetry block: fusion composition, solve composition +
        precision ladder, transpose chunking, and the content identity
        the warm pool keys on. Everything here was resolved ONCE in
        `_build_pencil_system`, so the block names the plan the compiled
        programs actually run — not whatever the config says now."""
        block = {"plan_version": 1}
        fusion = getattr(self, "_fusion_plan", None)
        if fusion is not None:
            block["fusion"] = {
                "solve": fusion.solve, "matvec": fusion.matvec,
                "transforms": fusion.transforms, "donate": fusion.donate,
                "pallas": fusion.pallas}
        solve = getattr(self, "_solve_plan", None)
        if solve is not None:
            block["solve_composition"] = solve.composition
            block["solve_dtype"] = solve.dtype
            block["refine_sweeps"] = solve.sweeps
            block["spike_chunks"] = solve.spike_chunks
        chunks = getattr(self, "_transpose_chunks", None)
        if chunks is not None:
            block["transpose_chunks"] = int(chunks)
        key = getattr(self, "assembly_key", None)
        if key:
            block["solver_key"] = str(key)[:16]
        # how the plan was chosen: `tuned` (empirical autotuner decision,
        # with its measured evidence), `config` (user-pinned solve
        # knobs), or `default` (the hand-coded auto heuristics)
        block["plan_source"] = getattr(self, "_plan_source", "default")
        tuning = getattr(self, "_tuning", None)
        if tuning is not None:
            block["tuning"] = tuning
        return block

    def _precision_summary(self):
        """The `precision` telemetry block: the resolved solve plan and
        the achieved relative residual of a probe solve against the
        current LHS factorization (None until the first factor)."""
        plan = self._solve_plan
        block = {
            "solve_dtype": plan.dtype,
            "composition": plan.composition,
            "refine_sweeps": plan.sweeps if plan.sweeps is not None
            else getattr(self.ops, "refine", None),
            "refine_tol": plan.tol,
        }
        ts = getattr(self, "timestepper", None)
        aux = getattr(ts, "_lhs_aux", None)
        if aux is None or not hasattr(self.ops, "solve_report"):
            return block
        aux0 = aux[0] if isinstance(aux, list) else aux
        try:
            _, rel = self.ops.solve_report(
                aux0, self.X, mats=(self.M_mat, self.L_mat))
            if rel is not None:
                block["achieved_residual"] = float(np.asarray(rel))
        except Exception:
            pass
        return block

    def evolve_resilient(self, timestep_function=None, dt=None,
                         log_cadence=100, **kw):
        """
        Run the main loop under the resilient driver
        (tools/resilience.ResilientLoop): rolling state-snapshot ring,
        automatic rewind + dt backoff on SolverHealthError, SIGTERM/
        SIGINT-safe durable checkpointing with validated resume, and
        transient-IO retry around checkpoint/telemetry writes. Keyword
        arguments (snapshot_cadence, max_retries, dt_backoff,
        checkpoint_dir, resume, chaos, ...) configure the loop; defaults
        come from the [resilience] config section. Returns the loop's
        summary dict (also attached to flushed telemetry records).
        """
        from ..tools.resilience import ResilientLoop
        loop = ResilientLoop(self, timestep_function=timestep_function,
                             dt=dt, **kw)
        try:
            return loop.run(log_cadence=log_cadence)
        finally:
            self.log_stats()

    def ensemble(self, members, **kw):
        """Build an EnsembleSolver over this (built, undistributed) IVP:
        one compiled, vmapped + mesh-sharded step advancing `members`
        independent copies with per-member initial conditions, RHS
        parameters, and (RK schemes) per-member dt (core/ensemble.py)."""
        from .ensemble import EnsembleSolver
        return EnsembleSolver(self, members, **kw)

    def differentiable(self, wrt=("initial_state",), loss=None,
                       checkpoint_segments=None, **kw):
        """Build a DifferentiableIVP over this (built, undistributed)
        IVP: compiled `jax.grad`-able value-and-grad programs of a
        scalar `loss` of the final state over n constant-dt steps, with
        adjoint pencil solves against the cached LHS factorization and
        `jax.checkpoint`-bounded backprop memory (core/adjoint.py,
        docs/differentiable.md)."""
        from .adjoint import DifferentiableIVP
        return DifferentiableIVP(self, wrt=wrt, loss=loss,
                                 checkpoint_segments=checkpoint_segments,
                                 **kw)

    def evolve(self, timestep_function=None, log_cadence=100):
        """Run the main loop to completion (reference: core/solvers.py:713)."""
        try:
            while self.proceed:
                dt = timestep_function() if timestep_function else self.dt
                if dt is None:
                    raise ValueError(
                        "evolve() requires a timestep_function, or a prior "
                        "solver.step(dt) to set the timestep.")
                self.step(dt)
                if self.iteration % log_cadence == 0:
                    logger.info(f"Iteration={self.iteration}, Time={self.sim_time:.6e}, dt={dt:.6e}")
            if self._health_error is not None:
                logger.error(
                    f"Main loop halted by health monitor: "
                    f"{self._health_error.reason} (error available as "
                    f"solver.health_error)")
        except Exception:
            logger.error("Exception raised, triggering end of main loop.")
            raise
        finally:
            self.log_stats()

    def print_subproblem_ranks(self, max_groups=16, **kw):
        """Rank/conditioning diagnostic of the first `max_groups` pencil
        matrices (reference: solver debug helper). Densifies per group on
        the host — O(S^3) each, so the group count is bounded by default
        (pass max_groups=None for all groups)."""
        subproblems = self.subproblems
        if max_groups is not None and len(subproblems) > max_groups:
            print(f"(showing {max_groups} of {len(subproblems)} groups; "
                  "pass max_groups=None for all)")
            subproblems = subproblems[:max_groups]
        for sp in subproblems:
            L = self.ops.densify_host(self._matrices["L"], sp.index)
            M = self.ops.densify_host(self._matrices["M"], sp.index)
            A = M + L
            print(f"group {sp.group}: rank={np.linalg.matrix_rank(A)}/{A.shape[0]}, "
                  f"cond={np.linalg.cond(A):.2e}")

    def load_state(self, path, index=-1, allow_missing=False,
                   fallback=False):
        """Restore state from an HDF5 checkpoint
        (reference: core/solvers.py:632 load_state).

        Hardened against truncated/corrupt files: failures raise a
        structured `CheckpointError` naming the file and write index
        instead of a raw h5py traceback. With `fallback=True`, a corrupt
        write falls back to the previous writes in the same file (newest
        surviving write wins); `tools.resilience.resume_latest` extends
        the fallback across set files.
        """
        import h5py
        from ..tools.exceptions import CheckpointError
        try:
            f = h5py.File(path, "r")
        except OSError as exc:
            raise CheckpointError(
                f"checkpoint {path} unreadable (truncated or corrupt): "
                f"{exc}", path=path) from exc
        with f:
            try:
                n_writes = len(f["scales/write_number"])
            except KeyError as exc:
                raise CheckpointError(
                    f"checkpoint {path} has no scales/write_number "
                    f"(not a handler file?)", path=path) from exc
            if n_writes == 0:
                raise CheckpointError(
                    f"checkpoint {path} has an empty write index",
                    path=path)
            start = index if index >= 0 else n_writes + index
            if not 0 <= start < n_writes:
                raise CheckpointError(
                    f"checkpoint {path}: write index {index} out of range "
                    f"({n_writes} writes)", path=path, index=index)
            candidates = range(start, -1, -1) if fallback else (start,)
            failures = []
            for idx in candidates:
                try:
                    self._load_write(f, path, idx, allow_missing)
                except CheckpointError as exc:
                    if not fallback:
                        raise
                    failures.append(str(exc))
                    logger.warning(f"checkpoint write unusable, "
                                   f"falling back: {exc}")
                    continue
                if failures:
                    logger.info(f"loaded write {idx} of {path} after "
                                f"{len(failures)} fallback(s)")
                write = int(np.asarray(f["scales/write_number"])[idx])
                break
            else:
                raise CheckpointError(
                    f"checkpoint {path}: no loadable write at or before "
                    f"index {index} ({'; '.join(failures)})",
                    path=path, index=index)
        self.X = self.gather_fields()
        return write, self.dt

    def _load_write(self, f, path, idx, allow_missing):
        """Load ONE write of an open checkpoint file into the solver,
        wrapping data-level corruption (h5py OSError/ValueError on torn
        datasets) as CheckpointError. Scalar clocks are restored last-
        writer-wins only after every field read back cleanly."""
        from ..tools.exceptions import CheckpointError
        try:
            sim_time = float(np.asarray(f["scales/sim_time"])[idx])
            iteration = int(np.asarray(f["scales/iteration"])[idx])
            dt = float(np.asarray(f["scales/timestep"])[idx]) \
                if "scales/timestep" in f else None
            tasks = f["tasks"]
            data = {}
            for var in self.state:
                if var.name not in tasks:
                    if allow_missing:
                        continue
                    raise KeyError(
                        f"State variable {var.name} not found in {path}")
                ds = tasks[var.name]
                if len(ds) <= idx:
                    raise CheckpointError(
                        f"checkpoint {path} write {idx}: task "
                        f"'{var.name}' has only {len(ds)} write(s) "
                        f"(torn write)", path=path, index=idx)
                layout = ds.attrs.get("layout", "g")
                if isinstance(layout, bytes):
                    layout = layout.decode()
                data[var.name] = (layout, np.asarray(ds[idx]))
        except CheckpointError:
            raise
        except (OSError, ValueError, IndexError) as exc:
            raise CheckpointError(
                f"checkpoint {path} write {idx} unreadable: {exc}",
                path=path, index=idx) from exc
        for var in self.state:
            if var.name in data:
                layout, arr = data[var.name]
                var[layout if layout in ("c", "g") else "g"] = arr
        self.sim_time = self.initial_sim_time = sim_time
        self.iteration = self.initial_iteration = iteration
        self.dt = dt
        logger.info(f"Loading iteration: {iteration} (write index {idx})")

    def log_stats(self, format=".4g"):
        """Log run statistics including the reference's throughput metric
        (reference: core/solvers.py:755-778 log_stats, modes-stages/cpu-sec),
        and dump profile artifacts when enabled (reference:
        core/solvers.py:780-806 dump_profiles)."""
        log_time = time_mod.time()
        total = log_time - self.init_time
        self._stop_trace()
        logger.info(f"Final iteration: {self.iteration}")
        logger.info(f"Final sim time: {self.sim_time}")
        logger.info(f"Setup time (init - iter 0): {self.start_time - self.init_time:{format}} sec")
        bp = self.build_phases.record()
        logger.info(
            f"Build phases: host_assembly {bp['host_assembly_sec']:{format}}"
            f" s, structure {bp['structure_sec']:{format}} s, factor "
            f"{bp['factor_sec']:{format}} s, compile "
            f"{bp['compile_sec']:{format}} s "
            f"(assembly cache: {bp['assembly_cache']})")
        phases = {"setup": self._setup_time,
                  "total": total}
        if self.iteration > self.warmup_iterations and self.warmup_time:
            warmup = self.warmup_time - self.start_time
            run = log_time - self.warmup_time
            iters = self.iteration - self.warmup_iterations
            logger.info(f"Warmup time (iter 0-{self.warmup_iterations}): {warmup:{format}} sec")
            logger.info(f"Run time (iter {self.warmup_iterations}-end): {run:{format}} sec")
            G, S = self.pencil_shape
            modes = G * S
            stages = self.timestepper.stages if hasattr(self.timestepper, "stages") else 1
            rate = modes * stages * iters / run if run > 0 else 0.0
            logger.info(f"Speed: {rate:.2e} mode-stages/sec")
            phases.update({"warmup": warmup, "run": run, "run_iterations": iters,
                           "mode_stages_per_sec": rate})
        else:
            logger.info(f"Total time: {total:{format}} sec")
        record = None
        if self.metrics.enabled:
            record = self.flush_metrics()
            if record and record.get("phase_samples"):
                for line in metrics_mod.format_phase_table(record):
                    logger.info(line)
        health_summary = self.health.summary()
        if health_summary is not None:
            status = "ok" if health_summary.get("ok") else \
                f"FAILED ({health_summary.get('reason')})"
            logger.info(f"Health: {status}, "
                        f"{health_summary.get('checks', 0)} checks, "
                        f"{health_summary.get('warnings', 0)} warnings")
        if self.profile:
            import json
            os.makedirs(self.profile_directory, exist_ok=True)
            if record:
                phases["step_metrics"] = record
            with open(self.profile_directory / "phase_times.json", "w") as f:
                json.dump(phases, f, indent=2)


class LinearBoundaryValueSolver(SolverBase):
    """LBVP solver (reference: core/solvers.py:324)."""

    matrices = ("L",)

    def __init__(self, problem, matsolver=None, **kw):
        super().__init__(problem, matsolver=matsolver, **kw)
        with self.build_phases.scope("factor"):
            self.L_mat = self.ops.to_device(self._matrices["L"],
                                            self.pencil_dtype)
            self._aux = self.ops.factor(self.L_mat)
        # RHS-evaluator construction is expression compilation, not
        # factorization: outside the factor scope so factor_sec stays
        # comparable across solver types (IVP builds eval_F unscoped too)
        self.eval_F = self.build_rhs_evaluator("F")
        from ..tools.jitlift import lifted_jit, device_constant
        mask_np, rd = self.valid_row_mask, self.real_dtype
        eval_F, ops = self.eval_F, self.ops

        def _rhs_solve(aux, X0, extra):
            mask = device_constant(mask_np, dtype=rd)
            return ops.solve(aux, eval_F(X0, extra_arrays=extra) * mask)

        self._rhs_solve = lifted_jit(_rhs_solve)
        self.iteration = 0

    def solve(self):
        """Solve L.X = F with current NCC/RHS fields
        (reference: core/solvers.py:369)."""
        X0 = self.gather_fields()
        X = self._rhs_solve(self._aux, X0, self.rhs_extra())
        self.scatter_fields(X)
        self.iteration += 1
        return self.state


class NonlinearBoundaryValueSolver(SolverBase):
    """Newton-Kantorovich NLBVP solver (reference: core/solvers.py:418)."""

    matrices = ("L",)
    # Jacobians rebuild around the moving state every Newton iteration;
    # persisting each one would churn the on-disk cache for zero reuse.
    cache_ok = False

    def __init__(self, problem, matsolver=None, **kw):
        # Matrices are in terms of the perturbation variables.
        self._problem_ref = problem
        super().__init__(problem, matsolver=matsolver, **kw)
        self.iteration = 0
        # residual expressions converted to equation-block domains
        self._residual_exprs = {}
        for block in self.equations:
            for member, cond in block["members"]:
                if member.get("residual") is not None:
                    self._residual_exprs[id(member)] = problem._wrap(
                        member["residual"], block["domain"])

    def matrix_variables(self, problem):
        return problem.perturbations

    @property
    def state(self):
        return self.problem.variables

    def _eval_residual(self):
        cache = getattr(self, "_residual_cache", None)
        if cache is None:
            exprs = self._residual_exprs
            eval_R = self.build_rhs_evaluator(
                get_expr=lambda member: exprs.get(id(member)))
            from ..tools.jitlift import lifted_jit, device_constant
            mask_np, rd = self.valid_row_mask, self.real_dtype
            # memoized via _residual_cache just below (hand-rolled guard
            # the static pass cannot see)
            fn = lifted_jit(  # dedalus-lint: disable=DTL003
                lambda extra: eval_R(None, extra_arrays=extra)
                * device_constant(mask_np, dtype=rd))
            cache = self._residual_cache = (eval_R.extra_fields, fn)
        fields, fn = cache
        return fn([f.coeff_data() for f in fields])

    def newton_iteration(self, damping=1.0):
        """One Newton step: solve dG.dX = -G, update variables
        (reference: core/solvers.py:470)."""
        # Rebuild Jacobian matrices around the current state (NCC data moves;
        # the structural path is re-selected since the pattern can change).
        self._build_pencil_system()
        L = self.ops.to_device(self._matrices["L"], self.pencil_dtype)
        aux = self.ops.factor(L)
        F = -self._eval_residual()
        dX = self.ops.solve(aux, F)
        self._last_perturbation = dX
        arrays = scatter_state(self.layout, self.variables, dX)
        for var, pert in zip(self.problem.variables, self.variables):
            var.preset_coeff(var.coeff_data() + damping * arrays[state_key(pert)])
            var.mark_modified()
        self.iteration += 1

    def perturbation_norm(self, order=2):
        """Norm of the last Newton update dX (reference convergence metric)."""
        if getattr(self, "_last_perturbation", None) is None:
            return np.inf
        dX = np.asarray(self._last_perturbation)
        if order == np.inf:
            return np.max(np.abs(dX))
        return np.sum(np.abs(dX) ** order) ** (1.0 / order)

    def residual_norm(self, order=2):
        data = np.asarray(self._eval_residual())
        return np.sum(np.abs(data) ** order) ** (1.0 / order)


class EigenvalueSolver(SolverBase):
    """EVP solver: lam*M.X + L.X = 0 (reference: core/solvers.py:134)."""

    matrices = ("M", "L")
    lazy_ok = True

    def __init__(self, problem, matsolver=None, **kw):
        super().__init__(problem, matsolver=matsolver, **kw)
        self.eigenvalues = None
        self.eigenvectors = None
        self.eigenvalue_subproblem = None

    def _group_csr(self, subproblem):
        """
        {name: scipy CSR} of one subproblem's pencil matrices, sparse
        end-to-end: lazy mode assembles the single group on demand; the
        batched shared-pattern store scatters directly to CSR; only the
        banded/dense device stores densify (reference: sparse per-
        subproblem matrices, core/subsystems.py:493-598).
        """
        import scipy.sparse as sps
        names = self.matrices
        G, S = self.pencil_shape
        if self._lazy:
            cache = getattr(self, "_lazy_cache", None)
            if cache is not None and cache[0] == subproblem.index:
                return cache[1]
            coos, _, _ = assemble_group_coos(
                subproblem, self.equations, self.variables, names)
            out = {name: sps.csr_matrix(
                (vals, (rows, cols)), shape=(S, S))
                for name, (rows, cols, vals) in coos.items()}
            self._lazy_cache = (subproblem.index, out)
            return out
        if self._batched is not None:
            pr, pc, vals, row_valid, col_valid = self._batched
            g = subproblem.index
            out = {}
            for name in names:
                mat = sps.csr_matrix((vals[name][g], (pr, pc)), shape=(S, S))
                out[name] = mat
            inv_rows = np.flatnonzero(~row_valid[g])
            inv_cols = np.flatnonzero(~col_valid[g])
            if len(inv_rows):
                closure = sps.csr_matrix(
                    (np.ones(len(inv_rows)), (inv_rows, inv_cols)),
                    shape=(S, S))
                out[names[-1]] = out[names[-1]] + closure
            return out
        return {name: sps.csr_matrix(
            self.ops.densify_host(self._matrices[name], subproblem.index))
            for name in names}

    def solve_dense(self, subproblem, left=False, normalize_left=True,
                    rebuild_matrices=False, **kw):
        """Dense generalized eigensolve for one pencil
        (reference: core/solvers.py:180 solve_dense). `rebuild_matrices`
        reassembles M/L around the current NCC field data (parameter
        continuation, e.g. the Mathieu example's q sweep)."""
        if rebuild_matrices:
            # parameter-continuation rebuilds change the NCC data every
            # call: each would hash to a never-reloaded fresh cache key,
            # churning the persistent store and LRU-evicting useful
            # entries — so rebuilds opt out (same rationale as NLBVP)
            self.cache_ok = False
            if self._lazy:
                self._lazy_cache = None
            else:
                self._build_pencil_system()
        mats = self._group_csr(subproblem)
        L = mats["L"].toarray()
        M = mats["M"].toarray()
        out = scipy.linalg.eig(L, b=-M, left=left, **kw)
        if left:
            evals, evecs_left, evecs = out
        else:
            evals, evecs = out
        # drop infinite eigenvalues from identity-closure/tau rows
        finite = np.isfinite(evals)
        self.eigenvalues = evals[finite]
        self.eigenvectors = evecs[:, finite]
        if left:
            self.left_eigenvectors = evecs_left[:, finite]
            if normalize_left:
                norms = np.einsum("ij,ij->j", np.conj(self.left_eigenvectors),
                                  -M @ self.eigenvectors)
                safe = np.where(np.abs(norms) > 0, norms, 1.0)
                self.left_eigenvectors = self.left_eigenvectors / np.conj(safe)
        self.eigenvalue_subproblem = subproblem
        return self.eigenvalues

    def solve_sparse(self, subproblem, N, target, left=False,
                     rebuild_matrices=False, **kw):
        """Sparse shift-invert eigensolve around `target`
        (reference: core/solvers.py:225 solve_sparse)."""
        from ..tools.array import scipy_sparse_eigs
        if rebuild_matrices:
            # see solve_dense: continuation rebuilds must not churn the
            # persistent assembly cache
            self.cache_ok = False
            if self._lazy:
                self._lazy_cache = None
            else:
                self._build_pencil_system()
        mats = self._group_csr(subproblem)
        L, M = mats["L"], mats["M"]
        out = scipy_sparse_eigs(A=L, B=-M, N=N, target=target, left=left, **kw)
        if left:
            self.eigenvalues, self.eigenvectors, self.left_eigenvalues, \
                self.left_eigenvectors = out
        else:
            self.eigenvalues, self.eigenvectors = out
        self.eigenvalue_subproblem = subproblem
        return self.eigenvalues

    def set_state(self, index, subproblem=None):
        """Load eigenvector `index` into the state fields
        (reference: core/solvers.py:296 set_state)."""
        subproblem = subproblem or self.eigenvalue_subproblem
        G, S = self.pencil_shape
        X = np.zeros((G, S), dtype=np.complex128)
        X[subproblem.index] = self.eigenvectors[:, index]
        arrays = scatter_state(self.layout, self.variables, jnp.asarray(X))
        for var in self.variables:
            data = arrays[state_key(var)]
            if not np.iscomplexobj(np.asarray(var.data)):
                data = data.real
            var.preset_coeff(jnp.asarray(data).astype(var.data.dtype))
            var.mark_modified()
