"""
DifferentiableIVP: adjoint gradients through the IVP step loop.

The one capability the MPI/FFTW reference can never have is `jax.grad`
through the timestepping loop — here the whole step is already JAX, so
this module opens the workload class: adjoint sensitivities of a scalar
loss of the final state w.r.t. initial conditions, RHS parameter/NCC
data fields, and forcing operands, for data assimilation, inverse
design, and solver-in-the-loop ML training.

Design:

  * The step loop is reconstructed as a PURE `(operands, state0) ->
    (loss, stateT)` function over the existing raw step bodies
    (`MultistepIMEX.advance_body` / `RungeKuttaIMEX.step_body`) — the
    same compositions the forward programs compile, so the adjoint's
    forward pass is bit-identical to the stepping loop. The multistep
    startup ramp (order build-up) is replayed from the host-side
    `coefficient_schedule`; the stationary remainder runs as a
    `lax.scan`.
  * Backprop memory is bounded by `jax.checkpoint` over fixed-size
    segments of that scan: K segments store K boundary carries and
    recompute inside a segment, so peak memory is O(G*S*(K + n/K))
    instead of O(G*S*n) — the PR-4 snapshot insight (device states are
    cheap to hold) applied to remat policy. `checkpoint_segments=None`
    picks K ~ sqrt(n).
  * The batched pivoted-LU pencil solve is opaque to autodiff at the
    factorization boundary; `libraries/pencilops.AdjointSolveOps` gives
    it a `jax.custom_vjp` whose backward pass is the adjoint solve —
    solve against the transposed factorization, reusing the cached LHS
    factors (the adjoint of a linear solve is a linear solve with the
    same matrix). Factorizations are computed OUTSIDE the differentiated
    program (host dispatches, like the stepping loop) and enter as
    non-differentiated operands, so gradients w.r.t. M/L assembly
    scalars are NOT available (documented in docs/differentiable.md).
  * The compiled value-and-grad program goes through `lifted_jit`
    (device constants lifted, retrace sentinel armed) and its outputs
    through the health monitor's fused non-finite check
    (`HealthMonitor.check_values`), so a NaN in the backward pass raises
    a structured `SolverHealthError` naming the adjoint phase instead of
    silently propagating into an optimizer.

Telemetry: `adjoint/...` counters plus an `adjoint` summary block
(grad_steps_per_sec, checkpoint segments, grad/forward cost ratio, peak
device memory) in every flushed record — `python -m dedalus_tpu report`
renders it; `benchmarks/adjoint.py` records the `diffusion64_adjoint`
bench row.
"""

import logging
import time as time_mod

import numpy as np
import jax
import jax.numpy as jnp

from .subsystems import scatter_state, state_key
from . import timesteppers as timesteppers_mod
from ..tools import metrics as metrics_mod
from ..tools import retrace as retrace_mod
from ..tools.jitlift import lifted_jit

logger = logging.getLogger(__name__)

__all__ = ["DifferentiableIVP"]

# wrt tokens: the named operand groups of the differentiable program.
# "parameters" and "forcing" both resolve to the RHS's non-variable field
# operands (structurally indistinguishable: every extra field enters F the
# same way); individual field names select subsets.
WRT_STATE = "initial_state"
WRT_EXTRA_GROUPS = ("parameters", "forcing")


class DifferentiableIVP:
    """
    Differentiable view of one built `InitialValueSolver`: compiled
    value-and-grad programs over n constant-dt steps from the solver's
    current state and RHS operands.

    Parameters
    ----------
    solver : InitialValueSolver
        Built, undistributed, native-precision template (same
        constraints as EnsembleSolver: no spatial mesh, no emulated-f64
        runner).
    wrt : tuple of str
        Operands to differentiate: "initial_state", the group tokens
        "parameters"/"forcing" (all RHS non-variable fields), and/or
        individual field names.
    loss : callable
        `loss(XT) -> scalar` over the final (G, S) pencil state; must be
        traceable jnp code. `self.state_arrays(XT)` splits XT back into
        per-field coefficient arrays for field-space losses.
    checkpoint_segments : int or None
        Remat segments K over the scanned steps (None: K ~ sqrt(n)).
        K=1 disables segmenting (full-memory backprop).
    """

    def __init__(self, solver, wrt=(WRT_STATE,), loss=None,
                 checkpoint_segments=None, metrics=None, metrics_file=None):
        if loss is None or not callable(loss):
            raise ValueError(
                "DifferentiableIVP requires loss=fn with fn(XT) -> scalar "
                "(traceable jnp code over the final pencil state).")
        if getattr(solver, "_dd", None) is not None:
            raise ValueError(
                "DifferentiableIVP requires the native step path; the "
                "solver uses the emulated-f64 (double-double) runner. "
                "Build it with [execution] EMULATED_F64 = never.")
        if getattr(solver.dist, "mesh", None) is not None:
            raise ValueError(
                "DifferentiableIVP requires an undistributed solver (the "
                "shard_map-routed solves have no transpose rule yet).")
        ts = solver.timestepper
        self._multistep = isinstance(ts, timesteppers_mod.MultistepIMEX)
        if not self._multistep and not isinstance(
                ts, timesteppers_mod.RungeKuttaIMEX):
            raise ValueError(f"Unsupported timestepper {type(ts).__name__}")
        self.solver = solver
        self.timestepper = ts
        self.loss = loss
        self.rd = solver.real_dtype
        if checkpoint_segments is not None:
            checkpoint_segments = int(checkpoint_segments)
            if checkpoint_segments < 1:
                raise ValueError("checkpoint_segments must be >= 1")
        self.checkpoint_segments = checkpoint_segments
        # ------------------------------------------------- wrt resolution
        extra_fields = solver.eval_F.extra_fields
        self.extra_names = [state_key(f) for f in extra_fields]
        sel = set()
        self._wrt_state = False
        for token in tuple(wrt):
            if token == WRT_STATE:
                self._wrt_state = True
            elif token in WRT_EXTRA_GROUPS:
                if not extra_fields:
                    raise ValueError(
                        f"wrt={token!r} selects the RHS's non-variable "
                        "field operands, but this problem's F has none.")
                sel.update(range(len(extra_fields)))
            elif token in self.extra_names:
                sel.add(self.extra_names.index(token))
            else:
                raise ValueError(
                    f"unknown wrt operand {token!r}: expected "
                    f"'initial_state', 'parameters', 'forcing', or one of "
                    f"the RHS field names {self.extra_names}")
        self._wrt_idx = tuple(sorted(sel))
        self._const_idx = tuple(i for i in range(len(extra_fields))
                                if i not in sel)
        if not self._wrt_state and not self._wrt_idx:
            raise ValueError("wrt selects no differentiable operand")
        self.wrt = ((WRT_STATE,) if self._wrt_state else ()) + tuple(
            self.extra_names[i] for i in self._wrt_idx)
        # --------------------------------------------------------- caches
        self._factor_cache = {}   # (rounded lead coeffs) -> lhs aux
        self._programs = {}       # (kind, n, K) -> lifted_jit wrapper
        self._last_segments = None
        # ------------------------------------------------------ telemetry
        self._grad_calls = 0
        self._grad_steps = 0
        self._grad_wall = 0.0
        self._fwd_calls = 0
        self._fwd_steps = 0
        self._fwd_wall = 0.0
        self._compile_sec = 0.0
        self.metrics = metrics_mod.resolve(
            metrics, sink=metrics_file,
            meta={"config": "adjoint",
                  "backend": jax.default_backend(),
                  "dtype": str(np.dtype(solver.pencil_dtype)),
                  "pencil_shape": list(solver.pencil_shape),
                  "wrt": list(self.wrt)})
        logger.info(
            f"DifferentiableIVP: wrt={list(self.wrt)}, "
            f"checkpoint_segments="
            f"{self.checkpoint_segments or 'auto(sqrt n)'}")

    # -------------------------------------------------------------- helpers

    def state_arrays(self, X):
        """Split a (G, S) pencil state into per-field coefficient arrays
        keyed by field name (traceable: safe inside a loss function)."""
        return scatter_state(self.solver.layout, self.solver.variables, X)

    def _merge_extras(self, diff_extras, const_extras):
        out = [None] * (len(self._wrt_idx) + len(self._const_idx))
        for i, v in zip(self._wrt_idx, diff_extras):
            out[i] = v
        for i, v in zip(self._const_idx, const_extras):
            out[i] = v
        return out

    def _segments(self, n_scan):
        K = self.checkpoint_segments
        if K is None:
            K = int(np.ceil(np.sqrt(max(n_scan, 1))))
        return max(1, min(int(K), max(n_scan, 1)))

    def _scan_checkpointed(self, step_once, carry, n_scan):
        """n_scan applications of `step_once` (carry -> carry) as a
        K-segment remat'd scan plus one plain remainder scan: backward
        stores K boundary carries and recomputes within a segment."""
        if n_scan <= 0:
            return carry
        K = self._segments(n_scan)
        self._last_segments = K
        L = n_scan // K
        rem = n_scan - K * L

        def body(c, _):
            return step_once(c), None

        def segment(c):
            c, _ = jax.lax.scan(body, c, None, length=L)
            return c

        if L > 0:
            if K > 1:
                seg = jax.checkpoint(segment)
                carry, _ = jax.lax.scan(lambda c, _: (seg(c), None),
                                        carry, None, length=K)
            else:
                carry = segment(carry)
        if rem:
            carry, _ = jax.lax.scan(body, carry, None, length=rem)
        return carry

    # ----------------------------------------------------- factorizations

    def _factors_multistep(self, dt, n):
        """Device coefficient triples + LHS auxes for an n-step constant-dt
        run: ([(a, b, c)...] ramp, [aux...] ramp, (a, b, c) stationary,
        aux stationary). Factors are host dispatches cached per leading
        coefficient pair — they enter the differentiable program as
        non-differentiated operands."""
        ts = self.timestepper
        solver = self.solver
        rd = self.rd
        ramp_np, stat_np = ts.coefficient_schedule(dt, n)

        def aux_for(a, b):
            key = (round(float(a[0]), 14), round(float(b[0]), 14))
            aux = self._factor_cache.get(key)
            if aux is None:
                aux = self._factor_cache[key] = ts._factor(
                    solver.M_mat, solver.L_mat,
                    jnp.asarray(a[0], dtype=rd), jnp.asarray(b[0], dtype=rd))
            return aux

        dev = lambda abc: tuple(jnp.asarray(v, dtype=rd) for v in abc)
        ramp = [dev(abc) for abc in ramp_np]
        ramp_auxs = [aux_for(a, b) for a, b, _ in ramp_np]
        return ramp, ramp_auxs, dev(stat_np), aux_for(*stat_np[:2])

    def _factors_rk(self, dt):
        key = round(float(dt), 14)
        auxs = self._factor_cache.get(key)
        if auxs is None:
            auxs = self._factor_cache[key] = self.timestepper._factor(
                self.solver.M_mat, self.solver.L_mat,
                jnp.asarray(float(dt), dtype=self.rd))
        return auxs

    # ----------------------------------------------------------- programs

    def _build_raw(self, n):
        """The pure (operands -> (loss, stateT)) function over n steps,
        composed from the timestepper's raw step body."""
        solver = self.solver
        ts = self.timestepper
        loss_fn = self.loss
        merge = self._merge_extras
        scan_ck = self._scan_checkpointed

        if self._multistep:
            s = ts.steps
            n_ramp = min(s - 1, n)
            advance = ts.advance_body
            G, S = solver.pencil_shape
            pdtype = solver.pencil_dtype

            def raw(M, L, X0, t0, dt, diff_extras, const_extras,
                    ramp, ramp_auxs, abc, aux):
                extras = merge(diff_extras, const_extras)
                hists = (jnp.zeros((s, G, S), dtype=pdtype),) * 3
                X, t = X0, t0
                with metrics_mod.trace_scope("adjoint", "forward"):
                    for (a, b, c), auxr in zip(ramp, ramp_auxs):
                        X, *hists = advance(M, L, X, t, extras, *hists,
                                            a, b, c, auxr)
                        t = t + dt
                    if n > n_ramp:
                        a, b, c = abc

                        def one(carry):
                            X, t, Fh, MXh, LXh = carry
                            Xn, Fh, MXh, LXh = advance(
                                M, L, X, t, extras, Fh, MXh, LXh,
                                a, b, c, aux)
                            return (Xn, t + dt, Fh, MXh, LXh)

                        X, t, *hists = scan_ck(one, (X, t, *hists),
                                               n - n_ramp)
                with metrics_mod.trace_scope("adjoint", "loss"):
                    val = loss_fn(X)
                return val, X
        else:
            step_body = ts.step_body

            def raw(M, L, X0, t0, dt, diff_extras, const_extras, lhs_auxs):
                extras = merge(diff_extras, const_extras)

                def one(carry):
                    X, t = carry
                    return (step_body(M, L, X, t, dt, extras, lhs_auxs),
                            t + dt)

                with metrics_mod.trace_scope("adjoint", "forward"):
                    X, _ = scan_ck(one, (X0, t0), n)
                with metrics_mod.trace_scope("adjoint", "loss"):
                    val = loss_fn(X)
                return val, X
        return raw

    def _program(self, kind, n):
        """Memoized lifted_jit program per (kind, n, K): retraces after
        warmup surface through the retrace sentinel exactly like the
        solver's step programs."""
        key = (kind, int(n), self.checkpoint_segments)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        raw = self._build_raw(int(n))
        if kind == "grad":
            argnums = ((2,) if self._wrt_state else ()) + \
                ((5,) if self._wrt_idx else ())
            fn = jax.value_and_grad(raw, argnums=argnums, has_aux=True)
        else:
            fn = raw
        prog = self._programs[key] = lifted_jit(fn)
        return prog

    # ------------------------------------------------------------ operands

    def _operands(self, initial_state, fields):
        solver = self.solver
        if initial_state is not None:
            X0 = jnp.asarray(initial_state, dtype=solver.pencil_dtype)
        else:
            X0 = solver.gather_fields() if solver.fields_dirty() \
                else solver.X
        extras = [jnp.asarray(a) for a in solver.rhs_extra()]
        if fields:
            unknown = set(fields) - set(self.extra_names)
            if unknown:
                raise ValueError(
                    f"field overrides {sorted(unknown)} are not RHS "
                    f"operands of this problem ({self.extra_names})")
            for name, arr in fields.items():
                i = self.extra_names.index(name)
                extras[i] = jnp.asarray(arr, dtype=extras[i].dtype)
        diff_extras = [extras[i] for i in self._wrt_idx]
        const_extras = [extras[i] for i in self._const_idx]
        return X0, diff_extras, const_extras

    def _args(self, n, dt, X0, diff_extras, const_extras):
        solver = self.solver
        t0 = jnp.asarray(float(solver.sim_time), dtype=self.rd)
        dtj = jnp.asarray(float(dt), dtype=self.rd)
        base = (solver.M_mat, solver.L_mat, X0, t0, dtj,
                diff_extras, const_extras)
        if self._multistep:
            ramp, ramp_auxs, abc, aux = self._factors_multistep(dt, n)
            return base + (ramp, ramp_auxs, abc, aux)
        return base + (self._factors_rk(dt),)

    def _grads_dict(self, grads):
        out = {}
        pos = 0
        if self._wrt_state:
            out[WRT_STATE] = grads[pos]
            pos += 1
        if self._wrt_idx:
            for i, g in zip(self._wrt_idx, grads[pos]):
                out[self.extra_names[i]] = g
        return out

    # -------------------------------------------------------------- public

    def forward(self, n_steps, dt, initial_state=None, fields=None):
        """Run the pure forward pass: (loss value as float, final pencil
        state). Numerically identical to n solver.step(dt) calls from a
        fresh history, and the denominator of the grad/forward cost
        ratio (benchmarks/adjoint.py)."""
        n = int(n_steps)
        if n < 1:
            raise ValueError("n_steps must be >= 1")
        args = self._args(n, dt, *self._operands(initial_state, fields))
        prog = self._program("forward", n)
        first = ("forward", n, self.checkpoint_segments) not in \
            self._compiled_keys()
        t0 = time_mod.perf_counter()
        with metrics_mod.annotate("dedalus/adjoint/forward"):
            val, XT = prog(*args)
            jax.block_until_ready(XT)
        wall = time_mod.perf_counter() - t0
        self._note_run("fwd", n, wall, first,
                       ("forward", n, self.checkpoint_segments))
        return float(val), XT

    def value(self, n_steps, dt, initial_state=None, fields=None):
        """The scalar loss of the forward pass (finite-difference probes
        and optimizer line searches)."""
        return self.forward(n_steps, dt, initial_state=initial_state,
                            fields=fields)[0]

    def value_and_grad(self, n_steps, dt, initial_state=None, fields=None,
                       check_health=True):
        """
        Loss and adjoint gradients of n constant-dt steps from the
        solver's current state (or the explicit operand overrides).
        Returns `(loss, grads)` with grads keyed by wrt operand name
        ("initial_state" and/or RHS field names). With `check_health`
        (default), a non-finite loss or gradient raises a structured
        `SolverHealthError` naming the adjoint phase
        (HealthMonitor.check_values).
        """
        n = int(n_steps)
        if n < 1:
            raise ValueError("n_steps must be >= 1")
        args = self._args(n, dt, *self._operands(initial_state, fields))
        prog = self._program("grad", n)
        first = ("grad", n, self.checkpoint_segments) not in \
            self._compiled_keys()
        t0 = time_mod.perf_counter()
        with metrics_mod.annotate("dedalus/adjoint/grad"):
            (val, XT), grads = prog(*args)
            jax.block_until_ready(grads)
        wall = time_mod.perf_counter() - t0
        self._note_run("grad", n, wall, first,
                       ("grad", n, self.checkpoint_segments))
        grads = self._grads_dict(grads)
        if check_health:
            self.solver.health.check_values(
                (val, grads), phase="adjoint",
                context=f"backward pass over {n} steps, "
                        f"wrt={list(self.wrt)}, dt={float(dt):.3e}")
        return float(val), grads

    def grad_program_handle(self, n_steps, dt):
        """(program, args) of the compiled value_and_grad program over
        n constant-dt steps from the solver's current state — the
        inspection handle the program contract checker
        (tools/lint/progcheck.py) lowers. `program` is the same
        lifted_jit wrapper value_and_grad dispatches (memoized per
        (kind, n, K)), so `program.jaxpr(*args)` exposes the primitive
        structure the adjoint actually backpropagates through — the
        no-host-callback / gradient-integrity contracts read it here."""
        n = int(n_steps)
        if n < 1:
            raise ValueError("n_steps must be >= 1")
        args = self._args(n, dt, *self._operands(None, None))
        return self._program("grad", n), args

    # ----------------------------------------------------------- telemetry

    def _compiled_keys(self):
        keys = getattr(self, "_compiled", None)
        if keys is None:
            keys = self._compiled = set()
        return keys

    def _note_run(self, kind, n, wall, first, key):
        """Loop accounting: the first run of each program carries its
        trace+compile and is recorded as compile time, not throughput."""
        if first:
            self._compiled_keys().add(key)
            self._compile_sec += wall
            self.metrics.inc(f"adjoint/{kind}_compiles")
        elif kind == "grad":
            self._grad_steps += n
            self._grad_wall += wall
        else:
            self._fwd_steps += n
            self._fwd_wall += wall
        if kind == "grad":
            self._grad_calls += 1
            self.metrics.inc("adjoint/grad_calls")
            self.metrics.inc("adjoint/grad_steps", n)
        else:
            self._fwd_calls += 1
            self.metrics.inc("adjoint/forward_calls")
            self.metrics.inc("adjoint/forward_steps", n)
        self.metrics.memory.sample()

    def summary(self):
        """Compact adjoint record (the `adjoint` block of flushed
        telemetry; `report` renders it). Rates exclude each program's
        compile-bearing first run."""
        grad_sps = round(self._grad_steps / self._grad_wall, 4) \
            if self._grad_wall > 0 else None
        fwd_sps = round(self._fwd_steps / self._fwd_wall, 4) \
            if self._fwd_wall > 0 else None
        ratio = None
        if grad_sps and fwd_sps and grad_sps > 0:
            ratio = round(fwd_sps / grad_sps, 3)
        return {
            "wrt": list(self.wrt),
            "checkpoint_segments": self._last_segments,
            "grad_calls": self._grad_calls,
            "grad_steps": self._grad_steps,
            "grad_steps_per_sec": grad_sps,
            "forward_steps_per_sec": fwd_sps,
            "grad_forward_ratio": ratio,
            "compile_sec": round(self._compile_sec, 4),
            "device_mem_peak_bytes": self.metrics.memory.peak_bytes,
        }

    def flush_metrics(self, extra=None):
        """Flush one telemetry record with the `adjoint` summary block
        (and the retrace-sentinel verdict) attached."""
        extra = dict(extra or {})
        extra.setdefault("adjoint", self.summary())
        extra.setdefault("retraces_post_warmup",
                         retrace_mod.sentinel.post_arm_retraces)
        # provenance of the wrapped forward solver: the adjoint programs
        # differentiate through the same resolved plan
        if hasattr(self.solver, "plan_provenance"):
            extra.setdefault("plan", self.solver.plan_provenance())
        return self.metrics.flush(extra=extra)
