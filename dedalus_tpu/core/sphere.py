"""
Sphere (S2) basis: Fourier azimuth x spin-weighted spherical harmonic
colatitude (reference: dedalus/core/basis.py:2672 SphereBasis and the SWSH
colatitude transform core/transforms.py:1252 SWSHColatitudeTransform).

TPU-native design (mirrors core/polar.py DiskBasis):
  * Coefficient layout is rectangular (Nphi, Ntheta) with slot l of azimuthal
    group (m, spin s) carrying harmonic degree l; slots l < lmin(m, s) =
    max(|m|, |s|) are invalid (triangular truncation as validity masking,
    reference: core/basis.py:2770 valid ell >= max(|m|,|s|)).
  * All m- and spin-dependent colatitude operations are zero-padded stacks
    applied as ONE batched matmul over the m groups (the reference loops
    per m in Python: core/transforms.py:1274-1288).
  * Tensor components are SPIN components in coefficient space; the
    coordinate<->spin rotation happens inside the transforms
    (reference: core/basis.py:1595 forward_spin_recombination).
  * Operators are SWSH ladder compositions: D_{+-} maps spin s -> s +- 1 and
    is diagonal in l; the spin-weighted Laplacian is diagonal with
    eigenvalues -(l(l+1) - s^2)/r^2.
"""

import numpy as np

from ..tools.cache import CachedMethod
from ..libraries import sphere as swsh
from .basis import Basis
from .coords import S2Coordinates, SphericalCoordinates
from .curvilinear import SpinBasisMixin, component_spins
from .polar import S1Basis, S1ComplexBasis
from ..tools.general import is_complex_dtype


class SphereBasis(SpinBasisMixin, Basis):
    """
    Two-sphere basis: Fourier azimuth x SWSH colatitude
    (reference: core/basis.py:2672 SphereBasis).
    """

    dim = 2

    def __init__(self, coordsystem, shape, dtype=np.float64, radius=1.0,
                 dealias=(1, 1), azimuth_library=None, colatitude_library=None,
                 ell_separable=False):
        if isinstance(coordsystem, SphericalCoordinates):
            coordsystem = coordsystem.S2coordsys
        if not isinstance(coordsystem, S2Coordinates):
            raise ValueError("Sphere coordsys must be S2Coordinates.")
        self.coordsystem = self.cs = coordsystem
        # Separability of the colatitude axis is a property of the PROBLEM,
        # not the coordinate system: inside a 3D shell/ball problem every
        # operator is ell-diagonal (ell is a group axis), while a standalone
        # S2 problem couples ell (e.g. MulCosine NCCs) even when built on an
        # embedded SphericalCoordinates.S2coordsys. Shell/Ball constructors
        # pass ell_separable=True explicitly for their boundary bases.
        self.ell_separable = bool(ell_separable)
        self.coord = coordsystem.coords[0]
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.radius = float(radius)
        if np.isscalar(dealias):
            dealias = (dealias, dealias)
        self.dealias = tuple(map(float, dealias))
        self.volume = 4 * np.pi * radius ** 2
        Nphi, Ntheta = self.shape
        self.Nphi, self.Ntheta = Nphi, Ntheta
        self.Lmax = Ntheta - 1
        self.complex = is_complex_dtype(self.dtype)
        if self.complex:
            self.azimuth_basis = S1ComplexBasis(
                coordsystem.azimuth, Nphi, dealias=self.dealias[0],
                library=azimuth_library)
        else:
            self.azimuth_basis = S1Basis(
                coordsystem.azimuth, Nphi, dealias=self.dealias[0],
                library=azimuth_library)
        self.colatitude_library = colatitude_library

    def __repr__(self):
        return f"SphereBasis({self.shape}, radius={self.radius})"

    # ------------------------------------------------------------ structure

    @property
    def first_axis(self):
        return self.coordsystem.first_axis

    def coeff_size(self, sub_axis):
        return self.shape[sub_axis]

    def sub_grid_size(self, sub_axis, scale):
        return int(np.ceil(scale * self.shape[sub_axis]))

    def sub_separable(self, sub_axis):
        if sub_axis == 0:
            return True
        return self.ell_separable

    def sub_group_shape(self, sub_axis):
        if sub_axis == 0:
            return 1 if self.complex else 2
        return 1

    def sub_n_groups(self, sub_axis):
        if sub_axis == 0:
            return self.Nphi if self.complex else self.Nphi // 2
        if self.sub_separable(sub_axis):
            return self.Ntheta  # ell groups in 3D problems
        return 1

    @CachedMethod
    def group_m(self):
        """Azimuthal wavenumber per group."""
        if self.complex:
            return np.fft.fftfreq(self.Nphi, d=1.0 / self.Nphi).astype(int)
        return np.arange(self.Nphi // 2)

    @staticmethod
    def _lmin(m, s):
        return max(abs(int(m)), abs(int(s)))

    def clone_with(self, **changes):
        args = dict(coordsystem=self.coordsystem, shape=self.shape,
                    dtype=self.dtype, radius=self.radius, dealias=self.dealias,
                    ell_separable=self.ell_separable)
        args.update(changes)
        return SphereBasis(**args)

    def derivative_basis(self, order=1):
        # SWSH ladders stay within the basis (no Jacobi k-ladder).
        return self

    # --------------------------------------------------------------- grids

    def global_grids(self, scales=(1, 1)):
        return (self.azimuth_grid(scales[0]), self.colatitude_grid(scales[1]))

    def azimuth_grid(self, scale=1.0):
        Ng = self.sub_grid_size(0, scale)
        return 2 * np.pi * np.arange(Ng) / Ng

    def colatitude_grid(self, scale=1.0):
        """theta = arccos(z) at the Gauss-Legendre nodes (z ascending, so
        theta descends from pi to 0)."""
        Ng = self.sub_grid_size(1, scale)
        z, _ = swsh.quadrature(Ng - 1)
        return np.arccos(z)

    # ---------------------------------------------------------- validity

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """(ncomp, gs_az, Ntheta) at one m group — or (ncomp, gs_az, 1) at
        one (m, ell) group when the colatitude is separable (3D problems):
        slot l valid iff l >= lmin(m, s_component)
        (reference: core/basis.py:2770)."""
        spins = component_spins(tensorsig, self.cs)
        ncomp = len(spins)
        az_axis = self.first_axis
        colat_axis = az_axis + 1
        gs = self.sub_group_shape(0)
        ms = self.group_m()
        if az_axis not in sep_widths:
            raise NotImplementedError("Sphere azimuth must be a pencil axis.")
        g = group[az_axis]
        m = ms[g]
        if colat_axis in sep_widths:
            ells = np.array([group[colat_axis]])
        else:
            ells = np.arange(self.Ntheta)
        mask = np.ones((ncomp, gs, ells.size), dtype=bool)
        for c, s in enumerate(spins):
            mask[c] &= (ells >= self._lmin(m, s))[None, :]
        if self.complex and g == self.Nphi // 2:
            mask[:] = False  # Nyquist
        if (not self.complex) and len(tensorsig) <= 1:
            # Drop msin slots at ell == 0 for real scalars and vectors; m == 0
            # symmetry is NOT imposed at ell > 0 (reference: core/basis.py:3206)
            mask[:, 1, ells == 0] = False
        return mask

    # ------------------------------------------- colatitude matrix stacks

    def _build_stack(self, build, rows, cols, row_off=None, col_off=None):
        """Assemble (G, rows, cols) stack from per-m builder
        `build(m) -> (r, c)`; `row_off(m)` / `col_off(m)` give the slot
        alignment offsets (None = 0, for grid/point dimensions)."""
        from ..tools.progress import log_progress
        ms = self.group_m()
        G = len(ms)
        out = np.zeros((G, rows, cols))
        for g, m in log_progress(list(enumerate(ms)), dt=10,
                                 desc=f"{type(self).__name__} stack group"):
            if self.complex and g == self.Nphi // 2:
                continue  # Nyquist
            if abs(m) > self.Lmax:
                continue  # no valid degrees at this m
            mat = build(int(m))
            if mat.size == 0:
                continue
            r0 = row_off(int(m)) if row_off else 0
            c0 = col_off(int(m)) if col_off else 0
            nr = min(mat.shape[0], rows - r0)
            nc = min(mat.shape[1], cols - c0)
            out[g, r0:r0 + nr, c0:c0 + nc] = mat[:nr, :nc]
        return out

    @CachedMethod
    def radial_forward_stack(self, s, scale=1.0):
        """(G, Ntheta, Ng): colatitude grid values -> aligned SWSH
        coefficients for spin s (reference: core/transforms.py:1252)."""
        Ng = self.sub_grid_size(1, scale)
        return self._build_stack(
            lambda m: swsh.forward_matrix(self.Lmax, m, s, Ng),
            self.Ntheta, Ng, row_off=lambda m: self._lmin(m, s))

    @CachedMethod
    def radial_backward_stack(self, s, scale=1.0):
        """(G, Ng, Ntheta): SWSH coefficients -> colatitude grid values."""
        Ng = self.sub_grid_size(1, scale)
        return self._build_stack(
            lambda m: swsh.backward_matrix(self.Lmax, m, s, Ng),
            Ng, self.Ntheta, col_off=lambda m: self._lmin(m, s))

    @CachedMethod
    def ladder_stack(self, s, ds):
        """(G, Ntheta, Ntheta): D_{ds} on spin-s components, in problem
        radius units (diagonal in l)."""
        return self._build_stack(
            lambda m: swsh.ladder_matrix(self.Lmax, m, s, ds) / self.radius,
            self.Ntheta, self.Ntheta,
            row_off=lambda m: self._lmin(m, s + ds),
            col_off=lambda m: self._lmin(m, s))

    @CachedMethod
    def laplacian_stack(self, s):
        """(G, Ntheta, Ntheta): spin-weighted Laplacian, diagonal with
        eigenvalues -(l(l+1) - s^2)/r^2."""
        ell = np.arange(self.Ntheta)
        eig = -(ell * (ell + 1) - s ** 2) / self.radius ** 2
        ms = self.group_m()
        out = np.zeros((len(ms), self.Ntheta, self.Ntheta))
        for g, m in enumerate(ms):
            if self.complex and g == self.Nphi // 2:
                continue
            lm = self._lmin(m, s)
            out[g, lm:, lm:] = np.diag(eig[lm:])
        return out

    @CachedMethod
    def cos_stack(self, s):
        """(G, Ntheta, Ntheta): multiplication by cos(theta) on spin-s
        components (tridiagonal in l; reference: SphereBasis MulCosine,
        core/operators.py:2695 SeparableSphereOperator)."""
        return self._build_stack(
            lambda m: swsh.cos_matrix(self.Lmax, m, s),
            self.Ntheta, self.Ntheta,
            row_off=lambda m: self._lmin(m, s),
            col_off=lambda m: self._lmin(m, s))

    @CachedMethod
    def sin_stack(self, s_out, s_in):
        """(G, Ntheta, Ntheta): multiplication by sin(theta) carrying
        spin-s_in components into the spin-s_out space (|ds| = 1; banded
        with |l_out - l_in| <= 1) — the spin-mixing half of meridional
        (ez-type) couplings."""
        return self._build_stack(
            lambda m: swsh.sin_matrix(self.Lmax, m, s_out, s_in),
            self.Ntheta, self.Ntheta,
            row_off=lambda m: self._lmin(m, s_out),
            col_off=lambda m: self._lmin(m, s_in))

    @CachedMethod
    def interpolation_stack(self, s, position):
        """(G, 1, Ntheta): evaluate spin-s components at colatitude
        `position`."""
        return self._build_stack(
            lambda m: swsh.interpolation_row(self.Lmax, m, s, position),
            1, self.Ntheta, col_off=lambda m: self._lmin(m, s))

    @CachedMethod
    def integration_row(self):
        """(1, Ntheta): integral against dz = sin(theta) dtheta for the
        (m=0, s=0) group, in problem units (x radius^2)."""
        z, w = swsh.quadrature(self.Lmax)
        Y = swsh.harmonics(self.Lmax, 0, 0, z)  # (Ntheta, Nz)
        row = (Y @ w)[None, :]
        return row * self.radius ** 2

    def constant_component_descr(self, sub_axis, device):
        """Descriptor embedding a constant into this basis along one of its
        axes (reference: core/basis.py constant-mode conversions)."""
        if sub_axis == 0:
            if device:
                col = np.zeros((self.Nphi, 1))
                col[0, 0] = 1.0
                return ("full", col)
            return ("blocks", self.azimuth_basis.constant_blocks())
        # colatitude: 1 = c * Y_00 with Y_00 the lowest harmonic
        Y00 = swsh.harmonics(self.Lmax, 0, 0, np.array([0.5]))[0, 0]
        col = np.zeros((self.Ntheta, 1))
        col[0, 0] = 1.0 / Y00
        return ("full", col)

    # ---------------------------------------------------- conversion terms

    def conversion_terms(self, target, tensorsig, tshape):
        """Sphere->sphere conversion is the identity (no k ladder)."""
        if not isinstance(target, SphereBasis) or target.shape != self.shape \
                or target.radius != self.radius:
            raise ValueError(f"No conversion from {self} to {target}.")
        return [(None, {})]


# ======================================================================
# Sphere-specific operators

from .polar import PolarSpinOperator  # noqa: E402 (cycle-safe)


class MulCosine(PolarSpinOperator):
    """
    Multiplication by cos(theta) — a sparse (tridiagonal-in-l) separable
    sphere operator usable on equation LHS, e.g. Coriolis terms
    zcross(u) = MulCosine(skew(u))
    (reference: core/operators.py:2695 SeparableSphereOperator; the sphere
    shallow-water example's zcross).
    """

    name = "MulCos"

    def __init__(self, operand, cs=None):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return MulCosine(new_args[0], self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        if not isinstance(basis, SphereBasis):
            raise ValueError("MulCosine requires a sphere basis.")
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az = basis.first_axis
        colat = az + 1
        spins = component_spins(operand.tensorsig, basis.cs)
        ncomp = len(spins)
        dim = operand.domain.dim
        terms = []
        for s in np.unique(spins):
            sel = np.diag((spins == s).astype(float)) if ncomp > 1 else None
            descrs = [None] * dim
            descrs[colat] = ("gblocks", az, basis.cos_stack(int(s)))
            terms.append((sel, descrs))
        return terms
