"""
Arithmetic expression nodes (reference: dedalus/core/arithmetic.py).

Add, Multiply, DotProduct, CrossProduct, Power. Grid-space products are
pointwise jnp ops (fused by XLA); LHS products with non-constant
coefficients (NCCs) assemble multiplication matrices by quadrature
(reference: core/arithmetic.py:257-585 Product/NCC pipeline, replaced here
by tools.jacobi.multiplication_matrix).
"""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from .field import Operand, Field
from .future import Future, ev
from .domain import Domain
from .basis import Jacobi
from ..tools.array import kron as sparse_kron, sparsify
from ..tools.exceptions import NonlinearOperatorError
from ..tools.general import is_complex_dtype

from .operators import (operand_expression_matrices, ConvertNode, Convert,
                        tensor_identity)


def _is_scalar(x):
    return np.isscalar(x) or (isinstance(x, np.ndarray) and x.ndim == 0)


def _max_basis(bases):
    out = None
    for b in bases:
        if b is None:
            continue
        if out is None:
            out = b
        elif isinstance(out, Jacobi) and isinstance(b, Jacobi):
            if (out.a0, out.b0, out.size, out.bounds) != (b.a0, b.b0, b.size, b.bounds):
                raise ValueError(f"Incompatible Jacobi bases: {out} vs {b}")
            if b.k > out.k:
                out = b
        elif type(out) is type(b) and hasattr(out, "family_key"):
            if out.family_key != b.family_key:
                raise ValueError(f"Incompatible bases: {out} vs {b}")
            if getattr(b, "k", 0) > getattr(out, "k", 0):
                out = b
        elif out != b:
            raise ValueError(f"Incompatible bases along axis: {out} vs {b}")
    return out


def _union_domain(dist, operands):
    dim = dist.dim
    bases = []
    for axis in range(dim):
        axis_bases = [op.domain.bases[axis] for op in operands
                      if isinstance(op, (Field, Future))]
        bases.append(_max_basis(axis_bases))
    return Domain(dist, tuple(bases))


def _product_domain(dist, operands):
    """
    Output domain of a product. On a coupled (Jacobi) axis where BOTH
    operands carry a basis, a true multiplication happens and the output
    lives at BASE derivative level — matching the NCC matrices
    (multiplication_matrix with dk_out=-k in ProductBase._ncc_axis_matrices).
    Where only one operand has the axis basis, the other is a scalar factor
    along that axis and the derivative level survives.
    """
    ops = [op for op in operands if isinstance(op, (Field, Future))]
    bases = []
    for axis in range(dist.dim):
        axis_bases = [op.domain.bases[axis] for op in ops
                      if op.domain.bases[axis] is not None]
        merged = _max_basis(axis_bases)
        if len(axis_bases) > 1 and isinstance(merged, Jacobi):
            merged = merged.base_basis()
        elif len(axis_bases) > 1 and getattr(merged, "k", 0) and hasattr(merged, "clone_with"):
            merged = merged.clone_with(k=0)
        bases.append(merged)
    return Domain(dist, tuple(bases))


def _promote_dtype(operands):
    dtypes = [op.dtype for op in operands if isinstance(op, (Field, Future))]
    dtypes += [np.asarray(op).dtype for op in operands if _is_scalar(op)]
    return np.result_type(*dtypes)


class Add(Future):
    """Addition (reference: core/arithmetic.py:50)."""

    name = "Add"
    natural_layout = "g"

    def __init__(self, *args):
        flat = []
        for a in args:
            if isinstance(a, Add):
                flat.extend(a.args)
            else:
                flat.append(a)
        super().__init__(*flat)

    def _build_metadata(self):
        operands = [a for a in self.args if isinstance(a, (Field, Future))]
        tensorsigs = {tuple(op.tensorsig) for op in operands}
        if len(tensorsigs) != 1:
            raise ValueError("Cannot add operands with different tensor signatures.")
        if any(_is_scalar(a) for a in self.args) and next(iter(tensorsigs)):
            raise ValueError("Cannot add scalars to tensor fields.")
        self.tensorsig = next(iter(tensorsigs))
        self.domain = _union_domain(self.dist, operands)
        self.dtype = _promote_dtype(self.args)

    def ev_impl(self, ctx):
        total = None
        for a in self.args:
            data = ev(a, ctx, "g") if isinstance(a, (Field, Future)) else a
            total = data if total is None else total + data
        return total

    def expression_matrices(self, subproblem, vars, **kw):
        out = {}
        for a in self.args:
            if _is_scalar(a):
                if a != 0:
                    raise NonlinearOperatorError("Nonzero constant on equation LHS.")
                continue
            term = a if tuple(a.domain.bases) == self.domain.bases else \
                ConvertNode(a, self.domain.bases)
            mats = operand_expression_matrices(term, subproblem, vars, **kw)
            for var, mat in mats.items():
                out[var] = out.get(var) + mat if var in out else mat
        return out

    def frechet_differential(self, variables, perturbations):
        # d(a + b) = da + db: the generic multilinear rule (rebuild with one
        # differentiated arg, siblings kept) would wrongly retain the
        # undifferentiated residual terms for a linear node.
        out = 0
        for a in self.args:
            if isinstance(a, (Field, Future)):
                d = a.frechet_differential(variables, perturbations)
                if not (_is_scalar(d) and d == 0):
                    out = out + d
        return out


class ScalarMultiply(Future):
    """Multiplication by a scalar constant: linear, layout-agnostic."""

    name = "ScalarMul"

    def __init__(self, scalar, operand):
        self.scalar = scalar
        super().__init__(operand)

    def rebuild(self, new_args):
        return ScalarMultiply(self.scalar, new_args[0])

    @property
    def operand(self):
        return self.args[0]

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = operand.tensorsig
        self.dtype = np.result_type(operand.dtype, np.asarray(self.scalar).dtype)

    def __repr__(self):
        return f"({self.scalar}*{self.args[0]})"

    def ev(self, ctx, layout):
        key = (id(self), layout)
        if key in ctx.memo:
            return ctx.memo[key]
        out = self.scalar * ev(self.operand, ctx, layout)
        ctx.memo[key] = out
        return out

    def expression_matrices(self, subproblem, vars, **kw):
        mats = operand_expression_matrices(self.operand, subproblem, vars, **kw)
        return {var: self.scalar * mat for var, mat in mats.items()}

    def frechet_differential(self, variables, perturbations):
        d = self.operand.frechet_differential(variables, perturbations)
        if _is_scalar(d) and d == 0:
            return 0
        return ScalarMultiply(self.scalar, d)


def Multiply(a, b):
    """Multiplication factory (reference: core/arithmetic.py:257 Product)."""
    if _is_scalar(a) and _is_scalar(b):
        return a * b
    if _is_scalar(a):
        if a == 0:
            return 0
        if a == 1:
            return b
        return ScalarMultiply(a, b)
    if _is_scalar(b):
        if b == 0:
            return 0
        if b == 1:
            return a
        return ScalarMultiply(b, a)
    return MultiplyFields(a, b)


def _filter_rel(mat, rel):
    """Drop entries of a sparse matrix below rel * max|entry|, REAL and
    IMAGINARY parts independently: source-precision residue often rides
    as a tiny real part on a large purely-imaginary coupling (or vice
    versa), and the azimuthal pair representation would otherwise spread
    it into spurious cross-pair couplings. (sparsify() passes sparse
    inputs through untouched, so totals need this explicit filter.)"""
    mat = mat.tocoo()
    if mat.nnz == 0:
        return mat.tocsr()
    cut = rel * np.abs(mat.data).max()
    if np.iscomplexobj(mat.data):
        re = np.where(np.abs(mat.data.real) >= cut, mat.data.real, 0.0)
        im = np.where(np.abs(mat.data.imag) >= cut, mat.data.imag, 0.0)
        data = re + 1j * im
    else:
        data = np.where(np.abs(mat.data) >= cut, mat.data, 0.0)
    keep = data != 0
    return sp.csr_matrix((data[keep], (mat.row[keep], mat.col[keep])),
                         shape=mat.shape)


def _interleave_gs(M, nout, nin, gs, X):
    """
    Lift a matrix over (component x X) index spaces to (component x gs x X)
    on the gs (azimuthal cos/sin pair) axis, matching the slot ordering
    component-major > pair > coupled axes. A real matrix acts identically
    on both pair slots (kron with I2); a complex matrix acts through its
    real 2x2 pair representation Re (x) I2 + Im (x) J — the same
    convention the transforms use for the spin recombination
    (curvilinear.real_pair_matrix).
    """
    if np.iscomplexobj(M.data if sp.issparse(M) else M):
        from .curvilinear import PAIR_J
        Mr = M.real
        Mi = M.imag
        K = (sp.kron(Mr, sp.identity(gs), format="csr")
             + sp.kron(Mi, sp.csr_matrix(PAIR_J), format="csr"))
    else:
        K = sp.kron(M, sp.identity(gs), format="csr")  # ordering (comp, X, j)

    def perm(ncomp):
        comp = np.repeat(np.arange(ncomp), gs * X)
        j = np.tile(np.repeat(np.arange(gs), X), ncomp)
        x = np.tile(np.arange(X), ncomp * gs)
        return comp * (X * gs) + x * gs + j

    return K[perm(nout)][:, perm(nin)]


class ProductBase(Future):
    """Shared NCC machinery for Multiply/Dot: grid-space products that become
    linear matrices when one side has no problem variables."""

    natural_layout = "g"

    def _split_ncc(self, vars, layout=None):
        """Return (ncc_side_index, ncc_field, operand_expr)."""

        def contains_vars(x):
            if _is_scalar(x):
                return False
            if isinstance(x, Field):
                return x in vars
            return x.has(*vars)

        has = [contains_vars(a) for a in self.args]
        if all(has):
            raise NonlinearOperatorError(
                f"Nonlinear term on LHS: {self!r} has variables on both sides.")
        if not any(has):
            raise NonlinearOperatorError(f"LHS term {self!r} contains no variables.")
        op_index = has.index(True)
        ncc_index = 1 - op_index
        ncc = self.args[ncc_index]
        if not isinstance(ncc, Field):
            ncc = ncc.evaluate()
        # NCCs must be constant along axes the LAYOUT keeps separable for
        # group-diagonality; axes the layout coupled (forced by this very
        # NCC, see subsystems._ncc_forced_coupled_axes) build full
        # multiplication matrices instead. Without layout context, fall
        # back to the conservative basis-level check.
        for axis, basis in enumerate(ncc.domain.bases):
            if basis is None or basis.dim != 1:
                # multi-dim curvilinear NCC bases validate angular
                # constancy in their own assembly paths
                continue
            separable = (axis in layout.sep_widths) if layout is not None \
                else basis.separable
            if separable:
                raise NonlinearOperatorError(
                    "LHS coefficient fields must be constant along separable axes.")
        return ncc_index, ncc, self.args[op_index]

    def _ncc_axis_terms(self, ncc, comp_index, operand):
        """
        [(scalar, descrs)] kron terms multiplying by ncc component
        `comp_index`. NCCs varying JOINTLY along several 1-D axes (e.g. a
        2-D background state U(x, z), reference:
        tests/test_cartesian_ncc.py:89 test_eval_fourier_jacobi_ncc)
        expand modally along the first varying axis — exact by linearity
        of the multiplication matrices in the NCC coefficients — with one
        kron term per significant mode (the reference reaches the same
        couplings through nested Clenshaw, core/arithmetic.py:406).
        """
        bases = list(ncc.domain.bases)
        if ncc.tensorsig and any(
                b is not None and b.dim in (2, 3)
                and hasattr(b, "radial_multiplication_matrix")
                for b in bases):
            raise NonlinearOperatorError(
                "Tensor-valued NCCs on curvilinear bases route through the "
                "spin/regularity assembly paths, not the per-axis path.")
        coeffs = np.asarray(ncc["c"])  # host transform of NCC data
        ccomp = coeffs[comp_index]
        # azimuthally-varying annulus NCC: per-azimuth-mode expansion into
        # (azimuth convolution) kron (radial multiplication) terms — valid
        # because the annulus radial space is m-independent. The SAME
        # classifier that forced the layout's m-coupling decides the route
        # (subsystems._ncc_forced_coupled_axes).
        for ax0, nb in enumerate(bases):
            if (nb is not None and nb.dim == 2
                    and hasattr(nb, "radial_multiplication_matrix")
                    and hasattr(nb, "azimuth_basis")
                    and ax0 == nb.first_axis
                    and ProductBase.polar_azimuth_varies(ncc, nb)):
                return self._polar_coupled_azimuth_terms(
                    ccomp, bases, operand, ax0)
        return self._ncc_axis_terms_from(ccomp, bases, operand)

    def _ncc_axis_terms_from(self, ccomp, bases, operand):
        """Recursive helper of `_ncc_axis_terms` operating on an explicit
        coefficient array and per-axis basis list."""
        one_d = [ax for ax in range(self.dist.dim)
                 if bases[ax] is not None and bases[ax].dim == 1
                 and ccomp.shape[ax] > 1]
        if len(one_d) < 2:
            return [self._ncc_axis_matrices_from(ccomp, bases, operand)]
        a1 = one_d[0]
        nb = bases[a1]
        ob = operand.domain.bases[a1]
        n1 = ccomp.shape[a1]
        tol = self._ncc_data_cutoff(ccomp) * max(np.abs(ccomp).max(), 1e-300)
        sub_bases = list(bases)
        sub_bases[a1] = None
        terms = []
        for j in range(n1):
            slice_j = np.take(ccomp, [j], axis=a1)
            if np.abs(slice_j).max() <= tol:
                continue
            e_j = np.zeros(n1)
            e_j[j] = 1.0
            if ob is None:
                descr_j = ("full", sparsify(e_j.reshape(-1, 1), 1e-13))
            elif isinstance(nb, Jacobi):
                descr_j = ("full", sparsify(
                    ob.multiplication_matrix(e_j, nb, dk_out=-ob.k), 1e-13))
            elif hasattr(nb, "multiplication_matrix") and nb.separable:
                descr_j = ("full", sparsify(
                    ob.multiplication_matrix(e_j, nb), 1e-13))
            else:
                raise NonlinearOperatorError(
                    f"LHS NCCs may not vary along basis {nb!r}.")
            for scalar, descrs in self._ncc_axis_terms_from(
                    slice_j, sub_bases, operand):
                descrs = list(descrs)
                descrs[a1] = descr_j
                terms.append((scalar, descrs))
        return terms

    def _polar_coupled_azimuth_terms(self, ccomp, bases, operand, ax0):
        """Kron terms of an azimuthally-VARYING annulus NCC (scalar data;
        reference: the geometry-generic NCC pipeline admits phi-dependent
        polar NCCs, dedalus/core/arithmetic.py:359-406): one term per
        significant azimuth mode j,

            (azimuth convolution of mode j) kron (radial mult of f_j(r)),

        assembled onto the layout-COUPLED azimuth axis (whole-axis
        convolution matrices, like Fourier-coupled Cartesian NCCs). The
        annulus radial space is m-independent, so the radial factor is a
        single multiplication matrix per mode. Disk NCCs (m-dependent
        Zernike spaces) route through _disk_ncc_matrix instead."""
        nb = bases[ax0]
        r_axis = ax0 + 1
        ob_pol = operand.domain.bases[ax0]
        if ob_pol is None or not hasattr(ob_pol, "azimuth_basis"):
            raise NonlinearOperatorError(
                "Azimuthally-varying polar NCCs require the operand on a "
                "polar basis too.")
        # Real-dtype TENSOR operands store spin-recombined (cos, -sin)
        # pairs; the recombination does NOT commute with the azimuth
        # convolution (reflection-type fold blocks anti-commute with the
        # pair-J), so a spin-diagonal convolution would be wrong. The
        # dtype-generic route conjugates the coordinate-component
        # convolution by the stored recombination W = Re(U) (x) I2 +
        # Im(U) (x) J (curvilinear.real_pair_matrix structure), which
        # expands each azimuth mode's term into at most four kron terms
        # with component-MIXING tensor factors:
        #   W_out (I_c (x) A (x) R) W_in^dagger
        #     =   Re(Uo)Re(Ui)^T (x) A        (x) R
        #       - Re(Uo)Im(Ui)^T (x) A Jz_in  (x) R
        #       + Im(Uo)Re(Ui)^T (x) Jz_out A (x) R
        #       - Im(Uo)Im(Ui)^T (x) Jz_out A Jz_in (x) R
        # with Jz = I_groups (x) PAIR_J acting on the whole interleaved
        # azimuth axis. Scalar operands (U = 1) reduce to the single
        # real term; complex dtypes keep the spin-diagonal fast path.
        real_tensor = bool(operand.tensorsig) \
            and not is_complex_dtype(operand.dtype)
        mixers = [(None, 0, 0)]
        if real_tensor:
            from .curvilinear import recombination_matrix, PAIR_J
            cs = nb.cs
            Uo = recombination_matrix(tuple(self.tensorsig), cs)
            Ui = recombination_matrix(tuple(operand.tensorsig), cs)
            mixers = [
                (Uo.real @ Ui.real.T, 0, 0),
                (-(Uo.real @ Ui.imag.T), 0, 1),
                (Uo.imag @ Ui.real.T, 1, 0),
                (-(Uo.imag @ Ui.imag.T), 1, 1),
            ]
        moved = np.moveaxis(ccomp, (ax0, r_axis), (0, 1))
        if moved.size != moved.shape[0] * moved.shape[1]:
            raise NonlinearOperatorError(
                "Azimuthally-varying polar NCCs may not vary along "
                "additional axes.")
        az_r = moved.reshape(moved.shape[0], moved.shape[1])
        tol = self._ncc_data_cutoff(az_r) * max(np.abs(az_r).max(), 1e-300)
        dim = self.dist.dim
        terms = []
        for j in range(az_r.shape[0]):
            prof = az_r[j]
            if np.abs(prof).max() <= tol:
                continue
            e_j = np.zeros(ccomp.shape[ax0], dtype=az_r.dtype)
            e_j[j] = 1.0
            A = ob_pol.azimuth_basis.multiplication_matrix(
                e_j, nb.azimuth_basis)
            A = sp.csr_matrix(A)
            R = ob_pol.radial_multiplication_matrix(prof, nb.k, k_out=0)
            cut = self._ncc_sparsify_cutoff(prof)
            R = sparsify(R, cut)
            for mix, left_j, right_j in mixers:
                if mix is not None and np.abs(mix).max() < 1e-14:
                    continue
                Ax = A
                if right_j:
                    Jz = sp.kron(sp.identity(A.shape[1] // 2), PAIR_J,
                                 format="csr")
                    Ax = Ax @ Jz
                if left_j:
                    Jz = sp.kron(sp.identity(A.shape[0] // 2), PAIR_J,
                                 format="csr")
                    Ax = Jz @ Ax
                descrs = [None] * dim
                descrs[ax0] = ("full", sparsify(Ax, 1e-14))
                descrs[r_axis] = ("full", R)
                terms.append((mix, descrs))
        if not terms:
            descrs = [None] * dim
            descrs[ax0] = ("full", sp.csr_matrix(
                (ccomp.shape[ax0], ccomp.shape[ax0])))
            terms.append((None, descrs))
        return terms

    def _ncc_axis_matrices_from(self, ccomp, ncc_bases, operand):
        """Per-axis matrices for a single-varying-axis coefficient array
        (`ncc_bases`: the NCC's per-axis basis list, None = constant)."""
        dist = self.dist
        descrs = []
        axis = 0
        while axis < dist.dim:
            nb = ncc_bases[axis]
            ob = operand.domain.bases[axis]
            if nb is None:
                descrs.append(None)  # constant along axis: scalar handled below
                axis += 1
            elif isinstance(nb, Jacobi):
                # collapse other axes of the coefficient array
                ax_coeffs = np.moveaxis(ccomp, axis, -1)
                assert ax_coeffs.size == ax_coeffs.shape[-1], \
                    "NCCs coupling multiple axes are not supported yet."
                cut = self._ncc_sparsify_cutoff(ax_coeffs)
                if ob is None:
                    # operand constant along axis: column embedding the NCC
                    descrs.append(("full", sparsify(ax_coeffs.reshape(-1, 1),
                                                    cut)))
                else:
                    M = ob.multiplication_matrix(ax_coeffs.ravel(), nb, dk_out=-ob.k)
                    descrs.append(("full", sparsify(M, cut)))
                axis += 1
            elif nb.dim in (2, 3) and hasattr(nb, "radial_multiplication_matrix"):
                # Angularly-constant NCC over a polar/spherical basis:
                # identity on the angular axes (m=0 [, ell=0] only), a radial
                # multiplication matrix on the coupled axis (reference:
                # coupled-only NCC requirement, core/arithmetic.py:359).
                # (Tensor-valued curvilinear NCCs route through the spin/
                # regularity paths before reaching here.)
                r_axis = axis + nb.dim - 1
                moved = np.moveaxis(ccomp, r_axis, -1)
                tol = 1e-10 * max(np.abs(ccomp).max(), 1e-300)
                non_const = moved.reshape(-1, moved.shape[-1])[1:]
                if non_const.size and np.abs(non_const).max() > tol:
                    raise NonlinearOperatorError(
                        "LHS coefficient fields on curvilinear bases must be "
                        "angularly constant (lowest angular mode only).")
                radial_coeffs = moved.reshape(-1, moved.shape[-1])[0] \
                    * getattr(nb, "constant_angular_mode_value", 1.0)
                if ob is None:
                    raise NonlinearOperatorError(
                        "Embedding a curvilinear NCC into a constant operand "
                        "is not supported yet.")
                M = ob.radial_multiplication_matrix(radial_coeffs, nb.k, k_out=0)
                descrs.extend([None] * (nb.dim - 1))  # angular identities
                descrs.append(("full", sparsify(
                    M, self._ncc_sparsify_cutoff(radial_coeffs))))
                axis += nb.dim
            elif hasattr(nb, "multiplication_matrix") and nb.separable:
                # Fourier-type NCC on a layout-coupled periodic axis:
                # whole-axis convolution matrix (reference: non-separable
                # Fourier-NCC subproblems, e.g. the Mathieu example)
                ax_coeffs = np.moveaxis(ccomp, axis, -1)
                assert ax_coeffs.size == ax_coeffs.shape[-1], \
                    "NCCs coupling multiple axes are not supported yet."
                cut = self._ncc_sparsify_cutoff(ax_coeffs)
                if ob is None:
                    descrs.append(("full", sparsify(ax_coeffs.reshape(-1, 1),
                                                    cut)))
                else:
                    M = ob.multiplication_matrix(ax_coeffs.ravel(), nb)
                    descrs.append(("full", sparsify(M, cut)))
                axis += 1
            else:
                raise NonlinearOperatorError(
                    f"LHS NCCs may not vary along basis {nb!r}.")
        # fully-constant NCC: scalar multiplier
        if all(d is None for d in descrs):
            scalar = complex(ccomp.ravel()[0]) if np.iscomplexobj(ccomp) else float(ccomp.ravel()[0])
            return scalar, descrs
        return None, descrs

    def _spherical_regularity_basis(self, operand):
        for b in operand.domain.bases:
            if b is not None and getattr(b, "regularity", False):
                return b
        return None

    def _polar_spin_basis(self, operand):
        from .curvilinear import SpinBasisMixin
        from .sphere import SphereBasis
        for b in operand.domain.bases:
            if (b is not None and b.dim == 2 and isinstance(b, SpinBasisMixin)
                    and not isinstance(b, SphereBasis)
                    and not getattr(b, "regularity", False)):
                return b
        return None

    def _s2_basis(self, operand):
        from .sphere import SphereBasis
        for b in operand.domain.bases:
            if isinstance(b, SphereBasis):
                return b
        return None

    def _disk_ncc_matrix(self, subproblem, ncc, operand, place_fn):
        """
        Pencil matrix of an angularly-constant NCC on the DISK (scalar or
        tensor valued; e.g. the pipe-flow example's w0*dz(u) advection and
        u@grad(w0) terms). Zernike radial spaces are (m, spin)-dependent,
        so each coordinate component c of the NCC contributes per-m radial
        stacks bracketed by the spin coupling C = U_out P_c U_in^H:

            term(c, i, j) = C_ij * F_out(s_i)[m] diag(f_c) B_in(s_j)[m]

        assembled through ("gblocks", az, stack) descriptors. Profiles are
        sampled on the 2x radial quadrature grid through the field's own
        transforms (spin-envelope-faithful), making the product projection
        exact for resolved data. `place_fn(c)` gives the coordinate-space
        component placement (outer product or contraction).
        """
        from .curvilinear import (recombination_matrix, real_pair_matrix,
                                  component_spins, PAIR_J)
        from .operators import _axis_identity, assemble_group_matrix
        nb = self._polar_spin_basis(ncc)
        ob = self._polar_spin_basis(operand)
        if ob is None:
            raise NonlinearOperatorError(
                "Disk NCCs require the operand on the disk basis too.")
        cs = nb.cs
        az_axis = nb.first_axis
        r_axis = az_axis + 1
        dim = self.dist.dim
        # profiles on the 2x quadrature grid, via the field's transforms
        old_scales = ncc.scales
        ncc.change_scales(2)
        grid = np.asarray(ncc["g"])
        ncc.change_scales(old_scales)
        tdim_n = len(ncc.tensorsig)
        ncomp_n = int(np.prod(ncc.tshape, dtype=int)) if ncc.tshape else 1
        flat = grid.reshape((ncomp_n,) + grid.shape[tdim_n:])
        tol = 1e-10 * max(np.abs(flat).max(), 1e-300)
        moved = np.moveaxis(flat, 1 + az_axis, 1)
        if ProductBase.polar_azimuth_varies(ncc, nb):
            # azimuthally varying by the SAME classifier that forced the
            # layout's m-coupling (subsystems._ncc_forced_coupled_axes):
            # cross-m assembly onto the coupled pencil
            if subproblem.group[az_axis] is not None:
                raise NonlinearOperatorError(
                    "Azimuthally-varying disk NCC reached a per-m pencil; "
                    "the layout classifier should have coupled azimuth.")
            return self._disk_coupled_ncc_matrix(subproblem, ncc, operand,
                                                 moved)
        if np.abs(moved - moved[:, :1]).max() > tol:
            raise NonlinearOperatorError(
                "LHS NCCs on disk bases must be angularly constant "
                "(sub-classifier azimuthal content at the data's own "
                "precision is treated as roundoff).")
        profiles = moved[:, 0].reshape(ncomp_n, -1)   # (ncomp_n, Ngr2)
        U_in = recombination_matrix(tuple(operand.tensorsig), cs)
        U_out = recombination_matrix(tuple(self.tensorsig), cs)
        s_in = component_spins(tuple(operand.tensorsig), cs)
        s_out = component_spins(tuple(self.tensorsig), cs)
        real = not is_complex_dtype(self.dtype)
        out_basis = self.domain.bases[az_axis]
        terms = []
        nonzero = [c for c in range(ncomp_n)
                   if np.abs(profiles[c]).max() > tol]
        for c in (nonzero or [0]):
            prof = profiles[c]
            C = U_out @ place_fn(c) @ U_in.conj().T
            for i in range(C.shape[0]):
                for j in range(C.shape[1]):
                    if abs(C[i, j]) < 1e-14 and nonzero:
                        continue
                    F = out_basis.radial_forward_stack(int(s_out[i]), 2.0)
                    B = ob.radial_backward_stack(int(s_in[j]), 2.0)
                    stack = np.einsum("gnr,r,grk->gnk", F, prof, B)
                    E = np.zeros((C.shape[0], C.shape[1]))
                    E[i, j] = 1.0
                    descrs = [None] * dim
                    if real:
                        az2 = (np.eye(2) * C[i, j].real
                               + PAIR_J * C[i, j].imag)
                        descrs[az_axis] = ("full", sparsify(az2, 1e-14))
                    else:
                        descrs[az_axis] = ("full", sp.csr_matrix(
                            np.array([[C[i, j]]])))
                    descrs[r_axis] = ("gblocks", az_axis, stack)
                    terms.append((E, descrs))
        return assemble_group_matrix(terms, operand.domain, operand.tshape,
                                     self.tshape, subproblem)

    def _disk_coupled_ncc_matrix(self, subproblem, ncc, operand, moved):
        """
        m-COUPLED pencil matrix of an azimuthally-varying DISK NCC
        (scalar data; reference: the geometry-generic NCC pipeline,
        dedalus/core/arithmetic.py:359-406, whose polar tests are
        axisymmetric). The NCC expands into azimuth modes j with radial
        2x-quadrature profiles f_j(r); each mode contributes, per operand
        spin component s,

            A_j[slots(m_out), slots(m_in)] (x) F_s[m_out] diag(f_j) B_s[m_in]

        with A_j the whole-axis azimuth convolution of basis mode j and
        F/B the per-m Zernike quadrature stacks (the radial spaces are
        m-dependent, so every coupled (m_out, m_in) pair gets its own
        radial block). Scalar NCCs only. Real-dtype TENSOR operands route
        through the stored-pair conjugation (the real spin-pair
        recombination does not commute with the azimuth convolution):
        each 2x2 azimuth pair block az2 carries the component-mixing
        combination C1 az2 + C2 az2 J + C3 J az2 + C4 J az2 J with
        Ck the Re/Im products of the spin recombinations — the disk
        analogue of the annulus kron-term expansion
        (_polar_coupled_azimuth_terms), with per-(m, spin) radial blocks.
        """
        from .curvilinear import component_spins
        nb = self._polar_spin_basis(ncc)
        ob = self._polar_spin_basis(operand)
        if ncc.tensorsig:
            raise NonlinearOperatorError(
                "Azimuthally-varying disk NCCs must be scalar fields; "
                "move tensor-valued azimuthal backgrounds to the RHS.")
        real = not is_complex_dtype(self.dtype)
        az_axis = nb.first_axis
        out_basis = self.domain.bases[az_axis]
        prof = moved[0].reshape(moved.shape[1], -1)       # (Ng_az, Ngr)
        # azimuth-mode expansion through the NCC basis's own forward MMT
        Af = np.asarray(nb.azimuth_basis._mult_forward_matrix(prof.shape[0]))
        modes = Af @ prof                                  # (Naz_ncc, Ngr)
        tol = (self._ncc_data_cutoff(modes)
               * max(np.abs(modes).max(), 1e-300))
        gs = ob.sub_group_shape(0)
        G = ob.sub_n_groups(0)
        Nr = ob.Nr
        cs = ob.cs
        s_in = component_spins(tuple(operand.tensorsig), cs) \
            if operand.tensorsig else np.zeros(1, dtype=int)
        ncomp = len(s_in)
        naz = G * gs
        dtype = complex if (not real) else float
        # azimuth convolutions are spin-independent: build once per mode
        conv = []                                    # [(j, A_j)]
        for j in range(modes.shape[0]):
            if np.abs(modes[j]).max() <= tol:
                continue
            e_j = np.zeros(nb.shape[0])
            e_j[j] = 1.0
            A_j = ob.azimuth_basis.multiplication_matrix(
                e_j, nb.azimuth_basis)
            conv.append((j, np.asarray(
                A_j.todense() if sp.issparse(A_j) else A_j)))
        if real:
            # stored-pair conjugation (docstring): component-mixing 2x2
            # azimuth blocks with per-(m, spin-pair) radial blocks. The
            # scalar-operand case reduces to C1 = 1 (K = az2), i.e. the
            # plain pair convolution.
            from .curvilinear import recombination_matrix, PAIR_J
            Uo = recombination_matrix(tuple(self.tensorsig), cs)
            Ui = recombination_matrix(tuple(operand.tensorsig), cs)
            s_out = component_spins(tuple(self.tensorsig), cs) \
                if self.tensorsig else np.zeros(1, dtype=int)
            Cs = [Uo.real @ Ui.real.T, -(Uo.real @ Ui.imag.T),
                  Uo.imag @ Ui.real.T, -(Uo.imag @ Ui.imag.T)]
            ncomp_out = len(s_out)
            J = PAIR_J
            F = {int(s): np.asarray(out_basis.radial_forward_stack(int(s),
                                                                   2.0))
                 for s in set(int(v) for v in s_out)}
            B = {int(s): np.asarray(ob.radial_backward_stack(int(s), 2.0))
                 for s in set(int(v) for v in s_in)}
            M = np.zeros((ncomp_out * naz * Nr, ncomp * naz * Nr))
            for j, A_j in conv:
                prof_j = modes[j]
                for ci in range(ncomp_out):
                    Fi = F[int(s_out[ci])]
                    for cj in range(ncomp):
                        cvals = [Ck[ci, cj] for Ck in Cs]
                        if max(abs(v) for v in cvals) < 1e-14:
                            continue
                        Bj = B[int(s_in[cj])]
                        r0 = ci * naz * Nr
                        c0 = cj * naz * Nr
                        for go in range(G):
                            Rrow = None
                            for gi in range(G):
                                az2 = A_j[go * gs:(go + 1) * gs,
                                          gi * gs:(gi + 1) * gs]
                                K = (cvals[0] * az2 + cvals[1] * (az2 @ J)
                                     + cvals[2] * (J @ az2)
                                     + cvals[3] * (J @ az2 @ J))
                                if np.abs(K).max() < 1e-14:
                                    continue
                                if Rrow is None:
                                    Rrow = Fi[go] * prof_j[None, :]
                                R = Rrow @ Bj[gi]          # (Nr, Nr)
                                M[r0 + go * gs * Nr:r0 + (go + 1) * gs * Nr,
                                  c0 + gi * gs * Nr:
                                  c0 + (gi + 1) * gs * Nr] += np.kron(K, R)
            return sp.csr_matrix(sparsify(M, 1e-14))
        spin_mats = {}
        for s in sorted(set(int(v) for v in s_in)):
            F = np.asarray(out_basis.radial_forward_stack(s, 2.0))
            B = np.asarray(ob.radial_backward_stack(s, 2.0))
            M = np.zeros((naz * Nr, naz * Nr), dtype=dtype)
            for j, A_j in conv:
                prof_j = modes[j]
                for go in range(G):
                    Rrow = None
                    for gi in range(G):
                        az2 = A_j[go * gs:(go + 1) * gs,
                                  gi * gs:(gi + 1) * gs]
                        if np.abs(az2).max() < 1e-14:
                            continue
                        if Rrow is None:
                            Rrow = F[go] * prof_j[None, :]
                        R = Rrow @ B[gi]                   # (Nr, Nr)
                        blk = np.kron(az2, R)
                        M[go * gs * Nr:(go + 1) * gs * Nr,
                          gi * gs * Nr:(gi + 1) * gs * Nr] += blk
            spin_mats[s] = sparsify(M, 1e-14)
        # component-diagonal (scalar NCC): block-diagonal over components
        return sp.csr_matrix(sp.block_diag(
            [spin_mats[int(s_in[c])] for c in range(ncomp)], format="csr"))

    def _polar_tensor_ncc_matrix(self, subproblem, ncc, operand, ncc_index):
        """
        Pencil matrix of a tensor-valued, angularly-constant polar NCC
        (e.g. the annulus example's radial-vector gravity b*g and
        rvec*lift(tau) terms; reference handles these via the Clenshaw
        tensor-NCC pipeline, core/arithmetic.py:359-558).

        The polar spin recombination U is m-independent, so each NCC
        COORDINATE component c with radial profile f_c(r) contributes
            (U_out P_c U_in^H)  (x)  angular-identity  (x)  RadialMult(f_c)
        with P_c placing component c in the coordinate component space.
        Real dtypes apply the complex component coupling jointly on the
        interleaved (cos, -sin) azimuth pair (real_pair_matrix).
        """
        from .curvilinear import recombination_matrix, real_pair_matrix
        from .operators import _axis_identity
        nb = self._polar_spin_basis(ncc)
        ob = self._polar_spin_basis(operand)
        if ob is None or not hasattr(ob, "radial_multiplication_matrix"):
            raise NonlinearOperatorError(
                "Tensor-valued polar NCCs require annulus bases on both "
                "factors (disk regularity spaces are not supported yet).")
        cs = nb.cs
        az_axis = nb.first_axis
        r_axis = az_axis + 1
        # angular constancy check on coordinate-component grid data, read
        # at scale 1 to match the radial forward matrix below
        old_scales = ncc.scales
        ncc.change_scales(1)
        grid = np.asarray(ncc["g"])
        ncc.change_scales(old_scales)
        ncomp_n = int(np.prod(ncc.tshape, dtype=int)) if ncc.tshape else 1
        flat = grid.reshape((ncomp_n,) + grid.shape[len(ncc.tshape):])
        tol = 1e-10 * max(np.abs(flat).max(), 1e-300)
        moved = np.moveaxis(flat, 1 + az_axis, 1)
        if np.abs(moved - moved[:, :1]).max() > tol:
            raise NonlinearOperatorError(
                "LHS tensor NCCs on polar bases must be angularly constant.")
        profiles = moved[:, 0].reshape(ncomp_n, -1)  # (ncomp_n, Nr)
        # radial coefficients of each component profile at the NCC's level
        fwd = np.asarray(nb._radial_forward_matrix(1.0))
        # intertwiner sandwich pieces
        U_in = recombination_matrix(tuple(operand.tensorsig), cs)
        out_tsig = (tuple(ncc.tensorsig) + tuple(operand.tensorsig)
                    if ncc_index == 0
                    else tuple(operand.tensorsig) + tuple(ncc.tensorsig))
        U_out = recombination_matrix(out_tsig, cs)
        ncomp_op = U_in.shape[0]
        real = not is_complex_dtype(self.dtype)
        dim = self.dist.dim
        sep_widths = subproblem.layout.sep_widths
        nonzero = [c for c in range(ncomp_n)
                   if np.abs(profiles[c]).max() > tol]
        total = None
        for c in (nonzero or [0]):     # all-zero NCC: one zero term (shape)
            f_coeffs = fwd @ profiles[c]
            R = sparsify(ob.radial_multiplication_matrix(f_coeffs, nb.k,
                                                         k_out=0), 1e-12)
            P_c = np.zeros((ncomp_n, 1))
            P_c[c, 0] = 1.0
            place = (np.kron(P_c, np.eye(ncomp_op)) if ncc_index == 0
                     else np.kron(np.eye(ncomp_op), P_c))
            C = U_out @ place @ U_in.conj().T
            if real:
                # joint (component, azimuth-pair) real representation; the
                # azimuth slot IS the (cos, -sin) pair (group_shape == 2),
                # so the pair action is absorbed into the leading factor.
                # That leading placement is only the azimuth-pair position
                # when no wider axis precedes the annulus in the pencil
                # ordering (width-1 leading identities are scalars and
                # commute through the kron).
                wide = [ax for ax in range(az_axis)
                        if sep_widths.get(ax, 1) != 1]
                if wide:
                    raise NonlinearOperatorError(
                        "Tensor-valued polar NCCs with real dtype require "
                        "the annulus azimuth to lead the pencil ordering "
                        f"(axes {wide} precede it with width > 1).")
                T = sp.csr_matrix(real_pair_matrix(C))
            else:
                T = sp.csr_matrix(C)
            factors = [T]
            for axis in range(dim):
                basisx = operand.domain.bases[axis]
                if axis == az_axis:
                    if not real:
                        factors.append(sp.identity(1, format="csr"))
                elif axis == r_axis:
                    factors.append(R)
                else:
                    sub = 0 if basisx is None else axis - basisx.first_axis
                    factors.append(_axis_identity(basisx,
                                                  sep_widths.get(axis), sub))
            mat = sparse_kron(*factors)
            total = mat if total is None else total + mat
        return total

    # ---------------------------------------------- bilinear component maps

    def _coord_bilinear_map(self, ncc, operand, ncc_index):
        """
        T_coord (ncomp_out, ncomp_ncc, ncomp_operand): the product's
        bilinear map over flattened COORDINATE tensor components,
        out_c = sum_{a,b} T[c, a, b] ncc_a operand_b. Defined per product
        class (outer product, contraction, Levi-Civita)."""
        raise NotImplementedError

    def _spin_bilinear_map(self, ncc, operand, ncc_index):
        """
        T_spin: the same bilinear map conjugated into SPIN components by the
        unitary coordinate->spin recombinations U (out = U_out T_coord
        (U_ncc^H x U_op^H)). Pointwise products conserve total spin, so
        T_spin[c, a, b] != 0 only when s_out[c] = s_ncc[a] + s_op[b]
        (asserted numerically; used as the selection rule downstream).
        """
        from .curvilinear import recombination_matrix
        T = np.asarray(self._coord_bilinear_map(ncc, operand, ncc_index),
                       dtype=complex)
        U_n = recombination_matrix(tuple(ncc.tensorsig), self._sph_cs(operand))
        U_o = recombination_matrix(tuple(operand.tensorsig),
                                   self._sph_cs(operand))
        U_out = recombination_matrix(tuple(self.tensorsig),
                                     self._sph_cs(operand))
        T_spin = np.einsum("cC,Cab,Aa,Bb->cAB", U_out, T,
                           np.conj(U_n), np.conj(U_o))
        T_spin[np.abs(T_spin) < 1e-13] = 0.0
        return T_spin

    def _sph_cs(self, operand):
        basis = self._spherical_regularity_basis(operand)
        if basis is None:
            basis = self._s2_basis(operand)
        return basis.cs

    def _sph_ncc_setup(self, ncc, operand, ncc_index):
        """
        Validate a radially-directed, angularly-constant spherical NCC and
        return its assembly context (operand basis, NCC basis, radial
        profile coefficients, ranks, per-sweep cache).
        """
        from .spherical3d import spherical_rank
        basis = self._spherical_regularity_basis(operand)
        ncc_basis = self._spherical_regularity_basis(ncc)
        if basis is None or ncc_basis is None:
            raise NonlinearOperatorError(
                "Curvilinear NCCs require shell/ball bases on both factors.")
        rank_n = spherical_rank(ncc.tensorsig, basis.cs)
        rank_in = spherical_rank(operand.tensorsig, basis.cs)
        ncomp_n = 3 ** rank_n
        radial_flat = ncomp_n - 1  # flat index of (2, ..., 2)
        # Cache radial multiplication stacks across groups of ONE assembly
        # sweep, invalidated when any field feeding the NCC changes (NLBVP
        # Jacobian rebuilds re-evaluate the NCC around the moving state;
        # a stale cache froze the Newton iteration's Jacobian).
        ncc_src = self.args[ncc_index]
        if isinstance(ncc_src, Field):
            version = ((id(ncc_src), ncc_src._version),)
        else:
            version = tuple(sorted((id(a), a._version)
                                   for a in ncc_src.atoms(Field)))
        cache = getattr(self, "_sph_ncc_cache", None)
        if cache is not None and cache.get("version") != version:
            cache = None
        if cache is None:
            # Validate: only the all-radial component, angularly constant.
            grid = np.asarray(ncc["g"])
            flat = grid.reshape((ncomp_n,) + grid.shape[rank_n:])
            tol = self._ncc_data_cutoff(flat) * max(np.abs(flat).max(),
                                                    1e-300)
            for c in range(ncomp_n):
                if c != radial_flat and np.abs(flat[c]).max() > tol:
                    raise NonlinearOperatorError(
                        "LHS tensor NCCs on spherical bases must have only "
                        "radial components (e.g. f(r)*er).")
            profile = flat[radial_flat]
            if np.abs(profile - profile[:1, :1, :]).max() > tol:
                raise NonlinearOperatorError(
                    "LHS NCCs on spherical bases must be angularly constant.")
            profile_coeffs = ncc_basis.scalar_radial_coeffs(profile[0, 0],
                                                            l_env=rank_n)
            cache = self._sph_ncc_cache = {"coeffs": profile_coeffs,
                                           "version": version}
        return {"basis": basis, "ncc_basis": ncc_basis, "cache": cache,
                "sparsify_cutoff":
                    self._ncc_sparsify_cutoff(np.dtype(ncc.dtype)),
                "rank_n": rank_n, "rank_in": rank_in,
                "rank_out": spherical_rank(self.tensorsig, basis.cs),
                "T_spin": self._spin_bilinear_map(ncc, operand, ncc_index),
                "radial_flat": radial_flat, "ncc_index": ncc_index}

    def _sph_ncc_pairs(self, setup, ell):
        """
        [(i, j, C_ij, M_ij)] for one ell: the Q-intertwined component
        coupling C = Q_out^T P Q_in (P = the product's spin bilinear map
        contracted against the radial NCC slot, so Multiply/Dot/Cross all
        route through here) and per-(ell, regularity) radial multiplication
        matrices.
        """
        from .spherical3d import q_stack, reg_totals
        basis = setup["basis"]
        cache = setup["cache"]
        rank_n, rank_in = setup["rank_n"], setup["rank_in"]
        rank_out = setup["rank_out"]
        ncomp_in = 3 ** rank_in
        P = setup["T_spin"][:, setup["radial_flat"], :]
        if np.abs(P.imag).max() < 1e-13:
            P = P.real
        totals_in = reg_totals(rank_in)
        totals_out = reg_totals(rank_out)
        Q_in = q_stack(basis.Ntheta, rank_in)[ell]
        Q_out = q_stack(basis.Ntheta, rank_out)[ell]
        C = Q_out.T @ P @ Q_in
        out = []
        for i in range(3 ** rank_out):
            for j in range(ncomp_in):
                if abs(C[i, j]) < 1e-12:
                    continue
                key = (int(totals_in[j]), int(totals_out[i]), int(ell))
                M = cache.get(key)
                if M is None:
                    M = sparsify(basis.ncc_radial_matrix(
                        cache["coeffs"], setup["ncc_basis"].k, totals_in[j],
                        totals_out[i], ell, k_out=0, l_env=rank_n),
                        setup["sparsify_cutoff"])
                    cache[key] = M
                out.append((i, j, C[i, j], M))
        return out

    def _spherical_ncc_matrix(self, subproblem, ncc, operand, ncc_index):
        """
        Pencil matrix for multiplication by a radially-directed,
        angularly-constant NCC (f(r), f(r)*er, f(r)*er*er, ...) over a
        shell/ball basis: per-(m, ell) group, the Q-intertwined component
        coupling kron'd with per-(ell, regularity) radial multiplication
        matrices (reference: core/arithmetic.py:559 Gamma machinery +
        core/basis.py:4101 ball NCC matrices, restricted to the radial-NCC
        case used by the shell/ball examples).
        """
        layout = subproblem.layout
        pre_basis = self._spherical_regularity_basis(operand)
        colat_axis = pre_basis.first_axis + 1
        if subproblem.group[colat_axis] is None:
            # layout-coupled colatitude (theta-dependent NCC somewhere in
            # the problem): ell-coupled assembly
            return self._sph_coupled_ncc_matrix(subproblem, ncc, operand,
                                                ncc_index)
        setup = self._sph_ncc_setup(ncc, operand, ncc_index)
        basis = setup["basis"]
        az_axis = basis.first_axis
        ell = subproblem.group[colat_axis]
        ncomp_in = 3 ** setup["rank_in"]
        rank_out = setup["rank_out"]
        gs = layout.sep_widths[az_axis]
        I_gs = sp.identity(gs, format="csr")
        Nr = basis.Nr
        total = sp.csr_matrix((3 ** rank_out * gs * Nr, ncomp_in * gs * Nr))
        for i, j, Cij, M in self._sph_ncc_pairs(setup, ell):
            sel = sp.csr_matrix(
                (np.ones(1), ([i], [j])), shape=(3 ** rank_out, ncomp_in))
            total = total + Cij * sparse_kron(sel, I_gs, M)
        return total

    NCC_ANGULAR_CUTOFF = 1e-10

    @staticmethod
    def _ncc_real_eps(arr_or_dtype):
        """Machine epsilon of the SOURCE data precision. Accepts an array
        or a dtype; complex dtypes resolve to their real component. The
        source dtype matters because expansions get promoted to f64/c128
        on the host — the promotion launders the f32-level roundoff that
        the cutoffs must track."""
        if isinstance(arr_or_dtype, (np.dtype, type)):
            dt = np.dtype(arr_or_dtype)
        else:
            dt = np.asarray(arr_or_dtype).dtype
        dt = np.dtype(dt)
        if dt.kind == "c":
            dt = np.dtype(np.float32) if dt.itemsize == 8                 else np.dtype(np.float64)
        return np.finfo(dt).eps if dt.kind == "f" else 0.0

    @staticmethod
    def _ncc_sparsify_cutoff(arr_or_dtype):
        """Relative sparsify threshold for matrices BUILT from NCC data:
        f32-sourced coefficient vectors carry ~eps-relative junk in every
        entry, which would otherwise populate spurious matrix diagonals
        and defeat band detection."""
        return max(1e-12, 10 * ProductBase._ncc_real_eps(arr_or_dtype))

    @staticmethod
    def _ncc_data_cutoff(arr_or_dtype):
        """Relative significance cutoff for NCC data, scaled to the data's
        own precision: f32 field data carries ~1e-7-relative roundoff in
        every expansion coefficient, and treating that as structure
        poisons both the angular-constancy classification (forcing
        spurious ell coupling) and the band detection (a near-full
        lattice of junk couplings)."""
        return max(ProductBase.NCC_ANGULAR_CUTOFF,
                   50 * ProductBase._ncc_real_eps(arr_or_dtype))

    @staticmethod
    def polar_azimuth_varies(ncc, basis):
        """Shared classifier: does a disk/annulus NCC vary with azimuth?
        Grid-space, dtype-aware (the SAME decision drives the layout's
        forced m-coupling in subsystems._ncc_forced_coupled_axes and the
        term builder's convolution route — a disagreement would assemble
        whole-axis matrices onto per-m pencils or vice versa)."""
        grid = np.asarray(ncc["g"])
        tdim = len(ncc.tensorsig)
        az = tdim + basis.first_axis
        moved = np.moveaxis(grid, az, 0)
        tol = (ProductBase._ncc_data_cutoff(grid)
               * max(np.abs(grid).max(), 1e-300))
        return bool(np.abs(moved - moved[:1]).max() > tol)

    @staticmethod
    def sph_ncc_angular_profile(ncc, basis, cs):
        """
        Classify a spherical NCC's angular structure from its grid data.
        Returns (spin_profiles, tol): spin_profiles[a] = (Ntheta, Nr) theta-
        radial data of flattened SPIN component a (axisymmetry along phi is
        validated here), tol the absolute significance cutoff. Used both by
        the layout coupling detection (subsystems._ncc_forced_coupled_axes)
        and the coupled assembly.
        """
        from .curvilinear import recombination_matrix
        rank_n = len(ncc.tensorsig)
        ncomp = int(np.prod(ncc.tshape, dtype=int)) if ncc.tshape else 1
        ncc.change_scales(1)
        grid = np.asarray(ncc["g"])
        flat = grid.reshape((ncomp,) + grid.shape[rank_n:])
        if flat.ndim == 3:  # standalone S2: insert a trivial radial axis
            flat = flat[..., None]
        tol = ProductBase._ncc_data_cutoff(flat) * max(np.abs(flat).max(),
                                                       1e-300)
        if np.abs(flat - flat[:, :1]).max() > tol:
            raise NonlinearOperatorError(
                "LHS NCCs on spherical bases must be axisymmetric (constant "
                "along phi); only theta/radial variation is supported.")
        prof = flat[:, 0]                       # (ncomp, Ntheta, Nr)
        U = recombination_matrix(tuple(ncc.tensorsig), cs)
        spin_prof = np.einsum("ac,ctr->atr", U, prof.astype(complex))
        return spin_prof, tol

    def _sph_ncc_general_data(self, ncc, operand, basis, ncc_basis,
                              ncc_index):
        """
        Expansion of a theta/radius-dependent axisymmetric NCC for the
        ell-coupled assembly: per flattened spin component a, the list of
        (L, B_L) with B_L the radial multiplication matrix (operand level-k
        -> level-0) of the NCC's Y_{L,(0,s_a)} angular mode's radial
        profile (reference: the theta-dependent Clenshaw NCC pipeline,
        dedalus/core/arithmetic.py:359-406 + basis.py:611-628, rebuilt
        by SWSH + Gauss quadrature).
        """
        from .curvilinear import component_spins
        from ..libraries import sphere as swsh
        ncc_src = self.args[ncc_index]
        if isinstance(ncc_src, Field):
            version = ((id(ncc_src), ncc_src._version),)
        else:
            version = tuple(sorted((id(a), a._version)
                                   for a in ncc_src.atoms(Field)))
        version = version + (("k", getattr(basis, "k", 0)),)
        cache = getattr(self, "_sph_gen_cache", None)
        if cache is not None and cache.get("version") == version:
            return cache
        from .spherical3d import ShellBasis, spherical_rank
        spin_prof, tol = self.sph_ncc_angular_profile(ncc, basis, basis.cs)
        spins = component_spins(ncc.tensorsig, basis.cs)
        rank_n = spherical_rank(ncc.tensorsig, basis.cs)
        shell = isinstance(basis, ShellBasis)
        Lmax_n = ncc_basis.Lmax
        Ntheta_n = spin_prof.shape[1]
        terms = {}
        max_L = 0
        for a in range(spin_prof.shape[0]):
            pa = spin_prof[a]
            if np.abs(pa).max() <= tol:
                continue
            s_a = int(spins[a])
            F = swsh.forward_matrix(Lmax_n, 0, s_a, Ng=Ntheta_n) @ pa
            l0 = swsh.lmin(0, s_a)
            rows = []
            for i in range(F.shape[0]):
                if np.abs(F[i]).max() <= tol:
                    continue
                L = l0 + i
                coeffs = F[i]
                if np.abs(coeffs.imag).max() < 1e-13 * max(
                        np.abs(coeffs).max(), 1e-300):
                    coeffs = coeffs.real
                if shell:
                    # shell: radial space is ell-independent — one
                    # multiplication matrix per (a, L)
                    B = sparsify(basis.radial_multiplication_matrix(
                        ncc_basis.scalar_radial_coeffs(coeffs),
                        ncc_basis.k, k_out=0),
                        self._ncc_sparsify_cutoff(np.dtype(ncc.dtype)))
                    rows.append((L, B))
                else:
                    # ball: Zernike spaces are ell-indexed; store the
                    # profile's Zernike coefficients (the minimal smooth
                    # envelope degree has parity L + rank and vanishing
                    # order >= L - rank) and build per-(ell, ell') pair
                    # matrices lazily at assembly
                    l_env = max(L - rank_n, (L + rank_n) % 2)
                    if np.iscomplexobj(coeffs):
                        rc = (ncc_basis.scalar_radial_coeffs(
                                  coeffs.real, l_env=l_env)
                              + 1j * ncc_basis.scalar_radial_coeffs(
                                  coeffs.imag, l_env=l_env))
                    else:
                        rc = ncc_basis.scalar_radial_coeffs(coeffs,
                                                            l_env=l_env)
                    rows.append((L, (rc, l_env)))
                max_L = max(max_L, L)
            if rows:
                terms[a] = rows
        cache = self._sph_gen_cache = {"version": version, "terms": terms,
                                       "spins": spins, "max_L": max_L,
                                       "pair_cache": {}}
        return cache

    def _sph_coupled_ncc_matrix(self, subproblem, ncc, operand, ncc_index):
        """
        Pencil matrix of this product at one azimuthal group of an
        ell-COUPLED layout: the NCC may vary along theta and radius
        (e.g. the ez Coriolis vector of rotating convection). Assembly:
        SWSH triple-product coupling matrices W_L[l', l] (quadrature-exact
        Gaunt couplings) kron radial multiplication matrices B_L, summed
        over the NCC's (spin component, L) modes and sandwiched between
        the per-ell regularity<->spin intertwiners Q
        (reference: dedalus/core/arithmetic.py:359-406 prep_nccs /
        build_ncc_matrices with Clenshaw, core/basis.py:611-628).
        """
        from .spherical3d import q_stack, spherical_rank, ShellBasis
        from .curvilinear import component_spins
        from ..libraries import sphere as swsh
        basis = self._spherical_regularity_basis(operand)
        ncc_basis = self._spherical_regularity_basis(ncc)
        if basis is None or ncc_basis is None:
            raise NonlinearOperatorError(
                "Curvilinear NCCs require shell/ball bases on both factors.")
        if not isinstance(basis, ShellBasis):
            return self._sph_coupled_ncc_matrix_ball(subproblem, ncc,
                                                     operand, ncc_index)
        layout = subproblem.layout
        az = basis.first_axis
        gs = layout.sep_widths[az]
        ms = basis.group_m()
        g = subproblem.group[az]
        m = int(ms[g])
        Lmax = basis.Lmax
        Ntheta, Nr = basis.Ntheta, basis.Nr
        rank_in = spherical_rank(operand.tensorsig, basis.cs)
        rank_out = spherical_rank(self.tensorsig, basis.cs)
        nin, nout = 3 ** rank_in, 3 ** rank_out
        shape = (nout * gs * Ntheta * Nr, nin * gs * Ntheta * Nr)
        if basis.complex and g == basis.Nphi // 2:
            return sp.csr_matrix(shape)  # Nyquist: all slots invalid
        T_spin = self._spin_bilinear_map(ncc, operand, ncc_index)
        data = self._sph_ncc_general_data(ncc, operand, basis, ncc_basis,
                                          ncc_index)
        s_in = component_spins(operand.tensorsig, basis.cs)
        s_out = component_spins(self.tensorsig, basis.cs)
        s_ncc = data["spins"]
        Qi = q_stack(Ntheta, rank_in)     # (Ntheta, nin, nin) spin x reg
        Qo = q_stack(Ntheta, rank_out)
        I_r = sp.identity(Nr, format="csr")

        def embed_W(W, sc, sb):
            """Place the (l'-slot, l-slot) W into full (Ntheta, Ntheta)."""
            out = np.zeros((Ntheta, Ntheta))
            r0 = swsh.lmin(m, sc)
            c0 = swsh.lmin(m, sb)
            out[r0:r0 + W.shape[0], c0:c0 + W.shape[1]] = W
            return out

        total = sp.csr_matrix((nout * Ntheta * Nr, nin * Ntheta * Nr),
                              dtype=complex)
        # Q sandwiches are m-independent: cache across the group sweep
        # (the per-m cost is then only the W couplings, which are
        # themselves cached by (m, spins, L))
        qcache = data.setdefault("q_sandwich", {})
        key_R = ("R", rank_out, Ntheta, Nr)
        key_C = ("C", rank_in, Ntheta, Nr)
        if key_R not in qcache:
            qcache[key_R] = [sp.vstack([
                sparse_kron(sp.diags(Qo[:, c, gam]), I_r)
                for gam in range(nout)], format="csr")
                for c in range(nout)]
        if key_C not in qcache:
            qcache[key_C] = [sp.hstack([
                sparse_kron(sp.diags(Qi[:, b, bet]), I_r)
                for bet in range(nin)], format="csr")
                for b in range(nin)]
        R_all = qcache[key_R]
        C_all = qcache[key_C]
        for c in range(nout):
            sc = int(s_out[c])
            R_c = R_all[c]
            for b in range(nin):
                sb = int(s_in[b])
                A_cb = None
                for a, rows in data["terms"].items():
                    t = T_spin[c, a, b]
                    if abs(t) < 1e-13:
                        continue
                    if sc != int(s_ncc[a]) + sb:
                        raise ValueError(
                            "Spin balance violated in NCC assembly "
                            f"(s_out={sc}, s_ncc={int(s_ncc[a])}, s_in={sb}).")
                    for L, B in rows:
                        W = swsh.triple_product_matrix(
                            Lmax, m, sc, int(s_ncc[a]), sb, L)
                        if W.size == 0 or np.abs(W).max() == 0.0:
                            continue
                        Wl = sparsify(embed_W(W, sc, sb), 1e-14)
                        term = t * sparse_kron(Wl, B)
                        A_cb = term if A_cb is None else A_cb + term
                if A_cb is None:
                    continue
                C_b = C_all[b]
                total = total + R_c @ A_cb @ C_b
        # Canonicalize BEFORE any derived views: .imag/.real of a
        # non-canonical CSR share index arrays with the parent, and
        # canonicalizing the view in place corrupts the parent
        # (scipy _with_data aliasing).
        total = total.tocoo().tocsr()
        # imaginary parts at the SOURCE dtype's roundoff are residue, not
        # couplings (f32 data leaves ~1e-7-relative imag junk whose pair-J
        # representation would litter the band structure)
        imag_tol = max(1e-13, 100 * self._ncc_real_eps(np.dtype(ncc.dtype)))
        total = _filter_rel(total, self._ncc_sparsify_cutoff(np.dtype(ncc.dtype)))
        if total.nnz and np.abs(total.imag).max() < imag_tol * max(
                np.abs(total).max(), 1e-300):
            total = total.real
        elif not is_complex_dtype(self.dtype) and gs < 2:
            raise NonlinearOperatorError(
                "This NCC product assembles complex couplings (e.g. a cross "
                "product) with no azimuthal pair slots to carry them; use a "
                "complex dtype, or move the term to the RHS.")
        if gs > 1:
            # slot layout is (component, azimuthal pair, ell, n): interleave
            # between the component and ell kron positions (complex
            # couplings act through the real 2x2 pair representation)
            total = _interleave_gs(total, nout, nin, gs, Ntheta * Nr)
        return sp.csr_matrix(total)

    def _sph_coupled_ncc_matrix_ball(self, subproblem, ncc, operand,
                                     ncc_index):
        """
        Ball variant of the ell-coupled NCC assembly: Zernike radial
        spaces are ell-indexed, so the kron(W, B) factorization of the
        shell does not apply — each (ell', ell) block combines the SWSH
        triple-product coupling with a PER-PAIR radial multiplication
        matrix mapping Z^(ell + t_in) -> Z^(ell' + t_out)
        (reference: the l-coupled Zernike Clenshaw couplings,
        dedalus/core/basis.py:4101 + core/arithmetic.py:359-406).
        """
        from .spherical3d import q_stack, spherical_rank, reg_totals
        from .curvilinear import component_spins
        from ..libraries import sphere as swsh
        basis = self._spherical_regularity_basis(operand)
        ncc_basis = self._spherical_regularity_basis(ncc)
        layout = subproblem.layout
        az = basis.first_axis
        gs = layout.sep_widths[az]
        ms = basis.group_m()
        g = subproblem.group[az]
        m = int(ms[g])
        Lmax = basis.Lmax
        Ntheta, Nr = basis.Ntheta, basis.Nr
        rank_in = spherical_rank(operand.tensorsig, basis.cs)
        rank_out = spherical_rank(self.tensorsig, basis.cs)
        nin, nout = 3 ** rank_in, 3 ** rank_out
        shape = (nout * gs * Ntheta * Nr, nin * gs * Ntheta * Nr)
        if basis.complex and g == basis.Nphi // 2:
            return sp.csr_matrix(shape)  # Nyquist: all slots invalid
        T_spin = self._spin_bilinear_map(ncc, operand, ncc_index)
        data = self._sph_ncc_general_data(ncc, operand, basis, ncc_basis,
                                          ncc_index)
        s_in = component_spins(operand.tensorsig, basis.cs)
        s_out = component_spins(self.tensorsig, basis.cs)
        s_ncc = data["spins"]
        t_in = reg_totals(rank_in)
        t_out = reg_totals(rank_out)
        Qi = q_stack(Ntheta, rank_in)
        Qo = q_stack(Ntheta, rank_out)
        pair_cache = data["pair_cache"]
        flat_terms = [(a, L, payload)
                      for a, rows in data["terms"].items()
                      for L, payload in rows]
        max_L = data["max_L"]
        X0 = Ntheta * Nr
        rows_l, cols_l, vals_l = [], [], []
        for lp in range(Ntheta):            # ell' (output)
            for l in range(max(0, lp - max_L),
                           min(Ntheta, lp + max_L + 1)):   # ell (input)
                # angular x tensor coefficient per (gamma, beta, term)
                A3 = np.zeros((nout, nin, len(flat_terms)), dtype=complex)
                for ti, (a, L, payload) in enumerate(flat_terms):
                    sa = int(s_ncc[a])
                    for c in range(nout):
                        sc = int(s_out[c])
                        for b in range(nin):
                            t = T_spin[c, a, b]
                            if abs(t) < 1e-13:
                                continue
                            sb = int(s_in[b])
                            W = swsh.triple_product_matrix(Lmax, m, sc,
                                                           sa, sb, L)
                            r0 = swsh.lmin(m, sc)
                            c0 = swsh.lmin(m, sb)
                            if (lp < r0 or l < c0
                                    or lp - r0 >= W.shape[0]
                                    or l - c0 >= W.shape[1]):
                                continue
                            w = W[lp - r0, l - c0]
                            if w == 0.0:
                                continue
                            A3[:, :, ti] += (t * w) * np.outer(
                                Qo[lp][c], Qi[l][b])
                if not np.abs(A3).any():
                    continue
                for gam in range(nout):
                    for bet in range(nin):
                        coefs = A3[gam, bet]
                        if not np.abs(coefs).any():
                            continue
                        blk = None
                        for ti, (a, L, payload) in enumerate(flat_terms):
                            cf = coefs[ti]
                            if abs(cf) < 1e-14:
                                continue
                            rc, l_env = payload
                            key = (id(rc), int(t_in[bet]), int(t_out[gam]),
                                   l, lp)
                            B = pair_cache.get(key)
                            if B is None:
                                B = sparsify(basis.ncc_radial_pair_matrix(
                                    rc, ncc_basis.k, l_env, t_in[bet],
                                    t_out[gam], l, lp, k_out=0),
                                    self._ncc_sparsify_cutoff(np.dtype(ncc.dtype)))
                                pair_cache[key] = B
                            term = cf * B
                            blk = term if blk is None else blk + term
                        if blk is None or blk.nnz == 0:
                            continue
                        coo = blk.tocoo()
                        rows_l.append(gam * X0 + lp * Nr + coo.row)
                        cols_l.append(bet * X0 + l * Nr + coo.col)
                        vals_l.append(coo.data)
        if rows_l:
            total = sp.csr_matrix(
                (np.concatenate(vals_l),
                 (np.concatenate(rows_l), np.concatenate(cols_l))),
                shape=(nout * X0, nin * X0))
        else:
            total = sp.csr_matrix((nout * X0, nin * X0), dtype=complex)
        total = total.tocoo().tocsr()
        imag_tol = max(1e-13, 100 * self._ncc_real_eps(np.dtype(ncc.dtype)))
        total = _filter_rel(total, self._ncc_sparsify_cutoff(np.dtype(ncc.dtype)))
        if total.nnz and np.abs(total.imag).max() < imag_tol * max(
                np.abs(total).max(), 1e-300):
            total = total.real
        elif total.nnz and not is_complex_dtype(self.dtype) and gs < 2:
            raise NonlinearOperatorError(
                "This NCC product assembles complex couplings with no "
                "azimuthal pair slots to carry them; use a complex dtype, "
                "or move the term to the RHS.")
        if gs > 1:
            total = _interleave_gs(total, nout, nin, gs, X0)
        return sp.csr_matrix(total)

    def _s2_coupled_ncc_matrix(self, subproblem, ncc, operand, ncc_index):
        """
        Pencil matrix of a product with an axisymmetric NCC on the
        standalone 2D SPHERE (e.g. a zonal background U(theta) in a
        linearized shallow-water problem): the surface analogue of the
        shell/ball paths — SWSH triple-product couplings with scalar
        (L-mode) coefficients, no radial factor. Sphere coefficients are
        already spin components, so no Q intertwiner sandwich is needed
        (reference: dedalus/core/arithmetic.py:359-406 restricted to S2).
        """
        from .curvilinear import component_spins
        from ..libraries import sphere as swsh
        basis = self._s2_basis(operand)
        ncc_basis = self._s2_basis(ncc)
        if basis is None or ncc_basis is None:
            raise NonlinearOperatorError(
                "S2 NCC products require sphere bases on both factors.")
        layout = subproblem.layout
        az = basis.first_axis
        colat = az + 1
        if subproblem.group[colat] is not None:
            raise NonlinearOperatorError(
                "S2 NCC products require the colatitude coupled "
                "(standalone sphere problems).")
        gs = layout.sep_widths[az]
        ms = basis.group_m()
        g = subproblem.group[az]
        m = int(ms[g])
        Lmax = basis.Lmax
        Ntheta = basis.Ntheta
        nin = int(np.prod(operand.tshape, dtype=int)) if operand.tshape else 1
        nout = int(np.prod(self.tshape, dtype=int)) if self.tshape else 1
        shape = (nout * gs * Ntheta, nin * gs * Ntheta)
        if basis.complex and g == basis.Nphi // 2:
            return sp.csr_matrix(shape)  # Nyquist
        T_spin = self._spin_bilinear_map(ncc, operand, ncc_index)
        spin_prof, tol = self.sph_ncc_angular_profile(ncc, basis, basis.cs)
        s_ncc = component_spins(ncc.tensorsig, basis.cs)
        s_in = component_spins(operand.tensorsig, basis.cs)
        s_out = component_spins(self.tensorsig, basis.cs)
        total = sp.csr_matrix((nout * Ntheta, nin * Ntheta), dtype=complex)
        for a in range(spin_prof.shape[0]):
            pa = spin_prof[a][:, 0]
            if np.abs(pa).max() <= tol:
                continue
            sa = int(s_ncc[a])
            F = swsh.forward_matrix(ncc_basis.Lmax, 0, sa) @ pa
            l0 = swsh.lmin(0, sa)
            for c in range(nout):
                sc = int(s_out[c])
                for b in range(nin):
                    t = T_spin[c, a, b]
                    if abs(t) < 1e-13:
                        continue
                    sb = int(s_in[b])
                    if sc != sa + sb:
                        raise ValueError(
                            "Spin balance violated in S2 NCC assembly.")
                    blk = None
                    for i in range(F.shape[0]):
                        if abs(F[i]) <= tol:
                            continue
                        L = l0 + i
                        W = swsh.triple_product_matrix(Lmax, m, sc, sa,
                                                       sb, L)
                        if W.size == 0 or np.abs(W).max() == 0.0:
                            continue
                        emb = np.zeros((Ntheta, Ntheta))
                        r0 = swsh.lmin(m, sc)
                        c0 = swsh.lmin(m, sb)
                        emb[r0:r0 + W.shape[0], c0:c0 + W.shape[1]] = W
                        term = (t * F[i]) * sparsify(emb, 1e-14)
                        blk = term if blk is None else blk + term
                    if blk is None:
                        continue
                    place = sp.csr_matrix(
                        (np.ones(1), ([c], [b])), shape=(nout, nin))
                    total = total + sp.kron(place, blk, format="csr")
        total = total.tocoo().tocsr()
        imag_tol = max(1e-13, 100 * self._ncc_real_eps(np.dtype(ncc.dtype)))
        total = _filter_rel(total, self._ncc_sparsify_cutoff(np.dtype(ncc.dtype)))
        if total.nnz and np.abs(total.imag).max() < imag_tol * max(
                np.abs(total).max(), 1e-300):
            total = total.real
        elif total.nnz and not is_complex_dtype(self.dtype) and gs < 2:
            raise NonlinearOperatorError(
                "This S2 NCC product assembles complex couplings with no "
                "azimuthal pair slots to carry them; use a complex dtype, "
                "or move the term to the RHS.")
        if gs > 1:
            total = _interleave_gs(total, nout, nin, gs, Ntheta)
        return sp.csr_matrix(total)

    def _assemble_ncc_matrix(self, subproblem, ncc, operand, tensor_factor_fn):
        """
        Sum over NCC components: kron(tensor_factor(comp), axis factors).
        `tensor_factor_fn(comp_index, value_is_scalar)` returns the sparse
        tensor factor for that component.
        """
        from .operators import _axis_identity
        operand_domain = operand.domain
        sep_widths = subproblem.layout.sep_widths
        total = None
        comp_indices = list(np.ndindex(*ncc.tshape)) if ncc.tshape else [()]
        for comp in comp_indices:
            for scalar, descrs in self._ncc_axis_terms(ncc, comp, operand):
                factors = [tensor_factor_fn(comp)]
                if scalar is not None and not np.isscalar(scalar):
                    # component-MIXING tensor factor (real-pair expansion
                    # of azimuthally-varying polar NCCs): composes with
                    # the ncc-component placement on the left
                    factors[0] = sp.csr_matrix(np.asarray(scalar)) \
                        @ sp.csr_matrix(factors[0])
                    scalar = None
                for axis, descr in enumerate(descrs):
                    ob = operand_domain.bases[axis]
                    if descr is None:
                        sub = 0 if ob is None else axis - ob.first_axis
                        factors.append(_axis_identity(ob,
                                                      sep_widths.get(axis),
                                                      sub))
                    else:
                        factors.append(descr[1])
                mat = sparse_kron(*factors)
                if scalar is not None:
                    mat = scalar * mat
                total = mat if total is None else total + mat
        return total


class MultiplyFields(ProductBase):
    """Pointwise (tensor outer) product (reference: core/arithmetic.py:822)."""

    name = "Mul"

    def _build_metadata(self):
        a, b = self.args
        self.tensorsig = tuple(a.tensorsig) + tuple(b.tensorsig)
        self.domain = _product_domain(self.dist, [a, b])
        self.dtype = _promote_dtype(self.args)

    def __repr__(self):
        return f"({self.args[0]}*{self.args[1]})"

    def ev_impl(self, ctx):
        a, b = self.args
        da = ev(a, ctx, "g")
        db = ev(b, ctx, "g")
        ta, tb = a.tdim, b.tdim
        da_x = da.reshape(da.shape[:ta] + (1,) * tb + da.shape[ta:])
        return da_x * db  # broadcasting over tensor + constant grid axes

    def _coord_bilinear_map(self, ncc, operand, ncc_index):
        nn = int(np.prod(ncc.tshape, dtype=int)) if ncc.tshape else 1
        no = int(np.prod(operand.tshape, dtype=int)) if operand.tshape else 1
        T = np.zeros((nn * no, nn, no))
        a, b = np.meshgrid(np.arange(nn), np.arange(no), indexing="ij")
        if ncc_index == 0:
            T[(a * no + b).ravel(), a.ravel(), b.ravel()] = 1.0
        else:
            T[(b * nn + a).ravel(), a.ravel(), b.ravel()] = 1.0
        return T

    def expression_matrices(self, subproblem, vars, **kw):
        ncc_index, ncc, operand = self._split_ncc(vars, subproblem.layout)
        if self._spherical_regularity_basis(ncc) is not None:
            M = self._spherical_ncc_matrix(subproblem, ncc, operand,
                                           ncc_index)
            op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        if (self._s2_basis(ncc) is not None
                and self._spherical_regularity_basis(operand) is None):
            M = self._s2_coupled_ncc_matrix(subproblem, ncc, operand,
                                            ncc_index)
            op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        pol = self._polar_spin_basis(ncc)
        if pol is not None and (ncc.tensorsig
                                or not hasattr(pol, "radial_multiplication_matrix")):
            if hasattr(pol, "radial_multiplication_matrix"):
                # annulus: spin-independent radial space, single matrix
                M = self._polar_tensor_ncc_matrix(subproblem, ncc, operand,
                                                  ncc_index)
            else:
                # disk: per-(m, spin) Zernike stacks
                n_n = int(np.prod(ncc.tshape, dtype=int)) if ncc.tshape else 1
                n_op = int(np.prod(operand.tshape, dtype=int)) \
                    if operand.tshape else 1

                def place(c):
                    P = np.zeros((n_n, 1))
                    P[c, 0] = 1.0
                    return (np.kron(P, np.eye(n_op)) if ncc_index == 0
                            else np.kron(np.eye(n_op), P))

                M = self._disk_ncc_matrix(subproblem, ncc, operand, place)
            op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        ncomp_op = int(np.prod([cs.dim for cs in operand.tensorsig], dtype=int)) \
            if operand.tensorsig else 1
        ncomp_ncc_shape = ncc.tshape

        def tensor_factor(comp):
            # column selecting the ncc component within the output tensorsig
            n_ncc = int(np.prod(ncomp_ncc_shape, dtype=int)) if ncomp_ncc_shape else 1
            col = sp.lil_matrix((n_ncc, 1))
            flat = int(np.ravel_multi_index(comp, ncomp_ncc_shape)) if comp else 0
            col[flat, 0] = 1.0
            col = sp.csr_matrix(col)
            I_op = sp.identity(ncomp_op, format="csr")
            if ncc_index == 0:
                return sparse_kron(col, I_op)
            return sparse_kron(I_op, col)

        M = self._assemble_ncc_matrix(subproblem, ncc, operand, tensor_factor)
        op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
        return {var: M @ mat for var, mat in op_mats.items()}


class DotProduct(ProductBase):
    """
    Contraction of the last index of the first operand with the first index
    of the second (reference: core/arithmetic.py:586).
    """

    name = "Dot"

    def __init__(self, a, b):
        if _is_scalar(a) or _is_scalar(b):
            raise ValueError("DotProduct requires tensor operands.")
        if not a.tensorsig or not b.tensorsig:
            raise ValueError("DotProduct requires tensor operands.")
        if a.tensorsig[-1].dim != b.tensorsig[0].dim:
            raise ValueError("Contracted dimensions do not match.")
        super().__init__(a, b)

    def _build_metadata(self):
        a, b = self.args
        self.tensorsig = tuple(a.tensorsig[:-1]) + tuple(b.tensorsig[1:])
        self.domain = _product_domain(self.dist, [a, b])
        self.dtype = _promote_dtype(self.args)

    def __repr__(self):
        return f"({self.args[0]}@{self.args[1]})"

    def _coord_bilinear_map(self, ncc, operand, ncc_index):
        if ncc_index == 0:
            lead = ncc.tshape[:-1]
            rest = operand.tshape[1:]
            d = ncc.tshape[-1]
            nl = int(np.prod(lead, dtype=int)) if lead else 1
            nr_ = int(np.prod(rest, dtype=int)) if rest else 1
            T = np.zeros((nl * nr_, nl * d, d * nr_))
            for al in range(nl):
                for ro in range(nr_):
                    for j in range(d):
                        T[al * nr_ + ro, al * d + j, j * nr_ + ro] = 1.0
        else:
            lead = operand.tshape[:-1]
            rest = ncc.tshape[1:]
            d = ncc.tshape[0]
            nl = int(np.prod(lead, dtype=int)) if lead else 1
            nr_ = int(np.prod(rest, dtype=int)) if rest else 1
            T = np.zeros((nl * nr_, d * nr_, nl * d))
            for al in range(nl):
                for ro in range(nr_):
                    for j in range(d):
                        T[al * nr_ + ro, j * nr_ + ro, al * d + j] = 1.0
        return T

    @staticmethod
    def contraction_subscripts(ta, tb):
        """einsum subscripts contracting the left factor's LAST tensor
        index with the right factor's FIRST (shared with the dd
        interpreter, core/ddstep.py)."""
        letters = "abcdefghijklm"
        l_sub = letters[:ta - 1] + "z" + "..."
        r_sub = "z" + letters[ta - 1:ta - 1 + tb - 1] + "..."
        o_sub = letters[:ta - 1] + letters[ta - 1:ta - 1 + tb - 1] + "..."
        return l_sub, r_sub, o_sub

    def ev_impl(self, ctx):
        a, b = self.args
        da = ev(a, ctx, "g")
        db = ev(b, ctx, "g")
        l_sub, r_sub, o_sub = self.contraction_subscripts(a.tdim, b.tdim)
        return jnp.einsum(f"{l_sub},{r_sub}->{o_sub}", da, db)

    def expression_matrices(self, subproblem, vars, **kw):
        ncc_index, ncc, operand = self._split_ncc(vars, subproblem.layout)
        d = ncc.tensorsig[-1].dim if ncc_index == 0 else ncc.tensorsig[0].dim

        if ncc_index == 0:
            # out comps: ncc[:-1] + op[1:]; contraction over op's first index
            rest_op = operand.tshape[1:]
            n_rest_op = int(np.prod(rest_op, dtype=int)) if rest_op else 1
            lead_ncc = ncc.tshape[:-1]
            n_lead = int(np.prod(lead_ncc, dtype=int)) if lead_ncc else 1

            def tensor_factor(comp):
                *alpha, j = comp
                lead_flat = int(np.ravel_multi_index(tuple(alpha), lead_ncc)) if lead_ncc else 0
                col = sp.lil_matrix((n_lead, 1)); col[lead_flat, 0] = 1.0
                row = sp.lil_matrix((1, d)); row[0, j] = 1.0
                return sparse_kron(sp.csr_matrix(col), sp.csr_matrix(row),
                                   sp.identity(n_rest_op, format="csr"))
        else:
            # operand @ ncc: contract operand's last index with ncc's first
            lead_op = operand.tshape[:-1]
            n_lead_op = int(np.prod(lead_op, dtype=int)) if lead_op else 1
            rest_ncc = ncc.tshape[1:]
            n_rest = int(np.prod(rest_ncc, dtype=int)) if rest_ncc else 1

            def tensor_factor(comp):
                j, *beta = comp
                rest_flat = int(np.ravel_multi_index(tuple(beta), rest_ncc)) if rest_ncc else 0
                row = sp.lil_matrix((1, d)); row[0, j] = 1.0
                col = sp.lil_matrix((n_rest, 1)); col[rest_flat, 0] = 1.0
                return sparse_kron(sp.identity(n_lead_op, format="csr"),
                                   sp.csr_matrix(row), sp.csr_matrix(col))

        if self._spherical_regularity_basis(ncc) is not None:
            M = self._spherical_ncc_matrix(subproblem, ncc, operand,
                                           ncc_index)
            op_mats = operand_expression_matrices(operand, subproblem, vars,
                                                  **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        if (self._s2_basis(ncc) is not None
                and self._spherical_regularity_basis(operand) is None):
            M = self._s2_coupled_ncc_matrix(subproblem, ncc, operand,
                                            ncc_index)
            op_mats = operand_expression_matrices(operand, subproblem, vars,
                                                  **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        pol = self._polar_spin_basis(ncc)
        if pol is not None and not hasattr(pol, "radial_multiplication_matrix"):
            # disk contraction (e.g. pipe flow's u@grad(w0)): the same
            # coordinate placement feeds the per-(m, spin) stack path
            place = lambda cflat: np.asarray(tensor_factor(
                tuple(np.unravel_index(cflat, ncc.tshape))).toarray())
            M = self._disk_ncc_matrix(subproblem, ncc, operand, place)
            op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        M = self._assemble_ncc_matrix(subproblem, ncc, operand, tensor_factor)
        op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
        return {var: M @ mat for var, mat in op_mats.items()}


class CrossProduct(ProductBase):
    """3D cross product (reference: core/arithmetic.py:677)."""

    name = "Cross"
    natural_layout = "g"

    def __init__(self, a, b):
        if a.tensorsig[-1].dim != 3 or b.tensorsig[0].dim != 3:
            raise ValueError("CrossProduct requires 3D vectors.")
        super().__init__(a, b)

    def _build_metadata(self):
        a, b = self.args
        self.tensorsig = tuple(a.tensorsig)
        self.domain = _product_domain(self.dist, [a, b])
        self.dtype = _promote_dtype(self.args)

    def ev_impl(self, ctx):
        a, b = self.args
        da = ev(a, ctx, "g")
        db = ev(b, ctx, "g")
        out = jnp.cross(da, db, axisa=0, axisb=0, axisc=0)
        # Left-handed component orderings (spherical (phi, theta, r)) flip
        # the orientation (reference: core/coords.py right_handed flags).
        if not getattr(a.tensorsig[-1], "right_handed", True):
            out = -out
        return out

    def _coord_bilinear_map(self, ncc, operand, ncc_index):
        if len(ncc.tshape) != 1 or len(operand.tshape) != 1:
            raise NonlinearOperatorError(
                "LHS cross products support vector x vector only.")
        eps = np.zeros((3, 3, 3))
        for i, j, k in ((0, 1, 2), (1, 2, 0), (2, 0, 1)):
            eps[i, j, k] = 1.0
            eps[i, k, j] = -1.0
        if not getattr(self.tensorsig[-1], "right_handed", True):
            eps = -eps
        if ncc_index == 0:
            return eps                       # out_i = eps_ijk ncc_j op_k
        return np.swapaxes(eps, 1, 2)        # out_i = eps_ijk op_j ncc_k

    def expression_matrices(self, subproblem, vars, **kw):
        """LHS cross with an NCC factor (e.g. the Coriolis term
        cross(ez, u) of rotating convection,
        reference: examples/evp_shell_rotating_convection)."""
        ncc_index, ncc, operand = self._split_ncc(vars, subproblem.layout)
        if self._spherical_regularity_basis(ncc) is not None:
            M = self._spherical_ncc_matrix(subproblem, ncc, operand,
                                           ncc_index)
            op_mats = operand_expression_matrices(operand, subproblem, vars,
                                                  **kw)
            return {var: M @ mat for var, mat in op_mats.items()}
        # Cartesian / interval bases: per-axis path with the Levi-Civita
        # tensor factor selecting each NCC component's action
        T = self._coord_bilinear_map(ncc, operand, ncc_index)

        def tensor_factor(comp):
            j = comp[0] if comp else 0
            return sparsify(sp.csr_matrix(T[:, j, :]), 1e-14)

        M = self._assemble_ncc_matrix(subproblem, ncc, operand, tensor_factor)
        op_mats = operand_expression_matrices(operand, subproblem, vars, **kw)
        return {var: M @ mat for var, mat in op_mats.items()}


class Power(Future):
    """Field ** scalar (reference: core/arithmetic.py via operators Power:305)."""

    name = "Pow"
    natural_layout = "g"

    def __init__(self, base, exponent):
        if not _is_scalar(exponent):
            raise ValueError("Exponent must be a scalar constant.")
        self.exponent = exponent
        super().__init__(base)

    def rebuild(self, new_args):
        return Power(new_args[0], self.exponent)

    def _build_metadata(self):
        base = self.args[0]
        if base.tensorsig:
            raise ValueError("Power requires scalar fields.")
        self.domain = base.domain
        self.tensorsig = ()
        self.dtype = base.dtype

    def __repr__(self):
        return f"({self.args[0]}**{self.exponent})"

    def ev_impl(self, ctx):
        return ev(self.args[0], ctx, "g") ** self.exponent

    def frechet_differential(self, variables, perturbations):
        base = self.args[0]
        d = base.frechet_differential(variables, perturbations)
        if _is_scalar(d) and d == 0:
            return 0
        n = self.exponent
        return n * Power(base, n - 1) * d


# parseables
from .operators import parseables  # noqa: E402
parseables["dot"] = DotProduct
parseables["cross"] = CrossProduct
