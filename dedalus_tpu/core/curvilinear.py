"""
Shared curvilinear-basis machinery: spin weights, spin recombination, and
group-batched (per-m) matrix application
(reference: dedalus/core/basis.py:1561 SpinRecombinationBasis,
dedalus/libraries/spin_recombination.pyx).

Coefficient-space convention: fields whose tensor signature contains a
curvilinear coordinate system store *spin components* (regularity components
on the ball/shell) in coefficient layout; grid layout holds coordinate
components. The rotation between them happens inside the basis transforms,
exactly as the reference's forward/backward_spin_recombination
(core/basis.py:1595-1663) — but here it is one small dense matmul fused by
XLA instead of a Cython loop.

Real-dtype representation: azimuthal coefficients are interleaved
(cos, -sin) pairs; multiplication by i acts on a pair as the rotation
J = [[0, -1], [1, 0]]. A complex matrix C acting on (tensor-component x m)
data therefore becomes the real matrix Re(C) (x) I2 + Im(C) (x) J acting on
(component, pair-slot) jointly.
"""

import numpy as np
import jax.numpy as jnp

from ..tools.array import match_precision

PAIR_J = np.array([[0.0, -1.0], [1.0, 0.0]])


def _entry_spins(tcs, cs):
    """Spin labels of one tensor index's components w.r.t. basis cs:
    the index's own ordering when it rotates with cs, per-factor labels
    for DirectProduct indices (zeros on non-matching factors), zeros
    otherwise."""
    if _cs_match(tcs, cs):
        return np.array(tcs.spin_ordering)
    subs = getattr(tcs, "coordsystems", None)
    if subs is not None:
        return np.concatenate([
            np.array(sub.spin_ordering) if _cs_match(sub, cs)
            else np.zeros(sub.dim, dtype=int)
            for sub in subs])
    return np.zeros(tcs.dim, dtype=int)


def component_spins(tensorsig, cs):
    """
    Total spin weight per flattened tensor component, counting only indices
    whose coordinate system is (or contains) `cs`
    (reference: core/basis.py spin_weights).
    """
    spins = [np.zeros(1, dtype=int)]
    for tcs in tensorsig:
        s = _entry_spins(tcs, cs)
        spins = [np.add.outer(sp, s).ravel() for sp in spins]
    return spins[0]


def _cs_match(tcs, cs):
    """Does tensor-index coordinate system `tcs` rotate with basis cs?
    Equality (not identity): cached bases may hold an equal twin of the
    user's coordinate-system object."""
    if tcs == cs:
        return True
    sub = getattr(tcs, "S2coordsys", None)
    if sub is not None and sub == cs:
        return True
    sup = getattr(cs, "S2coordsys", None)
    return sup is not None and sup == tcs


import functools


@functools.lru_cache(maxsize=None)
def recombination_matrix(tensorsig, cs):
    """Complex unitary (ncomp, ncomp): coordinate -> spin components, kron
    over tensor indices (identity on non-curvilinear indices; block
    diagonal on DirectProduct indices, rotating only the factor matching
    `cs`). Cached so downstream device-constant interning sees stable
    objects."""
    import scipy.linalg
    U = np.array([[1.0]])
    for tcs in tensorsig:
        if _cs_match(tcs, cs):
            Ui = tcs.U_forward(1)
        elif getattr(tcs, "coordsystems", None) is not None:
            Ui = scipy.linalg.block_diag(*[
                sub.U_forward(1) if _cs_match(sub, cs) else np.eye(sub.dim)
                for sub in tcs.coordsystems])
        else:
            Ui = np.eye(tcs.dim)
        U = np.kron(U, Ui)
    return U


def real_pair_matrix(C):
    """Real representation of complex matrix C on (component, pair) space:
    Re(C) (x) I2 + Im(C) (x) J."""
    return np.kron(C.real, np.eye(2)) + np.kron(C.imag, PAIR_J)


def apply_component_pair_matrix(data, C, tdim, az_axis, real):
    """
    Apply a complex component-mixing matrix C to data with flattened tensor
    components. For real dtype, C acts jointly on (components, azimuth pair
    slots); for complex dtype, directly on components.

    data: (*tshape_flattenable..., axes...) with tensor axes [0, tdim) and
    the azimuth axis at tdim + az_axis.
    """
    tshape = data.shape[:tdim]
    ncomp = int(np.prod(tshape, dtype=int)) if tdim else 1
    spatial = data.shape[tdim:]
    flat = data.reshape((ncomp,) + spatial)
    if not real:
        C = match_precision(C, data.dtype)
        out = jnp.tensordot(C, flat, axes=(1, 0))
    else:
        R = match_precision(real_pair_matrix(C), data.dtype)
        # bring azimuth axis next to components, expose pair slot
        a = 1 + az_axis
        moved = jnp.moveaxis(flat, a, 1)  # (ncomp, Naz, rest...)
        Naz = moved.shape[1]
        pairs = moved.reshape((ncomp, Naz // 2, 2) + moved.shape[2:])
        pairs = jnp.moveaxis(pairs, 2, 1)  # (ncomp, 2, M, rest...)
        merged = pairs.reshape((ncomp * 2,) + pairs.shape[2:])
        out = jnp.tensordot(R, merged, axes=(1, 0))
        out = out.reshape((ncomp, 2) + out.shape[1:])
        out = jnp.moveaxis(out, 1, 2)  # (ncomp, M, 2, rest...)
        out = out.reshape((ncomp, Naz) + out.shape[3:])
        out = jnp.moveaxis(out, 1, a)
    return out.reshape(tshape + spatial)


def apply_group_stack(data, stack, axis_groups, axis_target, group_width):
    """
    Apply per-group matrices along a coupled axis: out[..., g, ..., j, ...] =
    stack[g, j, i] * data[..., g, ..., i, ...], where the group index g lives
    on `axis_groups` (packed as G * group_width entries; the width slots
    broadcast) and the matrix is applied along `axis_target`.

    This is the zero-padded batched matmul that replaces the reference's
    per-m Python loops (core/transforms.py:1260-1288) — on TPU a single MXU
    einsum over the m batch.
    """
    stack = match_precision(stack, data.dtype)
    G = stack.shape[0]
    d = jnp.moveaxis(data, (axis_groups, axis_target), (-2, -1))
    lead = d.shape[:-2]
    d = d.reshape(lead + (G, group_width, d.shape[-1]))
    out = jnp.einsum("gji,...gpi->...gpj", stack, d)
    out = out.reshape(lead + (G * group_width, out.shape[-1]))
    return jnp.moveaxis(out, (-2, -1), (axis_groups, axis_target))


class SpinBasisMixin:
    """
    Shared machinery for 2D spin-weighted bases (disk, annulus, sphere):
    azimuth (separable, Fourier) x coupled axis with m- and spin-dependent
    matrix stacks (reference: core/basis.py:1561 SpinRecombinationBasis +
    the per-m transform loops in core/transforms.py:1252,1343).

    Concrete bases provide: `cs`, `complex`, `azimuth_basis`,
    `sub_group_shape(0)`, `radial_forward_stack(s, scale)` and
    `radial_backward_stack(s, scale)` (G, out, in) stacks over the m groups.
    """

    def forward_transform(self, gdata, axis, scale, library=None,
                          tensorsig=(), sub_axis=0):
        if sub_axis == 0:
            return self.azimuth_basis.forward_transform(gdata, axis, scale, library)
        tdim = len(tensorsig)
        az_axis = axis - 1
        out = gdata
        spins = component_spins(tensorsig, self.cs)
        if np.any(spins != 0):
            U = recombination_matrix(tensorsig, self.cs)
            out = apply_component_pair_matrix(out, U, tdim, az_axis - tdim,
                                              real=not self.complex)
        return self._radial_apply(out, tdim, az_axis, axis, spins, scale,
                                  forward=True)

    def backward_transform(self, cdata, axis, scale, library=None,
                           tensorsig=(), sub_axis=0):
        if sub_axis == 0:
            return self.azimuth_basis.backward_transform(cdata, axis, scale, library)
        tdim = len(tensorsig)
        az_axis = axis - 1
        spins = component_spins(tensorsig, self.cs)
        out = self._radial_apply(cdata, tdim, az_axis, axis, spins, scale,
                                 forward=False)
        if np.any(spins != 0):
            U = recombination_matrix(tensorsig, self.cs)
            out = apply_component_pair_matrix(out, U.conj().T, tdim, az_axis - tdim,
                                              real=not self.complex)
        return out

    def _radial_apply(self, data, tdim, az_axis, r_axis, spins, scale, forward):
        """Coupled-axis transform hook: default applies per-spin, per-m
        stacks; bases with m/spin-independent transforms override this with a
        single matmul."""
        if forward:
            stack_fn = lambda s: self.radial_forward_stack(s, scale)
        else:
            stack_fn = lambda s: self.radial_backward_stack(s, scale)
        return self._apply_radial_stacks(data, tdim, az_axis, r_axis, spins,
                                         stack_fn)

    def _apply_radial_stacks(self, data, tdim, az_axis, r_axis, spins, stack_fn):
        """Apply per-spin group stacks along the coupled axis (batched over m)."""
        tshape = data.shape[:tdim]
        ncomp = int(np.prod(tshape, dtype=int)) if tdim else 1
        flat = data.reshape((ncomp,) + data.shape[tdim:])
        gs = self.sub_group_shape(0)
        pieces = [None] * ncomp
        for s in np.unique(spins):
            stack = stack_fn(int(s))
            idx = np.flatnonzero(spins == s)
            sub = flat[idx]
            sub = apply_group_stack(sub, stack, 1 + az_axis - tdim, 1 + r_axis - tdim, gs)
            for j, i in enumerate(idx):
                pieces[i] = sub[j]
        out = jnp.stack(pieces, axis=0) if ncomp > 1 else pieces[0][None]
        new_spatial = out.shape[1:]
        return out.reshape(tshape + new_spatial)


