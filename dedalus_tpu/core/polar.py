"""
Disk and annulus bases and polar calculus operators
(reference: dedalus/core/basis.py:2305 DiskBasis, :2011 AnnulusBasis, and the
polar operator subclasses core/operators.py:2878 PolarMOperator,
:3023 PolarGradient etc.).

TPU-native design:
  * Coefficient layout is rectangular (Nphi, Nr) with right-aligned radial
    slots: slot n of azimuthal group m carries Zernike mode (n - nmin(m)),
    nmin(m) = |m|//2 (triangular truncation as validity masking,
    reference: core/basis.py:2368 _nmin, :1793 valid n >= nmin).
  * All m-dependent radial operations (transforms, ladders, conversions) are
    zero-padded stacks applied as ONE batched matmul over the m groups
    (reference loops per m in Python: core/transforms.py:1343).
  * Coefficient-space tensor components are SPIN components; the
    coordinate<->spin rotation happens inside the transforms
    (reference: core/basis.py:1595 forward_spin_recombination).
  * Spin ladder operators D_{+-} = (1/sqrt(2))(d/dr -+ (m+s)/r) assemble by
    quadrature in libraries.zernike; gradient/divergence/Laplacian are
    ladder compositions, diagonal in spin.
"""

import numpy as np

from ..tools.cache import CachedClass, CachedMethod
from ..libraries import zernike
from ..tools import jacobi as jacobi_tools
from .basis import Basis, RealFourier, ComplexFourier, AffineCOV, Jacobi
from .weighted_jacobi import WeightedJacobiRadial
from .coords import PolarCoordinates
from .curvilinear import (component_spins, recombination_matrix,
                          apply_component_pair_matrix, apply_group_stack,
                          SpinBasisMixin)
from ..tools.general import is_complex_dtype


class S1SpinTransformMixin:
    """Spin recombination around the parent Fourier transform, shared by the
    real and complex circle bases (reference: core/basis.py:1798 S1_basis)."""

    def _relevant(self, tensorsig):
        from .curvilinear import _cs_match
        return any(_cs_match(tcs, self.cs) for tcs in tensorsig)

    @property
    def _pair_real(self):
        return not is_complex_dtype_basis(self)

    def forward_transform(self, gdata, axis, scale, library=None,
                          tensorsig=(), sub_axis=0):
        out = super().forward_transform(gdata, axis, scale, library)
        if self._relevant(tensorsig):
            U = recombination_matrix(tensorsig, self.cs)
            tdim = len(tensorsig)
            out = apply_component_pair_matrix(out, U, tdim, axis - tdim,
                                              real=self._pair_real)
        return out

    def backward_transform(self, cdata, axis, scale, library=None,
                           tensorsig=(), sub_axis=0):
        out = cdata
        if self._relevant(tensorsig):
            U = recombination_matrix(tensorsig, self.cs)
            tdim = len(tensorsig)
            out = apply_component_pair_matrix(out, U.conj().T, tdim, axis - tdim,
                                              real=self._pair_real)
        return super().backward_transform(out, axis, scale, library)


def is_complex_dtype_basis(basis):
    from .basis import ComplexFourier
    return isinstance(basis, ComplexFourier)


class S1Basis(S1SpinTransformMixin, RealFourier):
    """
    Circle basis: the azimuth basis / disk edge. Like RealFourier, but
    tensor components over the parent curvilinear coordinate system are
    stored as spin components in coefficient space
    (reference: core/basis.py:1798 S1_basis).
    """

    def __init__(self, coord, size, bounds=(0, 2 * np.pi), dealias=1.0, library=None):
        super().__init__(coord, size, bounds=bounds, dealias=dealias, library=library)
        self.cs = coord.cs

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """Spin pairs carry complex data: all slots valid for tensors;
        scalars drop the m=0 minus-sin slot
        (reference: core/basis.py:1123-1133)."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        axis = self.first_axis
        if axis in sep_widths:
            g = group[axis]
            mask = np.ones((ncomp, 2), dtype=bool)
            if not self._relevant(tensorsig) and g == 0:
                mask[:, 1] = False
            return mask
        mask = np.ones((ncomp, self.size), dtype=bool)
        if not self._relevant(tensorsig):
            mask[:, 1] = False
        return mask


class S1ComplexBasis(S1SpinTransformMixin, ComplexFourier):
    """Complex-dtype circle basis with spin storage for tensors."""

    def __init__(self, coord, size, bounds=(0, 2 * np.pi), dealias=1.0, library=None):
        super().__init__(coord, size, bounds=bounds, dealias=dealias, library=library)
        self.cs = coord.cs


class DiskBasis(SpinBasisMixin, Basis):
    """
    Full disk basis: Fourier azimuth x Zernike radius
    (reference: core/basis.py:2305 DiskBasis).
    """

    dim = 2

    def __init__(self, coordsystem, shape, dtype=np.float64, radius=1.0, k=0,
                 alpha=0, dealias=(1, 1), azimuth_library=None, radius_library=None):
        if not isinstance(coordsystem, PolarCoordinates):
            raise ValueError("Disk coordsys must be PolarCoordinates.")
        self.coordsystem = self.cs = coordsystem
        self.coord = coordsystem.coords[0]
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.radius = float(radius)
        self.k = int(k)
        self.alpha = alpha
        if np.isscalar(dealias):
            dealias = (dealias, dealias)
        self.dealias = tuple(map(float, dealias))
        self.volume = np.pi * radius ** 2
        self.radial_COV = AffineCOV((0, 1), (0, radius))
        Nphi, Nr = self.shape
        self.Nphi, self.Nr = Nphi, Nr
        self.complex = is_complex_dtype(self.dtype)
        if self.complex:
            self.azimuth_basis = S1ComplexBasis(
                coordsystem.azimuth, Nphi, dealias=self.dealias[0],
                library=azimuth_library)
        else:
            self.azimuth_basis = S1Basis(
                coordsystem.azimuth, Nphi, dealias=self.dealias[0],
                library=azimuth_library)
        self.edge = self.azimuth_basis
        self.radius_library = radius_library

    def __repr__(self):
        return f"DiskBasis({self.shape}, k={self.k})"

    # ------------------------------------------------------------ structure

    @property
    def first_axis(self):
        return self.coordsystem.first_axis

    @property
    def family_key(self):
        return (type(self).__name__, self.shape, self.radius, self.alpha,
                self.dtype)

    def coeff_size(self, sub_axis):
        return self.shape[sub_axis]

    def sub_grid_size(self, sub_axis, scale):
        return int(np.ceil(scale * self.shape[sub_axis]))

    def sub_separable(self, sub_axis):
        return sub_axis == 0

    def sub_group_shape(self, sub_axis):
        if sub_axis == 0:
            return 1 if self.complex else 2
        return 1

    def sub_n_groups(self, sub_axis):
        if sub_axis == 0:
            return self.Nphi if self.complex else self.Nphi // 2
        return 1

    @CachedMethod
    def group_m(self):
        """Azimuthal wavenumber per group."""
        if self.complex:
            return np.fft.fftfreq(self.Nphi, d=1.0 / self.Nphi).astype(int)
        return np.arange(self.Nphi // 2)

    @staticmethod
    def _nmin(m):
        return abs(int(m)) // 2

    def clone_with(self, **changes):
        args = dict(coordsystem=self.coordsystem, shape=self.shape,
                    dtype=self.dtype, radius=self.radius, k=self.k,
                    alpha=self.alpha, dealias=self.dealias)
        args.update(changes)
        return DiskBasis(**args)

    def derivative_basis(self, order=1):
        return self.clone_with(k=self.k + order)

    # --------------------------------------------------------------- grids

    def global_grids(self, scales=(1, 1)):
        return (self.azimuth_grid(scales[0]), self.radial_grid(scales[1]))

    def azimuth_grid(self, scale=1.0):
        Ng = self.sub_grid_size(0, scale)
        return 2 * np.pi * np.arange(Ng) / Ng

    def radial_grid(self, scale=1.0):
        Ng = self.sub_grid_size(1, scale)
        z = jacobi_tools.build_grid(Ng, self.alpha, 0)
        return self.radius * np.sqrt((1 + z) / 2)

    # ---------------------------------------------------------- validity

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """(ncomp, gs_az, Nr) at one m group, or full-axis shape when the
        azimuth is not a pencil axis (reference: core/basis.py:1780)."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        az_axis = self.first_axis
        gs = self.sub_group_shape(0)
        ms = self.group_m()
        if az_axis in sep_widths:
            g = group[az_axis]
            m = ms[g]
            mask = np.ones((ncomp, gs, self.Nr), dtype=bool)
            n = np.arange(self.Nr)
            mask &= (n >= self._nmin(m))[None, None, :]
            if self.complex and g == self.Nphi // 2:
                mask[:] = False  # Nyquist
            if (not self.complex) and (not tensorsig) and m == 0:
                mask[:, 1, :] = False  # minus-sin slot of m=0 for scalars
            return mask
        # layout-coupled azimuth (forced matrix_coupling): all m groups
        # stacked into one flattened (m x r) pencil
        G = self.sub_n_groups(0)
        mask = np.ones((ncomp, G * gs, self.Nr), dtype=bool)
        for g in range(G):
            m = ms[g]
            n_ok = np.arange(self.Nr) >= self._nmin(m)
            mask[:, g * gs:(g + 1) * gs, :] &= n_ok[None, None, :]
            if self.complex and g == self.Nphi // 2:
                mask[:, g * gs:(g + 1) * gs, :] = False  # Nyquist
            if (not self.complex) and (not tensorsig) and m == 0:
                mask[:, g * gs + 1, :] = False  # minus-sin of m=0 scalars
            # spin-component validity at m=0 for tensors is enforced by
            # the separable path's per-m structure; under forced coupling
            # the same slots close via the identity machinery
        return mask.reshape(ncomp, G * gs, self.Nr)

    # ------------------------------------------------- radial matrix stacks

    def _build_stack(self, build, rows, cols, align_rows=True, align_cols=True):
        """Assemble (G, rows, cols) stack from per-m builder
        `build(m, nmodes) -> (r, c)`; slot dimensions (align_*=True) are
        right-aligned at nmin(m), grid/point dimensions are not."""
        from ..tools.progress import log_progress
        ms = self.group_m()
        G = len(ms)
        out = np.zeros((G, rows, cols))
        for g, m in log_progress(list(enumerate(ms)), dt=10,
                                 desc=f"{type(self).__name__} stack group"):
            if self.complex and g == self.Nphi // 2:
                continue  # Nyquist
            nmin = self._nmin(m)
            n = self.Nr - nmin
            if n <= 0:
                continue
            mat = build(int(m), n)
            r0 = nmin if align_rows else 0
            c0 = nmin if align_cols else 0
            out[g, r0:r0 + mat.shape[0], c0:c0 + mat.shape[1]] = mat
        return out

    @CachedMethod
    def radial_forward_stack(self, s, scale=1.0):
        """(G, Nr, Ngr): grid values -> right-aligned Zernike coefficients.
        Modes beyond the grid's quadrature exactness (the top |m+s|//2 per
        group) are zeroed, as are groups with |m| > 2(Nr-1)
        (reference: core/transforms.py:1408-1417)."""
        Ngr = self.sub_grid_size(1, scale)
        z = jacobi_tools.build_grid(Ngr, self.alpha, 0)
        _, w = zernike.quadrature(2, Ngr, self.alpha)
        extra = (1 - (1 + z) / 2) ** (self.k - self.alpha) if self.k != self.alpha else 1.0

        def build(m, n):
            if abs(m) > 2 * (self.Nr - 1):
                return np.zeros((n, Ngr))
            Q = zernike.polynomials(2, n, self.k, abs(m + s), z)
            Q = Q * w * extra
            dN = abs(m + s) // 2
            Q[max(Ngr - dN, 0):] = 0
            return Q
        return self._build_stack(build, self.Nr, Ngr, align_cols=False)

    @CachedMethod
    def radial_backward_stack(self, s, scale=1.0):
        """(G, Ngr, Nr): coefficients -> grid values (top modes zeroed to
        mirror the forward truncation)."""
        Ngr = self.sub_grid_size(1, scale)
        z = jacobi_tools.build_grid(Ngr, self.alpha, 0)

        def build(m, n):
            if abs(m) > 2 * (self.Nr - 1):
                return np.zeros((Ngr, n))
            Q = zernike.polynomials(2, n, self.k, abs(m + s), z)
            dN = abs(m + s) // 2
            Q[max(Ngr - dN, 0):] = 0
            return Q.T
        return self._build_stack(build, Ngr, self.Nr, align_rows=False)

    @CachedMethod
    def ladder_stack(self, s, ds):
        """(G, Nr, Nr): D_{ds} on spin-s components, k -> k+1, in problem
        radius units."""
        def build(m, n):
            mu = m + s
            l_in = abs(mu)
            l_out = abs(mu + ds)
            return zernike.ladder_matrix(2, n, self.k, l_in, l_out, mu, ds) / self.radius
        return self._build_stack(build, self.Nr, self.Nr)

    @CachedMethod
    def conversion_stack(self, s, dk):
        """(G, Nr, Nr): k -> k+dk conversion on spin-s components."""
        if dk == 0:
            ms = self.group_m()
            return np.tile(np.eye(self.Nr), (len(ms), 1, 1))

        def build(m, n):
            return zernike.conversion_matrix(2, n, self.k, abs(m + s), dk)
        return self._build_stack(build, self.Nr, self.Nr)

    @CachedMethod
    def laplacian_stack(self, s):
        """(G, Nr, Nr): spin-weighted Laplacian, k -> k+2."""
        up = self.ladder_stack(s, +1)
        k1 = self.clone_with(k=self.k + 1)
        down = k1.ladder_stack(s + 1, -1)
        return 2 * np.einsum("gij,gjk->gik", down, up)

    @CachedMethod
    def interpolation_stack(self, s, position):
        """(G, 1, Nr): evaluate spin-s components at problem radius
        `position`."""
        r0 = self.radial_COV.native_coord(position)

        def build(m, n):
            return zernike.interpolation_row(2, n, self.k, abs(m + s), r0)
        return self._build_stack(build, 1, self.Nr, align_rows=False)

    @CachedMethod
    def integration_row(self):
        """(1, Nr) radial integral against r dr for the m=0, s=0 group, in
        problem units (x radius^2)."""
        row = np.zeros((1, self.Nr))
        row[:, :] = zernike.integration_row(2, self.Nr, self.k, 0)
        return row * self.radius ** 2

    def lift_column(self, index):
        col = np.zeros((self.Nr, 1))
        col[index, 0] = 1.0
        return col

    def constant_component_descr(self, sub_axis, device):
        """Descriptor embedding a constant into this basis along one of its
        axes (reference: core/basis.py constant-mode conversions)."""
        if sub_axis == 0:
            if device:
                col = np.zeros((self.Nphi, 1))
                col[0, 0] = 1.0
                return ("full", col)
            return ("blocks", self.azimuth_basis.constant_blocks())
        # radius: 1 = c * Q_0^{(k,0)} (the lowest mode is constant in r)
        Q0 = zernike.polynomials(2, 1, self.k, 0, np.array([0.0]))[0, 0]
        col = np.zeros((self.Nr, 1))
        col[0, 0] = 1.0 / Q0
        return ("full", col)

    # ---------------------------------------------------- conversion terms

    def conversion_terms(self, target, tensorsig, tshape):
        """Terms converting coefficients into `target` (same family, higher
        k). Returns [(tensor_selector, {abs_axis: descr})]."""
        if not isinstance(target, DiskBasis) or target.shape != self.shape \
                or target.radius != self.radius:
            raise ValueError(f"No conversion from {self} to {target}.")
        dk = target.k - self.k
        if dk == 0:
            return [(None, {})]
        if dk < 0:
            raise ValueError("Cannot convert to lower k.")
        az_axis = self.first_axis
        r_axis = az_axis + 1
        spins = component_spins(tensorsig, self.cs)
        terms = []
        for s in np.unique(spins):
            sel = np.diag((spins == s).astype(float))
            descr = {r_axis: ("gblocks", az_axis, self.conversion_stack(int(s), dk))}
            terms.append((sel if len(spins) > 1 else None, descr))
        return terms


class AnnulusBasis(SpinBasisMixin, WeightedJacobiRadial, Basis):
    """
    Annulus basis: Fourier azimuth x weighted-Jacobi radius on [Ri, Ro]
    (reference: dedalus/core/basis.py:2011 AnnulusBasis and the shell radial
    operator algebra dedalus/libraries/dedalus_sphere/shell.py).

    TPU-native design: level-k fields carry a hidden (dR/r)^k grid prefactor,
    so the spin ladders D_{+-} = (1/sqrt(2))(d/dr -+ (m+s)/r) map level k to
    level k+1 with polynomial-exact matrices (the reference's weighted shell
    spaces; see core/weighted_jacobi.py). All per-m radial operators
    decompose as A - ds*(m+s)*B with m-independent A, B, so the full
    (G, Nr, Nr) stacks assemble without per-m quadrature; application is one
    batched MXU matmul over the m groups. The radial transform itself is m-
    and spin-independent: a single dense matmul (the m-loop of the
    reference, core/basis.py:2190-2210, disappears).
    """

    dim = 2
    radial_sub_axis = 1

    def __init__(self, coordsystem, shape, dtype=np.float64, radii=(1.0, 2.0),
                 k=0, alpha=(-0.5, -0.5), dealias=(1, 1), azimuth_library=None,
                 radius_library=None):
        if not isinstance(coordsystem, PolarCoordinates):
            raise ValueError("Annulus coordsys must be PolarCoordinates.")
        radii = tuple(map(float, radii))
        if min(radii) <= 0:
            raise ValueError("Annulus radii must be positive.")
        if radii[0] >= radii[1]:
            raise ValueError("Annulus radii must be increasing.")
        self.coordsystem = self.cs = coordsystem
        self.coord = coordsystem.coords[0]
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.radii = radii
        self.k = int(k)
        if np.isscalar(alpha):
            alpha = (alpha, alpha)
        self.alpha = tuple(map(float, alpha))
        if np.isscalar(dealias):
            dealias = (dealias, dealias)
        self.dealias = tuple(map(float, dealias))
        self.volume = np.pi * (radii[1] ** 2 - radii[0] ** 2)
        self.dR = radii[1] - radii[0]
        self.rho = (radii[1] + radii[0]) / self.dR
        self.radial_COV = AffineCOV((-1.0, 1.0), radii)
        Nphi, Nr = self.shape
        self.Nphi, self.Nr = Nphi, Nr
        self.complex = is_complex_dtype(self.dtype)
        if self.complex:
            self.azimuth_basis = S1ComplexBasis(
                coordsystem.azimuth, Nphi, dealias=self.dealias[0],
                library=azimuth_library)
        else:
            self.azimuth_basis = S1Basis(
                coordsystem.azimuth, Nphi, dealias=self.dealias[0],
                library=azimuth_library)
        self.inner_edge = self.outer_edge = self.edge = self.azimuth_basis
        self.radius_library = radius_library

    def __repr__(self):
        return f"AnnulusBasis({self.shape}, radii={self.radii}, k={self.k})"

    # ------------------------------------------------------------ structure

    @property
    def first_axis(self):
        return self.coordsystem.first_axis

    @property
    def family_key(self):
        return (type(self).__name__, self.shape, self.radii, self.alpha,
                self.dtype)

    def coeff_size(self, sub_axis):
        return self.shape[sub_axis]

    def sub_grid_size(self, sub_axis, scale):
        return int(np.ceil(scale * self.shape[sub_axis]))

    def sub_separable(self, sub_axis):
        return sub_axis == 0

    def sub_group_shape(self, sub_axis):
        if sub_axis == 0:
            return 1 if self.complex else 2
        return 1

    def sub_n_groups(self, sub_axis):
        if sub_axis == 0:
            return self.Nphi if self.complex else self.Nphi // 2
        return 1

    @CachedMethod
    def group_m(self):
        """Azimuthal wavenumber per group."""
        if self.complex:
            return np.fft.fftfreq(self.Nphi, d=1.0 / self.Nphi).astype(int)
        return np.arange(self.Nphi // 2)

    def clone_with(self, **changes):
        args = dict(coordsystem=self.coordsystem, shape=self.shape,
                    dtype=self.dtype, radii=self.radii, k=self.k,
                    alpha=self.alpha, dealias=self.dealias)
        args.update(changes)
        return AnnulusBasis(**args)

    def derivative_basis(self, order=1):
        return self.clone_with(k=self.k + order)

    # --------------------------------------------------------------- grids

    def global_grids(self, scales=(1, 1)):
        return (self.azimuth_grid(scales[0]), self.radial_grid(scales[1]))

    def azimuth_grid(self, scale=1.0):
        Ng = self.sub_grid_size(0, scale)
        return 2 * np.pi * np.arange(Ng) / Ng

    # ---------------------------------------------------------- validity

    def component_valid_mask(self, tensorsig, group, sep_widths):
        """(ncomp, gs_az, Nr) at one m group (all radial slots valid;
        reference: core/basis.py:2089 _nmin = 0)."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        az_axis = self.first_axis
        gs = self.sub_group_shape(0)
        ms = self.group_m()
        if az_axis in sep_widths:
            g = group[az_axis]
            mask = np.ones((ncomp, gs, self.Nr), dtype=bool)
            if self.complex and g == self.Nphi // 2:
                mask[:] = False  # Nyquist
            if (not self.complex) and (not tensorsig) and ms[g] == 0:
                mask[:, 1, :] = False  # minus-sin slot of m=0 for scalars
            return mask
        # layout-coupled azimuth (azimuthally-varying NCC): every m group's
        # slots live in one pencil, group-major pair order
        ngr = len(ms)
        mask = np.ones((ncomp, ngr, gs, self.Nr), dtype=bool)
        if self.complex:
            mask[:, self.Nphi // 2, :, :] = False  # Nyquist group
        if (not self.complex) and (not tensorsig):
            mask[:, np.asarray(ms) == 0, 1, :] = False
        return mask.reshape(ncomp, ngr * gs, self.Nr)

    # -------------------------------------------------- radial transforms

    def _radial_apply(self, data, tdim, az_axis, r_axis, spins, scale, forward):
        """The annulus radial transform is m- and spin-independent: one dense
        matmul along the radial axis (no per-m batching needed)."""
        return self._radial_matmul(data, r_axis, scale, forward)

    # ------------------------------------------------- radial matrix stacks

    def _tile(self, M):
        """Tile an m-independent matrix over the azimuthal groups, zeroing
        the complex Nyquist group."""
        G = self.sub_n_groups(0)
        out = np.tile(M, (G, 1, 1))
        if self.complex:
            out[self.Nphi // 2] = 0.0
        return out

    @CachedMethod
    def ladder_stack(self, s, ds):
        """(G, Nr, Nr): D_{ds} on spin-s components, k -> k+1, in problem
        radius units."""
        A, B = self._ladder_parts()
        ms = self.group_m()
        mu = (ms + s).astype(np.float64)
        stack = (A[None] - ds * mu[:, None, None] * B[None]) / (np.sqrt(2) * self.dR)
        if self.complex:
            stack = stack.copy()
            stack[self.Nphi // 2] = 0.0
        return stack

    @CachedMethod
    def laplacian_stack(self, s):
        """(G, Nr, Nr): spin-weighted Laplacian, k -> k+2."""
        up = self.ladder_stack(s, +1)
        k1 = self.clone_with(k=self.k + 1)
        down = k1.ladder_stack(s + 1, -1)
        return 2 * np.einsum("gij,gjk->gik", down, up)

    @CachedMethod
    def interpolation_stack(self, s, position):
        """(G, 1, Nr): evaluate spin-s components at problem radius
        `position`."""
        return self._tile(self.radial_interpolation_row(position))

    @CachedMethod
    def integration_row(self):
        """(1, Nr): radial integral against r dr for the (m=0, s=0) group,
        in problem units."""
        return self.radial_integration_row(power=1)

    def lift_column(self, index):
        col = np.zeros((self.Nr, 1))
        col[index, 0] = 1.0
        return col

    def constant_component_descr(self, sub_axis, device):
        """Descriptor embedding a constant into this basis along one of its
        axes."""
        if sub_axis == 0:
            if device:
                col = np.zeros((self.Nphi, 1))
                col[0, 0] = 1.0
                return ("full", col)
            return ("blocks", self.azimuth_basis.constant_blocks())
        return ("full", self.radial_constant_column())

    # ---------------------------------------------------- conversion terms

    def conversion_terms(self, target, tensorsig, tshape):
        """Terms converting coefficients into `target` (same family, higher
        k). Spin-independent: a single full radial matrix."""
        if not isinstance(target, AnnulusBasis) or target.shape != self.shape \
                or target.radii != self.radii:
            raise ValueError(f"No conversion from {self} to {target}.")
        dk = target.k - self.k
        if dk == 0:
            return [(None, {})]
        if dk < 0:
            raise ValueError("Cannot convert to lower k.")
        r_axis = self.first_axis + 1
        return [(None, {r_axis: ("full", self._conversion_matrix_total(dk))})]

# ======================================================================
# Polar calculus operators
# (reference: dedalus/core/operators.py:2878 PolarMOperator family)

from .operators import LinearOperator, parseables  # noqa: E402  (cycle-safe: operators imports nothing from here at module load)
from .domain import Domain  # noqa: E402
from .future import ev  # noqa: E402

SPIN_INDEX = {-1: 0, +1: 1}  # spin ordering (-, +) of PolarCoordinates


def _tile_J(G):
    from .curvilinear import PAIR_J
    return np.tile(PAIR_J, (G, 1, 1))


def _expand_complex_terms(terms, az_axis, G, complex_dtype):
    """
    Convert terms with complex tensor factors to the dtype's representation:
    complex dtype keeps them; real dtype splits C into Re(C) + Im(C) * J,
    with J the per-m-pair rotation on the azimuth axis
    (reference: libraries/spin_recombination.pyx pair arithmetic).
    """
    out = []
    for factor, descrs in terms:
        if factor is None or not np.iscomplexobj(factor):
            out.append((factor, descrs))
            continue
        if complex_dtype:
            out.append((factor, descrs))
            continue
        if np.any(factor.real):
            out.append((factor.real, descrs))
        if np.any(factor.imag):
            descrs_J = list(descrs)
            if descrs_J[az_axis] is not None:
                kind, blocks = descrs_J[az_axis]
                assert kind == "blocks"
                descrs_J[az_axis] = ("blocks",
                                     np.einsum("gij,gjk->gik", _tile_J(G), blocks))
            else:
                descrs_J[az_axis] = ("blocks", _tile_J(G))
            out.append((factor.imag, descrs_J))
    return out


class PolarSpinOperator(LinearOperator):
    """Base for spin-structured operators over a disk/annulus/sphere basis
    (any SpinBasisMixin basis exposing the stack interface)."""

    def _basis(self, operand=None):
        operand = operand or self.operand
        for b in operand.domain.bases:
            if isinstance(b, SpinBasisMixin):
                return b
        raise ValueError("Operand has no spin-weighted basis.")

    def _axes(self, basis):
        az = basis.first_axis
        return az, az + 1


class PolarGradient(PolarSpinOperator):
    """Covariant gradient on the disk: prepends a spin index; spin-s
    components map through D_{+-} ladders
    (reference: core/operators.py:3023 PolarGradient)."""

    name = "Grad"

    def __init__(self, operand, cs):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return PolarGradient(new_args[0], self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(1))
        self.tensorsig = (self.cs,) + tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        spins = component_spins(operand.tensorsig, basis.cs)
        ncomp = len(spins)
        dim = operand.domain.dim
        terms = []
        for sigma, ds in ((0, -1), (1, +1)):
            for s in np.unique(spins):
                sel = np.zeros((2 * ncomp, ncomp))
                for c in np.flatnonzero(spins == s):
                    sel[sigma * ncomp + c, c] = 1.0
                descrs = [None] * dim
                descrs[rad] = ("gblocks", az, basis.ladder_stack(int(s), ds))
                terms.append((sel, descrs))
        return terms


class PolarDivergence(PolarSpinOperator):
    """div u = D_+ u_- + D_- u_+ (contraction of the leading spin index)
    (reference: core/operators.py:3385 Divergence)."""

    name = "Div"

    def __init__(self, operand, index=0):
        if index != 0:
            raise NotImplementedError("Divergence only supports index=0.")
        self.cs = operand.tensorsig[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return PolarDivergence(new_args[0])

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(1))
        self.tensorsig = tuple(operand.tensorsig[1:])
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        rest_sig = operand.tensorsig[1:]
        rest_spins = component_spins(rest_sig, basis.cs)
        nrest = len(rest_spins)
        dim = operand.domain.dim
        terms = []
        for sigma, sspin in ((0, -1), (1, +1)):
            for sr in np.unique(rest_spins):
                sel = np.zeros((nrest, 2 * nrest))
                for c in np.flatnonzero(rest_spins == sr):
                    sel[c, sigma * nrest + c] = 1.0
                s_total = int(sspin + sr)
                descrs = [None] * dim
                descrs[rad] = ("gblocks", az, basis.ladder_stack(s_total, -sspin))
                terms.append((sel, descrs))
        return terms


class PolarLaplacian(PolarSpinOperator):
    """Spin-weighted Laplacian, diagonal over spin components
    (reference: core/operators.py:3952 Laplacian)."""

    name = "Lap"

    def __init__(self, operand, cs=None):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return PolarLaplacian(new_args[0], self.cs)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        self.domain = operand.domain.substitute_basis(basis, basis.derivative_basis(2))
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        spins = component_spins(operand.tensorsig, basis.cs)
        ncomp = len(spins)
        dim = operand.domain.dim
        terms = []
        for s in np.unique(spins):
            sel = np.diag((spins == s).astype(float)) if ncomp > 1 else None
            descrs = [None] * dim
            descrs[rad] = ("gblocks", az, basis.laplacian_stack(int(s)))
            terms.append((sel, descrs))
        return terms


class PolarInterpolate(PolarSpinOperator):
    """Radial interpolation onto the disk edge (S1 basis)
    (reference: core/operators.py:1037 Interpolate / basis.py:2360 edge)."""

    name = "interp"

    def __init__(self, operand, position):
        self.position = position
        super().__init__(operand)

    def rebuild(self, new_args):
        return PolarInterpolate(new_args[0], self.position)

    def _build_metadata(self):
        operand = self.args[0]
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        bases = list(operand.domain.bases)
        bases[az] = basis.azimuth_basis
        bases[rad] = None
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        spins = component_spins(operand.tensorsig, basis.cs)
        ncomp = len(spins)
        dim = operand.domain.dim
        terms = []
        for s in np.unique(spins):
            sel = np.diag((spins == s).astype(float)) if ncomp > 1 else None
            descrs = [None] * dim
            descrs[rad] = ("gblocks", az, basis.interpolation_stack(int(s), self.position))
            terms.append((sel, descrs))
        return terms


class PolarIntegrate(PolarSpinOperator):
    """Integral of a scalar over the disk (reference: core/operators.py:1120)."""

    name = "integ"

    def _build_metadata(self):
        operand = self.args[0]
        if operand.tensorsig:
            raise NotImplementedError("Disk integration of tensors not supported.")
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        bases = list(operand.domain.bases)
        bases[az] = None
        bases[rad] = None
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = ()
        self.dtype = operand.dtype

    def terms(self):
        basis = self._basis(self.operand)
        az, rad = self._axes(basis)
        dim = self.operand.domain.dim
        G = basis.sub_n_groups(0)
        gs = basis.sub_group_shape(0)
        az_blocks = np.zeros((G, gs, gs))
        az_blocks[0, 0, 0] = 2 * np.pi
        descrs = [None] * dim
        descrs[az] = ("blocks", az_blocks)
        descrs[rad] = ("full", basis.integration_row())
        return [(None, descrs)]

    def device_terms(self):
        basis = self._basis(self.operand)
        az, rad = self._axes(basis)
        dim = self.operand.domain.dim
        row = np.zeros((1, basis.Nphi))
        row[0, 0] = 2 * np.pi
        descrs = [None] * dim
        descrs[az] = ("full", row)
        descrs[rad] = ("full", basis.integration_row())
        return [(None, descrs)]


class PolarLift(PolarSpinOperator):
    """Lift an edge (S1) tau field into the disk via radial mode `n`
    (reference: core/operators.py:4228 Lift)."""

    name = "Lift"

    def __init__(self, operand, basis, n):
        self.basis = basis
        self.n = n
        super().__init__(operand)

    def rebuild(self, new_args):
        return PolarLift(new_args[0], self.basis, self.n)

    def _basis(self, operand=None):
        return self.basis

    def _build_metadata(self):
        operand = self.args[0]
        basis = self.basis
        az, rad = self._axes(basis)
        if operand.domain.bases[rad] is not None:
            raise ValueError("Lift operand must be constant along the radius.")
        bases = list(operand.domain.bases)
        bases[az] = basis
        bases[rad] = basis
        self.domain = Domain(operand.dist, bases)
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        basis = self.basis
        az, rad = self._axes(basis)
        dim = self.operand.domain.dim
        index = self.n if self.n >= 0 else basis.Nr + self.n
        descrs = [None] * dim
        descrs[rad] = ("full", basis.lift_column(index))
        return [(None, descrs)]


class PolarSkew(PolarSpinOperator):
    """skew(u) = z x u: multiplies spin-sigma components by +i*sigma
    ((z x u)_s = (-u_phi + s i u_r)/sqrt(2) = s i u_s;
    reference: core/operators.py:2019 Skew)."""

    name = "Skew"

    def _build_metadata(self):
        operand = self.args[0]
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        basis = self._basis(operand)
        az, rad = self._axes(basis)
        spins = component_spins(operand.tensorsig, basis.cs)
        factor = np.diag(+1j * spins).astype(complex)
        dim = operand.domain.dim
        raw = [(factor, [None] * dim)]
        return _expand_complex_terms(raw, az, basis.sub_n_groups(0), basis.complex)


class SpinTrace(PolarSpinOperator):
    """Trace of the two leading indices in 2D spin components: the spin
    metric contracts (-,+) and (+,-) (reference: core/operators.py:1693
    Trace with spin storage)."""

    name = "Trace"
    natural_layout = "g"

    def _build_metadata(self):
        operand = self.args[0]
        if len(operand.tensorsig) < 2 or operand.tensorsig[0] != operand.tensorsig[1]:
            raise ValueError("Trace requires two equal leading indices.")
        self.domain = operand.domain
        self.tensorsig = tuple(operand.tensorsig[2:])
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        rest = int(np.prod(operand.tshape[2:], dtype=int)) \
            if operand.tshape[2:] else 1
        # spin ordering (-, +): metric pairs (-,+) and (+,-)
        row = np.array([[0.0, 1.0, 1.0, 0.0]])
        factor = np.kron(row, np.identity(rest))
        return [(factor, [None] * operand.domain.dim)]

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "g")
        return data[0, 0] + data[1, 1]


class PolarComponent(LinearOperator):
    """
    Extract the radial or azimuthal coordinate component of the leading
    index (reference: core/operators.py:2160-2283 Component/Radial/Azimuthal).

    On the disk interior this is a grid-space selection (the coordinate
    component of a smooth vector is NOT a regular scalar, so there is no
    coefficient-space matrix). On edge (S1) fields, where spin pairs simply
    store the rotated components, a coefficient matrix exists and the
    operator can appear on equation LHS (e.g. radial(u(r=R)) = 0).
    """

    name = "Comp"
    natural_layout = "g"

    def __init__(self, operand, which, index=0):
        self.which = which  # 'radial' | 'azimuthal'
        self.index = int(index)
        self.comp_index = {"azimuthal": 0, "radial": 1}[which]
        super().__init__(operand)

    def rebuild(self, new_args):
        return PolarComponent(new_args[0], self.which, self.index)

    def _build_metadata(self):
        operand = self.args[0]
        self.cs = operand.tensorsig[self.index]
        ts = list(operand.tensorsig)
        ts.pop(self.index)
        self.domain = operand.domain
        self.tensorsig = tuple(ts)
        self.dtype = operand.dtype

    def ev_impl(self, ctx):
        data = ev(self.operand, ctx, "g")
        return data[(slice(None),) * self.index + (self.comp_index,)]

    def terms(self):
        operand = self.operand
        az_basis = None
        for b in operand.domain.bases:
            if isinstance(b, AnnulusBasis):
                # no coordinate singularity: the pointwise spin->coordinate
                # rotation is a valid coefficient-space operation
                az_basis = b.azimuth_basis
            elif isinstance(b, SpinBasisMixin):
                raise ValueError(
                    "Component extraction has no coefficient matrix on the "
                    f"interior of {b!r} (coordinate components of smooth "
                    "tensors are not regular there); apply it to edge fields "
                    "or on the RHS.")
            elif isinstance(b, (S1Basis, S1ComplexBasis)):
                az_basis = b
        # spin storage (-, +): u_r = (u_- + u_+)/sqrt(2);
        # u_phi = (i u_- - i u_+)/sqrt(2)
        if az_basis is None:
            raise ValueError("Component extraction needs an S1/polar basis.")
        before = int(np.prod(operand.tshape[:self.index], dtype=int)) \
            if operand.tshape[:self.index] else 1
        after = int(np.prod(operand.tshape[self.index + 1:], dtype=int)) \
            if operand.tshape[self.index + 1:] else 1
        if self.which == "radial":
            row = np.array([[1.0, 1.0]]) / np.sqrt(2)
        else:
            row = np.array([[1j, -1j]]) / np.sqrt(2)
        factor = np.kron(np.identity(before), np.kron(row, np.identity(after)))
        dim = operand.domain.dim
        raw = [(factor, [None] * dim)]
        complex_dtype = isinstance(az_basis, S1ComplexBasis)
        return _expand_complex_terms(raw, az_basis.first_axis,
                                     az_basis.n_groups, complex_dtype)
