"""
Cylinder calculus: vector operators over DirectProduct coordinate systems
(Coordinate/Cartesian factors x PolarCoordinates), covering periodic
cylinders (Fourier x disk) and cylindrical annuli (Fourier x annulus)
(reference: core/coords.py:99 DirectProduct; core/operators.py:2384
DirectProduct operator subclasses; tests/test_cylinder_calculus.py).

Component convention: the product's tensor components concatenate the
factors' components in order, with the polar factor stored as spin (-, +)
components in coefficient space (curvilinear.recombination_matrix applies
the block-diagonal intertwiner inside the disk transforms). The straight
factors' components carry spin 0.

Operator structure: every term is either
  * a straight-axis derivative (separable Fourier differentiation blocks)
    paired with a radial k -> k+1 conversion stack so all terms land on the
    disk's derivative basis, or
  * a polar ladder/Laplacian stack, exactly as the 2D polar operators.
Curl uses the standard embedding (right-handed x, y, z) orientation, the
convention the reference's cylinder tests check.
"""

import numpy as np

from .coords import CurvilinearCoordinateSystem, DirectProduct
from .curvilinear import SpinBasisMixin, component_spins
from .operators import LinearOperator, _diff_descr
from .polar import SPIN_INDEX, _expand_complex_terms

__all__ = ["CylinderGradient", "CylinderDivergence", "CylinderLaplacian",
           "CylinderCurl"]


def _cyl_parts(operand, dp):
    """
    Decompose a DirectProduct operand: returns (polar_cs, spin_basis,
    straight, pol_off) with `straight` = [(comp_offset, coord, axis,
    basis_or_None)] for the non-curvilinear factors' coordinates and
    `pol_off` the polar factor's component offset.
    """
    polar = dp.curvilinear_sub()
    if polar is None:
        raise ValueError("DirectProduct calculus requires a curvilinear factor.")
    disk = None
    for b in operand.domain.bases:
        if isinstance(b, SpinBasisMixin) and b.cs == polar:
            disk = b
    if disk is None:
        # Polar-constant operand (e.g. a z-only background profile):
        # gradients/Laplacians reduce to straight derivatives as long as no
        # tensor index couples to the polar factor (a constant-COMPONENT
        # polar vector is not a constant vector field — its covariant
        # derivatives need the basis).
        if any(np.any(_entry_spins_any(tcs, polar))
               or _touches(tcs, polar)
               for tcs in operand.tensorsig):
            raise ValueError(
                "DirectProduct operand with polar tensor components has no "
                "basis on the polar factor (covariant derivatives of "
                "constant-component polar vectors are not representable).")
    straight = []
    off = 0
    for cs in dp.coordsystems:
        if not isinstance(cs, CurvilinearCoordinateSystem):
            for j, coord in enumerate(cs.coords):
                axis = operand.dist.get_axis(coord)
                basis = operand.domain.bases[axis]
                if basis is not None and not basis.separable:
                    raise NotImplementedError(
                        "DirectProduct calculus requires separable (Fourier) "
                        "bases on the straight factors (a coupled straight "
                        "axis would need two-coupled-axis pencils).")
                straight.append((off + j, coord, axis, basis))
        off += cs.dim
    pol_off = dp.sub_slice(polar).start
    return polar, disk, straight, pol_off


def _touches(tcs, polar):
    """Whether a tensor index couples to the polar factor (directly, or as
    a factor of a DirectProduct index)."""
    from .curvilinear import _cs_match
    if _cs_match(tcs, polar):
        return True
    subs = getattr(tcs, "coordsystems", None)
    return subs is not None and any(_cs_match(sub, polar) for sub in subs)


def _entry_spins_any(tcs, polar):
    from .curvilinear import _entry_spins
    return _entry_spins(tcs, polar)


def _conv_descr(disk, az, s, dk):
    """Radial k -> k+dk conversion descriptor: per-m spin stacks on the
    disk (Zernike), one spin-independent matrix on the annulus; None when
    the operand has no polar basis (polar-constant fields)."""
    if dk == 0 or disk is None:
        return None
    if hasattr(disk, "conversion_stack"):
        return ("gblocks", az, disk.conversion_stack(int(s), dk))
    return ("full", disk._conversion_matrix_total(dk))


class CylinderOperator(LinearOperator):
    """Base for DirectProduct (cylinder) calculus operators."""

    def _parts(self, operand=None):
        return _cyl_parts(operand or self.operand, self._dp())

    def _dp(self):
        raise NotImplementedError


class CylinderGradient(CylinderOperator):
    """Covariant gradient on the product: straight components are plain
    derivatives (with radial k -> k+1 conversion); polar components map
    through the D_{+-} spin ladders (reference: core/operators.py:2384
    Gradient on DirectProduct)."""

    name = "Grad"

    def __init__(self, operand, cs):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return CylinderGradient(new_args[0], self.cs)

    def _dp(self):
        return self.cs

    def _build_metadata(self):
        operand = self.args[0]
        _, disk, _, _ = _cyl_parts(operand, self.cs)
        self.domain = (operand.domain if disk is None else
                       operand.domain.substitute_basis(
                           disk, disk.derivative_basis(1)))
        self.tensorsig = (self.cs,) + tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        polar, disk, straight, pol_off = self._parts()
        az = disk.first_axis if disk is not None else None
        rad = None if az is None else az + 1
        spins = component_spins(operand.tensorsig, polar)
        ncomp = len(spins)
        dim = operand.domain.dim
        D = self.cs.dim
        terms = []
        for off_c, coord, axis, basis in straight:
            if basis is None:
                continue  # derivative of a constant axis
            for s in np.unique(spins):
                sel = np.zeros((D * ncomp, ncomp))
                for c in np.flatnonzero(spins == s):
                    sel[off_c * ncomp + c, c] = 1.0
                descrs = [None] * dim
                descrs[axis] = _diff_descr(basis)
                if disk is not None:
                    descrs[rad] = _conv_descr(disk, az, s, 1)
                terms.append((sel, descrs))
        if disk is None:
            return terms   # polar-constant operand: ladder rows are zero
        for sigma, ds in ((0, -1), (1, +1)):
            for s in np.unique(spins):
                sel = np.zeros((D * ncomp, ncomp))
                for c in np.flatnonzero(spins == s):
                    sel[(pol_off + sigma) * ncomp + c, c] = 1.0
                descrs = [None] * dim
                descrs[rad] = ("gblocks", az, disk.ladder_stack(int(s), ds))
                terms.append((sel, descrs))
        return terms


class CylinderDivergence(CylinderOperator):
    """div u = sum_c d_c u_c + D_+ u_- + D_- u_+ over the leading product
    index (reference: core/operators.py:3385 Divergence)."""

    name = "Div"

    def __init__(self, operand, index=0):
        if index != 0:
            raise NotImplementedError("Divergence only supports index=0.")
        self.cs = operand.tensorsig[0]
        super().__init__(operand)

    def rebuild(self, new_args):
        return CylinderDivergence(new_args[0])

    def _dp(self):
        return self.cs

    def _build_metadata(self):
        operand = self.args[0]
        _, disk, _, _ = _cyl_parts(operand, self.cs)
        self.domain = operand.domain.substitute_basis(
            disk, disk.derivative_basis(1))
        self.tensorsig = tuple(operand.tensorsig[1:])
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        polar, disk, straight, pol_off = self._parts()
        az = disk.first_axis
        rad = az + 1
        rest_sig = operand.tensorsig[1:]
        rest_spins = component_spins(rest_sig, polar)
        nrest = len(rest_spins)
        dim = operand.domain.dim
        D = self.cs.dim
        terms = []
        for off_c, coord, axis, basis in straight:
            if basis is None:
                continue
            for s in np.unique(rest_spins):
                sel = np.zeros((nrest, D * nrest))
                for c in np.flatnonzero(rest_spins == s):
                    sel[c, off_c * nrest + c] = 1.0
                descrs = [None] * dim
                descrs[axis] = _diff_descr(basis)
                descrs[rad] = _conv_descr(disk, az, s, 1)
                terms.append((sel, descrs))
        for sigma, sspin in ((0, -1), (1, +1)):
            for sr in np.unique(rest_spins):
                sel = np.zeros((nrest, D * nrest))
                for c in np.flatnonzero(rest_spins == sr):
                    sel[c, (pol_off + sigma) * nrest + c] = 1.0
                s_total = int(sspin + sr)
                descrs = [None] * dim
                descrs[rad] = ("gblocks", az,
                               disk.ladder_stack(s_total, -sspin))
                terms.append((sel, descrs))
        return terms


class CylinderLaplacian(CylinderOperator):
    """lap X = sum_c d_c^2 X + polar spin-weighted Laplacian, diagonal over
    spin components (reference: core/operators.py:3952 Laplacian)."""

    name = "Lap"

    def __init__(self, operand, cs=None):
        self.cs = cs
        super().__init__(operand)

    def rebuild(self, new_args):
        return CylinderLaplacian(new_args[0], self.cs)

    def _dp(self):
        return self.cs

    def _build_metadata(self):
        operand = self.args[0]
        _, disk, _, _ = _cyl_parts(operand, self.cs)
        self.domain = (operand.domain if disk is None else
                       operand.domain.substitute_basis(
                           disk, disk.derivative_basis(2)))
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        polar, disk, straight, pol_off = self._parts()
        az = disk.first_axis if disk is not None else None
        rad = None if az is None else az + 1
        spins = component_spins(operand.tensorsig, polar)
        ncomp = len(spins)
        dim = operand.domain.dim
        terms = []
        for off_c, coord, axis, basis in straight:
            if basis is None:
                continue
            kind, blocks = _diff_descr(basis)
            assert kind == "blocks"
            blocks2 = np.einsum("gij,gjk->gik", blocks, blocks)
            for s in np.unique(spins):
                sel = (np.diag((spins == s).astype(float))
                       if ncomp > 1 else None)
                descrs = [None] * dim
                descrs[axis] = ("blocks", blocks2)
                if disk is not None:
                    descrs[rad] = _conv_descr(disk, az, s, 2)
                terms.append((sel, descrs))
        if disk is None:
            return terms
        for s in np.unique(spins):
            sel = np.diag((spins == s).astype(float)) if ncomp > 1 else None
            descrs = [None] * dim
            descrs[rad] = ("gblocks", az, disk.laplacian_stack(int(s)))
            terms.append((sel, descrs))
        return terms


class CylinderCurl(CylinderOperator):
    """
    Curl of a product vector (one straight coordinate z + polar), in the
    standard embedding orientation (the convention checked by the
    reference's tests/test_cylinder_calculus.py::test_curl_vector):

        (curl u)_z = i (D_+ u_-  -  D_- u_+)
        (curl u)_+ = i (d_z u_+  -  D_+ u_z)
        (curl u)_- = -i (d_z u_-  -  D_- u_z)

    derived from the cylindrical-coordinate curl with u_+- = (u_r +-
    i u_phi)/sqrt(2); multiplication by i is represented on real dtypes by
    the azimuthal pair rotation (polar._expand_complex_terms).
    """

    name = "Curl"

    def __init__(self, operand):
        if len(operand.tensorsig) != 1:
            raise ValueError("Curl requires a vector operand.")
        self.cs = operand.tensorsig[0]
        if self.cs.dim != 3:
            raise ValueError("Curl requires a 3D coordinate system.")
        super().__init__(operand)

    def rebuild(self, new_args):
        return CylinderCurl(new_args[0])

    def _dp(self):
        return self.cs

    def _build_metadata(self):
        operand = self.args[0]
        _, disk, _, _ = _cyl_parts(operand, self.cs)
        self.domain = operand.domain.substitute_basis(
            disk, disk.derivative_basis(1))
        self.tensorsig = tuple(operand.tensorsig)
        self.dtype = operand.dtype

    def terms(self):
        operand = self.operand
        polar, disk, straight, pol_off = self._parts()
        if len(straight) != 1:
            raise NotImplementedError(
                "Cylinder curl requires exactly one straight coordinate.")
        z_off, _, z_axis, z_basis = straight[0]
        az = disk.first_axis
        rad = az + 1
        dim = operand.domain.dim
        m_row = pol_off + SPIN_INDEX[-1]
        p_row = pol_off + SPIN_INDEX[+1]
        raw = []

        def term(row, col, coeff, descrs):
            E = np.zeros((3, 3), dtype=complex)
            E[row, col] = coeff
            raw.append((E, descrs))

        def rdescr(stack):
            d = [None] * dim
            d[rad] = ("gblocks", az, stack)
            return d

        # (curl u)_z = i D_+ u_-  -  i D_- u_+
        term(z_off, m_row, +1j, rdescr(disk.ladder_stack(-1, +1)))
        term(z_off, p_row, -1j, rdescr(disk.ladder_stack(+1, -1)))
        # (curl u)_+ = i d_z u_+  -  i D_+ u_z
        if z_basis is not None:
            d = [None] * dim
            d[rad] = _conv_descr(disk, az, +1, 1)
            d[z_axis] = _diff_descr(z_basis)
            term(p_row, p_row, +1j, d)
        term(p_row, z_off, -1j, rdescr(disk.ladder_stack(0, +1)))
        # (curl u)_- = -i d_z u_-  +  i D_- u_z
        if z_basis is not None:
            d = [None] * dim
            d[rad] = _conv_descr(disk, az, -1, 1)
            d[z_axis] = _diff_descr(z_basis)
            term(m_row, m_row, -1j, d)
        term(m_row, z_off, +1j, rdescr(disk.ladder_stack(0, -1)))
        return _expand_complex_terms(raw, az, disk.sub_n_groups(0),
                                     disk.complex)
