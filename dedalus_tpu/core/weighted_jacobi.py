"""
Weighted-Jacobi radial machinery shared by the annulus and spherical-shell
bases (reference: dedalus/libraries/dedalus_sphere/shell.py operator algebra,
dedalus/core/basis.py:2011 AnnulusBasis / :3682 ShellRadialBasis).

Level-k fields on [Ri, Ro] carry a hidden (dR/r)^k grid prefactor: the grid
values are f(r) = (dR/r)^k g(z) with g polynomial in the native coordinate
z in [-1, 1], r = (dR/2)(z + rho). In these spaces the ladder operators
D = d/dr + c/r map level k to level k+1 with polynomial-exact matrices:

    D f = (dR/r)^(k+1) (1/dR) [ (z+rho) g'(z) + (c - k) g(z) ]

so every radial operator decomposes as (A + c*B)/dR with the two
m/ell-independent quadrature projections A = proj[(z+rho) g' - k g] and
B = proj[g]. All matrices are assembled host-side by Gauss quadrature
(exact for the polynomial integrands) and shipped to device as constants.

Host classes provide: Nr, alpha (tuple), k, rho, dR, radial_COV, clone_with.
"""

import numpy as np

from ..tools.cache import CachedMethod
from ..tools import jacobi as jacobi_tools
from ..tools.array import apply_matrix_jax


class WeightedJacobiRadial:
    """Mixin: transforms and operator parts on the weighted radial interval."""

    @property
    def a_k(self):
        return self.alpha[0] + self.k

    @property
    def b_k(self):
        return self.alpha[1] + self.k

    def _z_grid(self, scale=1.0, sub_axis=None):
        Ng = self.sub_grid_size(self.radial_sub_axis, scale)
        return jacobi_tools.build_grid(Ng, self.alpha[0], self.alpha[1])

    def radial_grid(self, scale=1.0):
        return self.radial_COV.problem_coord(self._z_grid(scale))

    # ----------------------------------------------------------- transforms

    @CachedMethod
    def _radial_forward_matrix(self, scale=1.0):
        """(Nr, Ngr): grid values -> level-k coefficients. Projects onto the
        base (alpha) polynomials then applies the banded base->k conversion,
        with the (r/dR)^k weight folded into the quadrature columns."""
        Ngr = self.sub_grid_size(self.radial_sub_axis, scale)
        a0, b0 = self.alpha
        F = jacobi_tools.forward_matrix(self.Nr, a0, b0, Ngr)
        if self.k:
            r = self.radial_grid(scale)
            F = F * (r / self.dR) ** self.k
            C = jacobi_tools.conversion_matrix(self.Nr, a0, b0, self.k, self.k)
            F = C @ F
        return F

    @CachedMethod
    def _radial_backward_matrix(self, scale=1.0):
        """(Ngr, Nr): level-k coefficients -> grid values."""
        z = self._z_grid(scale)
        P = jacobi_tools.build_polynomials(self.Nr, self.a_k, self.b_k, z)
        B = P.T
        if self.k:
            r = self.radial_grid(scale)
            B = B * ((self.dR / r) ** self.k)[:, None]
        return B

    def _radial_matmul(self, data, r_axis, scale, forward):
        # pass the HOST matrix: apply_matrix_jax's match_precision funnel
        # routes it through tools.jitlift.device_constant (CachedMethod
        # keeps the object identity stable for interning), so compiled
        # programs receive it as a runtime argument, not program text
        M = self._radial_forward_matrix(scale) if forward \
            else self._radial_backward_matrix(scale)
        return apply_matrix_jax(M, data, r_axis)

    # ------------------------------------------------------- operator parts

    @CachedMethod
    def _ladder_parts(self):
        """(A, B): the m/ell-independent pieces of every radial ladder at
        this level, as maps into the level-(k+1) polynomials."""
        N = self.Nr
        a, b = self.a_k, self.b_k
        Nq = N + 8
        z = jacobi_tools.build_grid(Nq, a + 1, b + 1)
        w = jacobi_tools.build_weights(Nq, a + 1, b + 1)
        P = jacobi_tools.build_polynomials(N, a, b, z)
        dP = jacobi_tools.build_polynomial_derivatives(N, a, b, z)
        Pout = jacobi_tools.build_polynomials(N, a + 1, b + 1, z)
        W = Pout * w
        A = W @ ((z + self.rho) * dP - self.k * P).T
        B = W @ P.T
        return A, B

    def radial_ladder(self, c):
        """(Nr, Nr): D = d/dr + c/r, level k -> k+1, problem units."""
        A, B = self._ladder_parts()
        return (A + c * B) / self.dR

    @CachedMethod
    def _conversion_matrix_single(self):
        """(Nr, Nr): level k -> k+1 identity-conversion E (exact)."""
        N = self.Nr
        a, b = self.a_k, self.b_k
        Nq = N + 8
        z = jacobi_tools.build_grid(Nq, a + 1, b + 1)
        w = jacobi_tools.build_weights(Nq, a + 1, b + 1)
        P = jacobi_tools.build_polynomials(N, a, b, z)
        Pout = jacobi_tools.build_polynomials(N, a + 1, b + 1, z)
        return (Pout * w) @ (((z + self.rho) / 2) * P).T

    def _conversion_matrix_total(self, dk):
        """(Nr, Nr): level k -> k+dk."""
        M = np.eye(self.Nr)
        basis = self
        for _ in range(int(dk)):
            M = basis._conversion_matrix_single() @ M
            basis = basis.clone_with(k=basis.k + 1)
        return M

    @CachedMethod
    def radial_interpolation_row(self, position):
        """(1, Nr): evaluate level-k coefficients at problem radius."""
        z0 = self.radial_COV.native_coord(position)
        row = jacobi_tools.build_polynomials(self.Nr, self.a_k, self.b_k,
                                             np.array([float(z0)]))[:, 0]
        return (row * (self.dR / float(position)) ** self.k)[None, :]

    @CachedMethod
    def radial_integration_row(self, power):
        """(1, Nr): integral against r^power dr in problem units. Rational
        for k > power but smooth on the interval, so a generous Legendre
        rule is spectrally exact."""
        from scipy import special
        Nq = self.Nr + self.k + 64
        z, w = special.roots_legendre(Nq)
        P = jacobi_tools.build_polynomials(self.Nr, self.a_k, self.b_k, z)
        r_over_dR = (z + self.rho) / 2
        vals = r_over_dR ** (power - self.k)
        row = (P * (w * vals)) @ np.ones(Nq)
        return row[None, :] * self.dR ** (power + 1) / 2

    @CachedMethod
    def radial_constant_column(self):
        """(Nr, 1): level-k coefficients representing the constant 1."""
        a, b = self.a_k, self.b_k
        Nq = self.Nr + self.k + 4
        z = jacobi_tools.build_grid(Nq, a, b)
        w = jacobi_tools.build_weights(Nq, a, b)
        P = jacobi_tools.build_polynomials(self.Nr, a, b, z)
        col = (P * w) @ ((z + self.rho) / 2) ** self.k
        return col[:, None]

    def radial_multiplication_matrix(self, f_radial_coeffs, f_k, k_out=0):
        """
        (Nr, Nr): maps level-`self.k` radial coefficients of u to
        level-`k_out` coefficients of (f*u), for an angularly-constant NCC
        f with level-`f_k` radial coefficients. Assembled as
        transform->pointwise multiply->transform by quadrature
        (reference: core/basis.py:2293 _last_axis_component_ncc_matrix,
        Clenshaw replaced by direct quadrature).
        """
        a0, b0 = self.alpha
        f_radial_coeffs = np.asarray(f_radial_coeffs)
        if not np.iscomplexobj(f_radial_coeffs):
            f_radial_coeffs = f_radial_coeffs.astype(np.float64)
        Nf = f_radial_coeffs.shape[-1]
        Nq = self.Nr + Nf + self.k + int(abs(k_out)) + 32
        z = jacobi_tools.build_grid(Nq, a0 + k_out, b0 + k_out)
        w = jacobi_tools.build_weights(Nq, a0 + k_out, b0 + k_out)
        rr = (z + self.rho) / 2  # r/dR
        fvals = (f_radial_coeffs @ jacobi_tools.build_polynomials(
            Nf, a0 + f_k, b0 + f_k, z)) * rr ** (-f_k)
        U = jacobi_tools.build_polynomials(self.Nr, self.a_k, self.b_k, z) \
            * rr ** (k_out - self.k)
        Pout = jacobi_tools.build_polynomials(self.Nr, a0 + k_out, b0 + k_out, z)
        return (Pout * w) @ (fvals * U).T
