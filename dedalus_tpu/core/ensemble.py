"""
EnsembleSolver: one compiled step, thousands of simulations.

The production workload for a spectral-PDE service is rarely one big run —
it is parameter sweeps, uncertainty ensembles, and per-request scenarios:
thousands of *independent* IVPs that, stepped serially, each pay their own
dispatch and Python loop overhead. This module turns the repo's unit of
work from "a run" into "a fleet": it takes ONE built
`InitialValueSolver` (whose pencil matrices are already batched over
groups) and vmaps the timestepper's raw step body over a second, leading
**member** axis, then shards that axis over a 1-D
`jax.sharding.Mesh(("batch",))` so N members on D devices advance as one
XLA program — no per-member dispatch, no per-member compile, and (with a
common dt) ONE shared LHS factorization serving the whole fleet.

Batched operands per member:
  * initial conditions         — the gathered pencil state X, (N, G, S)
  * RHS parameters / NCC data  — every non-variable field feeding F
                                 (forcings, parameter fields) becomes a
                                 batched operand of the compiled step
  * simulation time            — (N,) device clock (members drift apart
                                 after drops/rewinds)
  * dt                         — (N,) operand; heterogeneous values need
                                 `per_member_dt=True` (RK schemes), which
                                 vmaps the LHS factorization too

Shared operands: the pencil matrices M/L, the (common-dt) factorization,
and the multistep coefficient vectors — replicated over the mesh.

Sharding layout (the SNIPPETS `get_naive_sharding` pattern): every
member-batched array leads with the member axis and is placed by ONE
`device_put` with `NamedSharding(mesh, P("batch"))`; the fleet step runs
inside `shard_map` over that axis (each device steps only its local
member block — XLA cannot partition fft/LU ops, so plain GSPMD would
all-gather; see core/meshctx.py and libraries/pencilops.shard_groups for
the same discipline on the group axis).

Per-member health: a jitted per-member probe (NaN/Inf count + max|coeff|)
runs on the PR-2 cadence machinery; a diverged member is restored from
its slot in the rolling fleet-snapshot ring (PR-4's capture-by-reference
trick — device arrays are immutable, so snapshots are O(1) and sync-free)
and either **dropped** (frozen + masked out, the default) or **rewound**
with a per-member dt backoff (`policy="rewind"`, RK + per_member_dt) —
without stopping the batch, and without retracing the compiled step (the
active mask is a value operand, not a shape).

Device loss: a fleet dispatch that loses a device (in production: an
XlaRuntimeError from the runtime; in tests: the chaos `lose_device`
fault) is reported through `notify_device_loss(d)` and handled before
the next dispatch — the fleet RE-SHARDS onto the surviving devices: live
member blocks are reconstructed host-side from the surviving shards
only, the lost device's members are restored from the newest finite
FleetSnapshot ring slot or from the last durable sharded checkpoint
(tools/dcheckpoint.py, `evolve(checkpoint_dir=...)`), a fresh 1-D mesh
over the survivors is built (members re-padded to the new device
multiple), and every block-memoized fleet program is rebuilt for the new
layout. Members with no finite snapshot and no checkpoint drop. Reshard
events are counted (`ensemble/reshards`) and itemized in
`reshard_events`.

Durable fleet checkpoints use the sharded format exclusively — each
device's member block is already the natural shard — written
synchronously or asynchronously on a cadence from `evolve`, and restored
ELASTICALLY: `restore_checkpoint` re-pads the true member rows onto
whatever mesh the restoring fleet has, so a checkpoint taken on 8
devices restores onto 4 or 1 (and vice versa) bit-identically.

Telemetry: `ensemble/...` counters (fleet_steps, member_steps, dropped,
rewinds, health_checks, reshards, checkpoints_written) plus an
`ensemble` summary block (members / active / dropped / reshards /
ensemble-steps-per-s) in every flushed record — `python -m dedalus_tpu
report` renders it as its own column set.

Serving (continuous batching, service/batching.py): a fleet can also be
driven as a **micro-batch of independent served requests** — members
attach (`attach_member`) and detach (`detach_member`) at block
boundaries as value operands (never a retrace), each carries its own
steps-remaining budget (`R`, carried through the scan so a finished
member freezes mid-block without leaving the compiled program), a
multistep member joining a running fleet replays its own order build-up
with everyone else frozen (`ramp_members` — bit-identical to a solo
run's ramp), per-member Hermitian-projection phases follow each
member's OWN iteration count (`project_members`), and `step_fleet`
dispatches steady blocks without the fleet-global cadence/ramp logic
the serving driver owns.
"""

import functools
import logging
import time as time_mod

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .subsystems import scatter_state, state_key
from . import timesteppers as timesteppers_mod
from ..tools import dcheckpoint
from ..tools import metrics as metrics_mod
from ..tools import retrace as retrace_mod
from ..tools.compat import shard_map
from ..tools.config import cfg_get
from ..tools.exceptions import CheckpointError

logger = logging.getLogger(__name__)

__all__ = ["EnsembleSolver", "FleetSnapshot"]

MEMBER_AXIS = "batch"

# default per-member steps-remaining budget: effectively unbounded (the
# classic evolve/step_many drivers stop the whole fleet, so members never
# exhaust it); the serving driver sets true per-request budgets
UNBOUNDED_STEPS = 1 << 30


def _repad(a, members, n_pad, pad_value=None):
    """Re-pad a member-leading host array onto a new padded length: the
    true member rows are kept, padding rows are clones of member 0 (or
    `pad_value`-filled for masks/counters). The single helper behind the
    two recovery paths that must stay bit-identical (device-loss reshard
    and elastic checkpoint restore)."""
    a = np.asarray(a)[:members]
    pad = n_pad - members
    if not pad:
        return a
    if pad_value is None:
        tail = np.broadcast_to(a[:1], (pad,) + a.shape[1:])
    else:
        tail = np.full((pad,) + a.shape[1:], pad_value, a.dtype)
    return np.concatenate([a, tail])


class FleetSnapshot:
    """One last-known-good capture of the whole fleet. Device arrays are
    held by REFERENCE (immutable), so capture is O(1) and never syncs;
    each member's slice doubles as that member's snapshot slot on the
    recovery path (restores are per-member `where` masks)."""

    __slots__ = ("X", "T", "hists", "iteration", "sim_times",
                 "wall_ts", "_finite", "_probe")

    def __init__(self, X, T, hists, iteration, sim_times, probe=None):
        self.X = X
        self.T = T
        self.hists = hists          # (F, MX, LX) or None for RK
        self.iteration = int(iteration)
        self.sim_times = np.array(sim_times)
        self.wall_ts = time_mod.time()
        self._finite = None
        self._probe = probe

    def member_finite(self, m):
        """Whether member m's captured state is fully finite. Routed
        through the fleet's jitted per-member probe (`probe` at capture):
        the reduction runs on device and only the (N,) nonfinite-count
        vector comes back — never the full fleet state. Recovery path
        only, never the stepping loop."""
        if self._finite is None:
            if self._probe is not None:
                nonfinite, _ = jax.device_get(self._probe(self.X))
                self._finite = np.asarray(nonfinite) == 0
            else:
                flat = np.asarray(self.X).reshape(self.X.shape[0], -1)
                self._finite = np.all(np.isfinite(flat), axis=1)
        return bool(self._finite[m])


class EnsembleSolver:
    """
    Fleet driver over one built `InitialValueSolver` template.

    Parameters
    ----------
    solver : InitialValueSolver
        The built template (undistributed, native-precision step path).
        Its state at construction seeds every member's default IC.
    members : int
        Number of ensemble members N.
    mesh : "auto" | None | jax.sharding.Mesh
        "auto" builds a 1-D Mesh(("batch",)) over all local devices when
        more than one is visible (the member count is padded up to a
        multiple of the device count with inactive clones); None disables
        sharding; an explicit 1-D mesh is used as given.
    per_member_dt : bool
        Carry dt as a genuinely heterogeneous (N,) operand, vmapping the
        LHS factorization per member (RK schemes only — multistep
        coefficient ramps are fleet-global). Required for
        policy="rewind"'s per-member dt backoff. Chosen at construction
        so the compiled program never switches variants mid-run (which
        would retrace).
    policy : "drop" | "rewind"
        What to do with a diverged member: freeze it at its newest
        finite snapshot slot and mask it out ("drop"), or restore it and
        retry with its dt scaled by `dt_backoff`, dropping after
        `max_member_retries` failed retries ("rewind").
    health_cadence, snapshot_cadence, ring_size, dt_backoff,
    max_member_retries :
        Recovery knobs; defaults from the [health]/[resilience] config
        sections.
    metrics, metrics_file :
        Fleet telemetry (tools/metrics.py); `metrics.iterations` counts
        MEMBER-steps, so the flushed `steps_per_sec` IS
        ensemble-steps-per-second.
    """

    def __init__(self, solver, members, mesh="auto", per_member_dt=False,
                 policy="drop", health_cadence=None, snapshot_cadence=None,
                 ring_size=None, dt_backoff=None, max_member_retries=None,
                 warmup_iterations=None, metrics=None, metrics_file=None):
        if getattr(solver, "_dd", None) is not None:
            raise ValueError(
                "EnsembleSolver requires the native step path; the template "
                "uses the emulated-f64 (double-double) runner. Build it "
                "with [execution] EMULATED_F64 = never.")
        if getattr(solver.dist, "mesh", None) is not None:
            raise ValueError(
                "EnsembleSolver shards the MEMBER axis; the template must "
                "be undistributed (no spatial mesh on the Distributor).")
        ts = solver.timestepper
        self._multistep = isinstance(ts, timesteppers_mod.MultistepIMEX)
        if not self._multistep and not isinstance(
                ts, timesteppers_mod.RungeKuttaIMEX):
            raise ValueError(f"Unsupported timestepper {type(ts).__name__}")
        if per_member_dt and self._multistep:
            raise ValueError(
                "per_member_dt requires a Runge-Kutta scheme (multistep "
                "coefficient ramps are fleet-global); use e.g. RK222.")
        if policy not in ("drop", "rewind"):
            raise ValueError(f"policy must be 'drop' or 'rewind', "
                             f"got {policy!r}")
        if policy == "rewind" and not per_member_dt:
            raise ValueError(
                "policy='rewind' retries with a per-member dt backoff; "
                "pass per_member_dt=True (RK schemes).")
        self.solver = solver
        self.timestepper = ts
        self.members = int(members)
        self.per_member_dt = bool(per_member_dt)
        self.policy = policy
        self.rd = solver.real_dtype
        # pencil axis of a 2-D batch x pencil mesh (None on 1-D meshes):
        # set by _resolve_mesh when the composition is active
        self.pencil_axis = None
        self.mesh = self._resolve_mesh(mesh)
        D = self.mesh.shape[MEMBER_AXIS] if self.mesh is not None else 1
        self.n_pad = -(-self.members // D) * D
        # ---------------------------------------------------- fleet state
        G, S = solver.pencil_shape
        X0 = solver.gather_fields()
        self.X = self._put(jnp.broadcast_to(X0, (self.n_pad, G, S)),
                           pencil_dim=1)
        self.sim_times = np.full(self.n_pad, float(solver.sim_time))
        self.T = self._put_host(self.sim_times, dtype=self.rd)
        self.dts = np.zeros(self.n_pad)
        self.DT = self._put(jnp.zeros(self.n_pad, dtype=self.rd))
        self.active_host = np.zeros(self.n_pad, dtype=bool)
        self.active_host[:self.members] = True
        self._active_dev = self._put_host(self.active_host)
        # per-member steps-remaining budget (host mirror + device value
        # operand carried through the fleet scan): a member whose budget
        # hits zero freezes mid-block — per-member stop without leaving
        # the compiled program. Unbounded by default.
        self.steps_left = np.full(self.n_pad, UNBOUNDED_STEPS,
                                  dtype=np.int64)
        self.R = self._put_host(self.steps_left, dtype=jnp.int32)
        if self._multistep:
            s = ts.steps
            zeros = jnp.zeros((self.n_pad, s, G, S),
                              dtype=solver.pencil_dtype)
            self.F_hist = self._put(zeros, pencil_dim=2)
            self.MX_hist = self._put(zeros, pencil_dim=2)
            self.LX_hist = self._put(zeros, pencil_dim=2)
            self._ms_iter = 0
            self._dt_hist = []
        # per-member RHS operands: every extra field batched (N, ...)
        self._extras = [self._put(jnp.broadcast_to(
            arr, (self.n_pad,) + arr.shape))
            for arr in solver.rhs_extra()]
        # ------------------------------------------------------- programs
        self._programs = {}
        self._project_prog = None
        self._probe_prog = None
        self._vfactor_prog = None
        self._lhs_key = None
        self._lhs_aux = None
        # ------------------------------------------------------- recovery
        self.iteration = 0
        self.ring = []
        self.ring_size = int(ring_size if ring_size is not None
                             else cfg_get("resilience", "RING_SNAPSHOTS", "4"))
        self.snapshot_cadence = int(
            snapshot_cadence if snapshot_cadence is not None
            else cfg_get("resilience", "SNAPSHOT_CADENCE", "50"))
        self.health_cadence = int(
            health_cadence if health_cadence is not None
            else cfg_get("health", "CHECK_CADENCE", "200"))
        self.max_abs_limit = float(cfg_get("health", "MAX_ABS_LIMIT", "1e12"))
        self.dt_backoff = float(dt_backoff if dt_backoff is not None
                                else cfg_get("resilience", "DT_BACKOFF", "0.5"))
        self.max_member_retries = int(
            max_member_retries if max_member_retries is not None
            else cfg_get("resilience", "MAX_RETRIES", "3"))
        self._health_gate = metrics_mod.CadenceGate(self.health_cadence)
        self._snapshot_gate = metrics_mod.CadenceGate(self.snapshot_cadence)
        self._retries = np.zeros(self.n_pad, dtype=int)
        self.dropped = []
        self.rewound = []
        # device-loss / reshard bookkeeping
        self._lost_devices = []
        self.reshard_events = []
        # durable sharded checkpoints (tools/dcheckpoint.py)
        self._checkpoint_dir = None
        self._checkpointer = None
        # ------------------------------------------------------ telemetry
        self.warmup_iterations = int(
            warmup_iterations if warmup_iterations is not None
            else solver.warmup_iterations)
        self._warmed = False
        self.metrics = metrics_mod.resolve(
            metrics, sink=metrics_file,
            meta={"config": f"ensemble[{self.members}]",
                  "backend": jax.default_backend(),
                  "dtype": str(np.dtype(solver.pencil_dtype)),
                  "pencil_shape": list(solver.pencil_shape),
                  "members": self.members})
        self.metrics.inc("ensemble/members", self.members)
        pencil_txt = (f" x {self.mesh.shape[self.pencil_axis]} pencil "
                      f"device(s)" if self.pencil_axis is not None else "")
        logger.info(
            f"EnsembleSolver: {self.members} members (padded {self.n_pad}) "
            f"on {D} batch device(s){pencil_txt}, "
            f"{'per-member' if self.per_member_dt else 'common'} dt, "
            f"policy={self.policy}")

    # ------------------------------------------------------------ plumbing

    def _resolve_mesh(self, mesh):
        if mesh is None:
            return None
        if mesh == "auto":
            devices = jax.devices()
            if len(devices) < 2:
                return None
            return Mesh(np.array(devices), (MEMBER_AXIS,))
        if len(mesh.axis_names) not in (1, 2):
            raise ValueError(
                "EnsembleSolver requires a 1-D member mesh or a 2-D "
                "batch x pencil mesh.")
        if mesh.axis_names[0] != MEMBER_AXIS:
            raise ValueError(
                f"member mesh axis must be named {MEMBER_AXIS!r} and "
                f"come first")
        if len(mesh.axis_names) == 2:
            # 2-D composition: members vmap over `batch` while every
            # member's pencil state distributes over the second axis —
            # the fleet programs run manual over batch with the pencil
            # axis in GSPMD auto mode, and the per-member transform
            # walks/solves route through meshctx/pencilops over the
            # pencil axis (the same discipline as distribute_solver's
            # 1-D pencil mesh, composed under the member axis)
            pencil = mesh.axis_names[1]
            if pencil == MEMBER_AXIS:
                raise ValueError("the pencil mesh axis must not be "
                                 f"named {MEMBER_AXIS!r}")
            if self.per_member_dt:
                raise ValueError(
                    "per_member_dt is not supported on a 2-D batch x "
                    "pencil mesh (the vmapped per-member factorization "
                    "is member-manual); use a 1-D member mesh.")
            G = self.solver.pencil_shape[0]
            n = mesh.shape[pencil]
            if G % n:
                raise ValueError(
                    f"pencil mesh axis {pencil!r} (size {n}) does not "
                    f"divide the pencil-group count {G}; choose "
                    f"resolutions with G % n == 0.")
            self.pencil_axis = pencil
        return mesh

    def _put(self, arr, pencil_dim=None):
        """One device_put onto the member sharding (SNIPPETS §[2]
        get_naive_sharding: lead axis on the batch mesh axis). On a 2-D
        batch x pencil mesh, `pencil_dim` names the array dim carrying
        the pencil-group axis (1 for the (N, G, S) state, 2 for the
        (N, steps, G, S) histories), sharded over the pencil axis."""
        if self.mesh is None:
            return jnp.asarray(arr)
        spec = [MEMBER_AXIS]
        if self.pencil_axis is not None and pencil_dim is not None:
            spec += [None] * (pencil_dim - 1) + [self.pencil_axis]
        return jax.device_put(arr, NamedSharding(self.mesh, P(*spec)))

    def _put_host(self, arr, dtype=None):
        """Place a HOST mirror (active mask, dts, clocks, step budgets)
        on device BY COPY. `jnp.asarray` zero-copies aligned numpy
        buffers on CPU, so placing a mirror without the copy aliases the
        device operand to the very buffer later in-place mutations
        (`active_host[m] = ...`, `sim_times += ...`) rewrite — which
        retroactively changes the operand of dispatches still queued on
        the async stream (observed: members silently freezing for the
        tail of a batch when a detach flipped the aliased mask)."""
        return self._put(jnp.array(arr, dtype=dtype))

    @property
    def layout(self):
        return self.solver.layout

    @property
    def variables(self):
        return self.solver.variables

    @property
    def active(self):
        """Per-member activity mask (true member count, no padding)."""
        return self.active_host[:self.members].copy()

    @property
    def n_active(self):
        return int(self.active_host[:self.members].sum())

    # ----------------------------------------------------------- member IO

    def init_members(self, fn):
        """
        Initialize the fleet: `fn(i)` is called for each member index and
        should set the template problem's fields (state variables AND any
        parameter/forcing fields) for member i; the gathered state and
        every RHS extra field are recorded as that member's batched
        operands. Fields `fn` leaves untouched simply repeat across
        members.
        """
        solver = self.solver
        X_rows, extra_rows = [], []
        for i in range(self.members):
            fn(i)
            X_rows.append(solver.gather_fields())
            extra_rows.append([jnp.asarray(a) for a in solver.rhs_extra()])
        pad = self.n_pad - self.members
        X_rows += [X_rows[0]] * pad
        extra_rows += [extra_rows[0]] * pad
        self.X = self._put(jnp.stack(X_rows), pencil_dim=1)
        self._extras = [self._put(jnp.stack([row[k] for row in extra_rows]))
                        for k in range(len(extra_rows[0]))]
        return self

    def set_states(self, X):
        """Install per-member initial pencil states directly:
        X is (members, G, S)."""
        X = jnp.asarray(X, dtype=self.solver.pencil_dtype)
        if X.shape[0] != self.members:
            raise ValueError(f"expected leading dim {self.members}, "
                             f"got {X.shape[0]}")
        pad = self.n_pad - self.members
        if pad:
            X = jnp.concatenate([X, jnp.broadcast_to(
                X[:1], (pad,) + X.shape[1:])])
        self.X = self._put(X, pencil_dim=1)
        return self

    def member_arrays(self, m):
        """{state_key: coefficient array} of member m's current state."""
        if not 0 <= m < self.members:
            raise IndexError(f"member {m} out of range [0, {self.members})")
        arrays = scatter_state(self.layout, self.variables, self.X[m])
        return {k: np.asarray(v) for k, v in arrays.items()}

    def load_member(self, m):
        """Scatter member m's state into the template problem fields (for
        plotting/analysis with the normal Field API)."""
        solver = self.solver
        arrays = scatter_state(self.layout, self.variables, self.X[m])
        for v in self.variables:
            v.preset_coeff(arrays[state_key(v)])
            v.mark_modified()
        return solver.state

    # ------------------------------------------------------------ programs

    def _specs(self, tree, batched):
        spec = P(MEMBER_AXIS) if batched else P()
        return jax.tree.map(lambda _: spec, tree)

    def _pencil_contexts(self, fn):
        """Wrap a fleet body so its TRACE runs under the pencil routing
        contexts of the 2-D batch x pencil composition: factor/solve
        funnels shard over the pencil axis (pencilops.pencil_mesh) and
        the per-member transform walks publish the mesh
        (field.mesh_transforms; meshctx.walk_axis_names filters the
        batch axis out, so the walks transpose over the pencil axes
        only). Identity on 1-D member meshes."""
        if self.pencil_axis is None:
            return fn
        from . import field as field_mod
        from ..libraries import pencilops

        def with_contexts(*args):
            with pencilops.pencil_mesh(self.mesh, self.pencil_axis), \
                    field_mod.mesh_transforms(
                        self.mesh,
                        chunks=getattr(self.solver, "_transpose_chunks",
                                       None)):
                return fn(*args)

        return with_contexts

    def _wrap(self, raw, label, args, batched_flags):
        """jit (and shard_map, when a mesh is active) one fleet program.
        `batched_flags` marks which top-level args carry the member axis;
        specs are built per-leaf from the actual argument tree. On a 2-D
        batch x pencil mesh the shard_map is MANUAL over the member axis
        only, with the pencil axis in GSPMD auto mode — inside, the
        vmapped bodies route their ffts/solves through nested shard_maps
        over the pencil axis (core/meshctx.local_fft,
        libraries/pencilops.shard_groups), the same targeted routing the
        1-D distributed solver uses, composed under the member axis."""
        fn = retrace_mod.noted(self._pencil_contexts(raw), label)
        if self.mesh is not None:
            in_specs = tuple(self._specs(a, b)
                             for a, b in zip(args, batched_flags))
            if self.pencil_axis is not None:
                fn = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                               out_specs=P(MEMBER_AXIS), check_rep=False,
                               auto=frozenset({self.pencil_axis}))
            else:
                fn = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                               out_specs=P(MEMBER_AXIS))
        # every call site memoizes the wrapper (self._programs[n] /
        # self._project_prog / self._vfactor_prog), so each fleet program
        # is built and traced exactly once
        return jax.jit(fn)  # dedalus-lint: disable=DTL003

    @staticmethod
    def _freeze(new, old, act):
        """Hold inactive members at their previous values (a dropped
        member's slice never advances; NaN arithmetic from a poisoned
        member is computed then discarded — vmap guarantees no
        cross-member reduction, so poison cannot leak)."""
        def one(a, b):
            keep = act.reshape((-1,) + (1,) * (a.ndim - 1))
            return jnp.where(keep, a, b)
        return jax.tree.map(one, new, old)

    def _fleet_multistep(self, n, M, L, X, T, DT, act, R, extras,
                         Fh, MXh, LXh, a, b, c, aux):
        body_fn = self.timestepper.advance_body

        def body(carry, _):
            X, T, R, Fh, MXh, LXh = carry
            # per-step liveness: the active mask AND a positive steps-
            # remaining budget — a member that finishes inside the block
            # freezes for the rest of the scan (computed-then-discarded,
            # same as a dropped member)
            live = act & (R > 0)
            af = live.astype(self.rd)
            with jax.named_scope("dedalus/ensemble/step"):
                Xn, Fhn, MXhn, LXhn = jax.vmap(
                    body_fn,
                    in_axes=(None, None, 0, 0, 0, 0, 0, 0,
                             None, None, None, None))(
                    M, L, X, T, extras, Fh, MXh, LXh, a, b, c, aux)
            Xn, Fhn, MXhn, LXhn = self._freeze(
                (Xn, Fhn, MXhn, LXhn), (X, Fh, MXh, LXh), live)
            return (Xn, T + DT * af, R - live, Fhn, MXhn, LXhn), None

        carry, _ = jax.lax.scan(body, (X, T, R, Fh, MXh, LXh), None,
                                length=n)
        return carry

    def _fleet_rk(self, n, M, L, X, T, DT, act, R, extras, auxs):
        body_fn = self.timestepper.step_body
        aux_ax = 0 if self.per_member_dt else None

        def body(carry, _):
            X, T, R = carry
            live = act & (R > 0)
            af = live.astype(self.rd)
            with jax.named_scope("dedalus/ensemble/step"):
                Xn = jax.vmap(
                    body_fn,
                    in_axes=(None, None, 0, 0, 0, 0, aux_ax))(
                    M, L, X, T, DT, extras, auxs)
            Xn = self._freeze(Xn, X, live)
            return (Xn, T + DT * af, R - live), None

        carry, _ = jax.lax.scan(body, (X, T, R), None, length=n)
        return carry

    def _program(self, n, args, batched_flags):
        # memoized per block size in self._programs (cache-subscript
        # guard): one wrapper per static n, so fixed-size drivers trace
        # each program exactly once and the retrace sentinel stays quiet
        prog = self._programs.get(n)
        if prog is None:
            raw = functools.partial(
                self._fleet_multistep if self._multistep else self._fleet_rk,
                n)
            prog = self._programs[n] = self._wrap(
                raw, f"ensemble/fleet_step[{n}]", args, batched_flags)
        return prog

    def _pencil_project_body(self):
        """Per-member dealias-roundtrip projection for the 2-D batch x
        pencil composition. The solver's own projection body is reused
        where a layout walk exists; variables too low-dimensional to
        walk (1-D tau fields: their only axis IS the pencil-sharded one)
        route their whole roundtrip through meshctx.gathered_apply —
        gather over the pencil axis, transform locally, slice the block
        back — instead of leaving an unrouted fft in the GSPMD-auto
        region (which the SPMD partitioner cannot place)."""
        from . import meshctx
        from .field import (transform_to_grid, transform_to_coeff,
                            _walk_divisible)
        from .subsystems import gather_state, scatter_state, state_key
        solver = self.solver
        layout, variables = solver.layout, solver.variables
        mesh, pencil = self.mesh, self.pencil_axis

        def project(X):
            arrays = scatter_state(layout, variables, X)
            out = {}
            for v in variables:
                scales = tuple(v.domain.dealias)
                tdim = len(v.tensorsig)
                data = arrays[state_key(v)]

                def roundtrip(a, v=v, scales=scales, tdim=tdim):
                    g = transform_to_grid(a, v.domain, scales, tdim,
                                          tensorsig=v.tensorsig)
                    return transform_to_coeff(g, v.domain, scales, tdim,
                                              tensorsig=v.tensorsig)

                walkable = (v.domain.dim > 1
                            and _walk_divisible(data, v.domain, scales,
                                                tdim, mesh, (pencil,)))
                if walkable:
                    out[state_key(v)] = roundtrip(data)
                else:
                    out[state_key(v)] = meshctx.gathered_apply(
                        roundtrip, data, mesh, pencil, dim=tdim)
            return gather_state(layout, variables, out)

        return project

    def _ensure_project_prog(self):
        if self._project_prog is None:
            if self.pencil_axis is None:
                self.solver._ensure_project()
                proj = self.solver._project_body
            else:
                proj = self._pencil_project_body()

            def raw(X, act):
                Xp = jax.vmap(proj)(X)
                return self._freeze(Xp, X, act)

            self._project_prog = self._wrap(
                raw, "ensemble/project", (self.X, self._active_dev),
                (True, True))
        return self._project_prog

    def _project_fleet(self):
        """Vmapped Hermitian/valid-mode re-projection of active members
        (mirrors solver.enforce_hermitian_symmetry; inactive members are
        frozen through it)."""
        self.X = self._ensure_project_prog()(self.X, self._active_dev)

    def _probe(self, X=None):
        """Per-member health reduction: (nonfinite count, max |coeff|) —
        one jitted program, host-read only on the health cadence. Also
        runs over ring-snapshot states (FleetSnapshot.member_finite), so
        snapshot validation never gathers the fleet to host."""
        if self._probe_prog is None:
            def raw(X):
                def one(x):
                    ax = jnp.abs(x)
                    return (jnp.sum(~jnp.isfinite(x)), jnp.max(ax))
                with metrics_mod.trace_scope("ensemble", "probe"):
                    return jax.vmap(one)(X)
            self._probe_prog = jax.jit(
                retrace_mod.noted(raw, "ensemble/probe"))
        return self._probe_prog(self.X if X is None else X)

    # ------------------------------------------------------ factorization

    def _factor_context(self):
        """Pencil routing for the (host-driven) LHS factorization of a
        2-D batch x pencil fleet: the factor program traces with the
        pencil mesh active, so the factors come out sharded over the
        pencil axis like the fleet state they solve against (the
        timestepper's own pencil_mesh(None) wrapper inherits this outer
        context). Null context on 1-D member meshes."""
        import contextlib
        if self.pencil_axis is None:
            return contextlib.nullcontext()
        from ..libraries import pencilops
        return pencilops.pencil_mesh(self.mesh, self.pencil_axis)

    def _ensure_factor_rk(self, dt):
        ts = self.timestepper
        solver = self.solver
        if not self.per_member_dt:
            key = round(float(dt), 14)
            if key != self._lhs_key:
                self._lhs_key = key
                with self._factor_context():
                    self._lhs_aux = ts._factor(
                        solver.M_mat, solver.L_mat,
                        jnp.asarray(float(dt), dtype=self.rd))
            return
        key = tuple(np.round(self.dts, 14))
        if key == self._lhs_key:
            return
        self._lhs_key = key
        if self._vfactor_prog is None:
            ops = solver.ops
            uniq = ts.uniq_H_diag
            slot = ts.stage_slot
            one = jnp.asarray(1.0, dtype=self.rd)

            def raw(M, L, dts):
                def member(dt):
                    return [ops.factor_lincomb(one, M, dt * h, L)
                            for h in uniq]
                auxs = jax.vmap(member)(dts)
                return [auxs[j] for j in slot]

            self._vfactor_prog = self._wrap(
                raw, "ensemble/vfactor",
                (solver.M_mat, solver.L_mat, self.DT),
                (False, False, True))
        self._lhs_aux = self._vfactor_prog(
            solver.M_mat, solver.L_mat, self.DT)

    def _ensure_factor_ms(self, a0, b0):
        key = (round(float(a0), 14), round(float(b0), 14))
        if key != self._lhs_key:
            self._lhs_key = key
            with self._factor_context():
                self._lhs_aux = self.timestepper._factor(
                    self.solver.M_mat, self.solver.L_mat,
                    jnp.asarray(a0, dtype=self.rd),
                    jnp.asarray(b0, dtype=self.rd))

    # ------------------------------------------------------------ stepping

    def _set_common_dt(self, dt):
        dt = float(dt)
        target = np.full(self.n_pad, dt)
        if self.per_member_dt:
            # members mid-rewind keep their backed-off dt (capped by the
            # request): a per-step driving loop re-passes the same scalar
            # dt every call, and overwriting the backoff would make the
            # member re-diverge identically until its retries burn out
            backed = self._retries > 0
            target[backed] = np.minimum(self.dts[backed], dt)
        live = self.active_host | (self.dts == 0.0)
        if not np.all(self.dts[live] == target[live]):
            self.dts = target
            self.DT = self._put_host(target, dtype=self.rd)

    def _dispatch(self, n, a=None, b=None, c=None, act_dev=None,
                  act_host=None):
        """One scanned fleet dispatch of n steps. `act_dev`/`act_host`
        override the activity mask for this dispatch only (the cohort-
        ramp path freezes everyone but the ramping members); both must
        describe the same membership. Returns the per-member steps
        actually taken (host array, padding rows included)."""
        solver = self.solver
        if act_dev is None:
            act_dev = self._active_dev
        if act_host is None:
            act_host = self.active_host
        if self._multistep:
            args = (solver.M_mat, solver.L_mat, self.X, self.T, self.DT,
                    act_dev, self.R, self._extras, self.F_hist,
                    self.MX_hist, self.LX_hist, a, b, c, self._lhs_aux)
            flags = (False, False, True, True, True, True, True, True,
                     True, True, True, False, False, False, False)
            prog = self._program(n, args, flags)
            self.X, self.T, self.R, self.F_hist, self.MX_hist, \
                self.LX_hist = prog(*args)
        else:
            args = (solver.M_mat, solver.L_mat, self.X, self.T, self.DT,
                    act_dev, self.R, self._extras, self._lhs_aux)
            flags = (False, False, True, True, True, True, True, True,
                     self.per_member_dt)
            prog = self._program(n, args, flags)
            self.X, self.T, self.R = prog(*args)
        self.iteration += n
        # host mirror of the in-scan liveness rule: an active member
        # takes min(n, budget) steps, everyone else none
        taken = np.where(act_host,
                         np.minimum(n, np.maximum(self.steps_left, 0)), 0)
        self.steps_left = self.steps_left - taken
        self.sim_times += taken * self.dts
        self.metrics.inc("ensemble/fleet_steps", n)
        member_steps = int(taken[:self.members].sum())
        self.metrics.inc("ensemble/member_steps", member_steps)
        self.metrics.observe_steps(member_steps)
        return taken

    def step_program_handle(self, n=None):
        """(program, args) of a compiled fleet step program — the
        inspection handle the program contract checker
        (tools/lint/progcheck.py) lowers: `program.lower(*args)` is the
        same jitted shard_map program `_dispatch` runs for a block of n
        steps, so collective placement (zero full-state gathers, the
        all-to-all census) and the manual/auto shard_map structure are
        checked on the EXECUTING program, not a reconstruction. Requires
        a warmed fleet (step_many has run at least one scanned block so
        factors and — for multistep schemes — the coefficient ramp
        exist). `n` defaults to the largest block already traced."""
        ts = self.timestepper
        if n is None:
            if not self._programs:
                raise RuntimeError(
                    "step_program_handle needs a stepped fleet: run "
                    "step_many first so a block program exists")
            n = max(self._programs)
        n = int(n)
        if self._multistep:
            s = ts.steps
            if len(self._dt_hist) < s:
                raise RuntimeError(
                    "step_program_handle needs the multistep ramp "
                    "complete: run step_many past the first "
                    f"{s} steps first")
            a, b, c = ts.compute_coefficients(self._dt_hist, s)
            a = np.concatenate([a, np.zeros(s + 1 - len(a))])
            b = np.concatenate([b, np.zeros(s + 1 - len(b))])
            c = np.concatenate([c, np.zeros(s - len(c))])
            self._ensure_factor_ms(a[0], b[0])
            args = (self.solver.M_mat, self.solver.L_mat, self.X, self.T,
                    self.DT, self._active_dev, self.R, self._extras,
                    self.F_hist, self.MX_hist, self.LX_hist,
                    jnp.asarray(a, dtype=self.rd),
                    jnp.asarray(b, dtype=self.rd),
                    jnp.asarray(c, dtype=self.rd), self._lhs_aux)
            flags = (False, False, True, True, True, True, True, True,
                     True, True, True, False, False, False, False)
        else:
            self._ensure_factor_rk(self.dts[0])
            args = (self.solver.M_mat, self.solver.L_mat, self.X, self.T,
                    self.DT, self._active_dev, self.R, self._extras,
                    self._lhs_aux)
            flags = (False, False, True, True, True, True, True, True,
                     self.per_member_dt)
        return self._program(n, args, flags), args

    def _ms_single(self, dt):
        """One fleet multistep step with the ramp's order build-up
        (mirrors MultistepIMEX.step coefficient handling)."""
        ts = self.timestepper
        s = ts.steps
        self._dt_hist = [float(dt)] + self._dt_hist[:s - 1]
        self._ms_iter += 1
        order = min(s, self._ms_iter)
        a, b, c = ts.compute_coefficients(self._dt_hist, order)
        a = np.concatenate([a, np.zeros(s + 1 - len(a))])
        b = np.concatenate([b, np.zeros(s + 1 - len(b))])
        c = np.concatenate([c, np.zeros(s - len(c))])
        self._ensure_factor_ms(a[0], b[0])
        self._dispatch(1, jnp.asarray(a, dtype=self.rd),
                       jnp.asarray(b, dtype=self.rd),
                       jnp.asarray(c, dtype=self.rd))

    def step(self, dt=None):
        self.step_many(1, dt)

    def step_many(self, n, dt=None):
        """
        Advance the whole fleet n constant-dt steps: the multistep ramp
        (order build-up) runs as single fleet steps, the remainder as ONE
        scanned device dispatch. With per_member_dt, `dt` may be a
        (members,) array; scalars apply fleet-wide.
        """
        n = int(n)
        if n <= 0:
            return
        if self._lost_devices:
            # pending device-loss notifications are drained BEFORE any
            # dispatch (and before the health probe can mistake the lost
            # shard's garbage for per-member divergence)
            self._handle_device_loss()
        solver = self.solver
        ts = self.timestepper
        if dt is not None:
            if np.ndim(dt) == 0:
                self._set_common_dt(dt)
            else:
                self.set_member_dts(dt)
        if not np.all(np.isfinite(self.dts[self.active_host])) \
                or not np.any(self.dts):
            raise ValueError(f"invalid ensemble dt state: {self.dts}")
        # Hermitian/valid-mode re-projection cadence (mirrors
        # solver.step_many's block condition)
        cadence = solver.enforce_real_cadence
        if cadence:
            r = self.iteration % cadence
            if (n >= cadence or r < ts.steps or (cadence - r) < n):
                self._project_fleet()
        if self._multistep:
            dt0 = float(self.dts[0])
            s = ts.steps
            while n > 0 and not (self._ms_iter >= s
                                 and len(self._dt_hist) == s
                                 and all(abs(k - dt0) < 1e-15 * abs(dt0)
                                         for k in self._dt_hist)):
                self._ms_single(dt0)
                n -= 1
            if n > 0:
                a, b, c = ts.compute_coefficients(self._dt_hist, s)
                self._ensure_factor_ms(a[0], b[0])
                self._dispatch(n, jnp.asarray(a, dtype=self.rd),
                               jnp.asarray(b, dtype=self.rd),
                               jnp.asarray(c, dtype=self.rd))
        else:
            self._ensure_factor_rk(self.dts[0])
            self._dispatch(n)
        if not self._warmed and self.iteration >= self.warmup_iterations:
            self._end_warmup()
        if self._health_gate.due(self.iteration):
            self.check_health()

    def set_member_dts(self, dts):
        """Install per-member timesteps (requires per_member_dt=True)."""
        if not self.per_member_dt:
            raise ValueError("per-member dt values require "
                             "per_member_dt=True")
        dts = np.asarray(dts, dtype=float)
        if dts.shape != (self.members,):
            raise ValueError(f"expected shape ({self.members},), "
                             f"got {dts.shape}")
        full = np.concatenate([dts, np.full(self.n_pad - self.members,
                                            dts[0] if len(dts) else 0.0)])
        if not np.array_equal(full, self.dts):
            self.dts = full
            self.DT = self._put_host(full, dtype=self.rd)

    def _end_warmup(self):
        """Warmup boundary: compile-bearing first dispatches stay out of
        the measured loop window; the retrace sentinel arms (each fleet
        program wrapper must trace exactly once from here on)."""
        self._warmed = True
        jax.block_until_ready(self.X)
        self.metrics.reset_loop()
        retrace_mod.sentinel.arm()

    # --------------------------------------- serving attach/detach/stepping
    #
    # The continuous-batching driver (service/batching.py) treats the
    # fleet as seats: requests attach and detach at block boundaries,
    # each with its own steps budget and projection phase. Everything
    # here is a VALUE-operand mutation of the already-compiled fleet
    # programs — zero post-warmup retraces across join/detach is the
    # serving acceptance bar.

    def _seat_mask(self, ms):
        mask = np.zeros(self.n_pad, dtype=bool)
        for m in np.atleast_1d(np.asarray(ms, dtype=int)):
            if not 0 <= m < self.members:
                raise IndexError(
                    f"member {m} out of range [0, {self.members})")
            mask[m] = True
        return mask

    def _masked_write(self, arr, mask_dev, row):
        """Seat write as a value-operand `where` (an `.at[m]` update
        would bake the seat index into the compiled scatter — one XLA
        program per seat; the mask form is one program per array
        shape)."""
        keep = mask_dev.reshape((-1,) + (1,) * (arr.ndim - 1))
        return jnp.where(keep, jnp.asarray(row, dtype=arr.dtype)[None],
                         arr)

    def attach_member(self, m, X_row, extras_rows=None, sim_time=0.0,
                      steps=None):
        """Seat a new member at index `m` (a serving join): install its
        state (and, when given, per-member RHS extra operand) rows, zero
        its multistep history, reset its clock/retry accounting, set its
        steps-remaining budget, and activate it. Multistep members
        seated into a running fleet still need their order build-up —
        call `ramp_members([m])` before steady stepping."""
        m = int(m)
        mask = self._seat_mask([m])
        if self.active_host[m]:
            raise ValueError(f"seat {m} is already active")
        mask_dev = self._put(jnp.asarray(mask))
        self.X = self._masked_write(self.X, mask_dev, X_row)
        if extras_rows is not None:
            if len(extras_rows) != len(self._extras):
                raise ValueError(
                    f"expected {len(self._extras)} extra operand row(s), "
                    f"got {len(extras_rows)}")
            self._extras = [self._masked_write(e, mask_dev, row)
                            for e, row in zip(self._extras, extras_rows)]
        if self._multistep:
            zeros = jnp.zeros((self.timestepper.steps,)
                              + tuple(self.solver.pencil_shape),
                              dtype=self.solver.pencil_dtype)
            self.F_hist = self._masked_write(self.F_hist, mask_dev, zeros)
            self.MX_hist = self._masked_write(self.MX_hist, mask_dev, zeros)
            self.LX_hist = self._masked_write(self.LX_hist, mask_dev, zeros)
        self.sim_times[m] = float(sim_time)
        # the member's device clock is seat-written (NOT rebuilt from the
        # host mirror: running members' device clocks are per-step
        # accumulations whose bits the per-dispatch host mirror does not
        # reproduce — clobbering them would perturb t-dependent RHSs)
        self.T = self._masked_write(
            self.T, mask_dev, jnp.asarray(float(sim_time), dtype=self.rd))
        self.steps_left[m] = int(steps) if steps is not None \
            else UNBOUNDED_STEPS
        self.R = self._put_host(self.steps_left, dtype=jnp.int32)
        self._retries[m] = 0
        self.active_host[m] = True
        self._active_dev = self._put_host(self.active_host)
        return m

    def detach_member(self, m):
        """Release seat `m` (completion, deadline, divergence, or a gone
        client): mask it out and zero its budget. Its row stays frozen —
        extract results BEFORE detaching."""
        m = int(m)
        self._seat_mask([m])   # range check
        self.active_host[m] = False
        self.steps_left[m] = 0
        self._active_dev = self._put_host(self.active_host)
        self.R = self._put_host(self.steps_left, dtype=jnp.int32)

    def set_fleet_dt(self, dt):
        """Serving: one uniform dt for every seat, unconditionally (the
        step-path `_set_common_dt` preserves per-member rewind backoffs
        a serving fleet never carries, and skips the update entirely
        when no seat is live — wrong for a fleet being re-armed between
        batches)."""
        dt = float(dt)
        if not np.isfinite(dt) or dt <= 0:
            raise ValueError(f"invalid fleet dt {dt!r}")
        self.dts = np.full(self.n_pad, dt)
        self.DT = self._put_host(self.dts, dtype=self.rd)

    def project_members(self, ms):
        """Masked Hermitian/valid-mode re-projection of a member subset:
        under serving, each member's projection cadence follows its OWN
        iteration count, not the fleet's (bit-identity with a solo run
        requires projecting exactly where the solo loop would). Same
        compiled program as the fleet-wide projection — the mask is a
        value operand."""
        mask = self._seat_mask(ms) & self.active_host
        if not mask.any():
            return
        self.X = self._ensure_project_prog()(
            self.X, self._put(jnp.asarray(mask)))

    def ramp_members(self, ms, project=False):
        """Multistep order build-up for newly attached members: `steps`
        single fleet dispatches with every OTHER member frozen, each
        using the ramp-order coefficients a fresh solo solver would use
        at that iteration — a member joining a running fleet bit-matches
        its own solo run. Requires the (uniform) fleet dt to be set.
        `project=True` re-projects the ramping cohort before each ramp
        step (solo projects on every iteration of the ramp window
        whenever a cadence is enabled). No-op for RK schemes. Returns
        the number of ramp dispatches."""
        if not self._multistep:
            return 0
        ts = self.timestepper
        s = ts.steps
        mask = self._seat_mask(ms) & self.active_host
        if not mask.any():
            return 0
        dts = self.dts[mask]
        dt = float(dts[0])
        if not np.all(dts == dt) or dt <= 0 or not np.isfinite(dt):
            raise ValueError(
                f"ramp_members requires one positive uniform dt for the "
                f"cohort, got {sorted(set(dts.tolist()))}")
        mask_dev = self._put(jnp.asarray(mask))
        for k in range(1, s + 1):
            if project:
                self.project_members(np.flatnonzero(mask))
            order = min(k, s)
            a, b, c = ts.compute_coefficients([dt] * order, order)
            a = np.concatenate([a, np.zeros(s + 1 - len(a))])
            b = np.concatenate([b, np.zeros(s + 1 - len(b))])
            c = np.concatenate([c, np.zeros(s - len(c))])
            self._ensure_factor_ms(a[0], b[0])
            self._dispatch(1, jnp.asarray(a, dtype=self.rd),
                           jnp.asarray(b, dtype=self.rd),
                           jnp.asarray(c, dtype=self.rd),
                           act_dev=mask_dev, act_host=mask)
        return s

    def step_fleet(self, n):
        """Serving steady dispatch: advance every active member by up to
        `n` steps, honoring each member's steps-remaining budget (a
        finished member freezes mid-scan — per-member stop without
        leaving the compiled program). Unlike `step_many` this never
        applies the fleet-global projection cadence or the multistep
        ramp — the serving driver owns per-member projection phases
        (`project_members`) and cohort ramps (`ramp_members`). Returns
        the per-member steps actually taken."""
        n = int(n)
        if n <= 0:
            return np.zeros(self.n_pad, dtype=np.int64)
        if self._lost_devices:
            self._handle_device_loss()
        ts = self.timestepper
        dt = float(self.dts[0])
        if not np.isfinite(dt) or dt <= 0:
            raise ValueError(f"invalid fleet dt {dt!r}")
        if self._multistep:
            s = ts.steps
            a, b, c = ts.compute_coefficients([dt] * s, s)
            a = np.concatenate([a, np.zeros(s + 1 - len(a))])
            b = np.concatenate([b, np.zeros(s + 1 - len(b))])
            c = np.concatenate([c, np.zeros(s - len(c))])
            self._ensure_factor_ms(a[0], b[0])
            taken = self._dispatch(n, jnp.asarray(a, dtype=self.rd),
                                   jnp.asarray(b, dtype=self.rd),
                                   jnp.asarray(c, dtype=self.rd))
        else:
            self._ensure_factor_rk(dt)
            taken = self._dispatch(n)
        if not self._warmed and self.iteration >= self.warmup_iterations:
            self._end_warmup()
        return taken

    # ------------------------------------------------- health and recovery

    def check_health(self):
        """Run the per-member probe now; diverged members are dropped or
        rewound per `policy`. Returns the list of member events handled."""
        nonfinite, max_abs = jax.device_get(self._probe())
        self.metrics.inc("ensemble/health_checks")
        bad = []
        for m in range(self.members):
            if not self.active_host[m]:
                continue
            if nonfinite[m]:
                bad.append((m, f"non-finite state ({int(nonfinite[m])} "
                               f"entries) at iteration {self.iteration}"))
            elif np.isfinite(self.max_abs_limit) \
                    and max_abs[m] > self.max_abs_limit:
                bad.append((m, f"growth bound exceeded: max|coeff| = "
                               f"{max_abs[m]:.3e} > {self.max_abs_limit:.3e}"
                               f" at iteration {self.iteration}"))
        if bad:
            self._handle_bad(bad)
        return bad

    def _newest_finite_slot(self, m):
        for snap in reversed(self.ring):
            if snap.member_finite(m):
                return snap
        return None

    def _restore_members(self, mask_np, snap):
        """Per-member rewind: `where` the snapshot slots of the masked
        members back into the fleet arrays (other members untouched)."""
        mask = self._put(jnp.asarray(mask_np))

        def back(cur, old):
            keep = mask.reshape((-1,) + (1,) * (cur.ndim - 1))
            return jnp.where(keep, old, cur)

        self.X = back(self.X, snap.X)
        self.T = back(self.T, snap.T)
        if self._multistep and snap.hists is not None:
            self.F_hist, self.MX_hist, self.LX_hist = jax.tree.map(
                back, (self.F_hist, self.MX_hist, self.LX_hist), snap.hists)
        self.sim_times[mask_np] = snap.sim_times[mask_np]

    def _handle_bad(self, bad):
        by_snap = {}
        for m, reason in bad:
            event = {"member": m, "iteration": self.iteration,
                     "reason": reason}
            snap = self._newest_finite_slot(m)
            rewind = (self.policy == "rewind"
                      and self._retries[m] < self.max_member_retries
                      and snap is not None)
            if rewind:
                self._retries[m] += 1
                new_dt = self.dts[m] * self.dt_backoff
                event.update(outcome="rewound",
                             rewind_iteration=snap.iteration,
                             retry=int(self._retries[m]), dt=new_dt)
                self.dts[m] = new_dt
                self.rewound.append(event)
                self.metrics.inc("ensemble/rewinds")
                logger.warning(
                    f"ensemble: member {m} diverged ({reason}); rewound to "
                    f"iteration {snap.iteration}, dt backed off to "
                    f"{new_dt:.3e} (retry {self._retries[m]}/"
                    f"{self.max_member_retries})")
            else:
                self.active_host[m] = False
                event.update(
                    outcome="dropped",
                    frozen_iteration=snap.iteration if snap else None)
                self.dropped.append(event)
                self.metrics.inc("ensemble/dropped")
                logger.warning(
                    f"ensemble: member {m} diverged ({reason}); dropped"
                    + (f", frozen at snapshot iteration {snap.iteration}"
                       if snap else " (no finite snapshot: state left "
                       "as-is, masked out)"))
            if snap is not None:
                by_snap.setdefault(id(snap), (snap, []))[1].append(m)
        for snap, ms in by_snap.values():
            mask = np.zeros(self.n_pad, dtype=bool)
            mask[ms] = True
            self._restore_members(mask, snap)
        self._active_dev = self._put_host(self.active_host)
        if self.per_member_dt:
            self.DT = self._put_host(self.dts, dtype=self.rd)
            self._lhs_key = None   # refactor with the backed-off dts

    def snapshot(self):
        """Capture the fleet (sync-free device references)."""
        hists = ((self.F_hist, self.MX_hist, self.LX_hist)
                 if self._multistep else None)
        self.ring.append(FleetSnapshot(
            self.X, self.T, hists, self.iteration, self.sim_times,
            probe=self._probe))
        del self.ring[:-self.ring_size]
        self.metrics.inc("ensemble/snapshots")

    # ------------------------------------------------- device-loss recovery

    def members_on_device(self, device_index):
        """Member indices (including inactive padding clones) whose shard
        lives on local device `device_index` under the 1-D batch
        sharding (contiguous equal blocks)."""
        if self.mesh is None:
            return list(range(self.n_pad)) if device_index == 0 else []
        D = self.mesh.shape[MEMBER_AXIS]
        per = self.n_pad // D
        d = int(device_index)
        return list(range(d * per, min((d + 1) * per, self.n_pad)))

    def notify_device_loss(self, device_index):
        """Report that a mesh device is lost (its shard of every fleet
        array is unreadable or garbage). In production this is the
        XlaRuntimeError path of a fleet dispatch; the chaos harness
        (`lose_device`) delivers the same notification deterministically.
        Handled before the next dispatch (`step_many` drains pending
        losses first)."""
        self._lost_devices.append(int(device_index))

    def _host_from_shards(self, arr, lost_devices, failed_out=None):
        """Host copy of a fleet array assembled from its SURVIVING shards
        only — the lost device's block is never read (it is gone, or
        garbage pretending not to be). Lost rows come back zero-filled
        and MUST be overwritten by the caller before use. A surviving
        shard that FAILS to read is recorded in `failed_out` — the
        caller promotes its device to lost so those members are restored
        too, never left as silently-finite zeros."""
        out = np.zeros(arr.shape, arr.dtype)
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            return np.array(arr)
        for sh in shards:
            if sh.device in lost_devices:
                continue
            try:
                out[sh.index] = np.asarray(sh.data)
            except Exception as exc:
                logger.warning(f"ensemble: surviving shard on "
                               f"{sh.device} unreadable: {exc}")
                if failed_out is not None:
                    failed_out.add(sh.device)
        return out

    def _host_best_effort(self, arr, failed_out=None):
        """Host copy of a fleet array trying EVERY shard — recovery may
        still be able to read a 'lost' device's block (poisoned-not-
        destroyed); shards that fail to read leave zeros for the caller
        to overwrite from the durable checkpoint, and are recorded in
        `failed_out` so their devices' members count as affected. Read
        failures must never escape: they would turn recovery into the
        crash it prevents."""
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            return np.array(arr)
        out = np.zeros(arr.shape, arr.dtype)
        for sh in shards:
            try:
                out[sh.index] = np.asarray(sh.data)
            except Exception as exc:
                logger.warning(f"ensemble: shard on {sh.device} "
                               f"unreadable during recovery: {exc}")
                if failed_out is not None:
                    failed_out.add(sh.device)
        return out

    def _validate_fleet_meta(self, meta, path):
        """Raise CheckpointError unless `meta` describes THIS fleet (an
        incompatible checkpoint must never be installed member-wise)."""
        if meta.get("kind") != "ensemble":
            raise CheckpointError(
                f"checkpoint {path} holds {meta.get('kind')!r} state, "
                f"not a fleet", path=path)
        if int(meta.get("members", -1)) != self.members:
            raise CheckpointError(
                f"checkpoint {path} holds {meta.get('members')} members, "
                f"this fleet has {self.members}", path=path)
        if list(meta.get("pencil_shape", [])) != \
                list(self.solver.pencil_shape):
            raise CheckpointError(
                f"checkpoint {path} pencil shape "
                f"{meta.get('pencil_shape')} does not match this solver's "
                f"{list(self.solver.pencil_shape)}", path=path)
        if meta.get("scheme") != type(self.timestepper).__name__:
            raise CheckpointError(
                f"checkpoint {path} was written by scheme "
                f"{meta.get('scheme')}, this fleet runs "
                f"{type(self.timestepper).__name__}", path=path)
        n_extras = meta.get("n_extras")
        if n_extras is not None and int(n_extras) != len(self._extras):
            raise CheckpointError(
                f"checkpoint {path} carries {n_extras} RHS parameter "
                f"operand(s), this fleet's problem has "
                f"{len(self._extras)} — different problem configuration",
                path=path)

    def _checkpoint_members(self):
        """Member-row arrays + meta from the newest valid durable sharded
        checkpoint, or None (no directory / nothing restorable /
        incompatible). Drains the async writer first so an in-flight
        (manifest-less) write is never quarantined out from under it."""
        if self._checkpoint_dir is None:
            return None
        quarantine = True
        if self._checkpointer is not None:
            self._checkpointer.drain()
            # drain can time out with a write still in flight: restore
            # must then leave its manifest-less directory alone
            quarantine = self._checkpointer.pending == 0
        try:
            event = dcheckpoint.restore_latest(self._checkpoint_dir,
                                               quarantine=quarantine)
            if event is not None:
                self._validate_fleet_meta(event["meta"], event["path"])
        except CheckpointError as exc:
            logger.warning(f"ensemble: durable checkpoint unusable for "
                           f"member restore: {exc}")
            return None
        return event

    def _handle_device_loss(self):
        """Re-shard the fleet onto the surviving devices. Live member
        blocks are rebuilt host-side from surviving shards; the lost
        device's members are restored from the newest finite
        FleetSnapshot slot (its arrays predate the loss) or, when the
        ring has nothing finite for a member, from the last durable
        sharded checkpoint; members with neither drop. Then a fresh 1-D
        mesh over the survivors is built, members re-pad to the new
        device multiple, and every block-memoized program is rebuilt for
        the new layout (fresh wrappers — a compile, not a retrace)."""
        pending = sorted(set(self._lost_devices))
        self._lost_devices = []
        if self.pencil_axis is not None:
            raise RuntimeError(
                "device-loss recovery supports 1-D member meshes only: a "
                "2-D batch x pencil fleet loses a SLICE of every member's "
                "pencil state with a device, so restore onto survivors "
                "must come from a durable sharded checkpoint "
                "(restore_checkpoint) on a rebuilt fleet.")
        if self.mesh is None:
            if pending:
                raise RuntimeError(
                    "device loss reported without a device mesh: a single-"
                    "device fleet has no surviving devices to reshard onto")
            return
        old_devices = list(self.mesh.devices.flat)
        # range-filter BEFORE deciding anything happened: a stale/bogus
        # index must not trigger a spurious reshard (program rebuilds +
        # a cleared snapshot ring are expensive AND destroy rewind
        # targets)
        lost = sorted({d for d in pending if 0 <= d < len(old_devices)})
        if not lost:
            if pending:
                logger.warning(f"ensemble: device-loss notification(s) "
                               f"{pending} out of range for a "
                               f"{len(old_devices)}-device mesh; ignored")
            return
        t0 = time_mod.perf_counter()
        lost_devs = {old_devices[d] for d in lost}
        # ---- host reconstruction from surviving shards only; a surviving
        # shard that fails to read promotes its device to lost so its
        # members are restored below instead of running on zeros
        failed = set()
        host = {"X": self._host_from_shards(self.X, lost_devs, failed),
                "T": self._host_from_shards(self.T, lost_devs, failed)}
        if self._multistep:
            host["F_hist"] = self._host_from_shards(
                self.F_hist, lost_devs, failed)
            host["MX_hist"] = self._host_from_shards(
                self.MX_hist, lost_devs, failed)
            host["LX_hist"] = self._host_from_shards(
                self.LX_hist, lost_devs, failed)
        # RHS parameter operands: constant per member mid-run; every
        # readable shard is recovered best-effort (a poisoned-not-
        # destroyed device's blocks survive), and the checkpoint branch
        # below overwrites affected rows from the durable extra<k> arrays
        host_extras = [self._host_best_effort(e, failed)
                       for e in self._extras]
        promoted = sorted(old_devices.index(dev) for dev in failed
                          if dev in old_devices and dev not in lost_devs)
        if promoted:
            logger.warning(f"ensemble: device(s) {promoted} failed reads "
                           f"during recovery; treating as lost too")
            lost = sorted(set(lost) | set(promoted))
            lost_devs |= {old_devices[d] for d in promoted}
        from . import meshctx
        survivors = meshctx.surviving_devices(self.mesh, lost)
        if not survivors:
            raise RuntimeError("ensemble: every mesh device lost")
        affected = sorted({m for d in lost
                           for m in self.members_on_device(d)
                           if m < self.members})
        # ---- restore the lost device's members. Ring first (its
        # snapshots predate the loss), durable checkpoint second, drop
        # last — and NOTHING here may raise for a read failure: a ring
        # slot whose shards died with the device must fall through to
        # the checkpoint, not crash the fleet.
        checkpoint = None
        restored, dropped_now, frozen_lost = [], [], []
        for m in affected:
            # INACTIVE members are walked too: a previously-dropped
            # member's row is its frozen last-good state (the drop
            # policy's contract) — losing its device must restore that
            # row, not silently replace it with zeros
            was_active = bool(self.active_host[m])
            rows = None
            try:
                snap = self._newest_finite_slot(m)
                if snap is not None:
                    rows = {"X": np.asarray(snap.X[m]),
                            "T": np.asarray(snap.T[m])}
                    if self._multistep and snap.hists is not None:
                        for name, h in zip(
                                ("F_hist", "MX_hist", "LX_hist"),
                                snap.hists):
                            rows[name] = np.asarray(h[m])
                    sim_time = snap.sim_times[m]
                    iteration = snap.iteration
            except Exception as exc:
                logger.warning(
                    f"ensemble: ring restore for member {m} failed "
                    f"({exc}); trying the durable checkpoint")
                rows = None
            if rows is not None:
                for name, row in rows.items():
                    host[name][m] = row
                self.sim_times[m] = sim_time
                entry = {"member": m, "source": "ring",
                         "iteration": iteration}
                if not was_active:
                    entry["frozen"] = True
                restored.append(entry)
                continue
            if checkpoint is None:
                checkpoint = self._checkpoint_members() or False
            if checkpoint:
                arrays, meta = checkpoint["arrays"], checkpoint["meta"]
                host["X"][m] = arrays["X"][m]
                host["T"][m] = arrays["T"][m]
                if self._multistep and "F_hist" in arrays:
                    for name in ("F_hist", "MX_hist", "LX_hist"):
                        host[name][m] = arrays[name][m]
                for k in range(len(host_extras)):
                    if f"extra{k}" in arrays:
                        host_extras[k][m] = arrays[f"extra{k}"][m]
                self.sim_times[m] = float(meta["sim_times"][m])
                entry = {"member": m, "source": "checkpoint",
                         "iteration": int(meta["iteration"])}
                if not was_active:
                    entry["frozen"] = True
                restored.append(entry)
                continue
            if not was_active:
                # already dropped AND no source: the frozen state is
                # genuinely gone — say so instead of pretending the
                # zero-filled row is data
                frozen_lost.append(m)
                logger.warning(
                    f"ensemble: dropped member {m}'s frozen state was on "
                    f"the lost device and no snapshot/checkpoint holds "
                    f"it; its row is zeroed")
                continue
            self.active_host[m] = False
            event = {"member": m, "iteration": self.iteration,
                     "reason": f"device {lost} lost, no finite snapshot "
                               f"or durable checkpoint to restore from",
                     "outcome": "dropped", "frozen_iteration": None}
            self.dropped.append(event)
            dropped_now.append(m)
            self.metrics.inc("ensemble/dropped")
        # ring-restored members got their X/hists from the (pre-loss)
        # snapshot, but their RHS parameter rows came from the
        # best-effort read of the LOST device — untrusted by definition.
        # When a durable checkpoint exists, its extra<k> rows (constant
        # per member mid-run, so any checkpoint's copy is the original)
        # replace them; without one the best-effort read stands (the
        # poisoned-not-destroyed case, as documented).
        ring_members = [r["member"] for r in restored
                        if r["source"] == "ring"]
        if ring_members and self._checkpoint_dir is not None \
                and host_extras:
            if checkpoint is None:
                checkpoint = self._checkpoint_members() or False
            if checkpoint:
                arrays = checkpoint["arrays"]
                for m in ring_members:
                    for k in range(len(host_extras)):
                        if f"extra{k}" in arrays:
                            host_extras[k][m] = arrays[f"extra{k}"][m]
        # ---- rebuild the mesh over the survivors and re-pad (same
        # meshctx.surviving_devices filter behind both, so the mesh and
        # the padding can never disagree)
        D2 = len(survivors)
        self.mesh = meshctx.surviving_mesh(self.mesh, lost)
        n_pad2 = -(-self.members // D2) * D2 if self.mesh is not None \
            else self.members
        repad = functools.partial(_repad, members=self.members,
                                  n_pad=n_pad2)
        self.n_pad = n_pad2
        self.X = self._put(jnp.asarray(repad(host["X"])))
        self.T = self._put(jnp.asarray(repad(host["T"])))
        if self._multistep:
            self.F_hist = self._put(jnp.asarray(repad(host["F_hist"])))
            self.MX_hist = self._put(jnp.asarray(repad(host["MX_hist"])))
            self.LX_hist = self._put(jnp.asarray(repad(host["LX_hist"])))
        self._extras = [self._put(jnp.asarray(repad(e)))
                        for e in host_extras]
        self.sim_times = repad(self.sim_times)
        self.dts = repad(self.dts)
        self.DT = self._put_host(self.dts, dtype=self.rd)
        self.active_host = repad(self.active_host, pad_value=False)
        self._retries = repad(self._retries, pad_value=0)
        self._active_dev = self._put_host(self.active_host)
        self.steps_left = repad(self.steps_left, pad_value=0)
        self.R = self._put_host(self.steps_left, dtype=jnp.int32)
        # the compiled fleet programs are layout-specific: rebuild (fresh
        # wrappers trace once each — a compile, not a retrace)
        self._programs = {}
        self._project_prog = None
        self._probe_prog = None
        self._vfactor_prog = None
        self._lhs_key = None
        self._lhs_aux = None
        # ring snapshots reference the old layout; fresh post-reshard anchor
        self.ring = []
        self.snapshot()
        event = {
            "iteration": self.iteration,
            "lost_devices": lost,
            "devices": D2,
            "restored": restored,
            "dropped": dropped_now,
            "wall_sec": round(time_mod.perf_counter() - t0, 4),
        }
        if frozen_lost:
            event["frozen_lost"] = frozen_lost
        self.reshard_events.append(event)
        self.metrics.inc("ensemble/reshards")
        sources = (", ".join(sorted({r["source"] for r in restored}))
                   if restored else "none")
        logger.warning(
            f"ensemble: lost device(s) {lost} at iteration "
            f"{self.iteration}; resharded {self.members} members onto "
            f"{D2} surviving device(s) — {len(restored)} member(s) "
            f"restored (source: {sources}), {len(dropped_now)} dropped, "
            f"{event['wall_sec']}s")

    # ---------------------------------------------------- durable checkpoints

    def init_checkpoints(self, directory, async_write=None, inflight=None,
                         keep=None, chaos=None):
        """Arm durable sharded fleet checkpoints under `directory`
        (tools/dcheckpoint.py; defaults from [resilience]
        CHECKPOINT_ASYNC / CHECKPOINT_INFLIGHT / CHECKPOINT_KEEP)."""
        from ..tools.resilience import _as_bool, io_retry_policy
        if async_write is None:
            async_write = _as_bool(cfg_get(
                "resilience", "CHECKPOINT_ASYNC", "False"))
        self._checkpoint_dir = directory
        self._checkpointer = dcheckpoint.ShardedCheckpointer(
            directory, async_write=_as_bool(async_write),
            inflight=int(inflight if inflight is not None
                         else cfg_get("resilience", "CHECKPOINT_INFLIGHT",
                                      "2")),
            keep=int(keep if keep is not None
                     else cfg_get("resilience", "CHECKPOINT_KEEP", "2")),
            io_retry=io_retry_policy(on_retry=lambda attempt, exc:
                self.metrics.inc("ensemble/io_retries")))
        if chaos is not None:
            wire = getattr(chaos, "wire_checkpointer", None)
            if wire is not None:
                wire(self._checkpointer)
        return self._checkpointer

    def write_checkpoint(self):
        """Write (or, async, submit) one durable sharded fleet checkpoint:
        the member axis is already the shard axis, so each device's block
        goes to its own checksummed file and the capture is a dict of
        immutable references — sync-free."""
        if self._checkpointer is None:
            raise ValueError("call init_checkpoints(directory) first (or "
                             "evolve(checkpoint_dir=...))")
        arrays = {"X": self.X, "T": self.T}
        if self._multistep:
            arrays.update(F_hist=self.F_hist, MX_hist=self.MX_hist,
                          LX_hist=self.LX_hist)
        for k, extra in enumerate(self._extras):
            arrays[f"extra{k}"] = extra
        meta = {
            "kind": "ensemble",
            "members": self.members,
            "n_pad": self.n_pad,
            "n_extras": len(self._extras),
            "iteration": int(self.iteration),
            "scheme": type(self.timestepper).__name__,
            "per_member_dt": self.per_member_dt,
            "pencil_shape": list(self.solver.pencil_shape),
            "sim_times": [float(v) for v in self.sim_times],
            "dts": [float(v) for v in self.dts],
            "active": [bool(v) for v in self.active_host],
            "retries": [int(v) for v in self._retries],
        }
        if self._multistep:
            meta["ms_iter"] = int(self._ms_iter)
            meta["dt_hist"] = [float(v) for v in self._dt_hist]
        result = self._checkpointer.save(arrays, meta)
        self.metrics.inc("ensemble/checkpoints_written")
        return result

    def restore_checkpoint(self, directory=None):
        """Elastic restore from the newest valid sharded fleet checkpoint
        (per-shard checksums validated, torn checkpoints quarantined with
        fallback): the TRUE member rows are re-padded onto THIS fleet's
        mesh — the writing and restoring device counts are independent,
        and member states restore bit-identically. Raises CheckpointError
        when nothing under `directory` is restorable."""
        directory = directory if directory is not None \
            else self._checkpoint_dir
        if directory is None:
            raise ValueError("restore_checkpoint requires a directory")
        quarantine = True
        if self._checkpointer is not None:
            # never quarantine a write the async writer has in flight
            self._checkpointer.drain()
            quarantine = self._checkpointer.pending == 0
        event = dcheckpoint.restore_latest(directory, quarantine=quarantine)
        if event is None:
            raise CheckpointError(
                f"no sharded checkpoint under {directory}", path=directory)
        arrays = event.pop("arrays")
        meta = event["meta"]
        self._validate_fleet_meta(meta, event["path"])
        repad = functools.partial(_repad, members=self.members,
                                  n_pad=self.n_pad)
        self.X = self._put(jnp.asarray(repad(arrays["X"])), pencil_dim=1)
        self.T = self._put(jnp.asarray(repad(arrays["T"])))
        if self._multistep and "F_hist" in arrays:
            self.F_hist = self._put(jnp.asarray(repad(arrays["F_hist"])),
                                    pencil_dim=2)
            self.MX_hist = self._put(jnp.asarray(repad(arrays["MX_hist"])),
                                     pencil_dim=2)
            self.LX_hist = self._put(jnp.asarray(repad(arrays["LX_hist"])),
                                     pencil_dim=2)
            self._ms_iter = int(meta.get("ms_iter", 0))
            self._dt_hist = [float(v) for v in meta.get("dt_hist", [])]
        extras = []
        for k in range(len(self._extras)):
            name = f"extra{k}"
            if name not in arrays:
                # _validate_fleet_meta already rejects count mismatches
                # for checkpoints that record n_extras; this guards the
                # same hazard for older manifests — a partial install
                # (checkpoint state + current parameters) would be a
                # silently inconsistent fleet
                raise CheckpointError(
                    f"checkpoint {event['path']} lacks the RHS parameter "
                    f"operand {name} this fleet's problem requires",
                    path=event["path"])
            extras.append(self._put(jnp.asarray(repad(arrays[name]))))
        self._extras = extras
        self.iteration = int(meta["iteration"])
        self.sim_times = repad(np.asarray(meta["sim_times"], dtype=float))
        self.dts = repad(np.asarray(meta["dts"], dtype=float))
        self.DT = self._put_host(self.dts, dtype=self.rd)
        self.active_host = repad(
            np.asarray(meta["active"], dtype=bool), pad_value=False)
        self._retries = repad(
            np.asarray(meta["retries"], dtype=int), pad_value=0)
        self._active_dev = self._put_host(self.active_host)
        self._lhs_key = None
        self._lhs_aux = None
        self.ring = []
        self.snapshot()
        self.metrics.inc("ensemble/restores")
        logger.info(
            f"ensemble: restored {self.members} members from "
            f"{event['path']} (iteration {self.iteration}) onto "
            f"{self.mesh.shape[MEMBER_AXIS] if self.mesh else 1} device(s)")
        return event

    # ------------------------------------------------------------ the loop

    def evolve(self, dt=None, stop_iteration=None, block=None, chaos=None,
               log_cadence=100, checkpoint_dir=None, checkpoint_iter=0,
               checkpoint_async=None):
        """
        Drive the fleet to `stop_iteration` in fixed-size scanned blocks
        (sizes {block, 1} only, so each program traces once): snapshot
        ring + per-member health on their cadences, chaos hooks for fault
        injection, durable sharded checkpoints every `checkpoint_iter`
        iterations (plus one final write) when `checkpoint_dir` is given,
        telemetry flush at the end. Returns the summary dict.
        """
        if stop_iteration is None:
            raise ValueError("evolve requires stop_iteration")
        block = int(block or min(16, max(self.snapshot_cadence, 1)))
        if dt is not None and np.ndim(dt) == 0:
            self._set_common_dt(dt)
        elif dt is not None:
            self.set_member_dts(dt)
        ckpt_gate = None
        if checkpoint_dir is not None:
            self.init_checkpoints(checkpoint_dir,
                                  async_write=checkpoint_async, chaos=chaos)
            if checkpoint_iter:
                ckpt_gate = metrics_mod.CadenceGate(int(checkpoint_iter))
                ckpt_gate.reset(self.iteration)
        self.snapshot()   # iteration-0 anchor
        while self.iteration < stop_iteration and self.n_active:
            n = block if stop_iteration - self.iteration >= block else 1
            self.step_many(n)
            if chaos is not None:
                chaos.after_step(self)
            if self._snapshot_gate.due(self.iteration):
                self.snapshot()
            if ckpt_gate is not None and ckpt_gate.due(self.iteration):
                try:
                    self.write_checkpoint()
                except Exception as exc:
                    logger.warning(f"periodic fleet checkpoint failed: "
                                   f"{exc}")
            if log_cadence and self.iteration % log_cadence < n:
                logger.info(
                    f"Ensemble iteration={self.iteration}, "
                    f"active={self.n_active}/{self.members}, "
                    f"dropped={len(self.dropped)}")
        if self._lost_devices:
            # a loss delivered after the last dispatch: recover before
            # the final checkpoint/flush reads the fleet state
            self._handle_device_loss()
        if self._checkpointer is not None:
            try:
                self.write_checkpoint()
            except Exception as exc:
                logger.warning(f"final fleet checkpoint failed: {exc}")
            for exc in self._checkpointer.close():
                logger.error(f"async fleet checkpoint write failed: {exc}")
        self.flush_metrics()
        return self.summary()

    # ----------------------------------------------------------- telemetry

    def summary(self):
        """Compact ensemble record (the `ensemble` block of flushed
        telemetry; `report` renders it as member columns)."""
        m = self.metrics
        wall = m.loop_wall()
        member_steps = m.iterations
        return {
            "members": self.members,
            "active": self.n_active,
            "dropped": len(self.dropped),
            "rewinds": len(self.rewound),
            "fleet_steps": self.iteration,
            "member_steps": member_steps,
            "ensemble_steps_per_sec": round(member_steps / wall, 4)
            if wall > 0 else 0.0,
            "devices": (int(np.prod(list(self.mesh.shape.values())))
                        if self.mesh is not None else 1),
            **({"mesh": dict(self.mesh.shape)}
               if self.pencil_axis is not None else {}),
            "per_member_dt": self.per_member_dt,
            "policy": self.policy,
            "dropped_members": [e["member"] for e in self.dropped],
            "reshards": len(self.reshard_events),
            **({"checkpoint": self._checkpointer.summary()}
               if self._checkpointer is not None else {}),
        }

    def flush_metrics(self, extra=None):
        """Block on the fleet state and flush one telemetry record with
        the `ensemble` summary block attached."""
        try:
            jax.block_until_ready(self.X)
        except Exception:
            pass
        extra = dict(extra or {})
        extra.setdefault("ensemble", self.summary())
        extra.setdefault("retraces_post_warmup",
                         retrace_mod.sentinel.post_arm_retraces)
        # the fleet compiles against the template solver's resolved plan,
        # so its provenance IS the fleet's provenance
        if hasattr(self.solver, "plan_provenance"):
            extra.setdefault("plan", self.solver.plan_provenance())
        return self.metrics.flush(extra=extra)
