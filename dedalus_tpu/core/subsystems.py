"""
Pencil layout and subproblem matrix assembly
(reference: dedalus/core/subsystems.py).

TPU-native redesign: the reference enumerates per-rank "subsystems"
(generalized pencils) and assembles one sparse matrix per subproblem, solved
serially with SuperLU. Here ALL groups form one uniform batch:

  * every variable occupies a fixed-size slot per group —
    (ncomp, group_shape per separable axis, coupled size or 1) — so the
    pencil matrices stack into a dense/banded (G, S, S) device array
    (pencil index = MXU batch dimension);
  * invalid slots (the reference's valid_modes masks, core/basis.py:1123)
    are zeroed and closed with identity rows, keeping every group the same
    shape instead of ragged per-group sizes;
  * gather/scatter between field coefficient arrays and the (G, S) state
    vector are pure jnp reshapes/transposes, fused into the jitted step
    (reference: core/subsystems.py:336-367 gather_inputs/scatter_inputs).
"""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from .field import Field
from .domain import Domain
from ..tools.general import is_complex_dtype


def _ncc_forced_coupled_axes(variables, equations):
    """
    Axes that LHS non-constant coefficients vary along: products on the
    matrix expressions whose non-variable factor has a basis on an
    otherwise-separable axis couple that axis's groups (the reference
    handles this by making such subproblems non-separable, e.g. Fourier
    NCCs in the Mathieu example).
    """
    from .arithmetic import ProductBase
    from .future import Future
    vset = set(variables)

    def contains_vars(x):
        if isinstance(x, Field):
            return x in vset
        if isinstance(x, Future):
            return x.has(*vset)
        return False

    forced = set()

    def couples_colatitude(ncc_expr, basis):
        """Does a spherical-basis NCC vary with colatitude (or carry
        non-radial components)? Evaluated from its data, mirroring the
        validation rules of the angularly-constant fast path
        (arithmetic.ProductBase._sph_ncc_setup); anything that path would
        reject couples ell instead (reference: theta-dependent NCCs make
        subproblems ell-coupled, core/arithmetic.py:359-406)."""
        from .arithmetic import ProductBase
        from ..tools.exceptions import NonlinearOperatorError
        try:
            ncc = ncc_expr if isinstance(ncc_expr, Field) \
                else ncc_expr.evaluate()
            spin_prof, tol = ProductBase.sph_ncc_angular_profile(
                ncc, basis, basis.cs)
        except NonlinearOperatorError:
            raise
        except Exception:
            return True  # cannot classify: couple conservatively
        ncomp = spin_prof.shape[0]
        radial_flat = ncomp - 1  # all-radial (spin 0) flat slot
        for c in range(ncomp):
            if c != radial_flat and np.abs(spin_prof[c]).max() > tol:
                return True
        rad = spin_prof[radial_flat]
        return bool(np.abs(rad - rad[:1, :]).max() > tol)

    def couples_azimuth_polar(ncc_expr, basis):
        """Does a disk/annulus NCC vary with azimuth? Delegates to the
        SHARED grid-space dtype-aware classifier the term builder uses
        (arithmetic.ProductBase.polar_azimuth_varies) so layout and
        assembly can never disagree (reference: azimuthally-varying NCCs
        make polar subproblems m-coupled, core/arithmetic.py:359-406)."""
        from .arithmetic import ProductBase
        from ..tools.exceptions import NonlinearOperatorError
        try:
            ncc = ncc_expr if isinstance(ncc_expr, Field) \
                else ncc_expr.evaluate()
            return ProductBase.polar_azimuth_varies(ncc, basis)
        except NonlinearOperatorError:
            raise
        except Exception:
            return True  # cannot classify: couple conservatively

    def walk(expr):
        if not isinstance(expr, Future):
            return
        if isinstance(expr, ProductBase):
            sides = [a for a in expr.args if isinstance(a, (Field, Future))]
            ncc_sides = [a for a in sides if not contains_vars(a)]
            if len(ncc_sides) == 1:
                for axis, basis in enumerate(ncc_sides[0].domain.bases):
                    if basis is None:
                        continue
                    if basis.dim != 1:
                        # multi-dim (curvilinear) NCC: angularly-constant
                        # radial profiles keep per-(m, ell) pencils;
                        # theta-dependent data couples the colatitude axis,
                        # azimuthally-varying polar data couples m
                        colat = basis.first_axis + 1
                        if (basis.dim == 3 and axis == colat
                                and basis.sub_separable(1)
                                and couples_colatitude(ncc_sides[0], basis)):
                            forced.add(colat)
                        from .polar import DiskBasis, AnnulusBasis
                        az = basis.first_axis
                        if (isinstance(basis, (DiskBasis, AnnulusBasis))
                                and axis == az and basis.sub_separable(0)
                                and couples_azimuth_polar(ncc_sides[0],
                                                          basis)):
                            forced.add(az)
                        continue
                    sub = axis - basis.first_axis
                    if basis.sub_separable(sub):
                        forced.add(axis)
        for a in expr.args:
            if isinstance(a, Future):
                walk(a)

    for eq in equations:
        for key in ("M", "L"):
            expr = eq.get(key)
            if isinstance(expr, Future):
                walk(expr)
    return forced


class PencilLayout:
    """Global pencil structure shared by all subproblems of a problem."""

    def __init__(self, dist, variables, equations, matrix_coupling=None):
        self.dist = dist
        dim = dist.dim
        sep_basis = [None] * dim      # (basis, sub_axis)
        coupled_basis = [None] * dim  # (basis, sub_axis)
        self.forced_coupled = _ncc_forced_coupled_axes(variables, equations)
        if matrix_coupling is not None:
            # reference parity: solvers accept matrix_coupling (per-axis
            # bools) to force axes coupled beyond what NCC detection
            # requires (reference: core/solvers.py matrix_coupling kwarg)
            for axis, forced in enumerate(matrix_coupling):
                if forced:
                    self.forced_coupled.add(axis)
        domains = [v.domain for v in variables] + [eq["domain"] for eq in equations]
        for domain in domains:
            for axis, basis in enumerate(domain.bases):
                if basis is None:
                    continue
                sub = axis - basis.first_axis
                if basis.sub_separable(sub) and axis not in self.forced_coupled:
                    if sep_basis[axis] is None:
                        sep_basis[axis] = (basis, sub)
                    else:
                        cur, csub = sep_basis[axis]
                        if (cur.sub_n_groups(csub) != basis.sub_n_groups(sub)
                                or cur.sub_group_shape(csub) != basis.sub_group_shape(sub)):
                            raise ValueError(f"Mismatched separable bases on axis {axis}")
                else:
                    cur = coupled_basis[axis]
                    if cur is None or getattr(basis, "k", 0) > getattr(cur[0], "k", 0):
                        coupled_basis[axis] = (basis, sub)
        self.sep_axes = [ax for ax in range(dim) if sep_basis[ax] is not None]
        self.sep_bases = {ax: sep_basis[ax][0] for ax in self.sep_axes}
        self.sep_widths = {ax: sep_basis[ax][0].sub_group_shape(sep_basis[ax][1])
                           for ax in self.sep_axes}
        self.coupled_axes = [ax for ax in range(dim) if coupled_basis[ax] is not None]
        self.group_counts = [sep_basis[ax][0].sub_n_groups(sep_basis[ax][1])
                             for ax in self.sep_axes]
        self.sep_n_groups = dict(zip(self.sep_axes, self.group_counts))
        self.n_groups = int(np.prod(self.group_counts, dtype=int)) if self.sep_axes else 1

    def groups(self):
        """Iterate full-length per-axis group tuples."""
        dim = self.dist.dim
        if not self.sep_axes:
            yield (None,) * dim
            return
        for multi in np.ndindex(*self.group_counts):
            group = [None] * dim
            for ax, g in zip(self.sep_axes, multi):
                group[ax] = int(g)
            yield tuple(group)

    # ------------------------------------------------------------ slots

    def slot_shape(self, domain, tensorsig):
        """(ncomp, *per-axis slot sizes) — uniform across groups."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        sizes = []
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                sizes.append(self.sep_widths[axis])
            elif basis is None:
                sizes.append(1)
            else:
                sizes.append(basis.coeff_size(axis - basis.first_axis))
        return (ncomp,) + tuple(sizes)

    def slot_size(self, domain, tensorsig):
        return int(np.prod(self.slot_shape(domain, tensorsig), dtype=int))

    def valid_mask(self, domain, tensorsig, group):
        """
        Validity of each slot entry for one group (bool, slot_shape).
        Component-resolved: curvilinear bases mask per tensor component
        (spin/regularity validity, reference: core/basis.py:1780,3183).
        """
        shape = self.slot_shape(domain, tensorsig)
        mask = np.ones(shape, dtype=bool)
        handled = set()
        for axis, basis in enumerate(domain.bases):
            if basis is None:
                ax_len = shape[1 + axis]
                ax_mask = np.ones(ax_len, dtype=bool)
                if axis in self.sep_widths:
                    ax_mask[:] = False
                    if group[axis] == 0:
                        ax_mask[0] = True
                view = [np.newaxis] * len(shape)
                view[1 + axis] = slice(None)
                mask = mask & ax_mask[tuple(view)]
            elif id(basis) not in handled:
                handled.add(id(basis))
                bmask = basis.component_valid_mask(tensorsig, group, self.sep_widths)
                # bmask: (ncomp, *sizes over the basis's axes); place its
                # dims at the basis's axes and broadcast over the rest
                first = basis.first_axis
                full = [bmask.shape[0]] + [1] * len(domain.bases)
                for sub in range(basis.dim):
                    full[1 + first + sub] = bmask.shape[1 + sub]
                mask = mask & bmask.reshape(full)
        return mask

    def valid_masks_all(self, domain, tensorsig):
        """
        (G, slot_size) bool validity for ALL groups at once. For interval
        (1D) bases the mask factorizes over axes — per-axis mask stacks are
        built once (one call per distinct axis-group index) and folded with
        vectorized outer products. Multi-axis (curvilinear) bases couple
        group indices across axes, so those domains fall back to the
        per-group `valid_mask` loop (their group counts are small).
        """
        cache = self.__dict__.setdefault("_valid_masks_cache", {})
        key = (domain, tuple(tensorsig))
        if key in cache:
            return cache[key]
        groups = list(self.groups())
        G = len(groups)
        if any(b is not None and b.dim > 1 for b in domain.bases):
            out = np.stack([self.valid_mask(domain, tensorsig, g).ravel()
                            for g in groups])
            cache[key] = out
            return out
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        group_idx = {ax: np.array([g[ax] for g in groups], dtype=int)
                     for ax in self.sep_axes}
        out = np.ones((G, ncomp, 1), dtype=bool)
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                Ga = self.sep_n_groups[axis]
                w = self.sep_widths[axis]
                if basis is None:
                    stack = np.zeros((Ga, ncomp, w), dtype=bool)
                    stack[0, :, 0] = True
                else:
                    probe = [None] * self.dist.dim
                    rows = []
                    for ga in range(Ga):
                        probe[axis] = ga
                        rows.append(basis.component_valid_mask(
                            tensorsig, tuple(probe), self.sep_widths))
                    stack = np.stack(rows).reshape(Ga, ncomp, w)
                axm = stack[group_idx[axis]]           # (G, ncomp, w)
            elif basis is None:
                axm = np.ones((1, ncomp, 1), dtype=bool)
            else:
                probe = (None,) * self.dist.dim
                m = basis.component_valid_mask(tensorsig, probe,
                                               self.sep_widths)
                axm = np.asarray(m).reshape(1, ncomp, -1)
            out = (out[:, :, :, None]
                   & axm[:, :, None, :]).reshape(G, ncomp, -1)
        out = out.reshape(G, -1)
        cache[key] = out
        return out

    # ------------------------------------------------- device gather/scatter

    def gather(self, array, domain, tensorsig):
        """
        (tensor..., coeff...) device array -> (G, slot) with constant
        separable axes zero-embedded at (group 0, element 0). Pure jnp.
        """
        tshape = tuple(cs.dim for cs in tensorsig)
        tdim = len(tshape)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        data = array.reshape((ncomp,) + array.shape[tdim:])
        # expand/embed separable axes
        new_shape = [ncomp]
        group_positions = []
        pos = 1
        for axis, basis in enumerate(domain.bases):
            size = data.shape[1 + axis]
            if axis in self.sep_widths:
                gs = self.sep_widths[axis]
                G = self.sep_n_groups[axis]
                if basis is None:
                    pad = [(0, 0)] * data.ndim
                    pad[1 + axis] = (0, G * gs - size)
                    from ..tools.array import zeropad
                    data = zeropad(data, pad)
                new_shape.extend([G, gs])
                group_positions.append(pos)
                pos += 2
            else:
                new_shape.append(size)
                pos += 1
        data = data.reshape(new_shape)
        # move group axes to the front (in separable-axis order)
        perm = group_positions + [i for i in range(data.ndim) if i not in group_positions]
        data = jnp.transpose(data, perm)
        G_total = self.n_groups
        return data.reshape(G_total, -1)

    def scatter(self, pencils, domain, tensorsig):
        """(G, slot) -> (tensor..., coeff...); inverse of `gather`."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        # Rebuild the transposed intermediate shape
        group_dims = []
        slot_dims = [ncomp]
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                group_dims.append(self.sep_n_groups[axis])
                slot_dims.append(self.sep_widths[axis])
            elif basis is None:
                slot_dims.append(1)
            else:
                slot_dims.append(basis.coeff_size(axis - basis.first_axis))
        data = pencils.reshape(group_dims + slot_dims)
        nG = len(group_dims)
        # inverse permutation: groups back next to their pair dims
        perm = []
        gi = 0
        si = nG  # position of ncomp
        perm.append(si)
        si += 1
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                perm.append(gi)
                perm.append(si)
                gi += 1
                si += 1
            else:
                perm.append(si)
                si += 1
        data = jnp.transpose(data, perm)
        # merge (G, gs) pairs and slice off constant-axis embeddings
        out_shape = []
        slices = []
        dims = list(data.shape)
        di = 1
        merged = [dims[0]]
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                merged.append(dims[di] * dims[di + 1])
                di += 2
            else:
                merged.append(dims[di])
                di += 1
        data = data.reshape(merged)
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths and basis is None:
                slices.append(slice(0, 1))
            else:
                slices.append(slice(None))
        data = data[(slice(None),) + tuple(slices)]
        return data.reshape(tshape + data.shape[1:])


class Subproblem:
    """One pencil group (reference: core/subsystems.py:234 Subproblem)."""

    def __init__(self, layout, group, index):
        self.layout = layout
        self.group = group      # full-length per-axis tuple
        self.index = index      # flat group index

    def field_size(self, operand):
        return self.layout.slot_size(operand.domain, operand.tensorsig)

    def field_shape(self, operand):
        return self.layout.slot_shape(operand.domain, operand.tensorsig)


def build_subproblems(layout):
    return [Subproblem(layout, group, i) for i, group in enumerate(layout.groups())]


def merge_conditional_equations(equations, dist, layout):
    """
    Convert the raw equation list into row BLOCKS: unconditioned equations
    keep their own block; conditioned equations with identical (bases,
    tensor signature) pack into shared blocks whose active member is
    chosen per pencil group by evaluating the condition over separable
    group indices named 'n' + coordinate name (reference:
    core/subsystems.py:527-541 per-group equation conditions). Packing is
    greedy over the per-group activity vectors, so independent
    complementary pairs (e.g. conditioned BCs at both boundaries) occupy
    separate blocks and each block has at most one active member per group.

    Each block is an eq-like dict ({"domain", "tensorsig", "members"}) and,
    for single-member blocks, passes through M/L/F/residual keys.
    """
    groups = list(layout.groups())
    names = {f"n{coord.name}": coord.axis for coord in dist.coords}
    blocks = []
    by_key = {}
    for eq in equations:
        condition = eq.get("condition")
        if condition is None:
            block = dict(eq)
            block["members"] = [(eq, None)]
            blocks.append(block)
            continue
        code = compile(condition, "<equation condition>", "eval")

        def make_fn(code=code):
            def fn(group):
                env = {name: group[axis] for name, axis in names.items()
                       if group[axis] is not None}
                return bool(eval(code, {}, env))
            return fn

        fn = make_fn()
        activity = np.array([fn(g) for g in groups], dtype=bool)
        key = (tuple(eq["domain"].bases), tuple(eq["tensorsig"]))
        placed = False
        for block, taken in by_key.get(key, []):
            if not (taken & activity).any():
                block["members"].append((eq, fn))
                taken |= activity
                placed = True
                break
        if not placed:
            block = {"domain": eq["domain"], "tensorsig": eq["tensorsig"],
                     "members": [(eq, fn)]}
            by_key.setdefault(key, []).append((block, activity.copy()))
            blocks.append(block)
    return blocks


def active_member(block, group):
    """The block's active equation for `group` (None if none active)."""
    actives = [eq for eq, cond in block["members"]
               if cond is None or cond(group)]
    if len(actives) > 1:
        raise ValueError(
            f"Multiple conditioned equations active for group {group}: "
            f"{[eq.get('LHS_str') for eq in actives]}")
    return actives[0] if actives else None


def block_valid_mask(layout, eq, group):
    """Flat row-validity of one equation block at one group: the active
    member's mask, or all-invalid when no member's condition holds."""
    if "members" in eq:
        active = active_member(eq, group)
        if active is None:
            size = layout.slot_size(eq["domain"], eq["tensorsig"])
            return np.zeros(size, dtype=bool)
    return layout.valid_mask(eq["domain"], eq["tensorsig"], group).ravel()


def _system_sizes(layout, equations, variables):
    var_sizes = [layout.slot_size(v.domain, v.tensorsig) for v in variables]
    var_offsets = np.concatenate([[0], np.cumsum(var_sizes)])
    S = int(var_offsets[-1])
    eq_sizes = [layout.slot_size(eq["domain"], eq["tensorsig"]) for eq in equations]
    R = int(np.sum(eq_sizes))
    if R != S:
        raise ValueError(f"Pencil system is not square: {R} equation rows for "
                         f"{S} variable columns.")
    return var_offsets, eq_sizes, S


def assemble_group_coo(subproblem, equations, variables, name,
                       eq_sizes, var_offsets):
    """
    Assemble one group's matrix `name` in COO form (rows, cols, vals),
    with validity enforcement (invalid rows/columns dropped) and — for
    name == '__closure__' entries handled by the caller. Returns
    (rows, cols, vals, row_valid, col_valid).
    """
    layout = subproblem.layout
    group = subproblem.group
    rows_l, cols_l, vals_l = [], [], []
    row0 = 0
    for eq, esize in zip(equations, eq_sizes):
        active = active_member(eq, group) if "members" in eq else eq
        expr = active.get(name) if active is not None else None
        if expr is not None and not (np.isscalar(expr) and expr == 0):
            from .operators import operand_expression_matrices
            mats = operand_expression_matrices(expr, subproblem, variables)
            for vi, var in enumerate(variables):
                if var in mats:
                    block = mats[var]
                    coo = sp.coo_matrix(block)
                    rows_l.append(coo.row + row0)
                    cols_l.append(coo.col + var_offsets[vi])
                    vals_l.append(coo.data)
        row0 += esize
    if rows_l:
        rows = np.concatenate(rows_l)
        cols = np.concatenate(cols_l)
        vals = np.concatenate(vals_l)
    else:
        rows = np.zeros(0, dtype=int)
        cols = np.zeros(0, dtype=int)
        vals = np.zeros(0)
    # validity enforcement
    col_valid = np.concatenate([
        layout.valid_mask(v.domain, v.tensorsig, subproblem.group).ravel()
        for v in variables])
    row_valid = np.concatenate([block_valid_mask(layout, eq, group)
                                for eq in equations])
    if col_valid.sum() != row_valid.sum():
        raise ValueError(
            f"Invalid row/column mismatch in group {subproblem.group}: "
            f"{row_valid.sum()} valid rows vs {col_valid.sum()} valid columns.")
    keep = row_valid[rows] & col_valid[cols]
    return rows[keep], cols[keep], vals[keep], row_valid, col_valid


def assemble_group_coos(subproblem, equations, variables, names, closure=True):
    """
    All matrices of one group in COO form (duplicates summed). With
    closure=True, identity closure of invalid slots is added to the last
    name in enumeration-pair order (the dense path's convention).
    Returns ({name: (rows, cols, vals)}, row_valid, col_valid).
    """
    layout = subproblem.layout
    var_offsets, eq_sizes, S = _system_sizes(layout, equations, variables)
    out = {}
    row_valid = col_valid = None
    for name in names:
        rows, cols, vals, row_valid, col_valid = assemble_group_coo(
            subproblem, equations, variables, name, eq_sizes, var_offsets)
        if closure and name == names[-1]:
            inv_rows = np.flatnonzero(~row_valid)
            inv_cols = np.flatnonzero(~col_valid)
            rows = np.concatenate([rows, inv_rows])
            cols = np.concatenate([cols, inv_cols])
            vals = np.concatenate([vals, np.ones(len(inv_rows))])
        # sum duplicate entries so downstream scatters can assign
        mat = sp.csr_matrix((vals, (rows, cols)), shape=(S, S))
        mat.sum_duplicates()
        coo = mat.tocoo()
        out[name] = (coo.row, coo.col, coo.data)
    return out, row_valid, col_valid


def assembly_workers(n_groups):
    """Worker-thread count for per-group assembly ([caching]
    ASSEMBLY_WORKERS: 0/off = serial, 'auto' = up to one thread per core,
    integer = explicit). Returns 0 when pooling is off or pointless."""
    from ..tools.config import config
    if not config.has_section("caching"):
        return 0
    spec = config["caching"].get("ASSEMBLY_WORKERS", "0").strip().lower()
    if spec in ("", "0", "off", "none", "false"):
        return 0
    import os
    workers = (os.cpu_count() or 1) if spec == "auto" else int(spec)
    workers = min(workers, n_groups)
    return workers if workers > 1 else 0


def map_groups(fn, subproblems):
    """
    `[fn(sp) for sp in subproblems]`, fanned over a thread pool when
    [caching] ASSEMBLY_WORKERS asks for one. The FIRST group always runs
    serially: it warms the per-basis/operator memoization caches
    (CachedMethod) and performs any NCC scale-change roundtrips, so the
    pooled remainder runs on read-mostly state. scipy/numpy kernels drop
    the GIL, which is where the per-group time goes.
    """
    if not subproblems:
        return []
    workers = assembly_workers(len(subproblems) - 1)
    if not workers:
        return [fn(sp) for sp in subproblems]
    import concurrent.futures
    first = fn(subproblems[0])
    with concurrent.futures.ThreadPoolExecutor(workers) as pool:
        rest = list(pool.map(fn, subproblems[1:]))
    return [first] + rest


def build_matrices(subproblems, equations, variables, names=("M", "L")):
    """
    Assemble the batched dense pencil matrices for all subproblems.
    Returns {name: np.ndarray (G, S, S)} with validity enforcement:
    invalid rows/columns zeroed; identity closure rows added to the LAST
    name in `names` (the 'L'-like matrix) to keep each group square
    (reference: core/subsystems.py:493-598 build_matrices).
    """
    layout = subproblems[0].layout
    _, _, S = _system_sizes(layout, equations, variables)
    complex_problem = any(is_complex_dtype(v.dtype) for v in variables)
    dtype = np.complex128 if complex_problem else np.float64
    G = len(subproblems)
    out = {name: np.zeros((G, S, S), dtype=dtype) for name in names}
    all_coos = map_groups(
        lambda sp: assemble_group_coos(sp, equations, variables, names)[0],
        subproblems)
    for sp_i, coos in enumerate(all_coos):
        for name in names:
            rows, cols, vals = coos[name]
            out[name][sp_i][rows, cols] = vals
    return out


class MatrixStructure:
    """
    Structural analysis of the pencil system enabling the banded + pinned
    Woodbury device solve (reference: the pre_left/pre_right
    bandwidth-minimizing permutations, core/subsystems.py:556-598,610-674,
    and the Woodbury bordered solve, libraries/matsolvers.py:285-316).

    The permutation interleaves all coupled-axis modes (mode-major:
    Modes > Equations/Variables > Components, matching the reference's
    interleave_components ordering). A maximum bipartite matching between
    coupled-equation rows and ALL columns — on the "qualified" pattern of
    entries present in every group where their row/column is valid —
    assigns each matched row the position of its matched column, making
    every banded diagonal structurally nonzero in every group. Dense rows
    (BCs, gauges) and unmatched rows are replaced by identity "pin" rows
    at leftover column positions, with their true content restored by a
    rank-t Woodbury correction. Pinning the low-mode coefficients removes
    the exponentially ill-conditioned null directions a boundary-row
    Schur complement would create (the pinned matrix's condition number
    matches the full tau system's).
    """

    @classmethod
    def from_state(cls, state, layout=None):
        """Rehydrate a finalized structure from its persisted scalar/array
        state (tools/assembly_cache.py): everything BandedOps and the
        solve path consume (permutations, pin data, band geometry) without
        re-running the symbolic analysis."""
        st = cls.__new__(cls)
        st.layout = layout
        st.ok = True
        st.reason = None
        for key, val in state.items():
            setattr(st, key, np.asarray(val) if isinstance(
                val, np.ndarray) else val)
        return st

    def __init__(self, layout, variables, equations):
        self.layout = layout
        caxes = list(layout.coupled_axes)
        self.ok = len(caxes) in (1, 2)
        self.reason = None if self.ok else \
            f"{len(caxes)} coupled axes (banded supports 1 or 2)"
        if not self.ok:
            return
        var_offsets, eq_sizes, S = _system_sizes(layout, equations, variables)
        self.S = S
        self.n_caxes = len(caxes)

        def base_order(items):
            """items: [(domain, tensorsig)] -> (by_mode, uncoupled) indices.
            With two coupled axes (e.g. Chebyshev x Chebyshev, reference:
            core/subsystems.py:493-598 sparse coupled sets), modes are the
            FLATTENED (outer, inner) coupled slots — the banded machinery
            then sees one super-axis whose band is wide but whose occupied
            diagonals stay sparse (kron structure)."""
            by_mode = None
            uncoupled = []
            offset = 0
            for domain, tsig in items:
                shape = layout.slot_shape(domain, tsig)
                n_slots = int(np.prod(shape))
                present = [ax for ax in caxes if domain.bases[ax] is not None]
                if not present:
                    uncoupled.extend(range(offset, offset + n_slots))
                elif len(present) < len(caxes):
                    # partial extent (e.g. an x-boundary tau field on a
                    # 2-coupled-axis domain): modes along the missing axis
                    # collapse; treat every slot as uncoupled (pinned)
                    uncoupled.extend(range(offset, offset + n_slots))
                else:
                    Nc = int(np.prod([shape[1 + ax] for ax in caxes]))
                    if by_mode is None:
                        by_mode = [[] for _ in range(Nc)]
                    elif len(by_mode) != Nc:
                        self.ok = False
                        self.reason = "mismatched coupled sizes"
                        return None, None
                    idx = np.arange(n_slots).reshape(shape)
                    idx = np.moveaxis(idx, [1 + ax for ax in caxes],
                                      list(range(len(caxes))))
                    idx = idx.reshape(Nc, -1)
                    for m in range(Nc):
                        by_mode[m].extend((offset + idx[m]).tolist())
                offset += n_slots
            return by_mode, uncoupled

        cols_by_mode, cols_unc = base_order(
            [(v.domain, v.tensorsig) for v in variables])
        rows_by_mode, rows_unc = base_order(
            [(eq["domain"], eq["tensorsig"]) for eq in equations])
        if not self.ok:
            return
        if cols_by_mode is None or rows_by_mode is None:
            self.ok = False
            self.reason = "no coupled-extent slots"
            return
        self._rows_int = np.array([i for m in rows_by_mode for i in m])
        self._rows_unc = np.array(rows_unc, dtype=int)
        self.n_modes = len(rows_by_mode)
        # inner-axis mode count (window sizing for 2-coupled-axis systems)
        self._inner_modes = 1
        if self.n_caxes == 2:
            for v in variables:
                if all(v.domain.bases[ax] is not None for ax in caxes):
                    shape = layout.slot_shape(v.domain, v.tensorsig)
                    self._inner_modes = shape[1 + caxes[-1]]
                    break
        self._cols_by_mode = cols_by_mode
        self._cols_unc = np.array(cols_unc, dtype=int)
        self._row_mode = -np.ones(S, dtype=int)
        for m, rows in enumerate(rows_by_mode):
            self._row_mode[rows] = m

    def finalize(self, union_pat, qual_pat, row_valid_all, col_valid_all,
                 vmax=None, band_cutoff=0.5, min_blocks=2,
                 allow_uneconomic=False):
        """
        Complete the structure from sparsity patterns (scipy bool CSR, SxS,
        original ordering) and per-group validity masks (G, S). Sets
        self.ok; on success defines row_perm, pinned rows, and band sizes.
        """
        if not self.ok:
            return self
        S = self.S
        # Place each uncoupled (tau) column at the mode of the rows that
        # reference it, so tau entries stay near the diagonal (the
        # reference's tau_left placement generalized per-column).
        pu_all = sp.coo_matrix(union_pat)
        col_key = {}
        for c in self._cols_unc:
            modes = self._row_mode[pu_all.row[pu_all.col == c]]
            modes = modes[modes >= 0]
            col_key[int(c)] = int(np.median(modes)) if len(modes) \
                else self.n_modes - 1
        unc_by_mode = [[] for _ in range(self.n_modes)]
        for c in self._cols_unc:
            unc_by_mode[col_key[int(c)]].append(int(c))
        self.col_perm = np.array(
            [c for m in range(self.n_modes)
             for c in list(self._cols_by_mode[m]) + unc_by_mode[m]],
            dtype=int)
        # mode of each permuted column position (outer-block matching)
        self._col_pos_mode = np.array(
            [m for m in range(self.n_modes)
             for _ in list(self._cols_by_mode[m]) + unc_by_mode[m]],
            dtype=int)
        pos_col = np.argsort(self.col_perm)
        # Stage A: greedy structural matching of coupled-equation rows to
        # columns. Rows are processed from the highest mode down, each
        # taking its highest-OFFSET significant qualified candidate (within
        # a mode window): aligning on the principal part (highest
        # derivative) makes the banded elimination a stable downward
        # coefficient recurrence — lower-offset terms (k^2, mass) act as
        # bounded perturbations — while aligning on a lower-offset term
        # leaves the principal term as an unstable upward forcing (the
        # exponentially ill-conditioned truncations measured in testing).
        # Top-down greed leaves the unmatched (pinned) columns at LOW
        # modes, where coefficient-pinning is well-conditioned — the
        # homogeneous solutions a boundary-row replacement must suppress
        # have O(1) low coefficients but exponentially small high ones.
        qual_r = qual_pat[self._rows_int][:, self.col_perm]
        if vmax is not None:
            qual_r = vmax[self._rows_int][:, self.col_perm].multiply(qual_r)
        Q = sp.coo_matrix(qual_r)
        window = 16 * max(8, len(self._rows_int) // self.n_modes)
        if getattr(self, "n_caxes", 1) > 1:
            # two flattened coupled axes: outer-axis couplings sit a full
            # inner extent apart, so the matching window must span them
            window = min(window * max(self._inner_modes, 1), self.S)
        near = np.abs(Q.col - Q.row) <= window
        Qr = sp.csr_matrix((Q.data[near], (Q.row[near], Q.col[near])),
                           shape=Q.shape)
        nr = len(self._rows_int)
        match = -np.ones(nr, dtype=int)
        col_taken = np.zeros(S, dtype=bool)
        indptr, indices, data = Qr.indptr, Qr.indices, Qr.data
        # With two flattened coupled axes, stability requires a CONSISTENT
        # alignment choice. For NCC-forced couplings (ell-coupled shell/
        # ball problems) the principal operator is the inner (radial) one:
        # every outer-axis (dl != 0) coupling is a physical side term
        # (Coriolis, anisotropic conductivity, ...) whose magnitude can be
        # anything — aligning on it turns the block elimination into an
        # exponentially growing outer recurrence (1/Ekman-scaled Coriolis
        # entries defeated a magnitude gate). So restrict each row's
        # candidates to columns in its OWN outer-mode block (exact mode
        # comparison; flat-offset windows leak neighbouring blocks). Two
        # GENUINE coupled bases (a rectangle's Dxx vs Dzz) are same-order
        # principals and keep the plain highest-offset rule.
        ncc_forced = bool(getattr(self.layout, "forced_coupled", None))
        outer_match = (getattr(self, "n_caxes", 1) > 1 and ncc_forced)
        if outer_match:
            inner = max(self._inner_modes, 1)
            cand_outer = self._col_pos_mode // inner
        for i in range(nr - 1, -1, -1):
            cand = indices[indptr[i]:indptr[i + 1]]
            w = data[indptr[i]:indptr[i + 1]]
            free = ~col_taken[cand]
            if free.any():
                cand, w = cand[free], w[free]
                sig = w >= 1e-10 * w.max()
                cand = cand[sig]
                if outer_match:
                    row_outer = self._row_mode[self._rows_int[i]] // inner
                    near = cand_outer[cand] == row_outer
                    if near.any():
                        cand = cand[near]
                c = cand.max()
                match[i] = c
                col_taken[c] = True
        row_pos = -np.ones(S, dtype=int)     # orig row index -> position
        row_pos[self._rows_int] = match       # position = matched col position
        # leftover rows pair with leftover positions by validity signature
        # (so validity closure stays aligned with the pinning)
        left_rows = np.concatenate([self._rows_int[match < 0], self._rows_unc])
        filled = np.zeros(S, dtype=bool)
        filled[match[match >= 0]] = True
        left_positions = np.flatnonzero(~filled)
        if len(left_rows) != len(left_positions):
            self.ok = False
            self.reason = "matching bookkeeping mismatch"
            return self
        row_sig = {r: row_valid_all[:, r].tobytes() for r in left_rows}
        col_sig = {p: col_valid_all[:, self.col_perm[p]].tobytes()
                   for p in left_positions}
        from collections import defaultdict
        by_sig_rows = defaultdict(list)
        by_sig_pos = defaultdict(list)
        for r in left_rows:
            by_sig_rows[row_sig[r]].append(int(r))
        for p in left_positions:
            by_sig_pos[col_sig[p]].append(int(p))
        if set(by_sig_rows) != set(by_sig_pos) or any(
                len(by_sig_rows[s]) != len(by_sig_pos[s]) for s in by_sig_rows):
            self.ok = False
            self.reason = "validity signatures of pins do not pair"
            return self
        pinned_rows = []
        pinned_positions = []
        for sig in by_sig_rows:
            rs = sorted(by_sig_rows[sig])
            ps = sorted(by_sig_pos[sig])
            pinned_rows.extend(rs)
            pinned_positions.extend(ps)
        order = np.argsort(pinned_positions)
        self.pinned_rows = np.array(pinned_rows, dtype=int)[order]
        self.pinned_positions = np.array(pinned_positions, dtype=int)[order]
        row_pos[self.pinned_rows] = self.pinned_positions
        if (row_pos < 0).any():
            self.ok = False
            self.reason = "row placement incomplete"
            return self
        self.row_pos = row_pos                      # orig row -> position
        self.row_perm = np.argsort(row_pos)         # position -> orig row
        self.n_interior = S
        self.t_pins = len(self.pinned_rows)
        # validity alignment of matched rows (guaranteed by the qualified
        # pattern: entry present wherever either endpoint is valid)
        matched = np.ones(S, dtype=bool)
        matched[self.pinned_rows] = False
        mrows = np.flatnonzero(matched)
        if not np.array_equal(row_valid_all[:, mrows],
                              col_valid_all[:, self.col_perm[row_pos[mrows]]]):
            self.ok = False
            self.reason = "validity misalignment on matched rows"
            return self
        # band extent from union pattern of matched (true-banded) rows
        pu = sp.coo_matrix(union_pat)
        keep = matched[pu.row]
        pr, pc = row_pos[pu.row[keep]], pos_col[pu.col[keep]]
        if len(pr) == 0:
            self.ok = False
            self.reason = "empty banded pattern"
            return self
        d = pc - pr
        self.kl = int(max(-d.min(), 0))
        self.ku = int(max(d.max(), 0))
        nd = self.kl + self.ku + 1
        # Block size constraints of the windowed-pivoting factorization
        # (pencilops.BandedOps): pivot window needs kl <= q; the block
        # tridiagonal carries ku <= 2q-1; fill width needs kl+ku <= 2q.
        # The smallest q satisfying these minimizes factor storage, which
        # scales linearly in q.
        q = max(self.kl, -(-(self.ku + 1) // 2), -(-(self.kl + self.ku) // 2), 1)
        self.q = int(-(-q // 8) * 8) if q > 8 else max(q, 1)
        self.NB = -(-S // self.q)
        # Caps. The lattice width (nd) may legitimately be large for two
        # flattened coupled axes (kron terms land a full inner extent
        # apart) — what the per-step matvec unrolls is the number of
        # OCCUPIED diagonals, so cap that; the relative cap rejects
        # structures where the blocked factorization (storage ~ 4 S q)
        # cannot beat dense (~ S^2).
        from ..tools.config import config
        max_diags = int(config["linear algebra"].get(
            "BANDED_MAX_DIAGS", "384"))
        n_occ = len(np.unique(d))
        uneconomic = (8 * self.q > S) and not allow_uneconomic
        if (nd > band_cutoff * S or n_occ > max_diags
                or self.NB < min_blocks or uneconomic):
            self.ok = False
            self.reason = (f"band too wide ({n_occ} occupied of {nd} "
                           f"diagonals for S={S}, q={self.q})")
        if self.t_pins > max(64, 0.25 * S):
            self.ok = False
            self.reason = f"too many pinned rows ({self.t_pins} of {S})"
        return self


class PatternAccumulator:
    """
    Accumulates per-group sparsity evidence for the structural analysis:
    `union` of all real entries (band extent), and entry counts + per-row
    validity counts yielding the "qualified" pattern — entries present in
    every group where their row is valid — which is what the no-pivot
    block LU needs on its diagonal.
    """

    def __init__(self, S):
        self.S = S
        self.union = None
        self.count = None
        self.vmax = None
        self.n_row_valid = np.zeros(S, dtype=np.int64)
        self.n_col_valid = np.zeros(S, dtype=np.int64)

    def add_group(self, coos, row_valid, col_valid):
        rows = np.concatenate([c[0] for c in coos.values()])
        cols = np.concatenate([c[1] for c in coos.values()])
        vals = np.concatenate([np.abs(c[2]) for c in coos.values()])
        pat = sp.csr_matrix((np.ones(len(rows), dtype=np.int64), (rows, cols)),
                            shape=(self.S, self.S))
        pat.sum_duplicates()
        pat.data[:] = 1
        vm = sp.csr_matrix((vals, (rows, cols)), shape=(self.S, self.S))
        if self.union is None:
            self.union = pat.astype(bool)
            self.count = pat
            self.vmax = vm
        else:
            self.union = (self.union + pat.astype(bool)).astype(bool)
            self.count = self.count + pat
            self.vmax = self.vmax.maximum(vm)
        self.n_row_valid += row_valid
        self.n_col_valid += col_valid

    def qualified(self):
        """Entries present in every group where their row is valid AND in
        every group where their column is valid — safe no-pivot diagonals
        whose validity closure aligns with the matching."""
        coo = self.count.tocoo()
        keep = ((coo.data >= self.n_row_valid[coo.row])
                & (coo.data >= self.n_col_valid[coo.col]))
        return sp.csr_matrix(
            (np.ones(keep.sum(), dtype=bool), (coo.row[keep], coo.col[keep])),
            shape=(self.S, self.S))


def compute_group_closure(structure, row_valid, col_valid):
    """
    Identity-closure placement for one group's invalid slots, aligned with
    the structure: every invalid row closes at the column whose position it
    occupies (its matched column, or its pin column), which is a diagonal
    entry of the permuted system. The structure's signature pairing
    guarantees that column is invalid in exactly the same groups.
    Returns (rows, cols).
    """
    st = structure
    inv_rows = np.flatnonzero(~row_valid)
    cols = st.col_perm[st.row_pos[inv_rows]]
    if col_valid[cols].any():
        return None  # should not happen given finalize's signature checks
    return inv_rows, cols


def build_banded_arrays(coo_store, structure, names, dtype, drop_tol=0.0,
                        closures=None):
    """
    Scatter per-group COO matrices into banded + pinned-row storage:
    matched rows' entries go to the (G, D, n_pad) diagonal bands at their
    positions; pinned rows' true content goes to Vt (G, t, n_pad) for the
    Woodbury correction (the identity pins themselves are injected at
    factor time, not stored, so the per-name arrays represent the TRUE
    matrices and matvec needs no special casing).

    `closures` optionally supplies per-group (rows, cols) identity-closure
    entries (value 1.0) for the LAST name, kept out of the COO store so
    the batched-assembly path's SHARED pattern survives — when all groups
    share one (rows, cols) pattern the scatter vectorizes over the whole
    group batch instead of looping (the loop dominated large builds).
    Returns {name: {"bands": ..., "Vt": ...}}.
    """
    st = structure
    G = len(coo_store)
    n_pad = st.NB * st.q
    nd = st.kl + st.ku + 1
    pos_col = np.argsort(st.col_perm)
    pin_index = -np.ones(st.S, dtype=int)
    pin_index[st.pinned_rows] = np.arange(st.t_pins)

    def masks_for(rows, cols, oob_max):
        """(mb, mv, d, pr, pc, pi) for one (rows, cols) pattern; raises on
        a genuine out-of-band entry, drops sub-tolerance ones. `oob_max`
        maps an out-of-band index mask to the max |value| there (called
        only when out-of-band entries exist, so the common all-in-band
        build never materializes an abs temp)."""
        pi = pin_index[rows]
        pr, pc = st.row_pos[rows], pos_col[cols]
        mb = pi < 0               # entries of banded (non-pinned) rows
        mv = ~mb                  # entries of pinned rows
        d = pc - pr + st.kl
        oob = mb & ((d < 0) | (d >= nd))
        if oob.any():
            # sub-tolerance out-of-band entries (excluded from the
            # detected pattern) are dropped; anything larger is a
            # genuine structure violation
            if oob_max(oob) > drop_tol:
                raise ValueError("Entry outside detected band")
            mb = mb & ~oob
        return mb, mv, d, pr, pc, pi

    out = {}
    for name in names:
        is_last = (closures is not None and name == names[-1])
        if is_last:
            # vectorized closure entries: concatenated (g, row, col),
            # value 1.0 (closure columns are the matched diagonal, always
            # in band; closure rows may be pinned)
            cl_g = np.concatenate([np.full(len(c[0]), g, dtype=int)
                                   for g, c in enumerate(closures)])
            cl_rows = np.concatenate([c[0] for c in closures])
            cl_cols = np.concatenate([c[1] for c in closures])
            cl = masks_for(cl_rows, cl_cols, lambda oob: np.inf)
        r0, c0, _ = coo_store[0][name]
        shared = all(coo_store[g][name][0] is r0
                     and coo_store[g][name][1] is c0 for g in range(G))
        if shared:
            vals_all = np.stack([coo_store[g][name][2] for g in range(G)])
            mb, mv, d, pr, pc, pi = masks_for(
                r0, c0, lambda oob: np.abs(vals_all[:, oob]).max(initial=0.0))
            # assemble straight into TRIMMED storage: only the occupied
            # diagonals are allocated (dsel maps stored rows to the full
            # 0..nd-1 lattice), skipping the (G, nd, n_pad) host lattice
            # and the trim copy to_device would otherwise pay
            dsel = np.unique(np.concatenate(
                [d[mb], [st.kl]] + ([cl[2][cl[0]]] if is_last else [])))
            remap = np.zeros(nd, dtype=int)
            remap[dsel] = np.arange(len(dsel))
            bands = np.zeros((G, len(dsel), n_pad), dtype=dtype)
            Vt = np.zeros((G, st.t_pins, n_pad), dtype=dtype)
            bands[:, remap[d[mb]], pr[mb]] = vals_all[:, mb]
            Vt[:, pi[mv], pc[mv]] = vals_all[:, mv]
            if is_last and len(cl_g):
                mb_c, mv_c, d_c, pr_c, pc_c, pi_c = cl
                bands[cl_g[mb_c], remap[d_c[mb_c]], pr_c[mb_c]] = 1.0
                Vt[cl_g[mv_c], pi_c[mv_c], pc_c[mv_c]] = 1.0
            out[name] = {"bands": bands, "Vt": Vt,
                         "dsel": tuple(int(x) for x in dsel)}
        else:
            bands = np.zeros((G, nd, n_pad), dtype=dtype)
            Vt = np.zeros((G, st.t_pins, n_pad), dtype=dtype)
            for g in range(G):
                rows, cols, vals = coo_store[g][name]
                mb, mv, d, pr, pc, pi = masks_for(
                    rows, cols, lambda oob: np.abs(vals[oob]).max(initial=0.0))
                bands[g][d[mb], pr[mb]] = vals[mb]
                Vt[g][pi[mv], pc[mv]] = vals[mv]
            if is_last and len(cl_g):
                mb_c, mv_c, d_c, pr_c, pc_c, pi_c = cl
                bands[cl_g[mb_c], d_c[mb_c], pr_c[mb_c]] = 1.0
                Vt[cl_g[mv_c], pi_c[mv_c], pc_c[mv_c]] = 1.0
            out[name] = {"bands": bands, "Vt": Vt}
    return out


def state_key(v):
    """Dict key for a state field: unnamed fields (e.g. tau fields created
    without name=, as in the reference examples) must not collide on
    name=None."""
    return v.name if v.name is not None else f"_anon_{id(v):x}"


def gather_state(layout, variables, arrays):
    """Stack per-variable coeff arrays into the (G, S) state vector,
    keyed by `state_key`."""
    parts = [layout.gather(arrays[state_key(v)], v.domain, v.tensorsig)
             for v in variables]
    return jnp.concatenate(parts, axis=1)


def scatter_state(layout, variables, X):
    """Split the (G, S) state vector back into per-variable coeff arrays."""
    out = {}
    offset = 0
    for v in variables:
        size = layout.slot_size(v.domain, v.tensorsig)
        out[state_key(v)] = layout.scatter(X[:, offset:offset + size],
                                           v.domain, v.tensorsig)
        offset += size
    return out


def gather_rhs(layout, equations, eq_arrays, valid_masks):
    """Stack per-equation F coeff arrays into the (G, S) RHS vector."""
    parts = []
    for eq, arr in zip(equations, eq_arrays):
        parts.append(layout.gather(arr, eq["domain"], eq["tensorsig"]))
    F = jnp.concatenate(parts, axis=1)
    return F * valid_masks


def row_valid_masks(layout, equations):
    """(G, S) float mask of valid equation rows (host numpy)."""
    groups = None
    parts = []
    for eq in equations:
        base = layout.valid_masks_all(eq["domain"], eq["tensorsig"])
        if "members" in eq and any(cond is not None
                                   for _, cond in eq["members"]):
            if groups is None:
                groups = list(layout.groups())
            active = np.zeros(len(groups), dtype=bool)
            for member, cond in eq["members"]:
                if cond is None:
                    active[:] = True
                else:
                    active |= np.array([cond(g) for g in groups], dtype=bool)
            base = base & active[:, None]
        parts.append(base)
    return np.concatenate(parts, axis=1).astype(np.float64)
