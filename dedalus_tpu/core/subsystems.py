"""
Pencil layout and subproblem matrix assembly
(reference: dedalus/core/subsystems.py).

TPU-native redesign: the reference enumerates per-rank "subsystems"
(generalized pencils) and assembles one sparse matrix per subproblem, solved
serially with SuperLU. Here ALL groups form one uniform batch:

  * every variable occupies a fixed-size slot per group —
    (ncomp, group_shape per separable axis, coupled size or 1) — so the
    pencil matrices stack into a dense/banded (G, S, S) device array
    (pencil index = MXU batch dimension);
  * invalid slots (the reference's valid_modes masks, core/basis.py:1123)
    are zeroed and closed with identity rows, keeping every group the same
    shape instead of ragged per-group sizes;
  * gather/scatter between field coefficient arrays and the (G, S) state
    vector are pure jnp reshapes/transposes, fused into the jitted step
    (reference: core/subsystems.py:336-367 gather_inputs/scatter_inputs).
"""

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from .field import Field
from .domain import Domain
from ..tools.general import is_complex_dtype


class PencilLayout:
    """Global pencil structure shared by all subproblems of a problem."""

    def __init__(self, dist, variables, equations):
        self.dist = dist
        dim = dist.dim
        sep_basis = [None] * dim      # (basis, sub_axis)
        coupled_basis = [None] * dim  # (basis, sub_axis)
        domains = [v.domain for v in variables] + [eq["domain"] for eq in equations]
        for domain in domains:
            for axis, basis in enumerate(domain.bases):
                if basis is None:
                    continue
                sub = axis - basis.first_axis
                if basis.sub_separable(sub):
                    if sep_basis[axis] is None:
                        sep_basis[axis] = (basis, sub)
                    else:
                        cur, csub = sep_basis[axis]
                        if (cur.sub_n_groups(csub) != basis.sub_n_groups(sub)
                                or cur.sub_group_shape(csub) != basis.sub_group_shape(sub)):
                            raise ValueError(f"Mismatched separable bases on axis {axis}")
                else:
                    cur = coupled_basis[axis]
                    if cur is None or getattr(basis, "k", 0) > getattr(cur[0], "k", 0):
                        coupled_basis[axis] = (basis, sub)
        self.sep_axes = [ax for ax in range(dim) if sep_basis[ax] is not None]
        self.sep_bases = {ax: sep_basis[ax][0] for ax in self.sep_axes}
        self.sep_widths = {ax: sep_basis[ax][0].sub_group_shape(sep_basis[ax][1])
                           for ax in self.sep_axes}
        self.coupled_axes = [ax for ax in range(dim) if coupled_basis[ax] is not None]
        self.group_counts = [sep_basis[ax][0].sub_n_groups(sep_basis[ax][1])
                             for ax in self.sep_axes]
        self.sep_n_groups = dict(zip(self.sep_axes, self.group_counts))
        self.n_groups = int(np.prod(self.group_counts, dtype=int)) if self.sep_axes else 1

    def groups(self):
        """Iterate full-length per-axis group tuples."""
        dim = self.dist.dim
        if not self.sep_axes:
            yield (None,) * dim
            return
        for multi in np.ndindex(*self.group_counts):
            group = [None] * dim
            for ax, g in zip(self.sep_axes, multi):
                group[ax] = int(g)
            yield tuple(group)

    # ------------------------------------------------------------ slots

    def slot_shape(self, domain, tensorsig):
        """(ncomp, *per-axis slot sizes) — uniform across groups."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        sizes = []
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                sizes.append(self.sep_widths[axis])
            elif basis is None:
                sizes.append(1)
            else:
                sizes.append(basis.coeff_size(axis - basis.first_axis))
        return (ncomp,) + tuple(sizes)

    def slot_size(self, domain, tensorsig):
        return int(np.prod(self.slot_shape(domain, tensorsig), dtype=int))

    def valid_mask(self, domain, tensorsig, group):
        """
        Validity of each slot entry for one group (bool, slot_shape).
        Component-resolved: curvilinear bases mask per tensor component
        (spin/regularity validity, reference: core/basis.py:1780,3183).
        """
        shape = self.slot_shape(domain, tensorsig)
        mask = np.ones(shape, dtype=bool)
        handled = set()
        for axis, basis in enumerate(domain.bases):
            if basis is None:
                ax_len = shape[1 + axis]
                ax_mask = np.ones(ax_len, dtype=bool)
                if axis in self.sep_widths:
                    ax_mask[:] = False
                    if group[axis] == 0:
                        ax_mask[0] = True
                view = [np.newaxis] * len(shape)
                view[1 + axis] = slice(None)
                mask = mask & ax_mask[tuple(view)]
            elif id(basis) not in handled:
                handled.add(id(basis))
                bmask = basis.component_valid_mask(tensorsig, group, self.sep_widths)
                # bmask: (ncomp, *sizes over the basis's axes); place its
                # dims at the basis's axes and broadcast over the rest
                first = basis.first_axis
                full = [bmask.shape[0]] + [1] * len(domain.bases)
                for sub in range(basis.dim):
                    full[1 + first + sub] = bmask.shape[1 + sub]
                mask = mask & bmask.reshape(full)
        return mask

    # ------------------------------------------------- device gather/scatter

    def gather(self, array, domain, tensorsig):
        """
        (tensor..., coeff...) device array -> (G, slot) with constant
        separable axes zero-embedded at (group 0, element 0). Pure jnp.
        """
        tshape = tuple(cs.dim for cs in tensorsig)
        tdim = len(tshape)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        data = array.reshape((ncomp,) + array.shape[tdim:])
        # expand/embed separable axes
        new_shape = [ncomp]
        group_positions = []
        pos = 1
        for axis, basis in enumerate(domain.bases):
            size = data.shape[1 + axis]
            if axis in self.sep_widths:
                gs = self.sep_widths[axis]
                G = self.sep_n_groups[axis]
                if basis is None:
                    pad = [(0, 0)] * data.ndim
                    pad[1 + axis] = (0, G * gs - size)
                    data = jnp.pad(data, pad)
                new_shape.extend([G, gs])
                group_positions.append(pos)
                pos += 2
            else:
                new_shape.append(size)
                pos += 1
        data = data.reshape(new_shape)
        # move group axes to the front (in separable-axis order)
        perm = group_positions + [i for i in range(data.ndim) if i not in group_positions]
        data = jnp.transpose(data, perm)
        G_total = self.n_groups
        return data.reshape(G_total, -1)

    def scatter(self, pencils, domain, tensorsig):
        """(G, slot) -> (tensor..., coeff...); inverse of `gather`."""
        tshape = tuple(cs.dim for cs in tensorsig)
        ncomp = int(np.prod(tshape, dtype=int)) if tshape else 1
        # Rebuild the transposed intermediate shape
        group_dims = []
        slot_dims = [ncomp]
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                group_dims.append(self.sep_n_groups[axis])
                slot_dims.append(self.sep_widths[axis])
            elif basis is None:
                slot_dims.append(1)
            else:
                slot_dims.append(basis.coeff_size(axis - basis.first_axis))
        data = pencils.reshape(group_dims + slot_dims)
        nG = len(group_dims)
        # inverse permutation: groups back next to their pair dims
        perm = []
        gi = 0
        si = nG  # position of ncomp
        perm.append(si)
        si += 1
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                perm.append(gi)
                perm.append(si)
                gi += 1
                si += 1
            else:
                perm.append(si)
                si += 1
        data = jnp.transpose(data, perm)
        # merge (G, gs) pairs and slice off constant-axis embeddings
        out_shape = []
        slices = []
        dims = list(data.shape)
        di = 1
        merged = [dims[0]]
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths:
                merged.append(dims[di] * dims[di + 1])
                di += 2
            else:
                merged.append(dims[di])
                di += 1
        data = data.reshape(merged)
        for axis, basis in enumerate(domain.bases):
            if axis in self.sep_widths and basis is None:
                slices.append(slice(0, 1))
            else:
                slices.append(slice(None))
        data = data[(slice(None),) + tuple(slices)]
        return data.reshape(tshape + data.shape[1:])


class Subproblem:
    """One pencil group (reference: core/subsystems.py:234 Subproblem)."""

    def __init__(self, layout, group, index):
        self.layout = layout
        self.group = group      # full-length per-axis tuple
        self.index = index      # flat group index

    def field_size(self, operand):
        return self.layout.slot_size(operand.domain, operand.tensorsig)

    def field_shape(self, operand):
        return self.layout.slot_shape(operand.domain, operand.tensorsig)


def build_subproblems(layout):
    return [Subproblem(layout, group, i) for i, group in enumerate(layout.groups())]


def build_matrices(subproblems, equations, variables, names=("M", "L")):
    """
    Assemble the batched pencil matrices for all subproblems.
    Returns {name: np.ndarray (G, S, S)} with validity enforcement:
    invalid rows/columns zeroed; identity closure rows added to the LAST
    name in `names` (the 'L'-like matrix) to keep each group square
    (reference: core/subsystems.py:493-598 build_matrices).
    """
    layout = subproblems[0].layout
    var_sizes = [layout.slot_size(v.domain, v.tensorsig) for v in variables]
    var_offsets = np.concatenate([[0], np.cumsum(var_sizes)])
    S = int(var_offsets[-1])
    eq_sizes = [layout.slot_size(eq["domain"], eq["tensorsig"]) for eq in equations]
    R = int(np.sum(eq_sizes))
    if R != S:
        raise ValueError(f"Pencil system is not square: {R} equation rows for "
                         f"{S} variable columns.")
    complex_problem = any(is_complex_dtype(v.dtype) for v in variables)
    dtype = np.complex128 if complex_problem else np.float64
    G = len(subproblems)
    out = {name: np.zeros((G, S, S), dtype=dtype) for name in names}

    for sp_i, subproblem in enumerate(subproblems):
        # validity masks
        col_valid = np.concatenate([
            layout.valid_mask(v.domain, v.tensorsig, subproblem.group).ravel()
            for v in variables])
        row_valid = np.concatenate([
            layout.valid_mask(eq["domain"], eq["tensorsig"], subproblem.group).ravel()
            for eq in equations])
        if col_valid.sum() != row_valid.sum():
            raise ValueError(
                f"Invalid row/column mismatch in group {subproblem.group}: "
                f"{row_valid.sum()} valid rows vs {col_valid.sum()} valid columns.")
        for name in names:
            mat = out[name][sp_i]
            row0 = 0
            for eq, esize in zip(equations, eq_sizes):
                expr = eq.get(name)
                if expr is not None and not (np.isscalar(expr) and expr == 0):
                    from .operators import operand_expression_matrices
                    mats = operand_expression_matrices(expr, subproblem, variables)
                    for vi, var in enumerate(variables):
                        if var in mats:
                            block = mats[var]
                            mat[row0:row0 + esize,
                                var_offsets[vi]:var_offsets[vi + 1]] += \
                                np.asarray(block.todense() if sp.issparse(block) else block)
                row0 += esize
            # validity enforcement
            mat[~row_valid, :] = 0.0
            mat[:, ~col_valid] = 0.0
        # identity closure on the final (L-like) matrix
        inv_rows = np.flatnonzero(~row_valid)
        inv_cols = np.flatnonzero(~col_valid)
        out[names[-1]][sp_i][inv_rows, inv_cols] = 1.0
    return out


def gather_state(layout, variables, arrays):
    """Stack per-variable coeff arrays into the (G, S) state vector."""
    parts = [layout.gather(arrays[v.name], v.domain, v.tensorsig) for v in variables]
    return jnp.concatenate(parts, axis=1)


def scatter_state(layout, variables, X):
    """Split the (G, S) state vector back into per-variable coeff arrays."""
    out = {}
    offset = 0
    for v in variables:
        size = layout.slot_size(v.domain, v.tensorsig)
        out[v.name] = layout.scatter(X[:, offset:offset + size], v.domain, v.tensorsig)
        offset += size
    return out


def gather_rhs(layout, equations, eq_arrays, valid_masks):
    """Stack per-equation F coeff arrays into the (G, S) RHS vector."""
    parts = []
    for eq, arr in zip(equations, eq_arrays):
        parts.append(layout.gather(arr, eq["domain"], eq["tensorsig"]))
    F = jnp.concatenate(parts, axis=1)
    return F * valid_masks


def row_valid_masks(layout, equations):
    """(G, S) float mask of valid equation rows (host numpy)."""
    masks = []
    for i, group in enumerate(layout.groups()):
        masks.append(np.concatenate([
            layout.valid_mask(eq["domain"], eq["tensorsig"], group).ravel()
            for eq in equations]))
    return np.array(masks, dtype=np.float64)
