"""
Group-batched pencil matrix assembly.

The per-group path (subsystems.assemble_group_coo) walks the expression tree
once per pencil group with scipy kron/matmul calls — O(G) Python/scipy
overhead that dominates setup for separable problems (G can be 10^4-10^5).
This module assembles ALL groups at once by composing the operators' own
term descriptors (operators.py module docstring) symbolically:

    matrix = sum of terms; term = scalar * kron(tensor_factor, axis factors)
    axis factor = I(w) identity | D group-independent matrix
                | B(idx_axis, stack) per-group blocks indexed by the group
                  index of a separable axis ("blocks"/"gblocks")

Products of kron terms compose axis-wise ((A1 x A2)(B1 x B2) = A1B1 x A2B2),
so the whole expression tree reduces to a term list per variable WITHOUT any
per-group work; materialization then emits one shared COO pattern with a
(G, nnz) value matrix via vectorized gathers. The reference has no analogue
(its per-pencil scipy assembly is the direct counterpart of the slow path;
reference: core/subsystems.py:493-598 build_matrices).

Falls back (BatchUnsupported) for node types without batchable descriptors
(currently: spherical regularity NCC products).

PARTIAL mode (`partial=True`, with `subproblems`): instead of abandoning
the whole system when one expression lacks batched terms, only THAT
expression drops to the per-group `operand_expression_matrices` walk
(fanned over the [caching] ASSEMBLY_WORKERS pool); its per-group entries
are unioned onto the shared pattern alongside the batched chunks. Layouts
with NCC-coupled separable axes are admitted here — descriptors on a
coupled axis convert to whole-axis block-diagonal matrices — so an
ell-coupled shell problem batches everything except the coupling NCC
itself instead of walking scipy O(G) times for every term.
"""

import numpy as np
import scipy.sparse as sp

from .field import Field
from .future import Future

__all__ = ["BatchUnsupported", "batched_system_coos"]


class BatchUnsupported(Exception):
    """Expression not representable as batched kron terms."""


# ----------------------------------------------------------------- factors
# Axis factor kinds: ("I", w) | ("D", mat) | ("B", idx_axis, stack)

def _dense(mat):
    return mat.toarray() if sp.issparse(mat) else np.asarray(mat)


def _factor_shape(f):
    kind = f[0]
    if kind == "I":
        return (f[1], f[1])
    if kind == "D":
        return f[1].shape
    return f[2].shape[1:]


def _factor_mul(f1, f2):
    """Axis-factor product f1 @ f2."""
    k1, k2 = f1[0], f2[0]
    if k1 == "I":
        return f2
    if k2 == "I":
        return f1
    if k1 == "D" and k2 == "D":
        m1, m2 = f1[1], f2[1]
        if sp.issparse(m1) or sp.issparse(m2):
            return ("D", sp.csr_matrix(m1) @ sp.csr_matrix(m2))
        return ("D", m1 @ m2)
    if k1 == "D" and k2 == "B":
        return ("B", f2[1], np.einsum("ij,gjk->gik", _dense(f1[1]), f2[2]))
    if k1 == "B" and k2 == "D":
        return ("B", f1[1], np.einsum("gij,jk->gik", f1[2], _dense(f2[1])))
    # B @ B
    if f1[1] != f2[1]:
        raise BatchUnsupported(
            f"Block factors indexed by different axes ({f1[1]} vs {f2[1]}).")
    return ("B", f1[1], np.einsum("gij,gjk->gik", f1[2], f2[2]))


class BTerm:
    """scalar * kron(tensor, factors[0], factors[1], ...)."""

    __slots__ = ("scalar", "tensor", "factors")

    def __init__(self, scalar, tensor, factors):
        self.scalar = scalar
        self.tensor = tensor    # None (identity) or dense (t_out, t_in)
        self.factors = factors  # list per distributor axis

    def matmul(self, other):
        if self.tensor is None:
            tensor = other.tensor
        elif other.tensor is None:
            tensor = self.tensor
        else:
            tensor = self.tensor @ other.tensor
        factors = [_factor_mul(a, b)
                   for a, b in zip(self.factors, other.factors)]
        return BTerm(self.scalar * other.scalar, tensor, factors)

    def scaled(self, scalar):
        return BTerm(self.scalar * scalar, self.tensor, self.factors)


def _coupled_blocks_matrix(stack, out_basis, in_basis):
    """
    Whole-axis matrix of a per-group "blocks" stack on a FORCE-COUPLED
    separable axis (the slot spans the whole axis, group-major):
    endomorphic blocks (both sides carry the axis) block-diagonalize;
    reductions (no output basis: integrate/interpolate rows) concatenate
    horizontally; embeddings (no operand basis) stack vertically.
    """
    blocks = [sp.csr_matrix(b) for b in stack]
    if out_basis is not None and in_basis is not None:
        return sp.block_diag(blocks, format="csr")
    if out_basis is None and in_basis is not None:
        if any(b.shape[0] != 1 for b in blocks):
            raise BatchUnsupported("coupled-axis reduction with >1 rows")
        return sp.hstack(blocks, format="csr")
    if out_basis is not None and in_basis is None:
        if any(b.shape[1] != 1 for b in blocks):
            raise BatchUnsupported("coupled-axis embedding with >1 cols")
        return sp.vstack(blocks, format="csr")
    raise BatchUnsupported("coupled-axis blocks without bases")


def _convert_descrs(layout, domain, terms, out_domain=None):
    """operators.terms() output -> [BTerm] (descr lists per axis).
    `domain` is the OPERAND's domain; `out_domain` (the expression's own
    domain) disambiguates reductions vs embeddings on coupled axes."""
    out = []
    for tensor_factor, axis_descrs in terms:
        tensor = None if tensor_factor is None else _dense(tensor_factor)
        factors = []
        for axis, descr in enumerate(axis_descrs):
            basis = domain.bases[axis]
            if descr is None:
                if axis in layout.sep_widths:
                    factors.append(("I", layout.sep_widths[axis]))
                elif basis is None:
                    factors.append(("I", 1))
                else:
                    # slot width of a coupled axis is the full coefficient
                    # size (subsystems.PencilLayout.slot_shape), including
                    # separable bases the layout force-coupled
                    factors.append(("I", basis.coeff_size(
                        axis - basis.first_axis)))
            else:
                kind = descr[0]
                if kind == "full":
                    factors.append(("D", descr[1]))
                elif kind == "blocks":
                    stack = np.asarray(descr[1])
                    if axis in layout.sep_widths:
                        factors.append(("B", axis, stack))
                    else:
                        out_basis = out_domain.bases[axis] \
                            if out_domain is not None else basis
                        factors.append(("D", _coupled_blocks_matrix(
                            stack, out_basis, basis)))
                elif kind == "gblocks":
                    _, group_axis, stack = descr
                    if group_axis not in layout.sep_widths:
                        raise BatchUnsupported(
                            f"gblocks indexed by coupled axis {group_axis}.")
                    factors.append(("B", group_axis, np.asarray(stack)))
                else:
                    raise BatchUnsupported(f"Descriptor kind {kind!r}.")
        out.append(BTerm(1.0, tensor, factors))
    return out


def _identity_terms(layout, operand):
    """Identity BTerm for a problem variable's slot space."""
    factors = []
    for axis, basis in enumerate(operand.domain.bases):
        if axis in layout.sep_widths:
            factors.append(("I", layout.sep_widths[axis]))
        elif basis is None:
            factors.append(("I", 1))
        else:
            factors.append(("I", basis.coeff_size(axis - basis.first_axis)))
    return [BTerm(1.0, None, factors)]


def _merge(into, other):
    for var, terms in other.items():
        into.setdefault(var, []).extend(terms)


def batched_expression_matrices(expr, layout, vars):
    """Compose the expression tree into {var: [BTerm]}."""
    from .operators import LinearOperator
    from .arithmetic import (Add, ScalarMultiply, ProductBase)
    if isinstance(expr, Field):
        if expr in vars:
            return {expr: _identity_terms(layout, expr)}
        raise BatchUnsupported(f"Field {expr} on LHS outside an NCC product.")
    if isinstance(expr, Add):
        from .operators import ConvertNode
        from ..tools.exceptions import NonlinearOperatorError
        out = {}
        for a in expr.args:
            if np.isscalar(a):
                if a != 0:
                    raise NonlinearOperatorError(
                        "Nonzero constant on equation LHS.")
                continue
            term = a if tuple(a.domain.bases) == expr.domain.bases else \
                ConvertNode(a, expr.domain.bases)
            _merge(out, batched_expression_matrices(term, layout, vars))
        return out
    if isinstance(expr, ScalarMultiply):
        sub = batched_expression_matrices(expr.operand, layout, vars)
        return {v: [t.scaled(expr.scalar) for t in ts] for v, ts in sub.items()}
    if isinstance(expr, ProductBase):
        return _batched_ncc_matrices(expr, layout, vars)
    if isinstance(expr, LinearOperator):
        if type(expr).expression_matrices is not LinearOperator.expression_matrices:
            raise BatchUnsupported(
                f"{type(expr).__name__} overrides expression_matrices.")
        op_terms = batched_expression_matrices(expr.operand, layout, vars)
        my_terms = _convert_descrs(layout, expr.operand.domain, expr.terms(),
                                   out_domain=expr.domain)
        out = {}
        for var, terms in op_terms.items():
            out[var] = [mt.matmul(ot) for mt in my_terms for ot in terms]
        return out
    raise BatchUnsupported(f"No batched matrices for {type(expr).__name__}.")


def _batched_spherical_ncc(expr, layout, vars, ncc_index, ncc, operand):
    """
    Spherical (shell/ball) radial NCC products, batched over groups: the
    ell-dependent Q-intertwined coupling C_ij(ell) folds into per-ell
    radial stacks C_ij(ell) * M_ij(ell), leaving one BTerm per active
    regularity pair with a one-hot tensor factor and a colatitude-indexed
    "gblocks" radial factor.
    """
    basis = expr._spherical_regularity_basis(ncc)
    az_axis = basis.first_axis
    colat_axis = az_axis + 1
    r_axis = az_axis + 2
    # guard BEFORE the angularly-constant setup: on an ell-coupled layout
    # (theta-dependent NCC elsewhere in the system) this product assembles
    # through the per-group whole-axis path, and _sph_ncc_setup's
    # radial-only validation may legitimately reject it
    if colat_axis not in layout.sep_n_groups or \
            az_axis not in layout.sep_widths:
        raise BatchUnsupported("spherical NCC on a coupled angular axis")
    setup = expr._sph_ncc_setup(ncc, operand, ncc_index)
    Nell = layout.sep_n_groups[colat_axis]
    ncomp_in = 3 ** setup["rank_in"]
    ncomp_out = 3 ** (setup["rank_n"] + setup["rank_in"])
    # per-(i, j) stacks over ell
    stacks = {}
    for ell in range(Nell):
        for i, j, Cij, M in expr._sph_ncc_pairs(setup, ell):
            Md = _dense(M)
            stack = stacks.get((i, j))
            if stack is None:
                stack = stacks[(i, j)] = np.zeros((Nell,) + Md.shape)
            if Md.shape != stack.shape[1:]:
                raise BatchUnsupported(
                    f"Inconsistent radial NCC shapes across ell for pair "
                    f"({i}, {j}): {Md.shape} vs {stack.shape[1:]}.")
            stack[ell] = Cij * Md
    my_terms = []
    dim = operand.domain.dim
    for (i, j), stack in stacks.items():
        tensor = np.zeros((ncomp_out, ncomp_in))
        tensor[i, j] = 1.0
        factors = [("I", 1)] * dim
        factors[az_axis] = ("I", layout.sep_widths[az_axis])
        factors[colat_axis] = ("I", layout.sep_widths.get(colat_axis, 1))
        factors[r_axis] = ("B", colat_axis, stack)
        my_terms.append(BTerm(1.0, tensor, factors))
    op_terms = batched_expression_matrices(operand, layout, vars)
    out = {}
    for var, terms in op_terms.items():
        out[var] = [mt.matmul(ot) for mt in my_terms for ot in terms]
    return out


def _batched_ncc_matrices(expr, layout, vars):
    """NCC products (MultiplyFields/DotProduct); group-independent axis
    matrices batch directly, spherical regularity NCCs via per-ell
    stacks."""
    ncc_index, ncc, operand = expr._split_ncc(vars, layout)
    if expr._spherical_regularity_basis(ncc) is not None:
        return _batched_spherical_ncc(expr, layout, vars, ncc_index, ncc,
                                      operand)
    pol = expr._polar_spin_basis(ncc)
    if pol is not None and (ncc.tensorsig
                            or not hasattr(pol, "radial_multiplication_matrix")):
        # polar tensor NCCs (intertwiner sandwich) and disk NCCs (per-m
        # Zernike stacks) assemble through the per-group path
        raise BatchUnsupported("polar tensor/disk NCC")
    tensor_factor_fn = _ncc_tensor_factor_fn(expr, ncc, operand, ncc_index)
    comp_indices = list(np.ndindex(*ncc.tshape)) if ncc.tshape else [()]
    my_terms = []
    for comp in comp_indices:
        ncc_terms = expr._ncc_axis_terms(ncc, comp, operand)
        if len(ncc_terms) != 1:
            raise BatchUnsupported("jointly-varying (multi-axis) NCC")
        scalar, descrs = ncc_terms[0]
        if scalar is not None and not np.isscalar(scalar):
            # component-mixing tensor factor (real-pair polar expansion):
            # handled by the per-group path
            raise BatchUnsupported("component-mixing NCC term")
        bterms = _convert_descrs(layout, operand.domain,
                                 [(tensor_factor_fn(comp), descrs)],
                                 out_domain=expr.domain)
        if scalar is not None:
            bterms = [t.scaled(scalar) for t in bterms]
        my_terms.extend(bterms)
    op_terms = batched_expression_matrices(operand, layout, vars)
    out = {}
    for var, terms in op_terms.items():
        out[var] = [mt.matmul(ot) for mt in my_terms for ot in terms]
    return out


def _ncc_tensor_factor_fn(expr, ncc, operand, ncc_index):
    """The per-component tensor factor builders from arithmetic.py,
    reused via the classes' own closures."""
    from .arithmetic import MultiplyFields, DotProduct
    from ..tools.array import kron as sparse_kron
    if isinstance(expr, MultiplyFields):
        ncomp_op = int(np.prod([cs.dim for cs in operand.tensorsig], dtype=int)) \
            if operand.tensorsig else 1
        shape = ncc.tshape

        def factor(comp):
            n_ncc = int(np.prod(shape, dtype=int)) if shape else 1
            col = np.zeros((n_ncc, 1))
            flat = int(np.ravel_multi_index(comp, shape)) if comp else 0
            col[flat, 0] = 1.0
            I_op = np.eye(ncomp_op)
            return np.kron(col, I_op) if ncc_index == 0 else np.kron(I_op, col)
        return factor
    if isinstance(expr, DotProduct):
        d = ncc.tensorsig[-1].dim if ncc_index == 0 else ncc.tensorsig[0].dim
        if ncc_index == 0:
            rest_op = operand.tshape[1:]
            n_rest_op = int(np.prod(rest_op, dtype=int)) if rest_op else 1
            lead_ncc = ncc.tshape[:-1]
            n_lead = int(np.prod(lead_ncc, dtype=int)) if lead_ncc else 1

            def factor(comp):
                *alpha, j = comp
                lead_flat = int(np.ravel_multi_index(tuple(alpha), lead_ncc)) \
                    if lead_ncc else 0
                col = np.zeros((n_lead, 1)); col[lead_flat, 0] = 1.0
                row = np.zeros((1, d)); row[0, j] = 1.0
                return np.kron(np.kron(col, row), np.eye(n_rest_op))
            return factor
        lead_op = operand.tshape[:-1]
        n_lead_op = int(np.prod(lead_op, dtype=int)) if lead_op else 1
        rest_ncc = ncc.tshape[1:]
        n_rest = int(np.prod(rest_ncc, dtype=int)) if rest_ncc else 1

        def factor(comp):
            j, *beta = comp
            rest_flat = int(np.ravel_multi_index(tuple(beta), rest_ncc)) \
                if rest_ncc else 0
            row = np.zeros((1, d)); row[0, j] = 1.0
            col = np.zeros((n_rest, 1)); col[rest_flat, 0] = 1.0
            return np.kron(np.kron(np.eye(n_lead_op), row), col)
        return factor
    raise BatchUnsupported(f"NCC tensor factors for {type(expr).__name__}.")


# ----------------------------------------------------------- materialization

def _factor_coo(f, group_idx):
    """Factor -> (rows, cols, vals) with vals (nnz,) or (G, nnz)."""
    kind = f[0]
    if kind == "I":
        w = f[1]
        r = np.arange(w)
        return r, r, np.ones(w)
    if kind == "D":
        coo = sp.coo_matrix(f[1])
        coo.eliminate_zeros()
        return coo.row, coo.col, coo.data
    _, idx_axis, stack = f
    union = np.abs(stack).max(axis=0) > 0
    rows, cols = np.nonzero(union)
    vals = stack[:, rows, cols][group_idx[idx_axis]]   # (G, nnz)
    return rows, cols, vals


def _kron_fold(parts):
    """Fold COO krons left to right; parts = [(shape, rows, cols, vals)]."""
    (m, n), rows, cols, vals = parts[0]
    for (m2, n2), r2, c2, v2 in parts[1:]:
        rows = (rows[:, None] * m2 + r2[None, :]).ravel()
        cols = (cols[:, None] * n2 + c2[None, :]).ravel()
        if vals.ndim == 1 and v2.ndim == 1:
            vals = (vals[:, None] * v2[None, :]).reshape(-1)
        else:
            a = vals if vals.ndim == 2 else vals[None, :]
            b = v2 if v2.ndim == 2 else v2[None, :]
            prod = a[:, :, None] * b[:, None, :]
            vals = prod.reshape(prod.shape[0], -1)
        m, n = m * m2, n * n2
    return (m, n), rows, cols, vals


def _materialize_term(term, group_idx, ncomp_in, ncomp_out):
    """BTerm -> ((R, C), rows, cols, vals (nnz,) or (G, nnz))."""
    parts = []
    if term.tensor is None:
        r = np.arange(ncomp_in)
        parts.append(((ncomp_in, ncomp_in), r, r, np.ones(ncomp_in)))
    else:
        t = np.asarray(term.tensor)
        rows, cols = np.nonzero(t)
        parts.append((t.shape, rows, cols, t[rows, cols]))
    for f in term.factors:
        shape = _factor_shape(f)
        rows, cols, vals = _factor_coo(f, group_idx)
        parts.append((shape, rows, cols, vals))
    shape, rows, cols, vals = _kron_fold(parts)
    if term.scalar != 1.0:
        vals = vals * term.scalar
    return shape, rows, cols, vals


def _pergroup_var_chunks(expr, subproblems, variables, act_groups, G, vdtype):
    """
    Per-group fallback of one expression (partial mode): walk
    `operand_expression_matrices` for each (active) group — fanned over
    the assembly worker pool — and union the per-group entries into
    shared-pattern chunks. Returns {var: (rows, cols, vals (G, nnz))}
    with rows/cols relative to the expression's own block.
    """
    from .operators import operand_expression_matrices
    from .subsystems import map_groups
    vset = set(variables)
    sps = [subproblems[g] for g in act_groups]
    mats_list = map_groups(
        lambda spx: operand_expression_matrices(expr, spx, vset), sps)
    out = {}
    for var in {v for mats in mats_list for v in mats}:
        csrs = {}
        for g, mats in zip(act_groups, mats_list):
            if var in mats:
                m = sp.csr_matrix(mats[var])
                m.sum_duplicates()
                m.eliminate_zeros()
                csrs[g] = m
        if not csrs:
            continue
        ncols = next(iter(csrs.values())).shape[1]
        pat = None
        for m in csrs.values():
            p = m.copy()
            p.data = np.ones_like(p.data)
            pat = p if pat is None else pat + p
        pat = pat.tocoo()
        lin = pat.row.astype(np.int64) * ncols + pat.col
        order = np.argsort(lin)
        lin = lin[order]
        rows = pat.row[order].astype(int)
        cols = pat.col[order].astype(int)
        vals = np.zeros((G, lin.size), dtype=vdtype)
        for g, m in csrs.items():
            coo = m.tocoo()
            idx = np.searchsorted(
                lin, coo.row.astype(np.int64) * ncols + coo.col)
            vals[g, idx] = coo.data
        out[var] = (rows, cols, vals)
    return out


def batched_system_coos(layout, equations, variables, names,
                        subproblems=None, partial=False):
    """
    Assemble the full pencil system for all groups at once.

    Returns (pattern_rows, pattern_cols, {name: vals (G, nnz)},
    row_valid (G, S), col_valid (G, S)) — one shared COO pattern
    (duplicates summed) with per-group values; validity is applied by
    ZEROING values (pattern stays shared). No closure entries are added.
    Raises BatchUnsupported when any LHS expression lacks batched terms —
    unless `partial=True` (requires `subproblems`), where unbatchable
    expressions drop to the per-group walk individually and everything
    else stays vectorized (module docstring, PARTIAL mode).
    """
    from .subsystems import _system_sizes
    if partial and subproblems is None:
        raise ValueError("partial mode requires subproblems")
    if getattr(layout, "forced_coupled", None) and not partial:
        # NCC-coupled separable axes build whole-axis multiplication
        # matrices; their group structure is not batchable (and is tiny —
        # typically G=1), so use the per-group walk
        raise BatchUnsupported("layout has NCC-coupled separable axes")
    var_offsets, eq_sizes, S = _system_sizes(layout, equations, variables)
    groups = list(layout.groups())
    G = len(groups)
    # per-separable-axis group index arrays
    group_idx = {ax: np.array([g[ax] for g in groups], dtype=int)
                 for ax in layout.sep_axes}
    ncomps = {}

    def ncomp(tsig):
        key = tuple(tsig)
        if key not in ncomps:
            ncomps[key] = int(np.prod([cs.dim for cs in key], dtype=int)) \
                if key else 1
        return ncomps[key]

    complex_problem = any(np.issubdtype(np.dtype(v.dtype), np.complexfloating)
                          for v in variables)
    vdtype = np.complex128 if complex_problem else np.float64

    # validity masks, vectorized over groups
    from .subsystems import row_valid_masks
    col_valid = np.concatenate(
        [layout.valid_masks_all(v.domain, v.tensorsig) for v in variables],
        axis=1)
    row_valid = row_valid_masks(layout, equations).astype(bool)

    # member activity masks for conditioned equations
    def member_activity(cond):
        if cond is None:
            return None
        return np.array([cond(g) for g in groups], dtype=float)

    var_index = {v: i for i, v in enumerate(variables)}
    # Collect per-name COO chunks on the shared row/col space; one shared
    # pattern across names is built by merging tagged chunks at the end.
    chunks = []  # (name, rows, cols, vals)
    for eq, esize, row0 in zip(equations, eq_sizes,
                               np.concatenate([[0], np.cumsum(eq_sizes)[:-1]])):
        members = eq["members"] if "members" in eq else [(eq, None)]
        activities = [member_activity(cond) for _, cond in members]
        if len(members) > 1:
            # mirror active_member's uniqueness diagnostic
            # (subsystems.py active_member): overlapping conditions would
            # silently SUM members' rows here
            counts = np.sum([np.ones(G) if a is None else a
                             for a in activities], axis=0)
            if counts.max() > 1:
                bad = groups[int(np.argmax(counts))]
                raise ValueError(
                    f"Multiple conditioned equations active for group {bad}: "
                    f"{[m.get('LHS_str') for m, _ in members]}")
        for (member, cond), activity in zip(members, activities):
            for name in names:
                expr = member.get(name)
                if expr is None or (np.isscalar(expr) and expr == 0):
                    continue
                staged = []
                try:
                    bmats = batched_expression_matrices(expr, layout,
                                                        set(variables))
                    for var, terms in bmats.items():
                        c0 = var_offsets[var_index[var]]
                        n_in = ncomp(var.tensorsig)
                        n_out = ncomp(eq["tensorsig"])
                        for term in terms:
                            shape, r, c, v = _materialize_term(
                                term, group_idx, n_in, n_out)
                            if v.ndim == 1:
                                v = np.broadcast_to(v, (G, v.size))
                            if activity is not None:
                                v = v * activity[:, None]
                            staged.append((name, r + row0, c + c0, v))
                    chunks.extend(staged)
                except BatchUnsupported:
                    if not partial:
                        raise
                    # per-group walk of just this expression; only groups
                    # where the member is active are assembled (others
                    # contribute structural zeros, like activity masking)
                    act = np.arange(G) if activity is None \
                        else np.flatnonzero(activity)
                    pg = _pergroup_var_chunks(expr, subproblems, variables,
                                              act, G, vdtype)
                    for var, (r, c, v) in pg.items():
                        c0 = var_offsets[var_index[var]]
                        chunks.append((name, r + row0, c + c0, v))

    if not chunks:
        raise BatchUnsupported("No assembled entries.")
    # Shared pattern: union over all chunks/names
    all_rows = np.concatenate([r for _, r, _, _ in chunks])
    all_cols = np.concatenate([c for _, _, c, _ in chunks])
    lin = all_rows.astype(np.int64) * S + all_cols
    uniq, inverse = np.unique(lin, return_inverse=True)
    nnz = uniq.size
    pattern_rows = (uniq // S).astype(int)
    pattern_cols = (uniq % S).astype(int)
    out_vals = {name: np.zeros((G, nnz), dtype=vdtype) for name in names}
    pos = 0
    for name, r, c, v in chunks:
        idx = inverse[pos:pos + r.size]
        pos += r.size
        np.add.at(out_vals[name], (slice(None), idx), v)
    # validity: zero invalid entries (pattern stays shared)
    keep = (row_valid[:, pattern_rows] & col_valid[:, pattern_cols])
    for name in names:
        out_vals[name] *= keep
    return pattern_rows, pattern_cols, out_vals, row_valid, col_valid
