"""
Spectral transform plans (reference: dedalus/core/transforms.py).

Each plan converts one axis of an N-d array between coefficient and grid
representations. Plans are registered per (basis class, library name) like
the reference's `@register_transform` registry (core/transforms.py:27-32):

  * 'matrix' — dense matrix-multiply transform (MMT). The test oracle, and
    on TPU a genuinely fast path: an MMT is one batched matmul on the MXU.
  * 'fft'    — jnp.fft fast path for Fourier bases; FFT-based DCT for
    Chebyshev.

All plan methods are pure jnp functions of their array argument (safe under
jit/vmap); the transform matrices are host-built numpy constants closed over
by the jitted step.
"""

import numpy as np
import jax.numpy as jnp

from . import meshctx
from ..tools.array import zeropad

from ..tools.array import apply_matrix_jax
from ..tools.metrics import scoped as _scoped

# Registry: {(basis_class_name, library): plan_class}
transform_registry = {}


def register_transform(basis_cls_name, name):
    def wrapper(cls):
        transform_registry[(basis_cls_name, name)] = cls
        cls.library = name
        return cls
    return wrapper


def get_plan(basis, scale, library=None):
    """Build a transform plan. Callers go through Basis.transform_plan
    (@CachedMethod), so plans — and the host matrices they own, which the
    device-constant registry interns by object identity — are built once
    per (basis, scale, library)."""
    lib = library or basis.library
    key = (type(basis).__name__, lib)
    # Fall back through base classes (e.g. ChebyshevT -> Jacobi)
    cls = None
    for klass in type(basis).__mro__:
        cls = transform_registry.get((klass.__name__, lib))
        if cls is not None:
            break
    if cls is None:
        raise KeyError(f"No transform plan registered for {key}")
    plan = cls(basis, scale)
    # single choke point for transform trace annotation: every plan built
    # through the registry gets phase-labeled forward/backward methods
    label = f"dedalus/transform/{type(basis).__name__}.{cls.library}"
    plan.forward = _scoped(plan.forward, label + ".fwd")
    plan.backward = _scoped(plan.backward, label + ".bwd")
    return plan


class TransformPlan:
    """Base transform plan for one axis at one grid scale."""

    def __init__(self, basis, scale):
        self.basis = basis
        self.scale = scale
        self.N = basis.size
        self.Ng = basis.grid_size(scale)


class MatrixTransform(TransformPlan):
    """Generic MMT plan: subclasses provide forward/backward matrices."""

    def __init__(self, basis, scale):
        super().__init__(basis, scale)
        self.forward_mat = self.build_forward(basis, scale)    # (N, Ng)
        self.backward_mat = self.build_backward(basis, scale)  # (Ng, N)

    def forward(self, gdata, axis):
        return apply_matrix_jax(self.forward_mat, gdata, axis)

    def backward(self, cdata, axis):
        return apply_matrix_jax(self.backward_mat, cdata, axis)


@register_transform("Jacobi", "matrix")
class JacobiMMT(MatrixTransform):
    """
    Jacobi MMT (reference: core/transforms.py:115 JacobiMMT).

    Grid is always the (a0, b0) Gauss grid of the basis family; forward
    projects onto (a0, b0) then applies the ultraspherical-style conversion
    to the basis's derivative level (a, b) = (a0+k, b0+k).
    """

    @staticmethod
    def build_forward(basis, scale):
        from ..tools import jacobi
        Ng = basis.grid_size(scale)
        F = jacobi.forward_matrix(basis.size, basis.a0, basis.b0, Ng)
        if basis.k > 0:
            C = jacobi.conversion_matrix(basis.size, basis.a0, basis.b0, basis.k, basis.k)
            F = C @ F
        return F

    @staticmethod
    def build_backward(basis, scale):
        from ..tools import jacobi
        Ng = basis.grid_size(scale)
        x = jacobi.build_grid(Ng, basis.a0, basis.b0)
        return jacobi.build_polynomials(basis.size, basis.a, basis.b, x).T


def _dct2(x, orig_axis=None):
    """
    Unnormalized DCT-II along the last axis with explicit dtype control:
    y_n = 2 sum_j x_j cos(pi n (2j+1) / (2N)), via Makhoul's single
    length-N FFT of the even/odd reordering. jax.scipy.fft.dct is avoided
    because its internal padding promotes f32 inputs to f64 under x64,
    and TPU backends have no f64 FFT kernels.
    """
    if jnp.iscomplexobj(x):
        # Makhoul's Re() identity only holds for real input: transform the
        # real and imaginary parts separately
        return _dct2(x.real, orig_axis) + 1j * _dct2(x.imag, orig_axis)
    N = x.shape[-1]
    cdt = jnp.complex64 if x.dtype == jnp.float32 else jnp.complex128
    v = jnp.concatenate([x[..., 0::2], x[..., 1::2][..., ::-1]], axis=-1)
    V = meshctx.local_fft(lambda a: jnp.fft.fft(a, axis=-1), v.astype(cdt),
                          orig_axis)
    n = np.arange(N)
    phase = jnp.asarray(np.exp(-1j * np.pi * n / (2 * N)), dtype=cdt)
    return 2.0 * (phase * V).real.astype(x.dtype)


def _idct2(y, orig_axis=None):
    """
    Inverse of _dct2 (up to the factor 2N): x_j such that
    _dct2(x) = y; equivalently a DCT-III evaluation
    x_j = y_0/(2N) + (1/N) sum_{n>=1} y_n cos(pi n (2j+1)/(2N)).
    """
    if jnp.iscomplexobj(y):
        return _idct2(y.real, orig_axis) + 1j * _idct2(y.imag, orig_axis)
    N = y.shape[-1]
    cdt = jnp.complex64 if y.dtype == jnp.float32 else jnp.complex128
    n = np.arange(N)
    phase = jnp.asarray(np.exp(1j * np.pi * n / (2 * N)) / 2, dtype=cdt)
    yrev = jnp.concatenate([jnp.zeros_like(y[..., :1]), y[..., 1:][..., ::-1]],
                           axis=-1)
    W = phase * (y.astype(cdt) - 1j * yrev.astype(cdt))
    v = meshctx.local_fft(lambda a: jnp.fft.ifft(a, axis=-1), W,
                          orig_axis).real.astype(y.dtype)
    half = (N + 1) // 2
    x = jnp.zeros_like(v)
    x = x.at[..., 0::2].set(v[..., :half])
    x = x.at[..., 1::2].set(v[..., half:][..., ::-1])
    return x


@register_transform("Jacobi", "fft")
class FastChebyshevTransform(TransformPlan):
    """
    O(N log N) Chebyshev transform via DCT with ultraspherical conversion
    (reference: core/transforms.py:801-890 FastChebyshevTransform).

    Applies to the Chebyshev grid family (a0 = b0 = -1/2):
      forward : flip grid -> DCT-II -> classical->orthonormal rescale ->
                truncate -> banded conversion to level k (vectorized
                diagonal shifts, offsets 0, 2, .., 2k)
      backward: inverse conversion k -> 0 solved level-by-level; each
                2-diagonal upper-triangular level telescopes into a
                strided reversed CUMSUM (no sequential scan on device) ->
                rescale -> zero-pad -> DCT-III -> flip.
    The cumsum chain weights are prefix products of the conversion
    diagonal ratios, checked at build time for overflow; non-Chebyshev
    families (no DCT grid) and unstable chains fall back to the MMT,
    which is itself MXU-native.
    """

    def __init__(self, basis, scale):
        super().__init__(basis, scale)
        self.cheb = (basis.a0 == -0.5 and basis.b0 == -0.5)
        self._mmt = None
        # no DCT grid for non-Chebyshev families; coarse scales (Ng < N)
        # need the rectangular MMT
        if not self.cheb or self.Ng < self.N:
            self._mmt = JacobiMMT(basis, scale)
            return
        from ..tools import jacobi as jt
        N, Ng, k = self.N, self.Ng, basis.k
        self.k = k
        # orthonormal P_n = r_n * cos(n theta): r_0 = 1/sqrt(pi), else sqrt(2/pi)
        r = np.full(N, np.sqrt(2.0 / np.pi))
        r[0] = 1.0 / np.sqrt(np.pi)
        self.rescale = r
        # per-level conversion diagonals (a0+l, b0+l) -> (a0+l+1, b0+l+1)
        self.levels = []
        stable = True
        for l in range(k):
            C = np.asarray(jt.conversion_matrix(N, basis.a0 + l, basis.b0 + l, 1, 1))
            d0 = np.diagonal(C).copy()
            d2 = np.zeros(N)
            d2[:N - 2] = np.diagonal(C, 2)
            # chain prefix products H_n (parity-strided) for the cumsum
            # inverse: u_n = (1/H_n) * revcumsum_parity(H * v/d0), with
            # H_{n+2} = H_n * (-d2_n / d0_n)
            rho = -d2 / d0
            H = np.ones(N)
            for n in range(2, N):
                H[n] = H[n - 2] * rho[n - 2]
            if not np.all(np.isfinite(H)) or np.abs(H).max() > 1e280 or \
                    np.abs(H[H != 0]).min() < 1e-280:
                stable = False
            self.levels.append((d0, d2, H))
        if not stable:
            self._mmt = JacobiMMT(basis, scale)

    @staticmethod
    def _revcumsum_parity(x):
        """Reversed cumulative sum along the last axis within each parity
        chain (stride-2): out[n] = sum_{m >= n, m = n mod 2} x[m]."""
        n = x.shape[-1]
        if n % 2:
            x = zeropad(x, [(0, 0)] * (x.ndim - 1) + [(0, 1)])
        pairs = x.reshape(x.shape[:-1] + (-1, 2))
        acc = jnp.cumsum(pairs[..., ::-1, :], axis=-2)[..., ::-1, :]
        return acc.reshape(x.shape[:-1] + (-1,))[..., :n]

    def forward(self, gdata, axis):
        if self._mmt is not None:
            return self._mmt.forward(gdata, axis)
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(gdata, axis, -1)[..., ::-1]
        dt = data.dtype
        y = _dct2(data, axis)                          # y_n = 2 sum g cos(n th)
        chat = y / Ng
        chat = chat.at[..., 0].divide(2.0)
        # constants cast to the data dtype: f32 data must not promote to
        # f64 (TPU backends have no f64 FFT kernels)
        u = chat[..., :N] / jnp.asarray(self.rescale, dtype=dt)
        for d0, d2, H in self.levels:
            v = jnp.asarray(d0, dtype=dt) * u
            v = v.at[..., :N - 2].add(jnp.asarray(d2[:N - 2], dtype=dt)
                                      * u[..., 2:])
            u = v
        return jnp.moveaxis(u, -1, axis)

    def backward(self, cdata, axis):
        if self._mmt is not None:
            return self._mmt.backward(cdata, axis)
        N, Ng = self.N, self.Ng
        u = jnp.moveaxis(cdata, axis, -1)
        dt = u.dtype
        for d0, d2, H in reversed(self.levels):
            Hj = jnp.asarray(H, dtype=dt)
            u = self._revcumsum_parity(Hj * u / jnp.asarray(d0, dtype=dt)) / Hj
        chat = u * jnp.asarray(self.rescale, dtype=dt)
        chat = zeropad(chat, [(0, 0)] * (chat.ndim - 1) + [(0, Ng - N)])
        # _idct2(y)_j = y_0/(2Ng) + (1/Ng) sum_n y_n cos(n th_j)
        chat = chat.at[..., 0].multiply(2.0)
        g = _idct2(chat * Ng, axis)
        return jnp.moveaxis(g[..., ::-1], -1, axis)


@register_transform("RealFourier", "matrix")
class RealFourierMMT(MatrixTransform):
    """
    Real Fourier MMT oracle (reference: core/transforms.py:388 RealFourierMMT).

    Coefficient layout matches the reference's interleaved (cos, -sin) pairs:
    c[2g] = cos-amplitude, c[2g+1] = minus-sin-amplitude of mode g
    (reference: core/basis.py:1108 RealFourier, group_shape=(2,)).
    """

    @staticmethod
    def build_forward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        g = np.arange(N // 2)
        F = np.zeros((N, Ng))
        cosrows = np.cos(np.outer(g, theta)) * 2.0 / Ng
        cosrows[0] /= 2.0
        sinrows = -np.sin(np.outer(g, theta)) * 2.0 / Ng
        sinrows[0] *= 0.0  # -sin(0x) mode is invalid
        F[0::2] = cosrows
        F[1::2] = sinrows
        return F

    @staticmethod
    def build_backward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        g = np.arange(N // 2)
        B = np.zeros((Ng, N))
        B[:, 0::2] = np.cos(np.outer(theta, g))
        B[:, 1::2] = -np.sin(np.outer(theta, g))
        B[:, 1] = 0.0
        return B


@register_transform("RealFourier", "fft")
class RealFourierFFT(TransformPlan):
    """
    Real Fourier fast path via jnp.fft.rfft/irfft
    (reference: core/transforms.py:513 ScipyRealFFT / :538 FFTWRealFFT).
    """

    def forward(self, gdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(gdata, axis, -1)
        F = meshctx.local_fft(lambda a: jnp.fft.rfft(a, axis=-1), data,
                              axis) / Ng
        K = N // 2
        F = F[..., :K]
        cos = 2.0 * F.real
        cos = cos.at[..., 0].divide(2.0)
        msin = 2.0 * F.imag
        msin = msin.at[..., 0].set(0.0)
        out = jnp.stack([cos, msin], axis=-1).reshape(data.shape[:-1] + (N,))
        return jnp.moveaxis(out, -1, axis)

    def backward(self, cdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(cdata, axis, -1)
        K = N // 2
        pairs = data.reshape(data.shape[:-1] + (K, 2))
        cos = pairs[..., 0]
        msin = pairs[..., 1].at[..., 0].set(0.0)
        F = (cos + 1j * msin) / 2.0
        F = F.at[..., 0].multiply(2.0)
        # pad spectrum to the grid's rfft length
        pad = Ng // 2 + 1 - K
        F = jnp.concatenate([F, jnp.zeros(F.shape[:-1] + (pad,), dtype=F.dtype)], axis=-1)
        out = meshctx.local_fft(
            lambda a: jnp.fft.irfft(a, n=Ng, axis=-1), F * Ng, axis)
        return jnp.moveaxis(out, -1, axis)


@register_transform("ComplexFourier", "matrix")
class ComplexFourierMMT(MatrixTransform):
    """
    Complex Fourier MMT oracle (reference: core/transforms.py:212).
    Coefficients ordered by FFT wavenumber layout [0..K, (nyquist), -K..-1];
    the Nyquist slot is invalid and masked to zero.
    """

    @staticmethod
    def _wavenumbers(N):
        return np.fft.fftfreq(N, d=1.0 / N).astype(int)

    @staticmethod
    def build_forward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        k = ComplexFourierMMT._wavenumbers(N)
        F = np.exp(-1j * np.outer(k, theta)) / Ng
        F[N // 2] = 0.0  # Nyquist mode invalid
        return F

    @staticmethod
    def build_backward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        k = ComplexFourierMMT._wavenumbers(N)
        B = np.exp(1j * np.outer(theta, k))
        B[:, N // 2] = 0.0
        return B


@register_transform("ComplexFourier", "fft")
class ComplexFourierFFT(TransformPlan):
    """Complex Fourier fast path via jnp.fft (reference: core/transforms.py:271)."""

    def forward(self, gdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(gdata, axis, -1)
        F = meshctx.local_fft(lambda a: jnp.fft.fft(a, axis=-1), data,
                              axis) / Ng
        K = N // 2
        # keep modes [0..K-1] and [-K..-1], zero the Nyquist slot
        out = jnp.concatenate([F[..., :K],
                               jnp.zeros(F.shape[:-1] + (1,), F.dtype),
                               F[..., Ng - K + 1:]], axis=-1)
        return jnp.moveaxis(out, -1, axis)

    def backward(self, cdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(cdata, axis, -1)
        K = N // 2
        pos = data[..., :K]
        neg = data[..., K + 1:]
        mid = jnp.zeros(data.shape[:-1] + (Ng - N + 1,), data.dtype)
        F = jnp.concatenate([pos, mid, neg], axis=-1)
        out = meshctx.local_fft(
            lambda a: jnp.fft.ifft(a, axis=-1), F * Ng, axis)
        return jnp.moveaxis(out, -1, axis)
