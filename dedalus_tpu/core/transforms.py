"""
Spectral transform plans (reference: dedalus/core/transforms.py).

Each plan converts one axis of an N-d array between coefficient and grid
representations. Plans are registered per (basis class, library name) like
the reference's `@register_transform` registry (core/transforms.py:27-32):

  * 'matrix' — dense matrix-multiply transform (MMT). The test oracle, and
    on TPU a genuinely fast path: an MMT is one batched matmul on the MXU.
  * 'fft'    — jnp.fft fast path for Fourier bases; FFT-based DCT for
    Chebyshev.

All plan methods are pure jnp functions of their array argument (safe under
jit/vmap); the transform matrices are host-built numpy constants closed over
by the jitted step.
"""

import numpy as np
import jax.numpy as jnp

from ..tools.array import apply_matrix_jax

# Registry: {(basis_class_name, library): plan_class}
transform_registry = {}


def register_transform(basis_cls_name, name):
    def wrapper(cls):
        transform_registry[(basis_cls_name, name)] = cls
        cls.library = name
        return cls
    return wrapper


def get_plan(basis, scale, library=None):
    lib = library or basis.library
    key = (type(basis).__name__, lib)
    # Fall back through base classes (e.g. ChebyshevT -> Jacobi)
    cls = None
    for klass in type(basis).__mro__:
        cls = transform_registry.get((klass.__name__, lib))
        if cls is not None:
            break
    if cls is None:
        raise KeyError(f"No transform plan registered for {key}")
    return cls(basis, scale)


class TransformPlan:
    """Base transform plan for one axis at one grid scale."""

    def __init__(self, basis, scale):
        self.basis = basis
        self.scale = scale
        self.N = basis.size
        self.Ng = basis.grid_size(scale)


class MatrixTransform(TransformPlan):
    """Generic MMT plan: subclasses provide forward/backward matrices."""

    def __init__(self, basis, scale):
        super().__init__(basis, scale)
        self.forward_mat = self.build_forward(basis, scale)    # (N, Ng)
        self.backward_mat = self.build_backward(basis, scale)  # (Ng, N)

    def forward(self, gdata, axis):
        return apply_matrix_jax(jnp.asarray(self.forward_mat), gdata, axis)

    def backward(self, cdata, axis):
        return apply_matrix_jax(jnp.asarray(self.backward_mat), cdata, axis)


@register_transform("Jacobi", "matrix")
class JacobiMMT(MatrixTransform):
    """
    Jacobi MMT (reference: core/transforms.py:115 JacobiMMT).

    Grid is always the (a0, b0) Gauss grid of the basis family; forward
    projects onto (a0, b0) then applies the ultraspherical-style conversion
    to the basis's derivative level (a, b) = (a0+k, b0+k).
    """

    @staticmethod
    def build_forward(basis, scale):
        from ..tools import jacobi
        Ng = basis.grid_size(scale)
        F = jacobi.forward_matrix(basis.size, basis.a0, basis.b0, Ng)
        if basis.k > 0:
            C = jacobi.conversion_matrix(basis.size, basis.a0, basis.b0, basis.k, basis.k)
            F = C @ F
        return F

    @staticmethod
    def build_backward(basis, scale):
        from ..tools import jacobi
        Ng = basis.grid_size(scale)
        x = jacobi.build_grid(Ng, basis.a0, basis.b0)
        return jacobi.build_polynomials(basis.size, basis.a, basis.b, x).T


@register_transform("Jacobi", "fft")
class JacobiAuto(JacobiMMT):
    """
    Placeholder fast path: Chebyshev DCT-via-FFT lands here later; MMT is
    already MXU-native and is used in the meantime.
    """


@register_transform("RealFourier", "matrix")
class RealFourierMMT(MatrixTransform):
    """
    Real Fourier MMT oracle (reference: core/transforms.py:388 RealFourierMMT).

    Coefficient layout matches the reference's interleaved (cos, -sin) pairs:
    c[2g] = cos-amplitude, c[2g+1] = minus-sin-amplitude of mode g
    (reference: core/basis.py:1108 RealFourier, group_shape=(2,)).
    """

    @staticmethod
    def build_forward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        g = np.arange(N // 2)
        F = np.zeros((N, Ng))
        cosrows = np.cos(np.outer(g, theta)) * 2.0 / Ng
        cosrows[0] /= 2.0
        sinrows = -np.sin(np.outer(g, theta)) * 2.0 / Ng
        sinrows[0] *= 0.0  # -sin(0x) mode is invalid
        F[0::2] = cosrows
        F[1::2] = sinrows
        return F

    @staticmethod
    def build_backward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        g = np.arange(N // 2)
        B = np.zeros((Ng, N))
        B[:, 0::2] = np.cos(np.outer(theta, g))
        B[:, 1::2] = -np.sin(np.outer(theta, g))
        B[:, 1] = 0.0
        return B


@register_transform("RealFourier", "fft")
class RealFourierFFT(TransformPlan):
    """
    Real Fourier fast path via jnp.fft.rfft/irfft
    (reference: core/transforms.py:513 ScipyRealFFT / :538 FFTWRealFFT).
    """

    def forward(self, gdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(gdata, axis, -1)
        F = jnp.fft.rfft(data, axis=-1) / Ng
        K = N // 2
        F = F[..., :K]
        cos = 2.0 * F.real
        cos = cos.at[..., 0].divide(2.0)
        msin = 2.0 * F.imag
        msin = msin.at[..., 0].set(0.0)
        out = jnp.stack([cos, msin], axis=-1).reshape(data.shape[:-1] + (N,))
        return jnp.moveaxis(out, -1, axis)

    def backward(self, cdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(cdata, axis, -1)
        K = N // 2
        pairs = data.reshape(data.shape[:-1] + (K, 2))
        cos = pairs[..., 0]
        msin = pairs[..., 1].at[..., 0].set(0.0)
        F = (cos + 1j * msin) / 2.0
        F = F.at[..., 0].multiply(2.0)
        # pad spectrum to the grid's rfft length
        pad = Ng // 2 + 1 - K
        F = jnp.concatenate([F, jnp.zeros(F.shape[:-1] + (pad,), dtype=F.dtype)], axis=-1)
        out = jnp.fft.irfft(F * Ng, n=Ng, axis=-1)
        return jnp.moveaxis(out, -1, axis)


@register_transform("ComplexFourier", "matrix")
class ComplexFourierMMT(MatrixTransform):
    """
    Complex Fourier MMT oracle (reference: core/transforms.py:212).
    Coefficients ordered by FFT wavenumber layout [0..K, (nyquist), -K..-1];
    the Nyquist slot is invalid and masked to zero.
    """

    @staticmethod
    def _wavenumbers(N):
        return np.fft.fftfreq(N, d=1.0 / N).astype(int)

    @staticmethod
    def build_forward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        k = ComplexFourierMMT._wavenumbers(N)
        F = np.exp(-1j * np.outer(k, theta)) / Ng
        F[N // 2] = 0.0  # Nyquist mode invalid
        return F

    @staticmethod
    def build_backward(basis, scale):
        Ng = basis.grid_size(scale)
        N = basis.size
        theta = 2 * np.pi * np.arange(Ng) / Ng
        k = ComplexFourierMMT._wavenumbers(N)
        B = np.exp(1j * np.outer(theta, k))
        B[:, N // 2] = 0.0
        return B


@register_transform("ComplexFourier", "fft")
class ComplexFourierFFT(TransformPlan):
    """Complex Fourier fast path via jnp.fft (reference: core/transforms.py:271)."""

    def forward(self, gdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(gdata, axis, -1)
        F = jnp.fft.fft(data, axis=-1) / Ng
        K = N // 2
        # keep modes [0..K-1] and [-K..-1], zero the Nyquist slot
        out = jnp.concatenate([F[..., :K],
                               jnp.zeros(F.shape[:-1] + (1,), F.dtype),
                               F[..., Ng - K + 1:]], axis=-1)
        return jnp.moveaxis(out, -1, axis)

    def backward(self, cdata, axis):
        N, Ng = self.N, self.Ng
        data = jnp.moveaxis(cdata, axis, -1)
        K = N // 2
        pos = data[..., :K]
        neg = data[..., K + 1:]
        mid = jnp.zeros(data.shape[:-1] + (Ng - N + 1,), data.dtype)
        F = jnp.concatenate([pos, mid, neg], axis=-1)
        out = jnp.fft.ifft(F * Ng, axis=-1)
        return jnp.moveaxis(out, -1, axis)
