"""
Distributor: process/device layout metadata and field factories
(reference: dedalus/core/distributor.py:35).

TPU-native redesign: instead of the reference's MPI layout chain (a ladder of
Transform/Transpose states walked at runtime), the distributor holds a
`jax.sharding.Mesh` and named shardings. Fields keep only two user-visible
layouts ('c' full-coefficient, 'g' full-grid); all intermediate pencil states
exist only inside jitted transform pipelines where XLA/GSPMD places the
all-to-alls (reference: core/transposes.pyx -> ICI collectives).
"""

import numpy as np
import jax

from .coords import Coordinate, CartesianCoordinates, CoordinateSystem


class Distributor:

    def __init__(self, coordsystems, dtype=np.float64, mesh=None, comm=None):
        if isinstance(coordsystems, CoordinateSystem):
            coordsystems = (coordsystems,)
        self.coordsystems = tuple(coordsystems)
        self.dtype = np.dtype(dtype)
        # Flatten coordinates and assign axes.
        coords = []
        for cs in self.coordsystems:
            cs.set_distributor(self)
            for coord in cs.coords:
                coord.axis = len(coords)
                coords.append(coord)
        self.coords = tuple(coords)
        self.dim = len(coords)
        # Device mesh: a jax.sharding.Mesh (or None for single-device).
        self.mesh = mesh
        self.comm = comm  # unused; accepted for API familiarity

    # ------------------------------------------------------------ factories

    def Field(self, name=None, bases=None, dtype=None, tensorsig=()):
        from .field import Field
        return Field(self, bases=bases, name=name, tensorsig=tensorsig,
                     dtype=dtype or self.dtype)

    def ScalarField(self, *args, **kw):
        return self.Field(*args, **kw)

    def VectorField(self, coordsys, name=None, bases=None, dtype=None):
        from .field import Field
        return Field(self, bases=bases, name=name, tensorsig=(coordsys,),
                     dtype=dtype or self.dtype)

    def TensorField(self, coordsys, name=None, bases=None, dtype=None, order=2):
        from .field import Field
        if isinstance(coordsys, tuple):
            tensorsig = coordsys
        else:
            tensorsig = (coordsys,) * order
        return Field(self, bases=bases, name=name, tensorsig=tensorsig,
                     dtype=dtype or self.dtype)

    # -------------------------------------------------------------- helpers

    def get_axis(self, coord):
        if isinstance(coord, Coordinate):
            return coord.axis
        return coord.first_axis

    def get_coord(self, name):
        """The Coordinate object with the given name (the single name
        lookup behind f(z=...) and string coord specs)."""
        for coord in self.coords:
            if coord.name == name:
                return coord
        raise ValueError(f"Unknown coordinate name: {name!r}")

    def expand_bases(self, bases):
        """Expand a basis/tuple-of-bases spec to a full per-axis tuple."""
        full = [None] * self.dim
        if bases is None:
            return tuple(full)
        if not isinstance(bases, (tuple, list)):
            bases = (bases,)
        seen = set()
        for basis in bases:
            if basis is None or id(basis) in seen:
                continue
            seen.add(id(basis))
            axis = self.get_axis(basis.coord)
            for sub in range(basis.dim):
                if full[axis + sub] is not None:
                    raise ValueError(f"Multiple bases along axis {axis + sub}")
                full[axis + sub] = basis
        return tuple(full)

    def remedy_scales(self, scales):
        if scales is None:
            scales = 1.0
        if np.isscalar(scales):
            return (float(scales),) * self.dim
        return tuple(float(s) for s in scales)

    def local_grid(self, basis, scale=None):
        """Grid points of `basis`, shaped for broadcasting over the domain."""
        scale = 1.0 if scale is None else scale
        grid = basis.global_grid(scale)
        axis = self.get_axis(basis.coord)
        shape = [1] * self.dim
        shape[axis] = grid.size
        return grid.reshape(shape)

    def local_grids(self, *bases, scales=None):
        """Broadcast-shaped grids; multi-axis bases yield one grid per
        sub-axis (e.g. `phi, r = dist.local_grids(disk)`)."""
        scales = self.remedy_scales(scales)
        out = []
        for b in bases:
            first = self.get_axis(b.coord)
            if b.dim == 1:
                out.append(self.local_grid(b, scales[first]))
            else:
                grids = b.global_grids(tuple(scales[first:first + b.dim]))
                for sub, grid in enumerate(grids):
                    shape = [1] * self.dim
                    shape[first + sub] = grid.size
                    out.append(np.reshape(grid, shape))
        return tuple(out)

    # ------------------------------------------------------------- sharding

    @property
    def process_index(self):
        return jax.process_index()

    def _layout_sharding(self, shift, tensorsig):
        """Mesh axis r shards spatial dim r + shift; tensor dims unsharded."""
        from jax.sharding import NamedSharding, PartitionSpec
        if self.mesh is None:
            return None
        R = len(self.mesh.axis_names)
        if R >= self.dim:
            raise ValueError(f"Mesh rank {R} must be below the domain "
                             f"dimension {self.dim}.")
        dim_to_axis = {r + shift: self.mesh.axis_names[r] for r in range(R)}
        spec = ([None] * len(tensorsig)
                + [dim_to_axis.get(d) for d in range(self.dim)])
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def coeff_sharding(self, tensorsig=()):
        """
        NamedSharding for full-coefficient arrays: mesh axis r shards
        spatial dim r (the reference's coeff-space block distribution of
        the first R axes, core/distributor.py:59-74). None without a mesh.
        """
        return self._layout_sharding(0, tensorsig)

    def grid_sharding(self, tensorsig=()):
        """
        NamedSharding for full-grid arrays: mesh axis r shards spatial dim
        r+1 — the post-transpose-walk layout of the reference chain
        (core/distributor.py:128-166)."""
        return self._layout_sharding(1, tensorsig)
