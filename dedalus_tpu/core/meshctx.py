"""
Mesh context for sharded transform walks.

XLA's SPMD partitioner cannot partition `fft` ops: a batched FFT whose
batch dims are sharded is lowered as all-gather + replicated full-size FFT
(observed on the compiled sharded step), which destroys both memory and
scaling at large sizes. The transform walk therefore publishes the current
{array dim: mesh axis} layout here, and the Fourier/DCT plans route their
FFT calls through `local_fft`, which runs the op inside shard_map so each
device transforms only its own batch block — the compiled program then
contains only the walk's intended all-to-all pencil transposes
(reference counterpart: FFTW transforms are always rank-local,
dedalus/core/transposes.pyx moves data so that stays true).
"""

import threading
from functools import partial

from jax.sharding import Mesh, PartitionSpec

from ..tools.compat import shard_map

_CTX = threading.local()

# Mesh axis names reserved for ENSEMBLE member batching (core/ensemble.py):
# a transform walk never transposes over them — on a 2-D batch x pencil
# mesh the walk distributes the pencil axes only, while the member axis
# stays manual (shard_map) around the whole fleet program.
BATCH_AXIS_NAMES = frozenset({"batch"})


def walk_axis_names(mesh):
    """Mesh axes that participate in transform-walk distribution: every
    axis except the reserved ensemble batch axes. The 2-D batch x pencil
    composition publishes the SAME mesh for walks and fleet sharding;
    this filter is what keeps the walk's transposes on the pencil axes
    while members ride the batch axis untouched."""
    return tuple(n for n in mesh.axis_names if n not in BATCH_AXIS_NAMES)


def surviving_devices(mesh, lost_indices):
    """Devices of a 1-D `mesh` left after losing `lost_indices` (local
    device indices; out-of-range entries ignored), in their original
    order. The single filter rule behind device-loss recovery — the mesh
    built from it (surviving_mesh) and the member re-padding derived
    from its length (core/ensemble.py) must never disagree."""
    if len(mesh.axis_names) != 1:
        raise ValueError("surviving_devices supports 1-D meshes only")
    devices = list(mesh.devices.flat)
    lost = {i for i in lost_indices if 0 <= i < len(devices)}
    return [dev for i, dev in enumerate(devices) if i not in lost]


def surviving_mesh(mesh, lost_indices):
    """
    The 1-D mesh left after losing `lost_indices` of a 1-D `mesh`: same
    axis name, surviving devices in their original order. Returns None
    when a single device survives — a single-device layout needs no
    mesh — and raises when nothing survives. The device-loss recovery
    path (core/ensemble.py) reshards onto this.
    """
    survivors = surviving_devices(mesh, lost_indices)
    if not survivors:
        raise RuntimeError("no surviving devices to build a mesh from")
    if len(survivors) < 2:
        return None
    import numpy as np
    return Mesh(np.array(survivors), mesh.axis_names)


def set_walk(mesh, layout):
    """Activate (mesh, {absolute data dim: mesh axis name}) for subsequent
    transform calls; returns the previous state for restoration."""
    prev = getattr(_CTX, "state", None)
    _CTX.state = (mesh, dict(layout)) if mesh is not None else None
    return prev


def restore_walk(prev):
    _CTX.state = prev


def active():
    return getattr(_CTX, "state", None)


def gathered_apply(fn, data, mesh, axis_name, dim=0):
    """
    Apply `fn` (a local whole-array function) to `data` whose `dim` is
    block-sharded over `axis_name`: all_gather the axis inside shard_map,
    apply `fn` to the replicated copy, and slice this device's block back
    out. The escape hatch for arrays too low-dimensional to layout-walk —
    a 1-D tau field's transform roundtrip under the 2-D batch x pencil
    fleet (core/ensemble.py): its only axis is the sharded one, so there
    is no free axis to keep local, and an unrouted fft on a
    manual-subgroup-sharded array hard-crashes the SPMD partitioner.
    `fn` must preserve the size of `dim`. Falls back to a direct call
    when the dim does not divide the mesh axis.
    """
    n = mesh.shape[axis_name]
    if data.shape[dim] % n:
        return fn(data)
    spec = PartitionSpec(*[axis_name if d == dim else None
                           for d in range(data.ndim)])

    def local(block):
        import jax
        full = jax.lax.all_gather(block, axis_name, axis=dim, tiled=True)
        out = fn(full)
        idx = jax.lax.axis_index(axis_name)
        blk = out.shape[dim] // n
        return jax.lax.dynamic_slice_in_dim(out, idx * blk, blk, axis=dim)

    return shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)(data)


def local_fft(fn, data, orig_axis):
    """
    Apply `fn` (an FFT-like op along the LAST axis of `data`, where `data`
    is the walk-level array with `orig_axis` moved to the end) per-device:
    inside shard_map each device runs the FFT on its local batch block.
    Falls back to the global-view call (which GSPMD will gather) when no
    walk is active, nothing is sharded, or a sharded dim does not divide
    the mesh axis.
    """
    state = active()
    if state is None or orig_axis is None:
        return fn(data)
    mesh, layout = state
    # moveaxis(orig_axis -> -1): dims before orig_axis keep their index,
    # dims after shift down one, the transformed axis lands last
    moved = {}
    for dim, name in layout.items():
        if name is None:
            continue
        if dim == orig_axis:
            # the walk must have localized the transform axis already
            return fn(data)
        moved[dim if dim < orig_axis else dim - 1] = name
    if not moved:
        return fn(data)
    for dim, name in moved.items():
        if data.shape[dim] % mesh.shape[name]:
            return fn(data)  # uneven block: let GSPMD handle it
    spec = PartitionSpec(*[moved.get(d) for d in range(data.ndim)])

    def local(block):
        # collapse batch dims to 2D around the FFT: XLA:CPU's fft thunk
        # requires a dim0-major operand layout, which fusion inside the
        # shard_map body does not always produce for high-rank operands;
        # the reshape forces a standard-layout copy when needed
        shp = block.shape
        flat = block.reshape((-1, shp[-1]))
        out = fn(flat)
        return out.reshape(shp[:-1] + out.shape[-1:])

    return partial(shard_map, mesh=mesh, in_specs=spec,
                   out_specs=spec)(local)(data)
